"""Raft wire types.

Reference: the eraftpb protobuf consumed by raft-rs (Entry, Message,
HardState, Snapshot, ConfChange) — plain dataclasses here; the transport
layer owns serialization.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from enum import Enum, auto
from typing import Optional


class EntryType(Enum):
    NORMAL = auto()
    CONF_CHANGE = auto()


@dataclass(frozen=True)
class Entry:
    term: int
    index: int
    data: bytes = b""
    entry_type: EntryType = EntryType.NORMAL


class ConfChangeType(Enum):
    ADD_NODE = auto()
    REMOVE_NODE = auto()
    ADD_LEARNER = auto()


@dataclass(frozen=True)
class ConfChange:
    change_type: ConfChangeType
    node_id: int
    context: bytes = b""

    def to_bytes(self) -> bytes:
        return b"%d:%d:%s" % (self.change_type.value, self.node_id,
                              self.context)

    @staticmethod
    def from_bytes(b: bytes) -> "ConfChange":
        t, n, ctx = b.split(b":", 2)
        return ConfChange(ConfChangeType(int(t)), int(n), ctx)


@dataclass(frozen=True)
class ConfChangeV2:
    """Joint-consensus membership change (raft §6 / raft-rs
    ConfChangeV2): several changes enter ATOMICALLY via the joint
    config C_old,new — commits and elections need majorities of BOTH
    sets until the leave entry retires C_old.

    Wire format: ``2|<leave>|t:n,t:n,...|context`` — the leading "2|"
    disambiguates from the V1 "<type>:<id>:<ctx>" format in the shared
    CONF_CHANGE entry type.
    """

    changes: tuple = ()         # tuple[(ConfChangeType, node_id)]
    context: bytes = b""
    leave_joint: bool = False

    def to_bytes(self) -> bytes:
        body = b",".join(b"%d:%d" % (t.value, n)
                         for t, n in self.changes)
        return b"2|%d|%s|%s" % (int(self.leave_joint), body,
                                self.context)

    @staticmethod
    def is_v2(data: bytes) -> bool:
        return data.startswith(b"2|")

    @staticmethod
    def from_bytes(b: bytes) -> "ConfChangeV2":
        _tag, leave, body, ctx = b.split(b"|", 3)
        changes = []
        if body:
            for part in body.split(b","):
                t, n = part.split(b":")
                changes.append((ConfChangeType(int(t)), int(n)))
        return ConfChangeV2(tuple(changes), ctx, bool(int(leave)))


@dataclass
class HardState:
    """Durable before any message send (raft paper §5)."""

    term: int = 0
    vote: int = 0
    commit: int = 0


@dataclass(frozen=True)
class SnapshotMetadata:
    index: int
    term: int
    voters: tuple = ()
    learners: tuple = ()
    # non-empty while the config is joint (C_old half of C_old,new)
    voters_outgoing: tuple = ()


@dataclass(frozen=True)
class Snapshot:
    metadata: SnapshotMetadata
    data: bytes = b""


class MsgType(Enum):
    HUP = auto()                # local: start election
    BEAT = auto()               # local: leader heartbeat tick
    PROPOSE = auto()            # local: client proposal
    APPEND = auto()
    APPEND_RESPONSE = auto()
    REQUEST_VOTE = auto()
    REQUEST_VOTE_RESPONSE = auto()
    PRE_VOTE = auto()
    PRE_VOTE_RESPONSE = auto()
    HEARTBEAT = auto()
    HEARTBEAT_RESPONSE = auto()
    SNAPSHOT = auto()
    TRANSFER_LEADER = auto()    # local: admin transfer
    TIMEOUT_NOW = auto()
    # follower/replica reads (raft §6.4 ReadIndex): a follower asks the
    # leader for its commit index; serving waits until applied >= it
    READ_INDEX = auto()
    READ_INDEX_RESP = auto()


@dataclass
class Message:
    msg_type: MsgType
    to: int = 0
    frm: int = 0
    term: int = 0
    # append/vote payloads
    log_term: int = 0           # term of entry at ``index``
    index: int = 0              # prev log index (append) / last index (vote)
    entries: tuple = ()
    commit: int = 0
    reject: bool = False
    reject_hint: int = 0        # follower's last index, speeds backtracking
    snapshot: Optional[Snapshot] = None
    # lease context: leaders stamp heartbeats with their send tick; the
    # response echoes it so the lease window is measured from SEND time
    # (reference: raftstore leader lease, store/peer.rs maybe_renew_lease).
    # None = no lease context — distinct from tick 0, which is a valid ack
    ctx: Optional[int] = None
