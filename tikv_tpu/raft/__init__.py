"""Raft consensus.

The reference consumes the external ``raft-rs`` crate (Cargo.toml:219);
the rebuild provides the capability natively: a deterministic, tick-driven
Raft state machine with the RawNode/Ready interface raftstore expects
(SURVEY.md §2.1 "architecturally load-bearing" external crates).
"""

from .messages import (
    ConfChange,
    ConfChangeType,
    Entry,
    EntryType,
    HardState,
    Message,
    MsgType,
    Snapshot,
    SnapshotMetadata,
)
from .raw_node import RawNode, Ready
from .storage import MemoryRaftStorage

__all__ = [
    "ConfChange", "ConfChangeType", "Entry", "EntryType", "HardState",
    "Message", "MsgType", "Snapshot", "SnapshotMetadata",
    "RawNode", "Ready", "MemoryRaftStorage",
]
