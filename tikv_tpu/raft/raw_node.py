"""The Raft state machine (RawNode + Ready interface).

Reference capability: raft-rs (RawNode::tick/step/propose/ready/advance),
which the reference's raftstore drives from its poll loop
(components/raftstore/src/store/fsm/peer.rs).  Implements the raft paper
with the extensions TiKV relies on: pre-vote (§9.6 extension), leader
transfer via TIMEOUT_NOW, rejection hints for fast log backtracking,
snapshot-based catch-up, and single-step membership change with the
one-in-flight rule.

Deviations tracked for later rounds: joint consensus (the reference
supports it via raft-rs; tests/integrations test_joint_consensus.rs),
check-quorum/lease-read safety is provided one layer up (raftstore lease).

Determinism: no wall clock, no global RNG — ``tick()`` advances logical
time and election timeouts are drawn from a node-seeded PRNG, so cluster
tests replay identically.
"""

from __future__ import annotations

import random
import time
from dataclasses import dataclass, field
from typing import Optional, Sequence

from .messages import (
    ConfChange,
    ConfChangeType,
    Entry,
    EntryType,
    HardState,
    Message,
    MsgType,
    Snapshot,
)
from .storage import MemoryRaftStorage

FOLLOWER = "follower"
PRE_CANDIDATE = "pre_candidate"
CANDIDATE = "candidate"
LEADER = "leader"

# Progress replication states (raft-rs progress.rs)
PROBE = "probe"
REPLICATE = "replicate"
SNAPSHOT = "snapshot"

_MAX_APPEND_ENTRIES = 256


@dataclass
class Progress:
    """Leader's view of one follower (raft-rs Progress)."""

    match: int = 0
    next: int = 1
    state: str = PROBE
    pending_snapshot: int = 0
    paused: bool = False


@dataclass
class Ready:
    """Work handed to the application per turn (raft-rs Ready)."""

    messages: list = field(default_factory=list)
    entries: list = field(default_factory=list)          # persist these
    committed_entries: list = field(default_factory=list)  # apply these
    hard_state: Optional[HardState] = None               # persist if set
    snapshot: Optional[Snapshot] = None                  # install if set
    soft_state: Optional[tuple] = None                   # (leader_id, role)


class RawNode:
    def __init__(self, node_id: int, storage: MemoryRaftStorage,
                 election_tick: int = 10, heartbeat_tick: int = 2,
                 pre_vote: bool = True, seed: int = 0,
                 tick_interval: Optional[float] = None):
        self.id = node_id
        self.storage = storage
        self._election_tick = election_tick
        self._heartbeat_tick = heartbeat_tick
        self._pre_vote = pre_vote
        # wall-clock seconds per tick, when the driver ticks on real time
        # (server/node.py).  None = manually-driven ticks (in-process
        # tests); the lease then rests on tick counts alone.
        self._tick_interval = tick_interval
        self._rng = random.Random((seed << 16) ^ node_id)

        hs, voters, learners = storage.initial_state()
        self.term = hs.term
        self.vote = hs.vote
        self.commit = hs.commit
        self.voters: set[int] = set(voters)
        # unsafe recovery: voter ids certified dead — excluded from all
        # quorums while non-empty (in-memory only; PD re-issues the
        # recovery plan after a restart, store/unsafe_recovery.rs)
        self.force_failed: set[int] = set()
        self.learners: set[int] = set(learners)
        # joint consensus (raft §6): non-empty while in C_old,new —
        # commits and elections then need majorities of BOTH sets
        init_out = getattr(storage, "initial_outgoing", None)
        self.voters_outgoing: set[int] = \
            set(init_out()) if callable(init_out) else set()

        self.state = FOLLOWER
        self.leader_id = 0
        self.progress: dict[int, Progress] = {}
        self._votes: dict[int, bool] = {}
        self._msgs: list[Message] = []
        self._elapsed = 0
        self._timeout = 0
        self._reset_timeout()

        self.applied = storage.snapshot.metadata.index
        self._stable_index = storage.last_index()
        self._last_applied_snapshot = storage.snapshot.metadata.index
        self._pending_snapshot: Optional[Snapshot] = None
        self._pending_conf_index = storage.last_index() \
            if self._has_pending_conf_entry() else 0
        self._lead_transferee = 0
        self._prev_hs = HardState(self.term, self.vote, self.commit)
        self._prev_soft = (self.leader_id, self.state)
        # leader lease (store/worker/read.rs ReadDelegate semantics, in
        # tick units): heartbeats carry the send tick; acks prove a
        # quorum heard from us within the lease window.  The reference
        # measures the lease in monotonic time (ReadDelegate
        # maybe_renew_lease); tick counts alone break when the tick loop
        # stalls (fsync pause, GC) while followers keep wall-clock time —
        # so each heartbeat's send is also stamped with time.monotonic()
        # and in_lease() cross-checks wall-clock age when tick_interval
        # is known.
        self._tick_count = 0
        self._lease_ack: dict[int, int] = {}
        self._hb_send_mono: dict[int, float] = {}   # send tick -> mono
        self._lease_ack_mono: dict[int, float] = {}  # nid -> mono of ack'd hb
        # ReadIndex answers: (commit index, ctx) pairs the peer drains
        self.read_states: list[tuple[int, int]] = []

    # ------------------------------------------------------------- helpers

    def _pending_conf_entry_index(self) -> int:
        last = 0
        for e in self.storage.entries:
            if e.entry_type is EntryType.CONF_CHANGE and \
                    e.index > self.applied:
                last = max(last, e.index)
        return last

    def _has_pending_conf_entry(self) -> bool:
        return self._pending_conf_entry_index() > 0

    def _reset_timeout(self) -> None:
        self._elapsed = 0
        self._timeout = self._rng.randint(self._election_tick,
                                          2 * self._election_tick - 1)

    def last_index(self) -> int:
        return self.storage.last_index()

    def last_term(self) -> int:
        t = self.storage.term(self.last_index())
        return t if t is not None else 0

    def _quorum(self) -> int:
        return len(self.voters) // 2 + 1

    def in_joint(self) -> bool:
        return bool(self.voters_outgoing)

    def all_voters(self) -> set:
        return self.voters | self.voters_outgoing

    def _majority_of(self, ids: set, granted) -> bool:
        """``granted(nid) -> bool`` holds for a majority of ``ids``.

        Unsafe recovery (store/unsafe_recovery.rs ForceLeader): voters
        declared failed are excluded from every quorum computation, so
        the surviving minority can elect and commit the membership
        change that removes the dead peers.
        """
        if self.force_failed:
            ids = ids - self.force_failed
        if not ids:
            return True
        return sum(1 for nid in ids if granted(nid)) >= \
            len(ids) // 2 + 1

    def _joint_won(self, granted) -> bool:
        """Joint decision rule: majority of the incoming set AND (while
        joint) of the outgoing set (raft §6 C_old,new)."""
        return self._majority_of(self.voters, granted) and \
            self._majority_of(self.voters_outgoing, granted)

    def _send(self, m: Message) -> None:
        m.frm = self.id
        if m.term == 0 and m.msg_type not in (MsgType.PRE_VOTE,):
            m.term = self.term
        self._msgs.append(m)

    # ------------------------------------------------------------- roles

    def _become_follower(self, term: int, leader_id: int) -> None:
        if term > self.term:
            self.term = term
            self.vote = 0
        self.state = FOLLOWER
        self.leader_id = leader_id
        self._lead_transferee = 0
        self._reset_timeout()

    def _become_pre_candidate(self) -> None:
        self.state = PRE_CANDIDATE
        self.leader_id = 0
        self._votes = {self.id: True}
        self._reset_timeout()

    def _become_candidate(self) -> None:
        self.state = CANDIDATE
        self.term += 1
        self.vote = self.id
        self.leader_id = 0
        self._votes = {self.id: True}
        self._reset_timeout()

    def _become_leader(self) -> None:
        self.state = LEADER
        self.leader_id = self.id
        # recompute the one-in-flight gate from the log: a new leader
        # inheriting a committed-but-unapplied conf entry must not
        # accept another conf change before applying it (raft-rs does
        # the same when campaigning)
        self._pending_conf_index = max(self._pending_conf_index,
                                       self._pending_conf_entry_index())
        self._lead_transferee = 0
        self._lease_ack = {}
        self._hb_send_mono = {}
        self._lease_ack_mono = {}
        last = self.last_index()
        self.progress = {
            nid: Progress(match=0, next=last + 1)
            for nid in self.voters | self.voters_outgoing | self.learners
            if nid != self.id
        }
        self.progress[self.id] = Progress(match=last, next=last + 1,
                                          state=REPLICATE)
        # noop entry to commit entries from previous terms (§5.4.2)
        self._append_entries([Entry(self.term, last + 1)])
        self._broadcast_append()
        self._maybe_commit()

    # ------------------------------------------------------------- ticking

    def tick(self) -> None:
        self._tick_count += 1
        self._elapsed += 1
        if self.state == LEADER:
            if self._elapsed >= self._heartbeat_tick:
                self._elapsed = 0
                self._broadcast_heartbeat()
        else:
            if self._elapsed >= self._timeout and \
                    self.id in self.voters:
                self._reset_timeout()
                self.campaign()

    def in_lease(self) -> bool:
        """Leader lease check for local (no-consensus) reads.

        Safe iff (a) pre-vote is on — a follower with live leader contact
        rejects pre-votes until its election timer (≥ election_tick
        ticks) expires, so no rival can be elected while a quorum acked
        our heartbeats within the last election_tick-2 ticks (measured
        from heartbeat SEND tick; 2 ticks of margin absorb cross-node
        tick skew the way the reference subtracts clock drift from
        max_lease); and (b) no leader transfer is in flight (the target
        campaigns immediately via TIMEOUT_NOW).
        """
        if self.state != LEADER or not self._pre_vote or \
                self._lead_transferee or self.force_failed:
            return False
        window = self._election_tick - 2
        if window <= 0:
            return False
        floor = self._tick_count - window
        # Only voters with a recorded ack count: a freshly-(re)started
        # leader has floor <= 0 and must not treat silent voters as live
        # (ADVICE r2: TIMEOUT_NOW transferee could serve lease reads with
        # zero acks).
        now = time.monotonic() if self._tick_interval is not None else None
        max_age = None if now is None else window * self._tick_interval

        def ack_live(nid: int) -> bool:
            if nid not in self._lease_ack or self._lease_ack[nid] < floor:
                return False
            if max_age is None:
                return True
            # wall-clock cross-check: if the tick loop stalled, tick
            # counts freeze while followers' election timers keep running
            # in real time — the ack must also be recent in mono time
            mono = self._lease_ack_mono.get(nid)
            return mono is not None and (now - mono) <= max_age

        def live(nid):
            return nid == self.id or ack_live(nid)
        return self._joint_won(live)

    def enter_force_leader(self, failed: set) -> None:
        """Unsafe recovery: certify ``failed`` voter ids as dead and
        campaign with the surviving minority as the full quorum.

        Refused when the survivors alone still form a majority — a
        normal election must be used then (the reference's PD-driven
        plan applies the same gate), and when this node is itself in the
        failed set.
        """
        failed = set(failed) & self.all_voters()
        if self.id in failed:
            raise ValueError("cannot force-lead from a failed voter")

        def alive_majority(ids: set) -> bool:
            if not ids:
                return True
            return sum(1 for n in ids if n not in failed) >= \
                len(ids) // 2 + 1
        # refuse only when a NORMAL election could still win — in a
        # joint config that needs a live majority of BOTH sets
        if alive_majority(self.voters) and \
                alive_majority(self.voters_outgoing):
            raise ValueError(
                "survivors form a quorum; use a normal election")
        self.force_failed = failed
        self.campaign(force=True)

    def exit_force_leader(self) -> None:
        self.force_failed = set()

    def campaign(self, force: bool = False) -> None:
        if self._pre_vote and not force:
            self._become_pre_candidate()
            if self._tally_won():                   # single node
                self._campaign_real()
                return
            for nid in self.all_voters():
                if nid == self.id:
                    continue
                self._msgs.append(Message(
                    MsgType.PRE_VOTE, to=nid, frm=self.id,
                    term=self.term + 1, log_term=self.last_term(),
                    index=self.last_index()))
        else:
            self._campaign_real()

    def _campaign_real(self) -> None:
        self._become_candidate()
        if self._tally_won():                       # single node wins now
            self._become_leader()
            return
        for nid in self.all_voters():
            if nid == self.id:
                continue
            self._send(Message(
                MsgType.REQUEST_VOTE, to=nid, term=self.term,
                log_term=self.last_term(), index=self.last_index()))

    def _tally_won(self) -> bool:
        return self._joint_won(
            lambda nid: self._votes.get(nid, False))

    def _tally_lost(self) -> bool:
        """A majority of either set rejected: the election cannot win."""
        def rejected(nid):
            return nid in self._votes and not self._votes[nid]
        return (self._majority_of(self.voters, rejected) and
                bool(self.voters)) or \
            (bool(self.voters_outgoing) and
             self._majority_of(self.voters_outgoing, rejected))

    # ------------------------------------------------------------- propose

    def propose(self, data: bytes) -> int:
        """Append a proposal; returns its index.  Raises if not leader."""
        if self.state != LEADER:
            raise NotLeader(self.leader_id)
        if self.force_failed:
            # force-leader mode exists ONLY to drive the membership
            # change that evicts dead voters (unsafe_recovery.rs: normal
            # proposals are rejected until recovery completes)
            raise ProposalDropped("force leader: recovery in progress")
        if self._lead_transferee:
            raise ProposalDropped("leader transfer in progress")
        index = self.last_index() + 1
        self._append_entries([Entry(self.term, index, data)])
        self._broadcast_append()
        self._maybe_commit()
        return index

    def propose_conf_change(self, cc: ConfChange) -> int:
        if self.state != LEADER:
            raise NotLeader(self.leader_id)
        if self._pending_conf_index > self.applied:
            raise ProposalDropped("conf change already in flight")
        index = self.last_index() + 1
        self._append_entries([Entry(self.term, index, cc.to_bytes(),
                                    EntryType.CONF_CHANGE)])
        self._pending_conf_index = index
        self._broadcast_append()
        self._maybe_commit()
        return index

    def apply_conf_change(self, cc: ConfChange) -> None:
        """Called by the application after applying a conf-change entry."""
        if cc.change_type is ConfChangeType.ADD_NODE:
            self.learners.discard(cc.node_id)
            self.voters.add(cc.node_id)
            if self.state == LEADER and cc.node_id not in self.progress:
                self.progress[cc.node_id] = Progress(
                    match=0, next=self.last_index() + 1)
        elif cc.change_type is ConfChangeType.ADD_LEARNER:
            self.voters.discard(cc.node_id)
            self.learners.add(cc.node_id)
            if self.state == LEADER and cc.node_id not in self.progress:
                self.progress[cc.node_id] = Progress(
                    match=0, next=self.last_index() + 1)
        else:
            self.voters.discard(cc.node_id)
            self.learners.discard(cc.node_id)
            self.progress.pop(cc.node_id, None)
        self.storage.set_conf(sorted(self.voters), sorted(self.learners),
                              sorted(self.voters_outgoing))
        if self.state == LEADER:
            self._maybe_commit()    # quorum may have shrunk

    def propose_conf_change_v2(self, cc2, force: bool = False) -> int:
        """Propose a joint membership change (raft §6; raft-rs
        ConfChangeV2).  Same one-in-flight rule as V1; ``force`` is the
        auto-leave path — the LEAVE is proposed from the ENTER's apply,
        where the enter is by definition the pending change it
        supersedes (raft-rs auto transition does the same)."""
        if self.state != LEADER:
            raise NotLeader(self.leader_id)
        if not force and self._pending_conf_index > self.applied:
            raise ProposalDropped("conf change already in flight")
        if not cc2.leave_joint and self.in_joint():
            raise ProposalDropped("already in a joint config")
        index = self.last_index() + 1
        self._append_entries([Entry(self.term, index, cc2.to_bytes(),
                                    EntryType.CONF_CHANGE)])
        self._pending_conf_index = index
        self._broadcast_append()
        self._maybe_commit()
        return index

    def apply_conf_change_v2(self, cc2) -> None:
        """Apply an enter-joint or leave-joint entry.

        Enter: outgoing = current voters; the change list produces the
        incoming set; decisions need BOTH majorities until leave.
        Leave: outgoing clears; nodes in neither set drop out.
        """
        if cc2.leave_joint:
            gone = self.voters_outgoing - self.voters - self.learners
            self.voters_outgoing = set()
            for nid in gone:
                self.progress.pop(nid, None)
        else:
            if self.in_joint():
                # raft-rs rejects entering a joint config while one is
                # active — overwriting outgoing would drop the real
                # C_old and break the both-majority invariant
                return False
            self.voters_outgoing = set(self.voters)
            for ctype, nid in cc2.changes:
                if ctype is ConfChangeType.ADD_NODE:
                    self.learners.discard(nid)
                    self.voters.add(nid)
                elif ctype is ConfChangeType.ADD_LEARNER:
                    self.voters.discard(nid)
                    self.learners.add(nid)
                else:       # REMOVE_NODE
                    self.voters.discard(nid)
                    self.learners.discard(nid)
            if self.state == LEADER:
                for nid in (self.voters | self.learners) -                         set(self.progress) - {self.id}:
                    self.progress[nid] = Progress(
                        match=0, next=self.last_index() + 1)
        self.storage.set_conf(sorted(self.voters), sorted(self.learners),
                              sorted(self.voters_outgoing))
        if self.state == LEADER:
            self._maybe_commit()
        return True

    def transfer_leader(self, target: int) -> None:
        self.step(Message(MsgType.TRANSFER_LEADER, to=self.id,
                          frm=target, term=self.term))

    # ------------------------------------------------------------- log ops

    def _append_entries(self, entries: Sequence[Entry]) -> None:
        self.storage.append(list(entries))
        if self.state == LEADER:
            pr = self.progress[self.id]
            pr.match = self.last_index()
            pr.next = pr.match + 1

    def _broadcast_append(self) -> None:
        for nid in list(self.progress):
            if nid != self.id:
                self._send_append(nid)

    def _send_append(self, to: int) -> None:
        pr = self.progress[to]
        if pr.state == SNAPSHOT or pr.paused:
            return
        prev_index = pr.next - 1
        prev_term = self.storage.term(prev_index)
        if prev_term is None:   # compacted: ship a snapshot
            self._send_snapshot(to)
            return
        hi = min(self.last_index() + 1, pr.next + _MAX_APPEND_ENTRIES)
        entries = tuple(self.storage.slice(pr.next, hi))
        if pr.state == PROBE and entries:
            pr.paused = True    # one probe in flight until acked
        self._send(Message(
            MsgType.APPEND, to=to, term=self.term, log_term=prev_term,
            index=prev_index, entries=entries, commit=self.commit))

    def _send_snapshot(self, to: int) -> None:
        snap = self.storage.snapshot_for_send()
        if snap.metadata.index == 0:
            return
        pr = self.progress[to]
        pr.state = SNAPSHOT
        pr.pending_snapshot = snap.metadata.index
        self._send(Message(MsgType.SNAPSHOT, to=to, term=self.term,
                           snapshot=snap))

    def _broadcast_heartbeat(self) -> None:
        if self._tick_interval is not None:
            self._hb_send_mono[self._tick_count] = time.monotonic()
            if len(self._hb_send_mono) > 4 * self._election_tick:
                horizon = self._tick_count - 2 * self._election_tick
                for t in [t for t in self._hb_send_mono if t < horizon]:
                    del self._hb_send_mono[t]
        for nid, pr in self.progress.items():
            if nid == self.id:
                continue
            self._send(Message(MsgType.HEARTBEAT, to=nid, term=self.term,
                               commit=min(pr.match, self.commit),
                               ctx=self._tick_count))

    def _commit_index_of(self, ids: set) -> int:
        if self.force_failed:
            ids = ids - self.force_failed
            if not ids:
                # every voter of this set is certified dead: the set
                # imposes NO constraint (mirrors _majority_of's vacuous
                # truth) — 0 would freeze commits during recovery
                return 1 << 62
        matches = sorted((self.progress[nid].match for nid in ids
                          if nid in self.progress), reverse=True)
        if len(matches) < len(ids) // 2 + 1:
            return 0
        return matches[len(ids) // 2]

    def _maybe_commit(self) -> bool:
        if not self.progress:
            return False
        n = self._commit_index_of(self.voters)
        if self.in_joint():
            # joint rule: an index commits only when BOTH configs'
            # majorities replicated it (raft §6)
            n = min(n, self._commit_index_of(self.voters_outgoing))
        if n > self.commit and self.storage.term(n) == self.term:
            self.commit = n
            return True
        return False

    # ------------------------------------------------------------- step

    def step(self, m: Message) -> None:
        if m.msg_type is MsgType.HUP:
            self.campaign()
            return
        if m.msg_type is MsgType.TRANSFER_LEADER:
            self._handle_transfer(m)
            return

        # term bookkeeping (raft-rs raft.rs Step)
        if m.term > self.term:
            if m.msg_type in (MsgType.PRE_VOTE,):
                pass    # pre-vote never bumps terms
            elif m.msg_type is MsgType.PRE_VOTE_RESPONSE and not m.reject:
                pass    # counted below; term bump happens on real campaign
            else:
                lead = m.frm if m.msg_type in (
                    MsgType.APPEND, MsgType.HEARTBEAT, MsgType.SNAPSHOT) \
                    else 0
                self._become_follower(m.term, lead)
        elif m.term < self.term:
            if m.msg_type in (MsgType.APPEND, MsgType.HEARTBEAT,
                              MsgType.SNAPSHOT):
                # stale leader: tell it the new term
                self._send(Message(MsgType.APPEND_RESPONSE, to=m.frm,
                                   term=self.term, reject=True,
                                   reject_hint=self.last_index()))
            elif m.msg_type is MsgType.PRE_VOTE:
                self._send(Message(MsgType.PRE_VOTE_RESPONSE, to=m.frm,
                                   term=self.term, reject=True))
            return

        handler = {
            MsgType.PRE_VOTE: self._handle_pre_vote,
            MsgType.PRE_VOTE_RESPONSE: self._handle_pre_vote_response,
            MsgType.REQUEST_VOTE: self._handle_vote,
            MsgType.REQUEST_VOTE_RESPONSE: self._handle_vote_response,
            MsgType.APPEND: self._handle_append,
            MsgType.APPEND_RESPONSE: self._handle_append_response,
            MsgType.HEARTBEAT: self._handle_heartbeat,
            MsgType.HEARTBEAT_RESPONSE: self._handle_heartbeat_response,
            MsgType.SNAPSHOT: self._handle_snapshot,
            MsgType.TIMEOUT_NOW: self._handle_timeout_now,
            MsgType.READ_INDEX: self._handle_read_index,
            MsgType.READ_INDEX_RESP: self._handle_read_index_resp,
        }.get(m.msg_type)
        if handler is not None:
            handler(m)

    # -- follower reads (raft §6.4 ReadIndex) --

    def request_read_index(self, ctx: int, read_ts: int = 0) -> bool:
        """Follower/replica read: ask the leader for its commit index;
        the answer lands in ``read_states``.  ``read_ts`` piggybacks so
        the leader can bump its concurrency manager's max_ts and veto
        reads that would race an async-commit prewrite.  Returns False
        when no leader is known (the peer's tick retries)."""
        if self.state == LEADER:
            self._handle_read_index(Message(MsgType.READ_INDEX,
                                            to=self.id, frm=self.id,
                                            term=self.term, ctx=ctx,
                                            index=read_ts))
            return True
        if not self.leader_id:
            return False
        self._send(Message(MsgType.READ_INDEX, to=self.leader_id,
                           term=self.term, ctx=ctx, index=read_ts))
        return True

    def _handle_read_index(self, m: Message) -> None:
        if self.state != LEADER:
            return      # stale routing; requester retries
        # the leader may only answer once it has committed in ITS term
        # (an old-term commit index could run behind a newer leader)
        if self.storage.term(self.commit) != self.term:
            return      # pending noop: requester retries
        # leadership confirmation (raft §6.4): a deposed leader behind a
        # partition must NOT answer with its stale commit index — the
        # quorum-acked lease is the evidence a heartbeat round would
        # give (the same basis LocalReader uses)
        if not self.in_lease():
            return      # requester retries; a live leader re-earns it
        # async-commit integration hook: the storage layer bumps max_ts
        # for the piggybacked read_ts and vetoes when an in-flight
        # prewrite's memory lock covers it (concurrency_manager)
        hook = getattr(self, "read_index_hook", None)
        if hook is not None and m.index and not hook(m.index):
            return      # blocked by a memory lock: requester retries
        if m.frm == self.id:
            self.read_states.append((self.commit, m.ctx))
        else:
            self._send(Message(MsgType.READ_INDEX_RESP, to=m.frm,
                               term=self.term, index=self.commit,
                               ctx=m.ctx))

    def _handle_read_index_resp(self, m: Message) -> None:
        self.read_states.append((m.index, m.ctx))

    # -- elections --

    def _log_up_to_date(self, m: Message) -> bool:
        lt, li = self.last_term(), self.last_index()
        return m.log_term > lt or (m.log_term == lt and m.index >= li)

    def _handle_pre_vote(self, m: Message) -> None:
        # grant if we'd grant a real vote at that term and have no live
        # leader contact (approximated by elapsed timeout share)
        grant = m.term > self.term and self._log_up_to_date(m) and \
            (self.leader_id == 0 or self._elapsed >= self._timeout)
        self._send(Message(MsgType.PRE_VOTE_RESPONSE, to=m.frm,
                           term=m.term, reject=not grant))

    def _handle_pre_vote_response(self, m: Message) -> None:
        if self.state != PRE_CANDIDATE:
            return
        self._votes[m.frm] = not m.reject
        if self._tally_won():
            self._campaign_real()
        elif self._tally_lost():
            self._become_follower(self.term, 0)

    def _handle_vote(self, m: Message) -> None:
        can_vote = (self.vote == 0 and self.leader_id == 0) or \
            self.vote == m.frm
        grant = can_vote and self._log_up_to_date(m)
        if grant:
            self.vote = m.frm
            self._reset_timeout()
        self._send(Message(MsgType.REQUEST_VOTE_RESPONSE, to=m.frm,
                           term=self.term, reject=not grant))

    def _handle_vote_response(self, m: Message) -> None:
        if self.state != CANDIDATE:
            return
        self._votes[m.frm] = not m.reject
        if self._tally_won():
            self._become_leader()
        elif self._tally_lost():
            self._become_follower(self.term, 0)

    # -- replication (follower side) --

    def _handle_append(self, m: Message) -> None:
        self.leader_id = m.frm
        self._reset_timeout()
        if m.index < self.commit:
            # stale prefix; never truncate below commit
            self._send(Message(MsgType.APPEND_RESPONSE, to=m.frm,
                               term=self.term, index=self.commit))
            return
        local_term = self.storage.term(m.index)
        if local_term is None or local_term != m.log_term:
            self._send(Message(
                MsgType.APPEND_RESPONSE, to=m.frm, term=self.term,
                reject=True, index=m.index,
                reject_hint=min(self.last_index(), m.index)))
            return
        # find first conflicting entry; truncate from there
        to_append: list[Entry] = []
        for e in m.entries:
            t = self.storage.term(e.index)
            if t is None or t != e.term:
                to_append = [x for x in m.entries if x.index >= e.index]
                break
        if to_append:
            self.storage.append(to_append)
            if to_append[0].index <= self._stable_index:
                self._stable_index = to_append[0].index - 1
        last_new = m.index + len(m.entries)
        if m.commit > self.commit:
            self.commit = min(m.commit, last_new)
        self._send(Message(MsgType.APPEND_RESPONSE, to=m.frm,
                           term=self.term, index=last_new))

    def _handle_heartbeat(self, m: Message) -> None:
        self.leader_id = m.frm
        self._reset_timeout()
        if m.commit > self.commit:
            self.commit = min(m.commit, self.last_index())
        self._send(Message(MsgType.HEARTBEAT_RESPONSE, to=m.frm,
                           term=self.term, index=self.last_index(),
                           ctx=m.ctx))

    def _handle_snapshot(self, m: Message) -> None:
        self.leader_id = m.frm
        self._reset_timeout()
        meta = m.snapshot.metadata
        if meta.index <= self.commit:
            self._send(Message(MsgType.APPEND_RESPONSE, to=m.frm,
                               term=self.term, index=self.commit))
            return
        # fast-forward: restore config + log position from the snapshot
        self._pending_snapshot = m.snapshot
        self.storage.apply_snapshot(m.snapshot)
        self.voters = set(meta.voters)
        self.learners = set(meta.learners)
        # a snapshot generated mid-joint carries C_old: the receiver
        # must enforce both majorities too, or it could elect itself on
        # an incoming-only majority (split brain in the joint window)
        self.voters_outgoing = set(
            getattr(meta, "voters_outgoing", ()))
        self.commit = meta.index
        self.applied = meta.index
        self._stable_index = meta.index
        self._send(Message(MsgType.APPEND_RESPONSE, to=m.frm,
                           term=self.term, index=meta.index))

    def _handle_timeout_now(self, m: Message) -> None:
        if self.id in self.voters:
            self.campaign(force=True)

    # -- replication (leader side) --

    def _handle_append_response(self, m: Message) -> None:
        if self.state != LEADER:
            return
        pr = self.progress.get(m.frm)
        if pr is None:
            return
        pr.paused = False
        if m.reject:
            if m.term > self.term:
                return      # already stepped down in step()
            pr.next = max(min(m.reject_hint, pr.next - 1), pr.match + 1)
            pr.state = PROBE
            self._send_append(m.frm)
            return
        if pr.state == SNAPSHOT and m.index >= pr.pending_snapshot:
            pr.state = PROBE
            pr.pending_snapshot = 0
        if m.index > pr.match:
            pr.match = m.index
            pr.next = max(pr.next, m.index + 1)
            pr.state = REPLICATE
            if self._maybe_commit():
                self._broadcast_append()
            elif pr.next <= self.last_index():
                self._send_append(m.frm)
            if m.frm == self._lead_transferee and \
                    pr.match == self.last_index():
                self._send(Message(MsgType.TIMEOUT_NOW, to=m.frm,
                                   term=self.term))

    def _handle_heartbeat_response(self, m: Message) -> None:
        if self.state != LEADER:
            return
        pr = self.progress.get(m.frm)
        if pr is None:
            return
        # explicit None check: a heartbeat broadcast at tick 0 carries
        # ctx == 0, which must still count as a lease ack
        if m.ctx is not None:
            prev = self._lease_ack.get(m.frm)
            if prev is None or m.ctx > prev:
                self._lease_ack[m.frm] = m.ctx
                mono = self._hb_send_mono.get(m.ctx)
                if mono is not None:
                    self._lease_ack_mono[m.frm] = mono
        pr.paused = False
        if pr.match < self.last_index():
            self._send_append(m.frm)

    def _handle_transfer(self, m: Message) -> None:
        target = m.frm
        if self.state != LEADER or target == self.id or \
                target not in self.voters:
            return
        self._lead_transferee = target
        pr = self.progress[target]
        if pr.match == self.last_index():
            self._send(Message(MsgType.TIMEOUT_NOW, to=target,
                               term=self.term))
        else:
            self._send_append(target)

    # ------------------------------------------------------------- ready

    def has_ready(self) -> bool:
        hs = HardState(self.term, self.vote, self.commit)
        return bool(self._msgs) or \
            self.last_index() > self._stable_index or \
            self.commit > self.applied or \
            self._pending_snapshot is not None or \
            (hs.term, hs.vote, hs.commit) != \
            (self._prev_hs.term, self._prev_hs.vote, self._prev_hs.commit) \
            or (self.leader_id, self.state) != self._prev_soft

    def ready(self) -> Ready:
        rd = Ready()
        rd.messages, self._msgs = self._msgs, []
        if self.last_index() > self._stable_index:
            lo = max(self._stable_index + 1, self.storage.first_index())
            rd.entries = self.storage.slice(lo, self.last_index() + 1)
        if self.commit > self.applied:
            lo = max(self.applied + 1, self.storage.first_index())
            rd.committed_entries = self.storage.slice(lo, self.commit + 1)
        hs = HardState(self.term, self.vote, self.commit)
        if (hs.term, hs.vote, hs.commit) != \
                (self._prev_hs.term, self._prev_hs.vote, self._prev_hs.commit):
            rd.hard_state = hs
        soft = (self.leader_id, self.state)
        if soft != self._prev_soft:
            rd.soft_state = soft
        rd.snapshot = self._pending_snapshot
        return rd

    def advance(self, rd: Ready) -> None:
        if rd.entries:
            # raft-rs stable_to: only raise the stable mark if the log
            # still holds the SAME entry — a truncation during an
            # async-IO persist window invalidated this batch, and
            # blindly advancing would let unpersisted replacement
            # entries skip their WAL write
            last = rd.entries[-1]
            if self.storage.term(last.index) == last.term:
                self._stable_index = max(self._stable_index, last.index)
        if rd.committed_entries:
            self.applied = rd.committed_entries[-1].index
        if rd.hard_state is not None:
            self.storage.set_hard_state(rd.hard_state)
            self._prev_hs = rd.hard_state
        if rd.soft_state is not None:
            self._prev_soft = rd.soft_state
        self._pending_snapshot = None


class NotLeader(Exception):
    def __init__(self, leader_id: int):
        super().__init__(f"not leader (hint: {leader_id})")
        self.leader_id = leader_id


class ProposalDropped(Exception):
    pass
