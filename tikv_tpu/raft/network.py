"""In-process raft network harness — deterministic message routing with
fault injection.

Reference: raft-rs's test Network + the message-level fault injection of
components/test_raftstore/src/transport_simulate.rs (drop/delay/partition
filters) — the fixture style SURVEY.md §4 calls out as load-bearing.
"""

from __future__ import annotations

import random

from typing import Callable, Optional, Sequence

from ..utils.failpoint import fail_point
from .messages import Message
from .raw_node import RawNode, Ready
from .storage import MemoryRaftStorage


class RaftNetwork:
    def __init__(self, ids: Sequence[int], election_tick: int = 10,
                 heartbeat_tick: int = 2, pre_vote: bool = True,
                 seed: int = 0):
        self.nodes: dict[int, RawNode] = {}
        self.applied: dict[int, list] = {}
        self.installed_snapshots: dict[int, int] = {}
        # filters: fn(msg) -> bool (True = deliver); reference:
        # transport_simulate.rs Filter trait
        self.filters: list[Callable[[Message], bool]] = []
        self._inbox: list[Message] = []
        # deterministic source for failpoint-driven reorder/duplicate
        self._chaos_rng = random.Random(seed)
        for nid in ids:
            storage = MemoryRaftStorage(voters=tuple(ids))
            self.nodes[nid] = RawNode(nid, storage, election_tick,
                                      heartbeat_tick, pre_vote, seed)
            self.applied[nid] = []

    # -- fault injection --

    def partition(self, group_a: Sequence[int], group_b: Sequence[int]):
        a, b = set(group_a), set(group_b)

        def filt(m: Message) -> bool:
            return not ((m.frm in a and m.to in b) or
                        (m.frm in b and m.to in a))
        self.filters.append(filt)
        return filt

    def isolate(self, nid: int):
        def filt(m: Message) -> bool:
            return m.frm != nid and m.to != nid
        self.filters.append(filt)
        return filt

    def heal(self, filt=None) -> None:
        if filt is None:
            self.filters.clear()
        else:
            self.filters.remove(filt)

    # -- pump --

    def _drain_node(self, nid: int) -> None:
        node = self.nodes[nid]
        while node.has_ready():
            rd = node.ready()
            for e in rd.committed_entries:
                self._apply(nid, e)
            for m in rd.messages:
                if not all(f(m) for f in self.filters):
                    continue
                # message-level fault sites (transport_simulate.rs
                # DropPacket/Delay/OutOfOrder filters as failpoints):
                # a fired "return" action drops / duplicates; "sleep"
                # on send_delay stalls the sender inline
                if fail_point("transport::drop_send") is not None:
                    continue
                fail_point("transport::send_delay")
                self._inbox.append(m)
                if fail_point("transport::dup_send") is not None:
                    self._inbox.append(m)
            node.advance(rd)

    def _apply(self, nid: int, entry) -> None:
        from .messages import ConfChange, EntryType
        if entry.entry_type is EntryType.CONF_CHANGE:
            if entry.data:
                self.nodes[nid].apply_conf_change(
                    ConfChange.from_bytes(entry.data))
        elif entry.data:
            self.applied[nid].append((entry.index, entry.data))

    def deliver_all(self) -> int:
        """Route queued messages until quiescent; returns deliveries."""
        n = 0
        for nid in self.nodes:
            self._drain_node(nid)
        while self._inbox:
            if len(self._inbox) > 1 and \
                    fail_point("transport::reorder") is not None:
                self._chaos_rng.shuffle(self._inbox)
            m = self._inbox.pop(0)
            if fail_point("transport::drop_recv") is not None:
                continue
            if m.to in self.nodes:
                self.nodes[m.to].step(m)
                self._drain_node(m.to)
                n += 1
        return n

    def tick_all(self, times: int = 1) -> None:
        for _ in range(times):
            for node in self.nodes.values():
                node.tick()
            self.deliver_all()

    # -- conveniences --

    def elect(self, nid: int) -> None:
        """Force ``nid`` to campaign and win (assuming connectivity)."""
        from .messages import MsgType
        self.nodes[nid].step(Message(MsgType.HUP))
        self.deliver_all()
        assert self.leader() == nid, \
            f"expected {nid} to win, leader={self.leader()}"

    def leader(self) -> Optional[int]:
        leaders = [nid for nid, n in self.nodes.items()
                   if n.state == "leader"]
        if not leaders:
            return None
        # the one with the highest term wins (stale leaders may linger
        # until they hear the new term)
        return max(leaders, key=lambda nid: self.nodes[nid].term)

    def propose(self, data: bytes) -> int:
        lead = self.leader()
        assert lead is not None, "no leader"
        idx = self.nodes[lead].propose(data)
        self.deliver_all()
        return idx

    def committed_data(self, nid: int) -> list:
        return [d for _, d in self.applied[nid]]
