"""Raft log storage.

Reference: raft-rs's ``Storage`` trait + MemoryStorage; the raftstore
layer implements it over the engine (PeerStorage,
components/raftstore/src/store/peer_storage.rs) — same split here.

Index convention (raft-rs): the log logically starts after a snapshot;
``first_index`` is snapshot_index + 1; entry 0/term 0 is the origin.
"""

from __future__ import annotations

from typing import Optional, Sequence

from .messages import Entry, HardState, Snapshot, SnapshotMetadata


class MemoryRaftStorage:
    def __init__(self, voters: Sequence[int] = ()):
        self.hard_state = HardState()
        self.snapshot = Snapshot(SnapshotMetadata(0, 0, tuple(voters)))
        self.entries: list[Entry] = []      # contiguous after snapshot

    # -- raft-rs Storage trait --

    def initial_state(self) -> tuple[HardState, tuple, tuple]:
        meta = self.snapshot.metadata
        return self.hard_state, meta.voters, meta.learners

    def initial_outgoing(self) -> tuple:
        return getattr(self.snapshot.metadata, "voters_outgoing", ())

    def first_index(self) -> int:
        return self.snapshot.metadata.index + 1

    def last_index(self) -> int:
        if self.entries:
            return self.entries[-1].index
        return self.snapshot.metadata.index

    def term(self, index: int) -> Optional[int]:
        meta = self.snapshot.metadata
        if index == meta.index:
            return meta.term
        if index < meta.index:
            return None     # compacted
        i = index - meta.index - 1
        if i >= len(self.entries):
            return None
        return self.entries[i].term

    def slice(self, lo: int, hi: int) -> list[Entry]:
        """Entries [lo, hi); lo must be >= first_index."""
        base = self.snapshot.metadata.index + 1
        assert lo >= base, (lo, base)
        return self.entries[lo - base:hi - base]

    # -- mutation (called when persisting a Ready) --

    def append(self, entries: Sequence[Entry]) -> None:
        if not entries:
            return
        base = self.snapshot.metadata.index + 1
        first_new = entries[0].index
        assert first_new >= base, "appending compacted entries"
        # truncate conflicting suffix, then extend
        self.entries = self.entries[:first_new - base] + list(entries)

    def set_hard_state(self, hs: HardState) -> None:
        self.hard_state = HardState(hs.term, hs.vote, hs.commit)

    def apply_snapshot(self, snap: Snapshot) -> None:
        assert snap.metadata.index >= self.snapshot.metadata.index
        self.snapshot = snap
        self.entries = []
        self.hard_state.commit = max(self.hard_state.commit,
                                     snap.metadata.index)

    def compact(self, index: int) -> None:
        """Drop entries up to ``index`` (inclusive), folding them into the
        snapshot marker (log GC; raftstore's raftlog_gc worker)."""
        meta = self.snapshot.metadata
        if index <= meta.index:
            return
        term = self.term(index)
        assert term is not None, "compacting beyond last index"
        base = meta.index + 1
        self.entries = self.entries[index - base + 1:]
        self.snapshot = Snapshot(
            SnapshotMetadata(index, term, meta.voters, meta.learners),
            self.snapshot.data)

    def snapshot_for_send(self) -> Snapshot:
        """Snapshot to ship to a lagging follower.  Subclasses may
        generate region data on demand (raftstore PeerStorage); metadata
        must match ``self.snapshot.metadata`` (the log arithmetic anchor).
        """
        return self.snapshot

    def set_conf(self, voters: Sequence[int],
                 learners: Sequence[int] = (),
                 voters_outgoing: Sequence[int] = ()) -> None:
        meta = self.snapshot.metadata
        self.snapshot = Snapshot(
            SnapshotMetadata(meta.index, meta.term, tuple(voters),
                             tuple(learners), tuple(voters_outgoing)),
            self.snapshot.data)
