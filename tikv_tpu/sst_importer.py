"""Bulk load: SST build / upload / atomic ingest.

Reference: components/sst_importer/ + src/import/ — a client (TiDB
Lightning / BR restore) BUILDS sorted files locally, uploads them in
chunks to every replica's store, then issues an ingest that lands the
file atomically; import mode relaxes background housekeeping while the
bulk load runs (import_mode.rs).

The TPU-native engine has no RocksDB SST to hard-link, so "ingest"
proposes the file's ops as ONE raft command on the target region —
atomic, replicated, and epoch-checked exactly like any admin write —
while this module keeps the reference's file format seam: a
self-contained sorted, checksummed artifact the client can build
offline (incl. pre-timestamped MVCC records, the Lightning trick of
writing Percolator state directly).
"""

from __future__ import annotations

import struct
import zlib

import msgpack

_SST_MAGIC = b"TKVSST1\n"


class SstWriter:
    """Client-side builder: collect (cf, key, value), emit one sorted,
    crc-sealed artifact (sst_importer writer.rs analog)."""

    def __init__(self):
        self._pairs: list[tuple] = []

    def put(self, cf: str, key: bytes, value: bytes) -> None:
        self._pairs.append((cf, key, value))

    def __len__(self) -> int:
        return len(self._pairs)

    def finish(self) -> bytes:
        self._pairs.sort(key=lambda p: (p[0], p[1]))
        payload = msgpack.packb(
            [[cf, bytes(k), bytes(v)] for cf, k, v in self._pairs],
            use_bin_type=True)
        return (_SST_MAGIC + payload +
                struct.pack(">I", zlib.crc32(payload) & 0xFFFFFFFF))


_SST2_MAGIC = b"TKVSST2\n"

# Ingest-parse memo: the apply thread unpacks every ingested v2 blob
# (read_sst_cf below); moments later the streaming cold pipeline's
# worker (copr/stream_build.py) re-reads the SAME decoded blob object
# off the observer event.  When a consumer opts in, the apply-side
# parse is kept (keyed by blob object identity, the blob itself pinned
# so the id cannot be recycled) and the worker's read consumes it —
# the msgpack unpack is the worker's dominant GIL hold, and paying it
# twice starved the worker behind the very apply loop that feeds it.
# Bounded: a lagging consumer evicts oldest-first and re-parses.
_INGEST_MEMO: dict = {}         # id(blob) -> (blob, groups)
_INGEST_MEMO_CAP = 2
_INGEST_MEMO_MU = __import__("threading").Lock()
_memo_consumers = 0


def enable_ingest_parse_memo(on: bool) -> None:
    """Consumer registration (refcounted): only memoize while someone
    (a ColdStreamBuilder) will actually consume the entries."""
    global _memo_consumers
    with _INGEST_MEMO_MU:
        _memo_consumers = max(0, _memo_consumers + (1 if on else -1))
        if not _memo_consumers:
            _INGEST_MEMO.clear()


def pop_ingest_parse(blob):
    """Pop the memoized decode of ``blob`` (→ {cf: (keys, vals)} or
    None).  The streaming cold pipeline calls this ON the observer
    event — the apply thread parsed this exact blob moments ago, so the
    hit rate at event time is ~100%, and the decoded groups travel with
    the queue entry instead of being re-unpacked by the worker (a
    multi-second GIL hold per 1M-row chunk that starved both the loader
    and the cold query's bounded take-wait)."""
    with _INGEST_MEMO_MU:
        hit = _INGEST_MEMO.pop(id(blob), None)
    if hit is not None and hit[0] is blob:
        return hit[1]
    return None


def read_sst(blob: bytes) -> list:
    """→ [(cf, key, value)]; raises ValueError on a corrupt artifact."""
    if blob.startswith(_SST2_MAGIC):
        return [(cf, k, v)
                for cf, (keys, vals) in read_sst_cf(blob).items()
                for k, v in zip(keys, vals)]
    if not blob.startswith(_SST_MAGIC) or len(blob) < len(_SST_MAGIC) + 4:
        raise ValueError("bad sst magic")
    payload = blob[len(_SST_MAGIC):-4]
    (crc,) = struct.unpack(">I", blob[-4:])
    if (zlib.crc32(payload) & 0xFFFFFFFF) != crc:
        raise ValueError("sst checksum mismatch")
    return [(cf, k, v) for cf, k, v in
            msgpack.unpackb(payload, raw=False)]


def is_sst_v2(blob: bytes) -> bool:
    return blob.startswith(_SST2_MAGIC)


def read_sst_cf(blob: bytes, validate: bool = True,
                memo: bool = False) -> dict:
    """v2 container → {cf: (keys list, values list)} with keys sorted.

    The column-group layout keeps the ingest path free of per-row
    Python: msgpack unpacks straight to lists of bytes, and the engine
    bulk-merges whole sorted runs (the analog of the reference's
    RocksDB file ingest, which links an SST without replaying ops).

    ``validate=False`` skips the sorted/duplicate re-check (a full
    sorted copy + set per group): sound ONLY for consumers re-reading a
    blob that apply already admitted — the streaming cold pipeline's
    parse worker observes entries post-engine-write, after this exact
    blob passed the checked path on the apply thread.

    ``memo=True`` (the APPLY path only — peer.py IngestSst) seeds the
    ingest-parse memo with this decode for the observer's follow-up
    read.  Seeding must stay off everywhere else: the RPC-side
    validation call's blob round-trips through the raft log as a fresh
    bytes object, so its entry could never be popped — it would pin a
    decoded chunk for the process lifetime and evict the useful
    apply-seeded entries from the small memo."""
    with _INGEST_MEMO_MU:
        hit = _INGEST_MEMO.pop(id(blob), None)
    if hit is not None and hit[0] is blob:
        return hit[1]
    if not blob.startswith(_SST2_MAGIC) or len(blob) < len(_SST2_MAGIC) + 4:
        raise ValueError("bad sst v2 magic")
    payload = blob[len(_SST2_MAGIC):-4]
    (crc,) = struct.unpack(">I", blob[-4:])
    if (zlib.crc32(payload) & 0xFFFFFFFF) != crc:
        raise ValueError("sst v2 checksum mismatch")
    out = {}
    for cf, keys, vals in msgpack.unpackb(payload, raw=False):
        if len(keys) != len(vals):
            raise ValueError("sst v2 cf group length mismatch")
        # the engine bulk-merges each group as a SORTED run, and apply
        # trusts that order on every replica — a client-built blob with
        # out-of-order or duplicate keys would silently corrupt the
        # merged keyspace, so reject it before it reaches the raft log.
        # C-speed checks: this runs on the apply path of every replica,
        # and an interpreted per-key loop would stall the apply loop on
        # multi-million-row ingests.
        if validate and len(keys) > 1 and (keys != sorted(keys) or
                                           len(set(keys)) != len(keys)):
            raise ValueError(
                f"sst v2 cf {cf!r}: keys not strictly ascending")
        out[cf] = (keys, vals)
    if _memo_consumers and memo:
        # the checked apply-side parse seeds the memo for the
        # streaming consumer's follow-up read of the same blob object
        with _INGEST_MEMO_MU:
            while len(_INGEST_MEMO) >= _INGEST_MEMO_CAP:
                _INGEST_MEMO.pop(next(iter(_INGEST_MEMO)))
            _INGEST_MEMO[id(blob)] = (blob, out)
    return out


def build_sst_v2(cf_map: dict) -> bytes:
    """{cf: (sorted keys, values)} → v2 blob (pure-python fallback for
    the native builder; same container)."""
    body = msgpack.packb(
        [[cf, list(keys), list(vals)]
         for cf, (keys, vals) in sorted(cf_map.items())],
        use_bin_type=True)
    return _SST2_MAGIC + body + \
        struct.pack(">I", zlib.crc32(body) & 0xFFFFFFFF)


def fast_mvcc_table_sst(table_id: int, handles, columns,
                        commit_ts: int, start_ts: int = 0) -> bytes:
    """Bulk pre-timestamped MVCC SST for one int/float table chunk.

    ``handles``: ascending int64 numpy array; ``columns``: list of
    (col_id, int64-or-float64 numpy array, validity-or-None).  Uses the
    native C++ builder when compiled (~10-20M rows/s vs ~80k rows/s for
    the per-row Python path); falls back to mvcc_sst row encoding.

    Reference: sst_importer sst_writer.rs + Lightning's native kv
    encoder — the client builds sorted files at native speed, the
    server ingests them without touching row codecs.
    """
    import numpy as np

    from .native import build_mvcc_sst
    start_ts = start_ts or commit_ts - 1
    h = np.ascontiguousarray(np.asarray(handles, dtype=np.int64))
    if build_mvcc_sst is not None:
        ids, kinds, bufs, valids = [], [], [], []
        for col_id, vals, valid in columns:
            a = np.asarray(vals)
            if a.dtype.kind == "f":
                kinds.append(1)
                a = np.ascontiguousarray(a, dtype=np.float64)
            else:
                kinds.append(0)
                a = np.ascontiguousarray(a, dtype=np.int64)
            ids.append(int(col_id))
            bufs.append(a.tobytes())
            valids.append(None if valid is None else
                          np.ascontiguousarray(
                              valid, dtype=np.uint8).tobytes())
        try:
            return build_mvcc_sst(table_id, h.tobytes(), tuple(ids),
                                  tuple(kinds), tuple(bufs), tuple(valids),
                                  commit_ts, start_ts)
        except ValueError as e:
            if "too many columns" not in str(e):
                raise       # real malformed input — don't mask it
            # >map16 columns: fall back to the interpreted encoder
            # (msgpack emits map32 headers natively)
    # interpreted fallback: per-row encode through the shared codecs
    from .codec.keys import table_record_key
    from .codec.row import encode_row
    rows = []
    col_arrs = [(int(cid), np.asarray(vals), valid)
                for cid, vals, valid in columns]
    for i, handle in enumerate(h.tolist()):
        payload = {}
        for cid, vals, valid in col_arrs:
            if valid is not None and not valid[i]:
                payload[cid] = None
            elif vals.dtype.kind == "f":
                payload[cid] = float(vals[i])
            else:
                payload[cid] = int(vals[i])
        rows.append((table_record_key(table_id, handle),
                     encode_row(payload)))
    w = mvcc_sst(rows, commit_ts, start_ts)
    by_cf: dict = {}
    w._pairs.sort(key=lambda p: (p[0], p[1]))
    for cf, k, v in w._pairs:
        by_cf.setdefault(cf, ([], []))
        by_cf[cf][0].append(k)
        by_cf[cf][1].append(v)
    return build_sst_v2(by_cf)


def mvcc_sst(rows, commit_ts: int, start_ts: int = 0) -> SstWriter:
    """Pre-timestamped Percolator records for ``rows`` = [(user_key,
    value)] — committed state written directly (write CF + default CF
    for long values), the Lightning/BR-restore ingestion shape.
    """
    from .engine.traits import CF_DEFAULT, CF_WRITE
    from .storage.txn_types import (
        SHORT_VALUE_MAX_LEN,
        Write,
        WriteType,
        append_ts,
        encode_key,
    )
    start_ts = start_ts or commit_ts - 1
    w = SstWriter()
    for key, value in rows:
        enc = encode_key(key)
        if len(value) <= SHORT_VALUE_MAX_LEN:
            rec = Write(WriteType.PUT, start_ts, short_value=value)
        else:
            rec = Write(WriteType.PUT, start_ts)
            w.put(CF_DEFAULT, append_ts(enc, start_ts), value)
        w.put(CF_WRITE, append_ts(enc, commit_ts), rec.to_bytes())
    return w
