"""Bulk load: SST build / upload / atomic ingest.

Reference: components/sst_importer/ + src/import/ — a client (TiDB
Lightning / BR restore) BUILDS sorted files locally, uploads them in
chunks to every replica's store, then issues an ingest that lands the
file atomically; import mode relaxes background housekeeping while the
bulk load runs (import_mode.rs).

The TPU-native engine has no RocksDB SST to hard-link, so "ingest"
proposes the file's ops as ONE raft command on the target region —
atomic, replicated, and epoch-checked exactly like any admin write —
while this module keeps the reference's file format seam: a
self-contained sorted, checksummed artifact the client can build
offline (incl. pre-timestamped MVCC records, the Lightning trick of
writing Percolator state directly).
"""

from __future__ import annotations

import struct
import zlib

import msgpack

_SST_MAGIC = b"TKVSST1\n"


class SstWriter:
    """Client-side builder: collect (cf, key, value), emit one sorted,
    crc-sealed artifact (sst_importer writer.rs analog)."""

    def __init__(self):
        self._pairs: list[tuple] = []

    def put(self, cf: str, key: bytes, value: bytes) -> None:
        self._pairs.append((cf, key, value))

    def __len__(self) -> int:
        return len(self._pairs)

    def finish(self) -> bytes:
        self._pairs.sort(key=lambda p: (p[0], p[1]))
        payload = msgpack.packb(
            [[cf, bytes(k), bytes(v)] for cf, k, v in self._pairs],
            use_bin_type=True)
        return (_SST_MAGIC + payload +
                struct.pack(">I", zlib.crc32(payload) & 0xFFFFFFFF))


def read_sst(blob: bytes) -> list:
    """→ [(cf, key, value)]; raises ValueError on a corrupt artifact."""
    if not blob.startswith(_SST_MAGIC) or len(blob) < len(_SST_MAGIC) + 4:
        raise ValueError("bad sst magic")
    payload = blob[len(_SST_MAGIC):-4]
    (crc,) = struct.unpack(">I", blob[-4:])
    if (zlib.crc32(payload) & 0xFFFFFFFF) != crc:
        raise ValueError("sst checksum mismatch")
    return [(cf, k, v) for cf, k, v in
            msgpack.unpackb(payload, raw=False)]


def mvcc_sst(rows, commit_ts: int, start_ts: int = 0) -> SstWriter:
    """Pre-timestamped Percolator records for ``rows`` = [(user_key,
    value)] — committed state written directly (write CF + default CF
    for long values), the Lightning/BR-restore ingestion shape.
    """
    from .engine.traits import CF_DEFAULT, CF_WRITE
    from .storage.txn_types import (
        SHORT_VALUE_MAX_LEN,
        Write,
        WriteType,
        append_ts,
        encode_key,
    )
    start_ts = start_ts or commit_ts - 1
    w = SstWriter()
    for key, value in rows:
        enc = encode_key(key)
        if len(value) <= SHORT_VALUE_MAX_LEN:
            rec = Write(WriteType.PUT, start_ts, short_value=value)
        else:
            rec = Write(WriteType.PUT, start_ts)
            w.put(CF_DEFAULT, append_ts(enc, start_ts), value)
        w.put(CF_WRITE, append_ts(enc, commit_ts), rec.to_bytes())
    return w
