"""Packed core-time representation + vectorized civil-calendar math.

Reference: tidb_query_datatype/src/codec/mysql/time/mod.rs — TiDB packs a
datetime into one u64 (``CoreTime``) so the columnar engine moves fixed
width values; this rebuild keeps that idea with an explicit bit layout
(not the reference's) chosen so every field unpacks with one shift+mask:

    bits  0..23   microsecond   (24 bits)
    bits 24..29   second        ( 6 bits)
    bits 30..35   minute        ( 6 bits)
    bits 36..40   hour          ( 5 bits)
    bits 41..45   day           ( 5 bits)
    bits 46..49   month         ( 4 bits)
    bits 50..63   year          (14 bits)

All functions are vectorized over numpy uint64 arrays (and trace under
jax.numpy for the device-safe extraction subset).  Calendar conversions
use the days-from-civil algorithm (Howard Hinnant's public-domain
``civil_from_days``/``days_from_civil``), which is branch-free and exact
over MySQL's DATETIME range (year 0..9999).
"""

from __future__ import annotations

import numpy as np

MICRO_BITS = 24
SECOND_SHIFT = 24
MINUTE_SHIFT = 30
HOUR_SHIFT = 36
DAY_SHIFT = 41
MONTH_SHIFT = 46
YEAR_SHIFT = 50

# MySQL TO_DAYS('1970-01-01') == 719528; days_from_civil(1970,1,1) == 0
_TO_DAYS_EPOCH = 719528


def pack_datetime(year, month, day, hour=0, minute=0, second=0, micro=0):
    """Pack component arrays/scalars into the u64 core."""
    y = np.asarray(year, np.uint64)
    return ((y << YEAR_SHIFT)
            | (np.asarray(month, np.uint64) << MONTH_SHIFT)
            | (np.asarray(day, np.uint64) << DAY_SHIFT)
            | (np.asarray(hour, np.uint64) << HOUR_SHIFT)
            | (np.asarray(minute, np.uint64) << MINUTE_SHIFT)
            | (np.asarray(second, np.uint64) << SECOND_SHIFT)
            | np.asarray(micro, np.uint64))


def dt_year(t, xp=np):
    return (t >> YEAR_SHIFT).astype(xp.int64 if xp is np else xp.int32)


def dt_month(t, xp=np):
    return ((t >> MONTH_SHIFT) & 0xF).astype(
        xp.int64 if xp is np else xp.int32)


def dt_day(t, xp=np):
    return ((t >> DAY_SHIFT) & 0x1F).astype(
        xp.int64 if xp is np else xp.int32)


def dt_hour(t, xp=np):
    return ((t >> HOUR_SHIFT) & 0x1F).astype(
        xp.int64 if xp is np else xp.int32)


def dt_minute(t, xp=np):
    return ((t >> MINUTE_SHIFT) & 0x3F).astype(
        xp.int64 if xp is np else xp.int32)


def dt_second(t, xp=np):
    return ((t >> SECOND_SHIFT) & 0x3F).astype(
        xp.int64 if xp is np else xp.int32)


def dt_micro(t, xp=np):
    return (t & np.uint64((1 << MICRO_BITS) - 1)).astype(
        xp.int64 if xp is np else xp.int32)


def days_from_civil(y, m, d):
    """Days since 1970-01-01 for proleptic-Gregorian (y, m, d) arrays."""
    y = np.asarray(y, np.int64)
    m = np.asarray(m, np.int64)
    d = np.asarray(d, np.int64)
    y = y - (m <= 2)
    era = np.where(y >= 0, y, y - 399) // 400
    yoe = y - era * 400                              # [0, 399]
    doy = (153 * (m + np.where(m > 2, -3, 9)) + 2) // 5 + d - 1
    doe = yoe * 365 + yoe // 4 - yoe // 100 + doy    # [0, 146096]
    return era * 146097 + doe - 719468


def civil_from_days(z):
    """Inverse of days_from_civil: → (y, m, d) arrays."""
    z = np.asarray(z, np.int64) + 719468
    era = np.where(z >= 0, z, z - 146096) // 146097
    doe = z - era * 146097                            # [0, 146096]
    yoe = (doe - doe // 1460 + doe // 36524 - doe // 146096) // 365
    y = yoe + era * 400
    doy = doe - (365 * yoe + yoe // 4 - yoe // 100)   # [0, 365]
    mp = (5 * doy + 2) // 153                         # [0, 11]
    d = doy - (153 * mp + 2) // 5 + 1                 # [1, 31]
    m = mp + np.where(mp < 10, 3, -9)                 # [1, 12]
    return y + (m <= 2), m, d


def to_days(t):
    """MySQL TO_DAYS over packed cores (numpy)."""
    return days_from_civil(dt_year(t), dt_month(t), dt_day(t)) \
        + _TO_DAYS_EPOCH


def is_leap(y):
    y = np.asarray(y, np.int64)
    return (y % 4 == 0) & ((y % 100 != 0) | (y % 400 == 0))


_DAYS_IN_MONTH = np.array([0, 31, 28, 31, 30, 31, 30, 31, 31, 30, 31, 30,
                           31], np.int64)


def days_in_month(y, m):
    m = np.asarray(m, np.int64)
    base = _DAYS_IN_MONTH[np.clip(m, 0, 12)]
    return base + (is_leap(y) & (m == 2))


def iso_week(y, m, d):
    """ISO-8601 week number (MySQL WEEKOFYEAR == WEEK(d, 3))."""
    dfc = days_from_civil(y, m, d)
    # ISO: week containing the year's first Thursday is week 1.
    # weekday: Mon=0 (1970-01-01 was a Thursday, dfc==0 -> 3)
    wd = (dfc + 3) % 7
    thursday = dfc - wd + 3
    iso_y, _, _ = civil_from_days(thursday)
    jan1 = days_from_civil(iso_y, 1, 1)
    return ((thursday - jan1) // 7 + 1).astype(np.int64)
