"""MySQL NEWDECIMAL semantics over stdlib ``decimal.Decimal``.

Reference: components/tidb_query_datatype/src/codec/mysql/decimal.rs —
a 65-digit fixed-point type with
- round HALF AWAY FROM ZERO (MySQL "round half up"),
- result scale rules: add/sub → max(s1,s2); mul → s1+s2;
  div → s1 + div_precision_increment (4); all capped at 30;
- division by zero → NULL (+warning), not an error, in the coprocessor.

The reference implements its own 9-digits-per-word bignum; here the host
representation IS ``decimal.Decimal`` (arbitrary precision, exact), with
this module supplying the MySQL-specific scale/rounding envelope.  The
device path never sees DECIMAL (DeviceRunner gates on INT/REAL).
"""

from __future__ import annotations

import decimal
from decimal import Decimal
from typing import Optional

WORD_BUF_LEN_MAX_DIGITS = 65    # decimal.rs: WORD_BUF_LEN * DIGITS_PER_WORD
MAX_FRAC = 30                   # mysql max scale
DIV_PRECISION_INCREMENT = 4     # @@div_precision_increment default

# exact arithmetic context: 65 significant digits, MySQL tie rule
CTX = decimal.Context(prec=WORD_BUF_LEN_MAX_DIGITS,
                      rounding=decimal.ROUND_HALF_UP)

ZERO = Decimal(0)


def frac_of(d: Decimal) -> int:
    """The value's scale (digits right of the point), >= 0."""
    exp = d.as_tuple().exponent
    return max(0, -exp) if isinstance(exp, int) else 0


def add(a: Decimal, b: Decimal) -> Decimal:
    return CTX.add(a, b)


def sub(a: Decimal, b: Decimal) -> Decimal:
    return CTX.subtract(a, b)


def mul(a: Decimal, b: Decimal) -> Decimal:
    return CTX.multiply(a, b)


def div(a: Decimal, b: Decimal,
        incr: int = DIV_PRECISION_INCREMENT) -> Optional[Decimal]:
    """a / b at scale frac(a) + incr (capped MAX_FRAC); None on b == 0
    (MySQL: division by zero yields NULL with a warning)."""
    if not b:
        return None
    frac = min(frac_of(a) + incr, MAX_FRAC)
    q = CTX.divide(a, b)
    return round_frac(q, frac)


def mod(a: Decimal, b: Decimal) -> Optional[Decimal]:
    """MySQL MOD: sign follows the dividend; None on b == 0."""
    if not b:
        return None
    return CTX.remainder(a, b)


def round_frac(d: Decimal, frac: int = 0) -> Decimal:
    """ROUND(d, frac) — half away from zero.  Negative frac rounds left
    of the point (MySQL ROUND(123, -2) = 100)."""
    frac = min(frac, MAX_FRAC)
    q = Decimal(1).scaleb(-frac)
    return d.quantize(q, rounding=decimal.ROUND_HALF_UP, context=CTX)


def ceil(d: Decimal) -> Decimal:
    return d.to_integral_value(rounding=decimal.ROUND_CEILING)


def floor(d: Decimal) -> Decimal:
    return d.to_integral_value(rounding=decimal.ROUND_FLOOR)


def truncate(d: Decimal, frac: int = 0) -> Decimal:
    frac = min(frac, MAX_FRAC)
    q = Decimal(1).scaleb(-frac)
    return d.quantize(q, rounding=decimal.ROUND_DOWN, context=CTX)


def to_int(d: Decimal) -> int:
    """CastDecimalAsInt: round half away from zero to an integer."""
    return int(d.to_integral_value(rounding=decimal.ROUND_HALF_UP))


def from_float(x: float) -> Decimal:
    """CastRealAsDecimal: MySQL converts through the decimal printout of
    the double (not the exact binary expansion)."""
    return CTX.create_decimal(repr(float(x)))


def from_int(x: int) -> Decimal:
    return Decimal(int(x))


def from_string(s) -> Optional[Decimal]:
    """Parse the longest numeric prefix (MySQL string→decimal coercion:
    '12.5abc' → 12.5, 'abc' → 0, '' → 0).  Never raises."""
    if isinstance(s, (bytes, bytearray)):
        s = s.decode("utf-8", "replace")
    s = s.strip()
    # longest valid prefix: sign, digits, one dot, optional exponent
    n = len(s)
    i = 0
    if i < n and s[i] in "+-":
        i += 1
    seen_digit = False
    seen_dot = False
    while i < n:
        ch = s[i]
        if ch.isdigit():
            seen_digit = True
        elif ch == "." and not seen_dot:
            seen_dot = True
        else:
            break
        i += 1
    # optional exponent only if digits follow it
    if seen_digit and i < n and s[i] in "eE":
        j = i + 1
        if j < n and s[j] in "+-":
            j += 1
        if j < n and s[j].isdigit():
            while j < n and s[j].isdigit():
                j += 1
            i = j
    prefix = s[:i]
    if not seen_digit:
        return ZERO
    try:
        return CTX.create_decimal(prefix)
    except decimal.InvalidOperation:    # pragma: no cover
        return ZERO


def to_string(d: Decimal) -> bytes:
    """MySQL text form: plain notation, scale preserved ('1.20' stays
    '1.20'), no exponent."""
    s = format(d, "f")
    return s.encode()
