"""Evaluation types and field types.

Reference: components/tidb_query_datatype/src/lib.rs (EvalType),
src/def/field_type.rs (FieldType/FieldTypeTp/FieldTypeFlag). The reference
distinguishes the wire-level MySQL column type (FieldTypeTp, ~30 variants)
from the evaluation type the vectorized engine computes on (EvalType, 9
variants, eval_type via EvalType::try_from_field_type). We keep the same
split: FieldType carries schema metadata; EvalType picks the kernel dtype.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field

import numpy as np


class EvalType(enum.Enum):
    """The 9 evaluation types of the vectorized engine.

    Reference: tidb_query_datatype/src/lib.rs EvalType enum.
    """

    INT = "int"            # signed/unsigned 64-bit (device: int64 pair-emulated, or int32 fast path)
    REAL = "real"          # f64 on host, f32 accumulate-in-f64 on device
    DECIMAL = "decimal"    # fixed point: decimal.Decimal objects, MySQL
                           # 65-digit semantics (datatype/mydecimal.py);
                           # host-only — device plans route INT/REAL
    BYTES = "bytes"        # var-length binary/string (host; device via dict-encoding)
    DATETIME = "datetime"  # packed u64 core time
    DURATION = "duration"  # i64 nanoseconds
    JSON = "json"          # host-side only
    ENUM = "enum"          # u64 ordinal + shared name table
    SET = "set"            # u64 bitmask + shared name table

    @property
    def is_device_native(self) -> bool:
        """Types that evaluate on-device as dense arrays without dictionary."""
        return self in (
            EvalType.INT,
            EvalType.REAL,
            EvalType.DATETIME,
            EvalType.DURATION,
            EvalType.ENUM,
            EvalType.SET,
        )

    @property
    def np_dtype(self) -> np.dtype:
        """Host-side storage dtype for the dense value array."""
        if self in (EvalType.INT, EvalType.DURATION):
            return np.dtype(np.int64)
        if self is EvalType.REAL:
            return np.dtype(np.float64)
        if self in (EvalType.DATETIME, EvalType.ENUM, EvalType.SET):
            return np.dtype(np.uint64)
        return np.dtype(object)  # BYTES / JSON / DECIMAL


class FieldTypeTp(enum.IntEnum):
    """MySQL protocol column types (subset that TiKV's coprocessor sees).

    Reference: tidb_query_datatype/src/def/field_type.rs FieldTypeTp.
    Values follow the MySQL wire protocol so DAG plans can round-trip.
    """

    UNSPECIFIED = 0
    TINY = 1
    SHORT = 2
    LONG = 3
    FLOAT = 4
    DOUBLE = 5
    NULL = 6
    TIMESTAMP = 7
    LONG_LONG = 8
    INT24 = 9
    DATE = 10
    DURATION = 11
    DATETIME = 12
    YEAR = 13
    NEW_DATE = 14
    VAR_CHAR = 15
    BIT = 16
    JSON = 0xF5
    NEW_DECIMAL = 0xF6
    ENUM = 0xF7
    SET = 0xF8
    TINY_BLOB = 0xF9
    MEDIUM_BLOB = 0xFA
    LONG_BLOB = 0xFB
    BLOB = 0xFC
    VAR_STRING = 0xFD
    STRING = 0xFE
    GEOMETRY = 0xFF


class FieldTypeFlag(enum.IntFlag):
    """Column flags. Reference: field_type.rs FieldTypeFlag."""

    NONE = 0
    NOT_NULL = 1
    PRI_KEY = 1 << 1
    UNSIGNED = 1 << 5
    BINARY = 1 << 7
    IS_BOOLEAN = 1 << 62  # internal


_TP_TO_EVAL = {
    FieldTypeTp.TINY: EvalType.INT,
    FieldTypeTp.SHORT: EvalType.INT,
    FieldTypeTp.INT24: EvalType.INT,
    FieldTypeTp.LONG: EvalType.INT,
    FieldTypeTp.LONG_LONG: EvalType.INT,
    FieldTypeTp.YEAR: EvalType.INT,
    FieldTypeTp.BIT: EvalType.INT,
    FieldTypeTp.FLOAT: EvalType.REAL,
    FieldTypeTp.DOUBLE: EvalType.REAL,
    FieldTypeTp.NEW_DECIMAL: EvalType.DECIMAL,
    FieldTypeTp.TIMESTAMP: EvalType.DATETIME,
    FieldTypeTp.DATE: EvalType.DATETIME,
    FieldTypeTp.NEW_DATE: EvalType.DATETIME,
    FieldTypeTp.DATETIME: EvalType.DATETIME,
    FieldTypeTp.DURATION: EvalType.DURATION,
    FieldTypeTp.JSON: EvalType.JSON,
    FieldTypeTp.ENUM: EvalType.ENUM,
    FieldTypeTp.SET: EvalType.SET,
    FieldTypeTp.VAR_CHAR: EvalType.BYTES,
    FieldTypeTp.VAR_STRING: EvalType.BYTES,
    FieldTypeTp.STRING: EvalType.BYTES,
    FieldTypeTp.TINY_BLOB: EvalType.BYTES,
    FieldTypeTp.MEDIUM_BLOB: EvalType.BYTES,
    FieldTypeTp.LONG_BLOB: EvalType.BYTES,
    FieldTypeTp.BLOB: EvalType.BYTES,
}


@dataclass(frozen=True)
class FieldType:
    """Schema metadata for one column.

    Reference: tipb FieldType / tidb_query_datatype field_type.rs accessors.
    """

    tp: FieldTypeTp = FieldTypeTp.LONG_LONG
    flag: FieldTypeFlag = FieldTypeFlag.NONE
    flen: int = -1
    decimal: int = -1
    collation: int = 63  # binary
    elems: tuple = field(default_factory=tuple)  # enum/set name table

    @property
    def eval_type(self) -> EvalType:
        try:
            return _TP_TO_EVAL[self.tp]
        except KeyError:
            raise ValueError(f"unsupported field type {self.tp!r}") from None

    @property
    def is_unsigned(self) -> bool:
        return bool(self.flag & FieldTypeFlag.UNSIGNED)

    @property
    def is_nullable(self) -> bool:
        return not (self.flag & FieldTypeFlag.NOT_NULL)

    @staticmethod
    def long(unsigned: bool = False, not_null: bool = False) -> "FieldType":
        flag = FieldTypeFlag.NONE
        if unsigned:
            flag |= FieldTypeFlag.UNSIGNED
        if not_null:
            flag |= FieldTypeFlag.NOT_NULL
        return FieldType(tp=FieldTypeTp.LONG_LONG, flag=flag)

    @staticmethod
    def double(not_null: bool = False) -> "FieldType":
        flag = FieldTypeFlag.NOT_NULL if not_null else FieldTypeFlag.NONE
        return FieldType(tp=FieldTypeTp.DOUBLE, flag=flag)

    @staticmethod
    def var_char(collation: int = 63) -> "FieldType":
        return FieldType(tp=FieldTypeTp.VAR_CHAR, collation=collation)

    @staticmethod
    def enum(elems, collation: int = 63) -> "FieldType":
        return FieldType(tp=FieldTypeTp.ENUM, collation=collation,
                         elems=tuple(elems))

    @staticmethod
    def set_(elems, collation: int = 63) -> "FieldType":
        return FieldType(tp=FieldTypeTp.SET, collation=collation,
                         elems=tuple(elems))

    @staticmethod
    def json() -> "FieldType":
        return FieldType(tp=FieldTypeTp.JSON)

    @staticmethod
    def new_decimal(flen: int = 20, frac: int = 4) -> "FieldType":
        # (named new_decimal: a constructor called "decimal" would shadow
        # the dataclass field's default with the function object)
        return FieldType(tp=FieldTypeTp.NEW_DECIMAL, flen=flen, decimal=frac)


def device_const_dtype(v) -> str:
    """Device dtype bucket for a hoistable numeric constant — THE
    compile-class identity of a predicate/aggregate constant once its
    value is hoisted into a traced scalar parameter.  Shared by the
    hoisting itself (device/selection.split_params), the const-blind
    kernel key (selection.shape_key), and the const-blind plan class
    (copr/dag.DAGRequest.class_key) so the three can never drift: a
    float traces as float32; an int traces int32 unless it crosses the
    int32 boundary, which is a genuinely new trace."""
    if isinstance(v, float):
        return "float32"
    return "int32" if -(2 ** 31) <= v < 2 ** 31 else "int64"
