"""Host-side columnar containers.

Reference: components/tidb_query_datatype/src/codec/data_type/vector.rs:14
(``VectorValue`` — an enum of ChunkedVec per eval type, each a value vec +
null bitmap) and codec/batch/lazy_column.rs:27 (``LazyBatchColumn`` — raw
encoded datums OR decoded vector). The TPU-first redesign drops the per-value
chunked encoding in favour of dense numpy arrays + boolean validity mask —
the layout the device consumes directly — and keeps the raw-vs-decoded split
at batch granularity: a column is either ``raw`` (list of undecoded datum
bytes, produced by scans) or ``decoded`` (dense arrays).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Iterable, Optional, Sequence

import numpy as np

from .eval_type import EvalType, FieldType


class Column:
    """A dense column: value array + validity mask.

    ``values`` is a numpy array of the eval type's host dtype; entries where
    ``validity`` is False are NULL (their value slot is unspecified but must
    be a *harmless* value — 0 — so device kernels never see NaN/garbage).

    For BYTES/JSON, ``values`` is a 1-D object array of ``bytes``.
    """

    __slots__ = ("eval_type", "values", "validity")

    def __init__(self, eval_type: EvalType, values: np.ndarray, validity: np.ndarray):
        assert values.shape == validity.shape, (values.shape, validity.shape)
        self.eval_type = eval_type
        self.values = values
        self.validity = validity

    # -- constructors -------------------------------------------------------

    @staticmethod
    def empty(eval_type: EvalType) -> "Column":
        return Column(
            eval_type,
            np.empty(0, dtype=eval_type.np_dtype),
            np.empty(0, dtype=np.bool_),
        )

    @staticmethod
    def from_list(eval_type: EvalType, items: Sequence,
                  unsigned: bool = False) -> "Column":
        """Build from a Python list where ``None`` means NULL.

        ``unsigned``: the column is declared UNSIGNED (FieldType flag) —
        the container is uint64 regardless of which values appear, so
        per-batch builds of the same column never mix int64/uint64
        (np.concatenate would silently promote that mix to float64).
        """
        n = len(items)
        validity = np.fromiter((x is not None for x in items), dtype=np.bool_, count=n)
        dtype = eval_type.np_dtype
        if dtype == np.dtype(object):
            # NULL slots hold a harmless same-type value so vectorized
            # object ops never mix representations (frompyfunc sigs run
            # over masked slots too)
            if eval_type is EvalType.DECIMAL:
                from .mydecimal import ZERO as fill
            elif eval_type is EvalType.JSON:
                fill = None     # the JSON null literal
            else:
                fill = b""
            values = np.empty(n, dtype=object)
            for i, x in enumerate(items):
                values[i] = x if x is not None else fill
        else:
            if dtype == np.int64 and (unsigned or any(
                    x is not None and x >= 1 << 63 for x in items)):
                # unsigned BIGINT domain lives above 2^63: keep the
                # container uint64 — INT columns carry signedness via
                # FieldType
                dtype = np.dtype(np.uint64)
            values = np.zeros(n, dtype=dtype)
            for i, x in enumerate(items):
                if x is not None:
                    values[i] = x
        return Column(eval_type, values, validity)

    @staticmethod
    def from_values(eval_type: EvalType, values: np.ndarray,
                    validity: Optional[np.ndarray] = None) -> "Column":
        if validity is None:
            validity = np.ones(values.shape, dtype=np.bool_)
        return Column(eval_type, values, validity)

    # -- accessors ----------------------------------------------------------

    def __len__(self) -> int:
        return len(self.values)

    def get(self, i: int):
        """Scalar accessor: value or None."""
        if not self.validity[i]:
            return None
        v = self.values[i]
        if isinstance(v, np.generic):
            return v.item()
        return v

    def to_list(self) -> list:
        return [self.get(i) for i in range(len(self))]

    def null_count(self) -> int:
        return int(len(self) - self.validity.sum())

    # -- mutation (builder-style; used by executors assembling output) ------

    def take(self, indices: np.ndarray) -> "Column":
        return Column(self.eval_type, self.values[indices], self.validity[indices])

    def filter(self, mask: np.ndarray) -> "Column":
        return Column(self.eval_type, self.values[mask], self.validity[mask])

    def slice(self, start: int, stop: int) -> "Column":
        return Column(self.eval_type, self.values[start:stop], self.validity[start:stop])

    @staticmethod
    def concat(cols: Sequence["Column"]) -> "Column":
        assert cols
        et = cols[0].eval_type
        return Column(
            et,
            np.concatenate([c.values for c in cols]),
            np.concatenate([c.validity for c in cols]),
        )

    def __repr__(self) -> str:
        return f"Column<{self.eval_type.value}>[{len(self)}]"


@dataclass
class ColumnBatch:
    """A batch of rows in columnar form.

    Reference: codec/batch/lazy_column_vec.rs:15 (``LazyBatchColumnVec``).
    ``schema`` gives each column's FieldType; ``columns`` the data. Executors
    hand these down the pipeline (pull model, reference
    tidb_query_executors/src/interface.rs:21).
    """

    schema: list[FieldType]
    columns: list[Column]

    def __post_init__(self):
        assert len(self.schema) == len(self.columns)
        if self.columns:
            n = len(self.columns[0])
            assert all(len(c) == n for c in self.columns), \
                [len(c) for c in self.columns]

    @property
    def num_rows(self) -> int:
        return len(self.columns[0]) if self.columns else 0

    @property
    def num_cols(self) -> int:
        return len(self.columns)

    @staticmethod
    def empty(schema: Iterable[FieldType]) -> "ColumnBatch":
        schema = list(schema)
        return ColumnBatch(schema, [Column.empty(ft.eval_type) for ft in schema])

    def filter(self, mask: np.ndarray) -> "ColumnBatch":
        return ColumnBatch(self.schema, [c.filter(mask) for c in self.columns])

    def take(self, indices: np.ndarray) -> "ColumnBatch":
        return ColumnBatch(self.schema, [c.take(indices) for c in self.columns])

    def slice(self, start: int, stop: int) -> "ColumnBatch":
        return ColumnBatch(self.schema, [c.slice(start, stop) for c in self.columns])

    @staticmethod
    def concat(batches: Sequence["ColumnBatch"]) -> "ColumnBatch":
        assert batches
        return ColumnBatch(
            batches[0].schema,
            [Column.concat([b.columns[i] for b in batches])
             for i in range(batches[0].num_cols)],
        )

    def rows(self) -> list[tuple]:
        """Materialize as Python rows (tests / response encoding)."""
        return [tuple(c.get(i) for c in self.columns) for i in range(self.num_rows)]
