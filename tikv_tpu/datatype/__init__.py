"""Type system + columnar containers.

Rebuild of the reference's ``components/tidb_query_datatype`` (46k LoC Rust):
``EvalType``/``FieldType`` (eval_type.rs, field_type.rs), the columnar
containers ``VectorValue``/``LazyBatchColumn``/``LazyBatchColumnVec``
(codec/data_type/vector.rs:14, codec/batch/lazy_column.rs:27,
codec/batch/lazy_column_vec.rs:15) — redesigned device-first: a column is a
dense numpy/jax value array plus a validity mask, padded to static tile
shapes so XLA sees fixed shapes (SURVEY.md §7 "Dynamic shapes").
"""

from .eval_type import (EvalType, FieldType, FieldTypeFlag, FieldTypeTp,
                        device_const_dtype)
from .column import Column, ColumnBatch
from .tile import Tile, TileBatch, pad_to_tile, TILE_ROWS

__all__ = [
    "EvalType",
    "FieldType",
    "FieldTypeFlag",
    "FieldTypeTp",
    "Column",
    "ColumnBatch",
    "Tile",
    "TileBatch",
    "pad_to_tile",
    "TILE_ROWS",
]
