"""MySQL JSON semantics over plain Python values.

Reference: tidb_query_datatype/src/codec/mysql/json/ — the reference
stores a MySQL-binary JSON encoding; the host representation here is the
parsed Python value (dict / list / str / int / float / bool / None for
the JSON null literal), with SQL NULL carried by the column validity
mask, so the two nulls never collide.  This module supplies the
MySQL-specific behavior: path expressions, type names, containment,
merge, and modify operations.
"""

from __future__ import annotations

import json
from typing import Optional


class _NotFound:
    __repr__ = lambda self: "JSON_NOT_FOUND"     # noqa: E731


NOT_FOUND = _NotFound()


def parse(text) -> object:
    """Parse JSON text (bytes/str) → value.  Raises ValueError on bad
    input (callers map to NULL/err per sig semantics)."""
    if isinstance(text, (bytes, bytearray)):
        text = text.decode("utf-8")
    return json.loads(text)


def dumps(value) -> bytes:
    """MySQL display form: ", "-separated, sorted-insertion order kept
    (python dicts preserve insertion; MySQL sorts keys by length then
    alphabetically in its binary format — we normalize to plain
    json.dumps with ", "/": " separators, the form MySQL prints)."""
    return json.dumps(value, separators=(", ", ": "),
                      ensure_ascii=False).encode()


def type_name(v) -> bytes:
    """JSON_TYPE — reference json/mod.rs json_type."""
    if v is None:
        return b"NULL"
    if isinstance(v, bool):
        return b"BOOLEAN"
    if isinstance(v, int):
        return b"INTEGER"
    if isinstance(v, float):
        return b"DOUBLE"
    if isinstance(v, str):
        return b"STRING"
    if isinstance(v, list):
        return b"ARRAY"
    if isinstance(v, dict):
        return b"OBJECT"
    raise TypeError(type(v))


# ---------------------------------------------------------------- paths

def parse_path(path) -> list:
    """$.key / $."quoted" / [3] / [*] / .* / ** → list of legs.

    Legs: ("key", name) | ("idx", n) | ("key*",) | ("idx*",) | ("**",).
    Reference: json/path_expr.rs.
    """
    if isinstance(path, (bytes, bytearray)):
        path = path.decode("utf-8")
    s = path.strip()
    if not s or s[0] != "$":
        raise ValueError(f"bad json path {path!r}")
    i, n = 1, len(s)
    legs: list = []
    while i < n:
        ch = s[i]
        if ch == ".":
            i += 1
            if i < n and s[i] == "*":
                legs.append(("key*",))
                i += 1
                continue
            if i < n and s[i] == '"':
                # closing quote search must skip backslash escapes; the
                # quoted segment is itself a JSON string literal
                j = i + 1
                while j < n:
                    if s[j] == "\\":
                        j += 2
                        continue
                    if s[j] == '"':
                        break
                    j += 1
                if j >= n:
                    raise ValueError(f"unterminated key in {path!r}")
                legs.append(("key", json.loads(s[i:j + 1])))
                i = j + 1
                continue
            j = i
            while j < n and (s[j].isalnum() or s[j] in "_$"):
                j += 1
            if j == i:
                raise ValueError(f"bad member leg in {path!r}")
            legs.append(("key", s[i:j]))
            i = j
        elif ch == "[":
            j = s.index("]", i)
            inner = s[i + 1:j].strip()
            if inner == "*":
                legs.append(("idx*",))
            else:
                legs.append(("idx", int(inner)))
            i = j + 1
        elif ch == "*" and i + 1 < n and s[i + 1] == "*":
            legs.append(("**",))
            i += 2
        elif ch.isspace():
            i += 1
        else:
            raise ValueError(f"bad json path {path!r} at {i}")
    return legs


def path_is_wild(legs) -> bool:
    return any(leg[0] in ("key*", "idx*", "**") for leg in legs)


def _walk(v, legs, out: list):
    if not legs:
        out.append(v)
        return
    leg, rest = legs[0], legs[1:]
    kind = leg[0]
    if kind == "key":
        if isinstance(v, dict) and leg[1] in v:
            _walk(v[leg[1]], rest, out)
    elif kind == "idx":
        if isinstance(v, list):
            if 0 <= leg[1] < len(v):
                _walk(v[leg[1]], rest, out)
        elif leg[1] == 0:
            # MySQL: scalar behaves as a single-element array for [0]
            _walk(v, rest, out)
    elif kind == "key*":
        if isinstance(v, dict):
            for x in v.values():
                _walk(x, rest, out)
    elif kind == "idx*":
        if isinstance(v, list):
            for x in v:
                _walk(x, rest, out)
    elif kind == "**":
        # ** requires a following leg in MySQL; match at every depth
        _walk(v, rest, out)
        if isinstance(v, dict):
            for x in v.values():
                _walk(x, legs, out)
        elif isinstance(v, list):
            for x in v:
                _walk(x, legs, out)


def extract(doc, paths) -> object:
    """JSON_EXTRACT(doc, path...) — single concrete path → the value;
    multiple paths or wildcards → array of matches; none → NOT_FOUND."""
    matches: list = []
    wild = len(paths) > 1
    for p in paths:
        legs = parse_path(p)
        wild = wild or path_is_wild(legs)
        _walk(doc, legs, matches)
    if not matches:
        return NOT_FOUND
    if wild:
        return matches
    return matches[0]


# ------------------------------------------------------------- semantics

def json_eq(a, b) -> bool:
    """Type-aware equality: JSON true != 1 (python True == 1 would)."""
    if isinstance(a, bool) or isinstance(b, bool):
        return isinstance(a, bool) and isinstance(b, bool) and a is b
    if isinstance(a, int) and isinstance(b, int):
        return a == b       # exact — float() would collapse above 2^53
    if isinstance(a, (int, float)) and isinstance(b, (int, float)):
        return float(a) == float(b)
    if type(a) is not type(b):
        return False
    if isinstance(a, list):
        return len(a) == len(b) and all(json_eq(x, y)
                                        for x, y in zip(a, b))
    if isinstance(a, dict):
        return a.keys() == b.keys() and all(json_eq(a[k], b[k])
                                            for k in a)
    return a == b


def contains(target, candidate) -> bool:
    """JSON_CONTAINS semantics (json/json_contains.rs):
    - object contains object: every key/value of candidate contained;
    - array contains array: every candidate element contained in target;
    - array contains scalar/object: some element contains it;
    - scalar contains scalar: equality."""
    if isinstance(target, list):
        if isinstance(candidate, list):
            return all(contains(target, c) for c in candidate)
        return any(contains(t, candidate) for t in target)
    if isinstance(target, dict):
        if isinstance(candidate, dict):
            return all(k in target and contains(target[k], v)
                       for k, v in candidate.items())
        return False
    return json_eq(target, candidate)


def member_of(value, array) -> bool:
    """value MEMBER OF(array): array → element equality; non-array →
    equality with the whole document."""
    if isinstance(array, list):
        return any(json_eq(value, x) for x in array)
    return json_eq(value, array)


def merge_preserve(docs) -> object:
    """JSON_MERGE_PRESERVE: arrays concat, objects union (recursive),
    scalars wrap to arrays (json/json_merge.rs)."""
    out = docs[0]
    for d in docs[1:]:
        out = _merge2(out, d)
    return out


def _merge2(a, b):
    if isinstance(a, dict) and isinstance(b, dict):
        out = dict(a)
        for k, v in b.items():
            out[k] = _merge2(out[k], v) if k in out else v
        return out
    la = a if isinstance(a, list) else [a]
    lb = b if isinstance(b, list) else [b]
    return la + lb


def depth(v) -> int:
    if isinstance(v, dict):
        return 1 + max((depth(x) for x in v.values()), default=0)
    if isinstance(v, list):
        return 1 + max((depth(x) for x in v), default=0)
    return 1


def length(v, path: Optional[bytes] = None):
    """JSON_LENGTH: scalars → 1; arrays/objects → element count; with a
    path, length of the value at the path (None when absent)."""
    if path is not None:
        got = extract(v, [path])
        if got is NOT_FOUND:
            return None
        v = got
    if isinstance(v, (dict, list)):
        return len(v)
    return 1


def keys(v, path: Optional[bytes] = None):
    if path is not None:
        got = extract(v, [path])
        if got is NOT_FOUND:
            return None
        v = got
    if isinstance(v, dict):
        return list(v.keys())
    return None


def unquote(v) -> bytes:
    """JSON_UNQUOTE: strings print raw; everything else prints as JSON
    text (json/json_unquote.rs)."""
    if isinstance(v, str):
        return v.encode()
    return dumps(v)


def quote(s) -> bytes:
    if isinstance(s, (bytes, bytearray)):
        s = s.decode("utf-8", "replace")
    return json.dumps(s, ensure_ascii=False).encode()


# ----------------------------------------------------------- modify ops

def _modify(doc, path_value_pairs, mode: str):
    """JSON_SET / JSON_INSERT / JSON_REPLACE (json/modifier.rs).

    set: create or replace; insert: create only; replace: existing only.
    Wildcard paths are rejected (as in MySQL).
    """
    import copy
    out = copy.deepcopy(doc)
    for path, value in path_value_pairs:
        legs = parse_path(path)
        if path_is_wild(legs):
            raise ValueError("wildcards not allowed in modify paths")
        # the value is inserted BY VALUE: without this copy a later pair
        # addressing into it would mutate the caller's (shared) object
        value = copy.deepcopy(value)
        if not legs:
            if mode in ("set", "replace"):
                out = value
            continue
        out = _set_leg(out, legs, value, mode)
    return out


def _set_leg(v, legs, value, mode):
    leg, rest = legs[0], legs[1:]
    kind = leg[0]
    if kind == "key":
        if not isinstance(v, dict):
            return v
        k = leg[1]
        if k in v:
            if rest:
                v[k] = _set_leg(v[k], rest, value, mode)
            elif mode in ("set", "replace"):
                v[k] = value
        elif not rest and mode in ("set", "insert"):
            v[k] = value
        return v
    # index leg
    idx = leg[1]
    if not isinstance(v, list):
        # scalar as single-element array: [0] addresses it; appending
        # past the end wraps to an array (MySQL autowrap)
        if idx == 0:
            if rest:
                return _set_leg(v, rest, value, mode)
            return value if mode in ("set", "replace") else v
        if mode in ("set", "insert") and not rest:
            return [v, value]
        return v
    if 0 <= idx < len(v):
        if rest:
            v[idx] = _set_leg(v[idx], rest, value, mode)
        elif mode in ("set", "replace"):
            v[idx] = value
    elif not rest and mode in ("set", "insert"):
        v.append(value)
    return v


def json_set(doc, pairs):
    return _modify(doc, pairs, "set")


def json_insert(doc, pairs):
    return _modify(doc, pairs, "insert")


def json_replace(doc, pairs):
    return _modify(doc, pairs, "replace")


def json_remove(doc, paths):
    import copy
    out = copy.deepcopy(doc)
    for path in paths:
        legs = parse_path(path)
        if path_is_wild(legs) or not legs:
            raise ValueError("bad remove path")
        out = _remove_leg(out, legs)
    return out


def _remove_leg(v, legs):
    leg, rest = legs[0], legs[1:]
    if leg[0] == "key" and isinstance(v, dict) and leg[1] in v:
        if rest:
            v[leg[1]] = _remove_leg(v[leg[1]], rest)
        else:
            del v[leg[1]]
    elif leg[0] == "idx" and isinstance(v, list) and \
            0 <= leg[1] < len(v):
        if rest:
            v[leg[1]] = _remove_leg(v[leg[1]], rest)
        else:
            del v[leg[1]]
    return v


import functools


@functools.lru_cache(maxsize=1024)
def _like_pattern(pattern: str, escape: int):
    """Compiled LIKE matcher (cached per pattern — JSON_SEARCH visits
    thousands of string nodes with ONE pattern).  Translation shared
    with impl_like via collation.like_regex_src."""
    import re
    from .collation import like_regex_src
    return re.compile(like_regex_src(pattern, escape))


def search(doc, one_or_all: bytes, target: bytes, escape: int = 92,
           scope_paths=()) -> object:
    """JSON_SEARCH: paths of STRING values LIKE ``target``; 'one' stops
    at the first hit.  ONE match returns the bare path string, several
    return an array (MySQL autowraps only on multiple matches); none ->
    NOT_FOUND.  ``scope_paths`` restrict the search to concrete
    subtrees; wildcard scopes raise ValueError (NULL at the sig layer).
    """
    if isinstance(one_or_all, (bytes, bytearray)):
        one_or_all = one_or_all.decode()
    if isinstance(target, (bytes, bytearray)):
        target = target.decode("utf-8", "replace")
    rx = _like_pattern(target, escape)
    found: list = []

    def walk(v, path):
        if isinstance(v, str) and rx.match(v):
            found.append(path)
            if one_or_all == "one":
                return True
        if isinstance(v, dict):
            for k, x in v.items():
                key = k if k.isalnum() and not k[:1].isdigit() \
                    else '"' + k.replace('"', '\\"') + '"'
                if walk(x, f"{path}.{key}"):
                    return True
        elif isinstance(v, list):
            for i, x in enumerate(v):
                if walk(x, f"{path}[{i}]"):
                    return True
        return False

    if scope_paths:
        for sp in scope_paths:
            legs = parse_path(sp)
            if path_is_wild(legs):
                raise ValueError("wildcard scope paths unsupported")
            sub = extract(doc, [sp])
            if sub is NOT_FOUND:
                continue
            prefix = (sp.decode() if isinstance(sp, (bytes, bytearray))
                      else sp).strip()
            if walk(sub, prefix):
                break
    else:
        walk(doc, "$")
    if not found:
        return NOT_FOUND
    if len(found) == 1:
        return found[0]
    return found



def array_append(doc, pairs):
    """JSON_ARRAY_APPEND: value at each path wraps to an array (if not
    one already) and the new element appends (json/modifier.rs)."""
    import copy
    out = copy.deepcopy(doc)
    for path, value in pairs:
        legs = parse_path(path)
        if path_is_wild(legs):
            raise ValueError("wildcards not allowed")
        target = extract(out, [path])
        if target is NOT_FOUND:
            continue
        if isinstance(target, list):
            new = target + [copy.deepcopy(value)]
        else:
            new = [copy.deepcopy(target), copy.deepcopy(value)]
        out = json_set(out, [(path, new)]) if legs else new
    return out
