"""Collations — string comparison orders beyond raw bytes.

Reference: tidb_query_datatype/src/codec/collation/ (Collator impls per
collation id, dispatched through ``match_template_collator!``).  The
host representation keeps BYTES columns as raw bytes; a collation is a
pure function bytes → sort key, so compare/order/group under collation
C is bytewise compare of ``sort_key(C, value)`` — exactly the
reference's ``write_sort_key`` contract.

Supported (the set TiDB enables by default with new_collations):
- binary (63): identity.
- ascii_bin (65) / latin1_bin (47) / utf8_bin (83) / utf8mb4_bin (46):
  PAD SPACE — trailing spaces are insignificant, otherwise bytewise.
- utf8_general_ci (33) / utf8mb4_general_ci (45): PAD SPACE +
  case-insensitive; weight = uppercase codepoint (BMP), the
  general_ci simplification the reference implements (collator/
  charset.rs general ci weight tables; supplementary-plane chars weight
  0xFFFD).
- utf8mb4_unicode_ci (224): approximated by general_ci weights — a
  documented deviation (the reference ships full UCA tables).

TiDB wire quirk: new-collation framework sends NEGATED ids; abs() on
ingestion (field_type.rs collation accessor does the same).
"""

from __future__ import annotations

BINARY = 63
ASCII_BIN = 65
LATIN1_BIN = 47
UTF8_BIN = 83
UTF8MB4_BIN = 46
UTF8_GENERAL_CI = 33
UTF8MB4_GENERAL_CI = 45
UTF8MB4_UNICODE_CI = 224

_PAD_BIN = {ASCII_BIN, LATIN1_BIN, UTF8_BIN, UTF8MB4_BIN}
_GENERAL_CI = {UTF8_GENERAL_CI, UTF8MB4_GENERAL_CI, UTF8MB4_UNICODE_CI}

NAMES = {
    BINARY: "binary",
    ASCII_BIN: "ascii_bin",
    LATIN1_BIN: "latin1_bin",
    UTF8_BIN: "utf8_bin",
    UTF8MB4_BIN: "utf8mb4_bin",
    UTF8_GENERAL_CI: "utf8_general_ci",
    UTF8MB4_GENERAL_CI: "utf8mb4_general_ci",
    UTF8MB4_UNICODE_CI: "utf8mb4_unicode_ci",
}


def normalize_id(collation: int) -> int:
    return abs(int(collation))


def sort_key(value: bytes, collation: int = BINARY) -> bytes:
    """bytes → memcomparable weight string for the collation."""
    c = normalize_id(collation)
    if c == BINARY or c not in NAMES:
        return value
    if c in _PAD_BIN:
        return value.rstrip(b" ")
    # general_ci family
    s = value.decode("utf-8", "replace").rstrip(" ")
    out = bytearray()
    for ch in s:
        cp = ord(ch)
        if cp > 0xFFFF:
            w = 0xFFFD          # supplementary plane: replacement weight
        else:
            w = ord(ch.upper()[0]) if ch.upper() else cp
            if w > 0xFFFF:      # rare expanding uppercase (ß→SS etc.)
                w = cp
        out += w.to_bytes(2, "big")
    return bytes(out)


def compare(a: bytes, b: bytes, collation: int = BINARY) -> int:
    ka, kb = sort_key(a, collation), sort_key(b, collation)
    return (ka > kb) - (ka < kb)


def eq(a: bytes, b: bytes, collation: int = BINARY) -> bool:
    return sort_key(a, collation) == sort_key(b, collation)


# ---------------------------------------------------------------- enum/set

def enum_name(ordinal: int, elems) -> bytes:
    """MySQL ENUM: 1-based ordinal into the definition; 0 — and any
    ordinal beyond the table (stale/corrupt row after a definition
    shrink) — is the empty ('data truncated') value, never an error."""
    if ordinal <= 0 or ordinal > len(elems):
        return b""
    name = elems[int(ordinal) - 1]
    return name if isinstance(name, bytes) else str(name).encode()


def set_names(mask: int, elems) -> bytes:
    """MySQL SET: bit i set → elems[i]; display is comma-joined in
    definition order."""
    out = []
    for i, name in enumerate(elems):
        if mask & (1 << i):
            out.append(name if isinstance(name, bytes)
                       else str(name).encode())
    return b",".join(out)


def parse_enum(name: bytes, elems, collation: int = BINARY) -> int:
    """name → 1-based ordinal (0 when absent, MySQL's coercion).
    Name resolution honors the column collation (ci / pad-space)."""
    target = sort_key(name if isinstance(name, bytes)
                      else str(name).encode(), collation)
    for i, e in enumerate(elems):
        e = e if isinstance(e, bytes) else str(e).encode()
        if sort_key(e, collation) == target:
            return i + 1
    return 0


def parse_set(text: bytes, elems, collation: int = BINARY) -> int:
    mask = 0
    if not text:
        return 0
    keys = [sort_key(e if isinstance(e, bytes) else str(e).encode(),
                     collation) for e in elems]
    for part in text.split(b","):
        pk = sort_key(part, collation)
        for i, k in enumerate(keys):
            if k == pk:
                mask |= 1 << i
    return mask


def like_regex_src(pattern: str, escape: int) -> str:
    """MySQL LIKE pattern → anchored regex SOURCE (str mode) — the ONE
    translation shared by expr/impl_like.py (ci branch) and
    myjson.search, so escape/%/_ semantics can never drift."""
    import re as _re
    esc = chr(escape & 0xFF)
    out = ["^"]
    i, n = 0, len(pattern)
    while i < n:
        ch = pattern[i]
        if ch == esc and i + 1 < n:
            out.append(_re.escape(pattern[i + 1]))
            i += 2
            continue
        if ch == "%":
            out.append("(?s:.*)")
        elif ch == "_":
            out.append("(?s:.)")
        else:
            out.append(_re.escape(ch))
        i += 1
    out.append("$")
    return "".join(out)
