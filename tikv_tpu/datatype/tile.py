"""Device tiles: static-shape padded column blocks.

XLA wants static shapes (SURVEY.md §7 "Dynamic shapes": reference batches
grow 32→1024 and the last batch is ragged — tidb_query_executors/src/
runner.rs:38-45). The device representation is therefore a *tile*: a dense
value array padded to a fixed row count plus a validity mask that doubles as
the ragged-tail mask. All device kernels take (values, validity) pairs and
are jit-compiled once per (tile_rows, dtype) bucket.

Device dtype policy (TPU v5e):
- INT  → int32 when the column fits, else int64 (XLA pair-emulates i64);
  aggregation accumulators are always int64.
- REAL → float32 values, float64 *not* used on device; SUM/AVG accumulate
  in float64-emulated pairs on host merge, and in f32 + compensation on
  device (see ops/agg.py).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Sequence

import numpy as np

from .column import Column, ColumnBatch
from .eval_type import EvalType

# Default device tile: 1 Mi rows. The reference's BATCH_MAX_SIZE is 1024
# (runner.rs:45) because its unit of work is a CPU cache tile; on TPU the
# unit of work must amortize dispatch + HBM latency, so tiles are large and
# the 8×128 VPU lanes are filled by reshaping to (rows/128, 128) internally.
TILE_ROWS = 1 << 20


def _device_dtype(eval_type: EvalType, values: np.ndarray) -> np.dtype:
    if eval_type in (EvalType.INT, EvalType.DURATION):
        if values.size and (values.min() < -(2**31) or values.max() >= 2**31):
            return np.dtype(np.int64)
        return np.dtype(np.int32)
    if eval_type is EvalType.REAL:
        return np.dtype(np.float32)
    if eval_type in (EvalType.DATETIME, EvalType.ENUM, EvalType.SET):
        return np.dtype(np.uint32) if not values.size or values.max() < 2**32 \
            else np.dtype(np.uint64)
    raise ValueError(f"{eval_type} has no device-native representation")


def pad_to_tile(values: np.ndarray, validity: np.ndarray,
                tile_rows: int = TILE_ROWS,
                dtype: Optional[np.dtype] = None) -> tuple[np.ndarray, np.ndarray]:
    """Pad a ragged column to ``tile_rows`` with invalid zero rows."""
    n = len(values)
    assert n <= tile_rows, (n, tile_rows)
    out_dtype = dtype if dtype is not None else values.dtype
    v = np.zeros(tile_rows, dtype=out_dtype)
    v[:n] = values.astype(out_dtype, copy=False)
    m = np.zeros(tile_rows, dtype=np.bool_)
    m[:n] = validity
    return v, m


@dataclass
class Tile:
    """One device-ready column block: padded values + validity mask.

    ``n_rows`` is the logical (unpadded) row count; rows >= n_rows have
    validity False.
    """

    eval_type: EvalType
    values: np.ndarray      # shape (tile_rows,), device dtype
    validity: np.ndarray    # shape (tile_rows,), bool
    n_rows: int

    @staticmethod
    def from_column(col: Column, tile_rows: int = TILE_ROWS,
                    dtype: Optional[np.dtype] = None) -> "Tile":
        dt = dtype if dtype is not None else _device_dtype(col.eval_type, col.values)
        v, m = pad_to_tile(col.values, col.validity, tile_rows, dt)
        return Tile(col.eval_type, v, m, len(col))


@dataclass
class TileBatch:
    """A batch of tiles sharing one row dimension — the unit shipped to
    device kernels. Mirrors ColumnBatch at device granularity."""

    tiles: list[Tile]
    n_rows: int
    tile_rows: int

    @staticmethod
    def from_batch(batch: ColumnBatch, tile_rows: int = TILE_ROWS) -> list["TileBatch"]:
        """Split a ColumnBatch into tile-sized chunks (last one padded).

        The device dtype is decided once per *column* (whole-column range),
        not per chunk — otherwise one column's tiles could mix int32/int64
        and defeat the per-(shape, dtype) jit cache.
        """
        dtypes = [_device_dtype(c.eval_type, c.values) for c in batch.columns]
        out = []
        for start in range(0, max(batch.num_rows, 1), tile_rows):
            chunk = batch.slice(start, min(start + tile_rows, batch.num_rows))
            tiles = [Tile.from_column(c, tile_rows, dtype=dt)
                     for c, dt in zip(chunk.columns, dtypes)]
            out.append(TileBatch(tiles, chunk.num_rows, tile_rows))
        return out


def column_chunks(values: np.ndarray, validity: np.ndarray,
                  tile_rows: int = TILE_ROWS):
    """Yield (padded_values, padded_validity, n) chunks for streaming feeds."""
    n_total = len(values)
    for start in range(0, max(n_total, 1), tile_rows):
        stop = min(start + tile_rows, n_total)
        if stop - start == tile_rows:
            yield values[start:stop], validity[start:stop], tile_rows
        else:
            v, m = pad_to_tile(values[start:stop], validity[start:stop], tile_rows)
            yield v, m, stop - start
