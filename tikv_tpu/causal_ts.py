"""Causally-ordered timestamp provider for RawKV ApiV2.

Reference: components/causal_ts/src/tso.rs — ``BatchTsoProvider`` keeps a
pre-fetched window of PD timestamps so every raw write gets a causally
ordered ts without a per-write PD round trip.  The window is renewed when
exhausted (doubling up to a cap, halving back when demand drops), and
``flush()`` discards the window and fetches a fresh one — called on region
leader transfer so the new leader's first ts exceeds anything the old
leader handed out (lib.rs ``CausalTsProvider::flush``).
"""

from __future__ import annotations

import threading
from typing import Protocol


class CausalTsProvider(Protocol):
    def get_ts(self) -> int: ...
    def flush(self) -> None: ...


class BatchTsoProvider:
    """Pre-fetched TSO window with adaptive batch sizing.

    ``pd`` needs ``tso_batch(count) -> list[int]`` (monotonic ascending)
    or falls back to per-renew ``tso()``.
    """

    DEFAULT_BATCH = 128
    MAX_BATCH = 8192

    def __init__(self, pd, init_batch: int = DEFAULT_BATCH,
                 max_batch: int = MAX_BATCH):
        self._pd = pd
        self._batch = init_batch
        self._min_batch = init_batch
        self._max_batch = max_batch
        self._lock = threading.Lock()
        self._window: list[int] = []
        self._pos = 0
        self._stale = False

    def _renew(self):
        """Fetch the next window (caller holds the lock)."""
        # adaptive sizing (tso.rs renew_tso_batch): a fully-consumed
        # window grows the next one; an under-half-used window shrinks it
        if self._window:
            used = self._pos
            if used >= len(self._window):
                self._batch = min(self._batch * 2, self._max_batch)
            elif used * 2 < len(self._window):
                self._batch = max(self._min_batch, self._batch // 2)
        fn = getattr(self._pd, "tso_batch", None)
        self._window = list(fn(self._batch)) if fn is not None \
            else [self._pd.tso()]
        self._pos = 0
        self._stale = False

    def get_ts(self) -> int:
        with self._lock:
            if self._stale or self._pos >= len(self._window):
                self._renew()
            ts = self._window[self._pos]
            self._pos += 1
            return ts

    def flush(self) -> None:
        """Discard the window and pre-fetch a fresh one.  Any ts handed
        out after flush() is greater than every PD ts allocated before
        it — the causality barrier used on region leader transfer."""
        with self._lock:
            self._renew()

    def mark_stale(self) -> None:
        """Invalidate the window WITHOUT a PD round trip: the next
        get_ts() renews (and a renew failure raises there, at the write
        that needs the ts — never swallowed).  Used from apply-path
        observers where a blocking PD call is off limits.  The true
        ``_pos`` is preserved so adaptive sizing sees real usage, not a
        faked full window."""
        with self._lock:
            self._stale = True

    @property
    def batch_size(self) -> int:
        return self._batch


from .raftstore.observer import Observer as _Observer


class CausalObserver(_Observer):
    """Invalidates the provider's window when a region BECOMES leader,
    so the new leader's first raw-write ts exceeds every ts the old
    leader used.

    Reference: components/causal_ts/src/observer.rs — registered on the
    raftstore CoprocessorHost's role-change seam.  Uses ``mark_stale``
    rather than ``flush``: the observer host swallows callback
    exceptions and runs on the apply path, so the PD renewal (and any
    renewal failure) must happen at the next get_ts() instead — where it
    blocks only the write that needs it and raises to its caller.
    """

    def __init__(self, provider):
        self._provider = provider

    def on_role_change(self, region_id: int, is_leader: bool) -> None:
        if is_leader:
            self._provider.mark_stale()
