"""Crash-recovery invariant checks for chaos schedules.

Four families, mirroring what the reference proves across its
tests/failpoints tree:

1. balance conservation — any MVCC read of the bank table sums to the
   initial total (the workload asserts it on every successful copr
   read; ``check_conservation`` asserts it per-key against the serial
   model after healing);
2. no lost acknowledged writes — every transfer whose Commit returned
   is readable at exactly its commit_ts after any crash-restart;
3. replica agreement — ComputeHash/VerifyHash across every replica of
   the region (a diverged replica raises InconsistentRegion out of the
   drive loop);
4. raft state monotonicity — per (store, region): applied/commit/term
   never regress across observations (taken at healed, quiesced
   points), and applied ≤ commit ≤ last_index.
"""

from __future__ import annotations


class InvariantViolation(AssertionError):
    pass


class RaftStateTracker:
    """Observes per-peer raft progress at quiesced points and rejects
    any regression between observations."""

    def __init__(self):
        self._seen: dict = {}

    def observe(self, cluster) -> None:
        for sid, store in cluster.stores.items():
            for rid, peer in store.peers.items():
                node = peer.node
                applied = node.applied
                commit = node.commit
                last = node.storage.last_index()
                term = node.term
                if not (applied <= commit <= last):
                    raise InvariantViolation(
                        f"store {sid} region {rid}: applied {applied} "
                        f"<= commit {commit} <= last {last} violated")
                prev = self._seen.get((sid, rid))
                if prev is not None:
                    p_applied, p_commit, p_term = prev
                    if applied < p_applied or commit < p_commit or \
                            term < p_term:
                        raise InvariantViolation(
                            f"store {sid} region {rid} regressed: "
                            f"applied {p_applied}->{applied}, commit "
                            f"{p_commit}->{commit}, term "
                            f"{p_term}->{term}")
                self._seen[(sid, rid)] = (applied, commit, term)


def check_conservation(workload) -> None:
    """Per-key model equality + total conservation through MVCC reads
    on the current leader.  Call after heal + resolve_indeterminate —
    every surviving lock has been settled, so reads cannot block."""
    st = workload._storage()
    ts = workload._tso()
    total = 0
    for handle, key in enumerate(workload.keys):
        raw = st.get(key, ts)
        if raw is None:
            raise InvariantViolation(f"account {handle} vanished")
        bal = workload._balance(raw)
        want = workload.balances[handle]
        if bal != want:
            raise InvariantViolation(
                f"account {handle}: engine {bal} != model {want}")
        total += bal
    if total != workload.expected_total:
        raise InvariantViolation(
            f"sum {total} != expected {workload.expected_total}")


def check_no_lost_acks(workload) -> None:
    """Every acknowledged transfer is readable at exactly its
    commit_ts — acked writes survive crashes, partitions, restarts."""
    st = workload._storage()
    for rec in workload.acked:
        for key, value in rec["pairs"]:
            got = st.get(key, rec["commit_ts"])
            if got != value:
                raise InvariantViolation(
                    f"acked write at ts {rec['commit_ts']} lost for "
                    f"{key!r}: engine {got!r} != acked {value!r}")


def check_replica_consistency(cluster, region_id: int = 1) -> int:
    """ComputeHash on the leader, VerifyHash applied by every replica;
    a diverged replica raises InconsistentRegion.  → the digest."""
    return cluster.check_consistency(region_id)


# ------------------------------------------- overload / tail invariants
#
# A deadline-bounded point-read workload records one dict per op:
#   {"key":..., "value":..., "ok": bool, "elapsed": s, "deadline_s": s}
# The three checks below are the brownout contract: acked responses are
# timely (never produced from expired work), correct (hedging/stale
# reads never violate the linearizable guarantee), and goodput does not
# collapse while a store is merely SLOW rather than dead.


def check_no_late_acks(results, slack_s: float = 0.0) -> None:
    """No acknowledged response arrived after its deadline.  The server
    sheds expired work with DeadlineExceeded; ``slack_s`` absorbs
    client-side wire/scheduling overhead on top of the server check."""
    for r in results:
        if r["ok"] and r["elapsed"] > r["deadline_s"] + slack_s:
            raise InvariantViolation(
                f"acked read of {r['key']!r} took "
                f"{r['elapsed'] * 1e3:.1f}ms against a "
                f"{r['deadline_s'] * 1e3:.0f}ms deadline (+slack) — "
                "late work was acknowledged")


def check_read_correctness(results, model: dict) -> None:
    """Every acknowledged read returned the model value — a hedged or
    stale-served response that shows anything else broke the
    linearizable-read guarantee (read_ts ≤ resolved_ts on follower
    serves is the rule that keeps this true)."""
    for r in results:
        if r["ok"] and r["value"] != model[r["key"]]:
            raise InvariantViolation(
                f"read of {r['key']!r} returned {r['value']!r}, "
                f"model holds {model[r['key']]!r}")


def check_hbm_within_budget(runner) -> None:
    """Device-state integrity: the feed arena's resident bytes never
    exceed the configured HBM budget — admission/eviction holds under
    churn, splits, and hbm_oom squeezes (unpinned lines evict; a feed
    that cannot fit serves transiently and is never retained)."""
    st = runner.hbm_stats()
    # pinned bytes are in use by launched kernels and CANNOT be
    # reclaimed until their fetch completes — the cap may be exceeded
    # by at most that much, never by evictable state
    slack = st.get("pinned_bytes", 0)
    if st["budget_bytes"] > 0 and \
            st["resident_bytes"] > st["budget_bytes"] + slack:
        raise InvariantViolation(
            f"HBM resident {st['resident_bytes']}B exceeds the "
            f"{st['budget_bytes']}B budget "
            f"(+{slack}B pinned slack; {st['resident_lines']} lines, "
            f"{st['pinned_lines']} pinned)")


def check_no_stale_epoch(node) -> None:
    """Every resident columnar cache line belongs to a region this node
    still hosts, at that region's CURRENT epoch — lifecycle teardown
    (split/merge/leader loss/destroy) left no stale-epoch line behind
    to serve a superseded key range."""
    current = {rid: p.region.epoch.version
               for rid, p in node.raft_store.peers.items()}
    for ln in node.copr_cache.stats()["lines"]:
        want = current.get(ln["region"])
        if want is None or ln["epoch"] != want:
            raise InvariantViolation(
                f"stale cache line: region {ln['region']} epoch "
                f"{ln['epoch']} (current: {want})")


def check_scrub_clean(supervisor) -> None:
    """A quiesced, healed system scrubs clean: every resident device
    plane re-hashes to its recorded digest (any injected corruption was
    caught, quarantined, and rebuilt before this point)."""
    out = supervisor.scrub()
    if out["divergences"]:
        raise InvariantViolation(
            f"scrub found {out['divergences']} diverged line(s) after "
            f"heal: {out}")


def check_no_quarantined_dispatch(runner) -> None:
    """Chip failure domains: no device kernel ever LAUNCHED on a slice
    while it was quarantined (the dispatch gate refused instead — the
    request degraded or rescued), and a quarantined slice holds no
    resident feed lines (the drain actually ran and nothing re-uploaded
    onto a condemned chip).  Call at a quiesced point — an in-flight
    upload racing the trip is exactly what this hunts."""
    board = getattr(runner, "_board", None)
    if board is None:
        return
    for s in board.stats():
        if s["launched_quarantined"]:
            raise InvariantViolation(
                f"slice {s['slice']} launched "
                f"{s['launched_quarantined']} dispatch(es) while "
                f"quarantined (score {s['score']}, strikes "
                f"{s['strikes']})")
    placer = getattr(runner, "placer", None)
    if placer is not None:
        for i in board.quarantined_set():
            # bytes, not entry count: a refused request's empty memo
            # bucket is host bookkeeping; FEED bytes on a condemned
            # chip are the leak this hunts
            nbytes = placer.slices[i]._arena.resident_bytes()
            if nbytes:
                raise InvariantViolation(
                    f"quarantined slice {i} still holds {nbytes} "
                    f"resident feed byte(s) — the drain leaked")


def check_mesh_serves_degraded(records, device_floor: float = 0.5
                               ) -> None:
    """Elastic mesh degrade contract: while a chip is quarantined the
    system keeps SERVING — zero wrong results, zero late acks, and at
    least ``device_floor`` of the warm stream still answers from the
    device backend (surviving slices / healthy submesh), because
    "everything falls back to host" is not a survivable steady state
    (the host link cannot absorb a mesh's traffic — Jouppi cost model).

    ``records``: one dict per warm request observed DURING the degrade,
    ``{"backend": "device"|"host", "wrong": bool, "late": bool}``.
    """
    if not records:
        raise InvariantViolation("no requests observed during degrade")
    for i, r in enumerate(records):
        if r.get("wrong"):
            raise InvariantViolation(
                f"request {i} returned a WRONG result during mesh "
                "degrade")
        if r.get("late"):
            raise InvariantViolation(
                f"request {i} was acknowledged after its deadline "
                "during mesh degrade")
    dev = sum(1 for r in records if r.get("backend") == "device")
    frac = dev / len(records)
    if frac < device_floor:
        raise InvariantViolation(
            f"only {frac:.0%} ({dev}/{len(records)}) of warm requests "
            f"served from the device during degrade (floor "
            f"{device_floor:.0%}) — the mesh collapsed to the host "
            "rung instead of its healthy submesh")


def _pct(values, q: float) -> float:
    """Nearest-rank percentile over a non-empty sequence (no numpy —
    the invariants module stays dependency-free)."""
    s = sorted(values)
    idx = min(len(s) - 1, max(0, round(q / 100.0 * (len(s) - 1))))
    return s[idx]


def check_fg_latency_bounded(fg_results, baseline_p99_s: float,
                             factor: float = 1.5,
                             slack_s: float = 0.05) -> None:
    """Multi-tenant resource-control contract, foreground half: with
    a background group storming, the foreground group's P99 stays
    within ``factor`` of its measured SOLO baseline (+``slack_s`` of
    scheduling noise) — the enforcement sites actually isolated the
    latency tenant instead of letting the storm monopolize the
    coalescer lanes, the arena, and the read-pool slots."""
    lats = [r["elapsed"] for r in fg_results if r.get("ok")]
    if not lats:
        raise InvariantViolation(
            "no foreground requests served during the storm — the "
            "latency tenant was starved outright")
    p99 = _pct(lats, 99)
    bound = factor * baseline_p99_s + slack_s
    if p99 > bound:
        raise InvariantViolation(
            f"foreground P99 {p99 * 1e3:.1f}ms exceeds "
            f"{factor}x solo baseline "
            f"{baseline_p99_s * 1e3:.1f}ms (+{slack_s * 1e3:.0f}ms "
            "slack) under a background storm — enforcement failed to "
            "protect the latency tenant")


def check_bg_not_starved(bg_results,
                         min_served_fraction: float = 0.2) -> None:
    """Multi-tenant resource-control contract, background half: a
    throttled group is THROTTLED, not starved — at least
    ``min_served_fraction`` of its requests eventually complete
    (deferral re-parks and the shed hint's retry-after both promise
    forward progress; zero completions means something dropped work
    on the floor)."""
    if not bg_results:
        raise InvariantViolation("no background requests attempted")
    ok = sum(1 for r in bg_results if r.get("ok"))
    frac = ok / len(bg_results)
    if ok == 0 or frac < min_served_fraction:
        raise InvariantViolation(
            f"background group served only {frac:.0%} "
            f"({ok}/{len(bg_results)}) of its requests (floor "
            f"{min_served_fraction:.0%}) — throttling degenerated "
            "into starvation")


def check_no_cold_rebuild_on_serving_path(before, after,
                                          supervisor=None) -> None:
    """Warm-failover contract: across a failover window (leader kill
    or transfer, slice trip/drain, store quarantine) the serving path
    minted NO cold columnar line — promotion re-verified the already-
    patched replica feed against its scrub digests, it never ran a
    ``columnar_build``.  ``before``/``after`` are
    ``RegionColumnarCache.stats()`` snapshots bracketing the window;
    ``supervisor`` (optional) additionally proves no promotion failed
    digest re-verify and fell back to an invalidating rebuild."""
    for ctr in ("misses", "rebuilds", "device_builds"):
        if after.get(ctr, 0) > before.get(ctr, 0):
            raise InvariantViolation(
                f"cold build on the serving path: cache counter "
                f"{ctr!r} grew {before.get(ctr, 0)} -> "
                f"{after.get(ctr, 0)} across the failover window")
    if supervisor is not None and \
            getattr(supervisor, "promotion_rebuilds", 0):
        raise InvariantViolation(
            f"{supervisor.promotion_rebuilds} promotion(s) failed "
            "scrub-digest re-verify and fell back to an invalidating "
            "rebuild during the failover window")


def check_no_remint_on_move(before, after, placer_stats=None) -> None:
    """Elastic-lifecycle contract: across a placement move window (a
    rebalance, a drain, a join co-location pull) the host minted NO
    new columnar line — the resident feed MIGRATED over ICI, digests
    and journal position traveling with it.  ``before``/``after`` are
    ``RegionColumnarCache.stats()`` snapshots bracketing the window;
    ``placer_stats`` (optional, ``SlicePlacer.stats()``) additionally
    proves at least one migration actually happened and none failed
    arrival re-verify into the rebuild fallback."""
    for ctr in ("misses", "rebuilds", "device_builds"):
        if after.get(ctr, 0) > before.get(ctr, 0):
            raise InvariantViolation(
                f"re-mint on a placement move: cache counter {ctr!r} "
                f"grew {before.get(ctr, 0)} -> {after.get(ctr, 0)} "
                f"across the move window")
    if placer_stats is not None:
        if not placer_stats.get("migrations", 0):
            raise InvariantViolation(
                "no ICI migration recorded across the move window — "
                "the move must have dropped and re-minted instead")
        if placer_stats.get("migration_failures", 0):
            raise InvariantViolation(
                f"{placer_stats['migration_failures']} migration(s) "
                "failed and fell back to drop-and-re-mint during the "
                "move window")


def check_remint_concurrency_bounded(governor_stats, bound) -> None:
    """Re-mint storm-control contract: across a mass-invalidation (a
    split storm, a quarantine drain) the host never ran more than
    ``bound`` columnar rebuilds concurrently — the governor queued or
    shed the rest.  ``governor_stats`` is ``RemintGovernor.stats()``;
    ``observed_max`` is its high-water mark of simultaneously admitted
    rebuilds."""
    seen = governor_stats.get("observed_max", 0)
    if seen > bound:
        raise InvariantViolation(
            f"re-mint concurrency exceeded its bound: observed "
            f"{seen} simultaneous rebuilds > limit {bound}")


def check_replica_read_correctness(leader_rows, follower_rows) -> None:
    """Replica-read answer parity: a follower-served coprocessor read
    at read_ts ≤ resolved_ts returns EXACTLY what the leader serves
    for the same request at the same timestamp — the resolved-ts gate
    plus the shared per-region delta stream make follower feeds
    indistinguishable from the leader's, and any divergence is a
    consistency hole, not a performance bug."""
    if len(leader_rows) != len(follower_rows):
        raise InvariantViolation(
            f"replica read row-count mismatch: leader "
            f"{len(leader_rows)} != follower {len(follower_rows)}")
    for i, (a, b) in enumerate(zip(leader_rows, follower_rows)):
        if a != b:
            raise InvariantViolation(
                f"replica read diverged at row {i}: leader {a!r} != "
                f"follower {b!r}")


def check_goodput(results, floor: float) -> None:
    """The served fraction stays above ``floor`` during the brownout —
    fail-slow must not degrade into fail-stop."""
    if not results:
        raise InvariantViolation("no reads attempted")
    ok = sum(1 for r in results if r["ok"])
    frac = ok / len(results)
    if frac < floor:
        raise InvariantViolation(
            f"goodput {frac:.2%} ({ok}/{len(results)}) below the "
            f"{floor:.0%} brownout floor")
