"""Nemesis — seeded fault schedules against the in-process cluster.

Reference shape: Jepsen's nemesis process + the reference's
tests/failpoints/cases/ steering (fail::cfg from the test body).  A
``Fault`` is pure data; ``generate_schedule(seed, ...)`` derives a
reproducible fault sequence from one RNG; ``Nemesis`` applies a fault
to a ``testing.cluster.Cluster`` (transport filters, failpoint actions,
crash-restart via FailpointPanic at a crash boundary) and heals it.
"""

from __future__ import annotations

import random
from dataclasses import dataclass
from typing import Sequence

from ..engine.traits import CF_DEFAULT
from ..raftstore.cmd import RaftCmd, WriteOp
from ..utils import failpoint
from ..utils.failpoint import FailpointPanic

FAULT_KINDS = ("partition", "asym_partition", "leader_isolate",
               "crash_restart", "msg_chaos", "disk_stall", "fail_slow")

# device faults (opt-in: schedules against device-serving rigs pass
# them explicitly — the in-process raft cluster has no accelerator):
# hbm_squeeze arms device::hbm_oom so the feed arena's effective budget
# collapses (eviction pressure / transient feeds); feed_corrupt arms
# device::feed_corrupt so the next scrub pass bit-flips a resident
# plane and must catch it; d2h_corrupt arms device::d2h_corrupt so a
# fraction of fetches surface as detected transfer corruption and
# degrade to the host pipeline; shard_launch arms device::shard_launch
# so a fraction of SHARDED mesh dispatches fail one shard's enqueue —
# the whole plan must degrade to host (never a partial per-shard
# answer) without wedging the serialized dispatch stream; slice_dead
# arms device::slice_dead PERSISTENTLY against one slice (a chip gone
# for the fault's whole duration — the failure-domain supervisor must
# quarantine it, drain its anchors, downsize whole-mesh serving to the
# largest healthy submesh, and re-admit after heal); chip_flap arms
# the same site at a percentage AND faults the degrade path itself
# (device::mesh_rebuild) — the nastiest mix: strikes accumulate and
# decay while the downsize that would route around them intermittently
# fails to the host rung; device_degrade arms one of the plain
# degrade-to-host sites (DEGRADE_SITES) at a percentage so every
# device::* site sees nemesis traffic
DEVICE_FAULT_KINDS = ("hbm_squeeze", "feed_corrupt", "d2h_corrupt",
                      "shard_launch", "slice_dead", "chip_flap",
                      "device_degrade")

# plan-IR faults (kept OUT of DEVICE_FAULT_KINDS so existing seeded
# device-chaos schedules stay byte-identical): plan_fault arms BOTH
# plan-path sites — device::join_dispatch (a device join fragment's
# probe dispatch fails → the executor host-joins THAT fragment only)
# and copr::plan_route (the fragment router is forced to route the
# whole request host) — at a percentage, so mixed-fragment plans see
# both the per-fragment degrade and the all-host path under chaos
PLAN_FAULT_KINDS = ("plan_fault",)

# multi-tenant faults (their own tuple, same seeded-schedule-stability
# reason): tenant_storm floods ONE resource group's RU ledger — a
# burst of measured charges lands on the storm group through the
# metering recorder, driving its token bucket deep into debt exactly
# as a real request flood would have priced it — while the foreground
# group keeps serving.  Every enforcement site (coalescer DWFQ, arena
# eviction bias, read-pool shed) must then throttle the storm group
# WITHOUT starving it (check_bg_not_starved) and hold the foreground
# group's latency bounded (check_fg_latency_bounded).  The
# copr::rc_throttle failpoint is the surgical sibling: force-throttle
# one named group with no load at all.
TENANT_FAULT_KINDS = ("tenant_storm",)

# microsecond-warm-path faults (own tuple, seeded-schedule stability):
# fastpath_fault arms the copr::fastpath site with one of its three
# arms — force-miss (every request takes the full decode path),
# force-full-decode (same, but counted distinctly so a schedule can
# tell deliberate bypass from template misses), or corrupt-fingerprint
# (a cached template's fixed segment is bit-flipped IN PLACE before
# matching).  The invariant under all three: wrong answers are
# IMPOSSIBLE — the corrupted/missed template can only fail to match,
# which routes the request to the full decode path; chaos schedules
# assert responses stay byte-equal to an unfaulted control.
FASTPATH_FAULT_KINDS = ("fastpath_fault",)

# replicated-device-serving faults (own tuple, seeded-schedule
# stability): leader_kill crash-kills the region's CURRENT leader
# store and restarts it over its surviving engine (resolved at apply
# time, like leader_isolate — but the process actually dies), so the
# election hands leadership to a follower whose already-patched
# replica feed must be PROMOTED warm (resolved-ts catch-up + scrub-
# digest re-verify) — never re-minted on the serving path
# (check_no_cold_rebuild_on_serving_path).  replica_lag arms
# device::replica_stale at a percentage so the follower stale-read
# freshness gate refuses with DataIsNotReady — hedged device legs and
# direct replica reads must fall through to the leader with byte-
# identical answers (check_replica_read_correctness), never serve
# from behind the resolved-ts watermark.
REPLICA_FAULT_KINDS = ("leader_kill", "replica_lag")

# elastic-feed-lifecycle faults (own tuple, seeded-schedule
# stability): migrate_fault arms device::feed_migrate at a percentage
# so a plane transferred over ICI arrives bit-flipped — the arrival
# re-verify on the destination slice must catch EVERY corrupted
# transfer (drop the partial install, quarantine the source anchor,
# rebuild from host) and never serve a silently-wrong plane.
# split_storm arms device::device_split at a percentage so the
# device-side region split falls back to host re-mint for the child
# regions — under a storm of such fallbacks the re-mint governor must
# bound concurrent columnar rebuilds
# (check_remint_concurrency_bounded) while moves that CAN migrate
# still mint nothing (check_no_remint_on_move).
ELASTIC_FAULT_KINDS = ("migrate_fault", "split_storm")

# the plain degrade-to-host failpoint sites the device_degrade nemesis
# rotates over; the remaining device::* sites have dedicated kinds
# above (the inventory test asserts the union covers EVERY device::*
# site in the tree, so a new site needs a nemesis before it ships)
DEGRADE_SITES = ("device::before_feed_upload", "device::before_dispatch",
                 "device::before_fetch", "device::mvcc_resolve")

# crash boundaries: a ``panic`` here unwinds out of the drive loop like
# a process kill at that point of the write path (the same boundaries
# the reference's failpoint cases crash at)
CRASH_SITES = ("apply::before_write", "apply::after_write",
               "raftlog::before_persist")


@dataclass(frozen=True)
class Fault:
    kind: str
    params: tuple = ()      # sorted (key, value) pairs — hashable

    def param(self, key, default=None):
        return dict(self.params).get(key, default)


def _mk(kind: str, **params) -> Fault:
    return Fault(kind, tuple(sorted(params.items())))


def generate_schedule(seed: int, steps: int,
                      kinds: Sequence[str] = FAULT_KINDS,
                      n_stores: int = 3,
                      n_slices: int = 8) -> list[Fault]:
    """Derive a reproducible fault schedule from one seed.
    ``n_slices`` bounds the slice indices chip-death faults target."""
    rng = random.Random(seed)
    stores = list(range(1, n_stores + 1))
    out: list[Fault] = []
    for _ in range(steps):
        kind = rng.choice(tuple(kinds))
        if kind in ("partition", "asym_partition"):
            shuffled = stores[:]
            rng.shuffle(shuffled)
            cut = rng.randint(1, n_stores - 1)
            out.append(_mk(kind, group_a=tuple(sorted(shuffled[:cut])),
                           group_b=tuple(sorted(shuffled[cut:]))))
        elif kind == "leader_isolate":
            out.append(_mk(kind))       # leader resolved at apply time
        elif kind == "crash_restart":
            out.append(_mk(kind, store=rng.choice(stores),
                           site=rng.choice(CRASH_SITES)))
        elif kind == "msg_chaos":
            out.append(_mk(kind,
                           delay_p=round(rng.uniform(0.05, 0.3), 2),
                           dup_p=round(rng.uniform(0.0, 0.15), 2),
                           reorder=True))
        elif kind == "disk_stall":
            out.append(_mk(kind, ms=rng.choice((2, 5, 10))))
        elif kind == "fail_slow":
            out.append(_mk(kind, store=rng.choice(stores),
                           ms=rng.choice((10, 20, 40))))
        elif kind == "hbm_squeeze":
            out.append(_mk(kind, bytes=rng.choice((0, 1 << 16, 1 << 20))))
        elif kind == "feed_corrupt":
            out.append(_mk(kind))
        elif kind == "d2h_corrupt":
            out.append(_mk(kind, pct=rng.choice((25, 50, 100))))
        elif kind == "shard_launch":
            out.append(_mk(kind, pct=rng.choice((25, 50, 100))))
        elif kind == "slice_dead":
            out.append(_mk(kind, slice=rng.randrange(n_slices)))
        elif kind == "chip_flap":
            out.append(_mk(kind, slice=rng.randrange(n_slices),
                           pct=rng.choice((25, 50, 75))))
        elif kind == "device_degrade":
            out.append(_mk(kind, site=rng.choice(DEGRADE_SITES),
                           pct=rng.choice((25, 50, 100))))
        elif kind == "plan_fault":
            out.append(_mk(kind, pct=rng.choice((25, 50, 100)),
                           route_pct=rng.choice((0, 25, 50))))
        elif kind == "tenant_storm":
            out.append(_mk(kind, group="storm",
                           ru=rng.choice((2000.0, 5000.0, 10000.0))))
        elif kind == "fastpath_fault":
            out.append(_mk(kind, arm=rng.choice(("miss", "full",
                                                 "corrupt")),
                           pct=rng.choice((25, 50, 100))))
        elif kind == "leader_kill":
            out.append(_mk(kind))   # leader resolved at apply time
        elif kind == "replica_lag":
            out.append(_mk(kind, pct=rng.choice((25, 50, 100))))
        elif kind == "migrate_fault":
            out.append(_mk(kind, pct=rng.choice((25, 50, 100))))
        elif kind == "split_storm":
            out.append(_mk(kind, pct=rng.choice((25, 50, 100))))
        else:   # pragma: no cover
            raise ValueError(kind)
    return out


class Nemesis:
    """Applies/heals one fault at a time against a Cluster."""

    def __init__(self, cluster, seed: int = 0, region_id: int = 1):
        self.cluster = cluster
        self.region_id = region_id
        self.rng = random.Random(seed)
        self._heals: list = []
        self._probe_n = 0
        self.crashes = 0        # crash boundaries actually hit

    # ------------------------------------------------------------- apply

    def apply(self, fault: Fault) -> None:
        getattr(self, f"_apply_{fault.kind}")(fault)

    def heal(self) -> None:
        while self._heals:
            self._heals.pop()()

    def _apply_partition(self, fault: Fault) -> None:
        filt = self.cluster.partition(fault.param("group_a"),
                                      fault.param("group_b"))
        self._heals.append(lambda: self.cluster.heal(filt))

    def _apply_asym_partition(self, fault: Fault) -> None:
        filt = self.cluster.partition_oneway(fault.param("group_a"),
                                             fault.param("group_b"))
        self._heals.append(lambda: self.cluster.heal(filt))

    def _apply_leader_isolate(self, fault: Fault) -> None:
        sid = self.cluster.leader_store(self.region_id)
        if sid is None:
            sid = self.rng.choice(sorted(self.cluster.stores))
        filt = self.cluster.isolate_store(sid)
        self._heals.append(lambda: self.cluster.heal(filt))

    def _apply_msg_chaos(self, fault: Fault) -> None:
        t = self.cluster.transport
        t.set_chaos(self.rng, delay_p=fault.param("delay_p", 0.0),
                    dup_p=fault.param("dup_p", 0.0),
                    reorder=fault.param("reorder", False))
        self._heals.append(t.clear_chaos)

    def _apply_fail_slow(self, fault: Fault) -> None:
        """Persistent per-store brownout — distinct from the transient
        global ``disk_stall``: ONE store's write AND read paths gain a
        fixed latency (RaftStore.slow_down) that persists until heal,
        the fail-*slow* mode the slow-score control loop is built to
        detect (a sick disk, a throttled VM, a saturated NIC)."""
        sid = fault.param("store")
        ms = fault.param("ms", 20)
        store = self.cluster.stores.get(sid)
        if store is None:
            return
        store.slow_down(ms / 1000.0)

        def heal(sid=sid):
            # crash_restart may have replaced the store object: always
            # heal whatever currently answers to the id
            cur = self.cluster.stores.get(sid)
            if cur is not None:
                cur.slow_down(0.0)
        self._heals.append(heal)

    # -- device faults: armed via failpoints; the device-state
    #    supervisor (budget/eviction, scrub+quarantine, degrade-to-host
    #    fetches) must keep every served answer correct under them

    def _apply_hbm_squeeze(self, fault: Fault) -> None:
        failpoint.cfg("device::hbm_oom",
                      f"return({fault.param('bytes', 0)})")
        self._heals.append(lambda: failpoint.remove("device::hbm_oom"))

    def _apply_feed_corrupt(self, fault: Fault) -> None:
        # 1*return: exactly one resident plane takes the bit-flip; the
        # scrub pass that trips it must detect + quarantine
        failpoint.cfg("device::feed_corrupt", "1*return")
        self._heals.append(
            lambda: failpoint.remove("device::feed_corrupt"))

    def _apply_d2h_corrupt(self, fault: Fault) -> None:
        pct = fault.param("pct", 100)
        failpoint.cfg("device::d2h_corrupt", f"{pct}%return")
        self._heals.append(
            lambda: failpoint.remove("device::d2h_corrupt"))

    def _apply_shard_launch(self, fault: Fault) -> None:
        pct = fault.param("pct", 100)
        failpoint.cfg("device::shard_launch", f"{pct}%return")
        self._heals.append(
            lambda: failpoint.remove("device::shard_launch"))

    def _apply_slice_dead(self, fault: Fault) -> None:
        """Persistent chip death: every dispatch/fetch/canary touching
        the targeted slice fails until heal.  The failure-domain
        supervisor must quarantine it, drain its placed anchors,
        downsize whole-mesh sharded serving (healthy_submesh), rescue
        in-flight work — and only RE-ADMIT after this heals."""
        failpoint.cfg("device::slice_dead",
                      f"return({fault.param('slice', 0)})")
        self._heals.append(
            lambda: failpoint.remove("device::slice_dead"))

    def _apply_chip_flap(self, fault: Fault) -> None:
        """Flapping chip: the slice dies intermittently (pct%) while
        the mesh-degrade path ITSELF faults some of the time — strikes
        accumulate and decay, half-open probes race re-deaths, and a
        failed rebuild must land on the host rung, never wedge."""
        pct = fault.param("pct", 50)
        failpoint.cfg("device::slice_dead",
                      f"{pct}%return({fault.param('slice', 0)})")
        failpoint.cfg("device::mesh_rebuild", f"{min(pct, 25)}%return")
        self._heals.append(
            lambda: (failpoint.remove("device::slice_dead"),
                     failpoint.remove("device::mesh_rebuild")))

    def _apply_device_degrade(self, fault: Fault) -> None:
        """One plain degrade-to-host site (DEGRADE_SITES) fires at a
        percentage — the answer must stay correct, just host-served."""
        site = fault.param("site", DEGRADE_SITES[0])
        failpoint.cfg(site, f"{fault.param('pct', 100)}%return")
        self._heals.append(lambda s=site: failpoint.remove(s))

    def _apply_fastpath_fault(self, fault: Fault) -> None:
        """Arm one copr::fastpath arm (FASTPATH_FAULT_KINDS doc): the
        fast path must fall back to the full decode path under every
        arm — a corrupted template can only fail to match, never
        mis-extract, so wrong answers are impossible by construction
        (the chaos run's answer-parity invariant asserts it)."""
        arm = fault.param("arm", "miss")
        pct = fault.param("pct", 100)
        failpoint.cfg("copr::fastpath", f"{pct}%return({arm})")
        self._heals.append(lambda: failpoint.remove("copr::fastpath"))

    def _apply_leader_kill(self, fault: Fault) -> None:
        """Crash-kill the CURRENT leader store of ``region_id`` and
        restart it over its surviving engine — the election that
        follows hands leadership to a follower, and the device layer
        must promote that follower's already-patched replica feed
        instead of cold-building a new line on the serving path."""
        sid = self.cluster.leader_store(self.region_id)
        if sid is None:
            sid = self.rng.choice(sorted(self.cluster.stores))
        self.cluster.restart_store(sid)

    def _apply_migrate_fault(self, fault: Fault) -> None:
        """Bit-flip a fraction of ICI feed migrations in flight: the
        destination's arrival digest re-verify must reject the install
        (quarantine + rebuild), never serve the corrupted plane."""
        pct = fault.param("pct", 100)
        failpoint.cfg("device::feed_migrate", f"{pct}%return")
        self._heals.append(
            lambda: failpoint.remove("device::feed_migrate"))

    def _apply_split_storm(self, fault: Fault) -> None:
        """Force a fraction of device-side region splits to fall back
        to host re-mint — the re-mint governor must bound the rebuild
        concurrency the resulting storm creates."""
        pct = fault.param("pct", 100)
        failpoint.cfg("device::device_split", f"{pct}%return")
        self._heals.append(
            lambda: failpoint.remove("device::device_split"))

    def _apply_replica_lag(self, fault: Fault) -> None:
        """Lagging replica: device::replica_stale forces the follower
        stale-read freshness gate to refuse (DataIsNotReady) at pct% —
        hedged device legs and direct replica reads must fall through
        to the leader, never answer from behind the resolved-ts
        watermark."""
        pct = fault.param("pct", 100)
        failpoint.cfg("device::replica_stale", f"{pct}%return")
        self._heals.append(
            lambda: failpoint.remove("device::replica_stale"))

    def _apply_tenant_storm(self, fault: Fault) -> None:
        """One tenant's request flood, modeled at the RU ledger: a
        burst of measured host-wall charges lands on the storm group
        through the metering recorder — the same stream the resource
        controller's token buckets drain from — so the group goes
        into debt exactly as if the flood's requests had run, without
        needing a gRPC client stack inside the in-process harness.
        The enforcement sites then see a flooding tenant (deep debt,
        high recent-RU rate) while the foreground workload keeps
        serving; heal is organic (the bucket refills at the group's
        share — throttled, not starved, by construction)."""
        from ..resource_metering import (
            GLOBAL_RECORDER,
            ResourceTagFactory,
        )
        from ..ru_model import GLOBAL_MODEL
        group = fault.param("group", "storm")
        ru = float(fault.param("ru", 5000.0))
        w = GLOBAL_MODEL.weights()["ru_per_host_s"]
        host_s = ru / w if w > 0 else 0.0
        tag = ResourceTagFactory.tag(group, "storm")
        with GLOBAL_RECORDER.attach(tag, requests=0):
            GLOBAL_RECORDER.charge("read_pool::host", host_s=host_s)

    def _apply_plan_fault(self, fault: Fault) -> None:
        """Plan-IR fault mix: device::join_dispatch fails a device
        join fragment's probe dispatch at pct% — the plan executor
        must host-join that FRAGMENT only, the plan's other fragments
        keep their routes — while copr::plan_route (route_pct%) forces
        whole-request host routing.  Answers stay correct under both."""
        failpoint.cfg("device::join_dispatch",
                      f"{fault.param('pct', 100)}%return")
        rp = fault.param("route_pct", 0)
        if rp:
            failpoint.cfg("copr::plan_route", f"{rp}%return")
        self._heals.append(
            lambda: (failpoint.remove("device::join_dispatch"),
                     failpoint.remove("copr::plan_route")))

    def _apply_disk_stall(self, fault: Fault) -> None:
        ms = fault.param("ms", 5)
        # the WAL site stalls DiskEngine-backed stores at the fsync
        # boundary; the apply site stalls the engine write for
        # MemoryEngine clusters — both model a slow device, healed
        # together
        failpoint.cfg("wal::fsync_stall", f"sleep({ms})")
        failpoint.cfg("apply::before_write", f"sleep({ms})")
        self._heals.append(lambda: (failpoint.remove("wal::fsync_stall"),
                                    failpoint.remove("apply::before_write")))

    # -- crash-restart: FailpointPanic at a crash boundary, then the
    #    store is recreated over its surviving engine (the process-kill
    #    + restart cycle of the reference's failpoint crash cases).

    def _apply_crash_restart(self, fault: Fault) -> None:
        c = self.cluster
        victim = fault.param("store")
        site = fault.param("site", CRASH_SITES[0])
        if victim not in c.stores:
            return
        self._probe_write()
        crashed = False
        for _ in range(15):
            # healthy stores drive with the site unarmed...
            for sid in list(c.stores):
                if sid != victim:
                    try:
                        c.stores[sid].drive()
                    except FailpointPanic:  # pragma: no cover - scoped off
                        pass
            c.transport.route_all()
            # ...then the victim drives with the crash site armed, so
            # the panic fires inside ITS apply/persist path only
            failpoint.cfg(site, "panic")
            try:
                if victim in c.stores:
                    c.stores[victim].drive()
            except FailpointPanic:
                crashed = True
            finally:
                failpoint.remove(site)
            c.transport.route_all()
            if crashed:
                break
        if crashed:
            self.crashes += 1
        # even if the boundary was never reached (no traffic routed to
        # the victim under the current fault mix) the schedule still
        # crash-restarts it — a kill needs no cooperation
        c.restart_store(victim)

    def _probe_write(self) -> None:
        """Nudge a write through region ``region_id`` so the crash
        boundary sees traffic (proposed fire-and-forget; the nemesis
        drives routing itself)."""
        c = self.cluster
        peer = c.leader_peer(self.region_id)
        if peer is None:
            return
        self._probe_n += 1
        key = b"zz~nemesis~%06d" % self._probe_n
        cmd = RaftCmd(peer.region.id, peer.region.epoch,
                      (WriteOp("put", CF_DEFAULT, key, b"probe"),))
        try:
            peer.propose(cmd, lambda r: None)
        except Exception:   # noqa: BLE001 — no leader right now is fine
            pass


def stabilize(cluster, region_id: int = 1, rounds: int = 80) -> None:
    """Drive a healed cluster until a leader exists, the transport has
    drained, and every replica of ``region_id`` applied to the same
    index — the quiesced point invariant checks observe at."""
    for _ in range(rounds):
        try:
            cluster.pump(max_rounds=100)
        except RuntimeError:
            pass
        lead = cluster.leader_store(region_id)
        if lead is not None and not cluster.transport.queue:
            applied = {p.node.applied
                       for s in cluster.stores.values()
                       for rid, p in s.peers.items() if rid == region_id}
            if len(applied) == 1:
                return
        for store in list(cluster.stores.values()):
            store.tick()
    raise TimeoutError(f"cluster did not stabilize for {region_id}")
