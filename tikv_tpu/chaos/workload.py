"""Bank-transfer + coprocessor workload for chaos schedules.

The classic Jepsen bank test shape over the full stack: accounts are
rows of a fixture table; transfers are Percolator 2PC transactions
(Prewrite → Commit) through the txn scheduler over RaftKv, so every
operation crosses gRPC-shaped routing, raft consensus, MVCC, and the
engine.  The workload keeps a serial model plus an op journal:

- ``acked``: transfers whose Commit returned — these MUST survive any
  fault (the no-lost-acknowledged-writes invariant);
- ``indeterminate``: transfers that errored mid-2PC — the commit may or
  may not have landed; ``resolve_indeterminate`` settles them through
  CheckTxnStatus/ResolveLockLite/Rollback exactly like a client-go
  resolver, folding resolved commits back into the model.

Coprocessor reads run SUM(balance) through the same
BatchExecutorsRunner pipeline the copr endpoint uses — any successful
read, even mid-fault, must observe the conserved total.
"""

from __future__ import annotations

import random
from typing import Optional

from ..codec.row import decode_row, encode_row
from ..codec.keys import table_record_key
from ..copr.storage_impl import MvccScanStorage
from ..executors.runner import BatchExecutorsRunner
from ..kv.engine import SnapContext
from ..raftstore import RaftKv
from ..storage import Storage
from ..storage.mvcc.errors import KeyIsLocked
from ..storage.mvcc.reader import MvccReader
from ..storage.txn import commands as cmds
from ..storage.txn.actions import Mutation
from ..testing.dag import DagSelect
from ..testing.fixture import int_table

BALANCE_COL_ID = 2      # int_table: id (pk, col 1) + c0 (col 2)


class BankWorkload:
    def __init__(self, cluster, n_accounts: int = 8,
                 init_balance: int = 100, seed: int = 0,
                 region_id: int = 1, table_id: int = 7001,
                 driver_rounds: int = 20):
        self.c = cluster
        self.rng = random.Random(seed)
        self.n_accounts = n_accounts
        self.init_balance = init_balance
        self.region_id = region_id
        self.table = int_table(1, table_id=table_id)
        self.keys = [table_record_key(table_id, h)
                     for h in range(n_accounts)]
        self.balances = {h: init_balance for h in range(n_accounts)}
        self.expected_total = n_accounts * init_balance
        self._driver_rounds = driver_rounds
        # journals
        self.acked: list[dict] = []
        self.indeterminate: list[dict] = []
        self.aborted = 0
        self.copr_reads = 0

    # ---------------------------------------------------------- plumbing

    def _driver(self, done) -> None:
        """Bounded cluster pump for RaftKv waits: under an active fault
        an op must fail fast (TimeoutError → indeterminate), not hang."""
        c = self.c
        for _ in range(self._driver_rounds):
            if done():
                return
            try:
                c.pump(max_rounds=40)
            except RuntimeError:        # still turbulent, keep driving
                pass
            if done():
                return
            for store in list(c.stores.values()):
                store.tick()
        raise TimeoutError("chaos workload driver budget exhausted")

    def _leader_sid(self) -> Optional[int]:
        return self.c.leader_store(self.region_id)

    def _storage(self) -> Storage:
        """Fresh facade over the CURRENT leader store (stores are
        replaced on crash-restart, so never cache across ops)."""
        sid = self._leader_sid()
        if sid is None:
            from ..raftstore.metapb import NotLeaderError
            raise NotLeaderError(self.region_id)
        kv = RaftKv(self.c.stores[sid], driver=self._driver)
        return Storage(kv)

    @staticmethod
    def _row(balance: int) -> bytes:
        return encode_row({BALANCE_COL_ID: balance})

    @staticmethod
    def _balance(raw: bytes) -> int:
        return int(decode_row(raw)[BALANCE_COL_ID])

    def _tso(self) -> int:
        return self.c.pd.tso()

    # ------------------------------------------------------------- setup

    def init_data(self) -> None:
        st = self._storage()
        muts = [Mutation("put", k, self._row(self.init_balance))
                for k in self.keys]
        start = self._tso()
        st.sched_txn_command(cmds.Prewrite(muts, self.keys[0], start))
        st.sched_txn_command(cmds.Commit(list(self.keys), start,
                                         self._tso()))

    # --------------------------------------------------------------- ops

    def run_ops(self, n: int) -> None:
        for _ in range(n):
            if self.rng.random() < 0.25:
                self.copr_query()
            else:
                self.transfer()

    def op_stream(self, n: int) -> list[tuple]:
        """The DECISIONS the next n ops would make (for determinism
        assertions) — consumes the rng the same way run_ops does."""
        out = []
        for _ in range(n):
            if self.rng.random() < 0.25:
                out.append(("copr",))
            else:
                a, b = self.rng.sample(range(self.n_accounts), 2)
                out.append(("transfer", a, b,
                            self.rng.randint(1, 5)))
        return out

    def transfer(self) -> bool:
        a, b = self.rng.sample(range(self.n_accounts), 2)
        amt = self.rng.randint(1, 5)
        try:
            st = self._storage()
            ts = self._tso()
            bal_a = self._read_balance(st, a, ts)
            bal_b = self._read_balance(st, b, ts)
        except Exception:   # noqa: BLE001 — routing/lock/timeout: abort
            self.aborted += 1
            return False
        ka, kb = self.keys[a], self.keys[b]
        va, vb = self._row(bal_a - amt), self._row(bal_b + amt)
        start_ts = self._tso()
        # the model tracks DELTAS, not the absolute balances this txn
        # wrote: a commit whose ack was lost may be settled long after
        # later transfers touched the same accounts, and replaying its
        # stale absolutes would regress the model (deltas commute; the
        # engine-side lock protects the read-modify-write itself)
        rec = {"start_ts": start_ts, "primary": ka, "keys": [ka, kb],
               "pairs": [(ka, va), (kb, vb)],
               "deltas": {a: -amt, b: +amt},
               "commit_possible": False}
        try:
            st.sched_txn_command(cmds.Prewrite(
                [Mutation("put", ka, va), Mutation("put", kb, vb)],
                ka, start_ts))
        except KeyIsLocked:
            self.aborted += 1       # blocked by an unresolved txn
            return False
        except Exception:   # noqa: BLE001 — locks may or may not exist
            self.indeterminate.append(rec)
            return False
        commit_ts = self._tso()
        rec["commit_ts"] = commit_ts
        rec["commit_possible"] = True
        try:
            st.sched_txn_command(cmds.Commit([ka, kb], start_ts,
                                             commit_ts))
        except Exception:   # noqa: BLE001 — the indeterminate window
            self.indeterminate.append(rec)
            return False
        self.acked.append(rec)
        self._apply_deltas(rec)
        return True

    def _apply_deltas(self, rec: dict) -> None:
        for handle, delta in rec["deltas"].items():
            self.balances[handle] += delta

    def _read_balance(self, st: Storage, handle: int, ts: int) -> int:
        key = self.keys[handle]
        try:
            raw = st.get(key, ts)
        except KeyIsLocked as e:
            # our own earlier indeterminate txn still holds the lock:
            # settle it, then retry once
            self._resolve_by_start_ts(st, e.lock.start_ts)
            raw = st.get(key, ts)
        assert raw is not None, f"account {handle} missing"
        return self._balance(raw)

    # -------------------------------------------------------- resolution

    def _resolve_by_start_ts(self, st: Storage, start_ts: int) -> None:
        for rec in self.indeterminate:
            if rec["start_ts"] == start_ts:
                self._resolve_one(st, rec)
                self.indeterminate.remove(rec)
                return
        # not ours / already settled: protective rollback of the lock
        raise KeyError(f"unknown lock owner start_ts={start_ts}")

    def _resolve_one(self, st: Storage, rec: dict) -> None:
        """Settle one indeterminate txn (client-go resolver protocol)."""
        start_ts = rec["start_ts"]
        if rec["commit_possible"]:
            now = self._tso()
            r = st.sched_txn_command(cmds.CheckTxnStatus(
                rec["primary"], start_ts, caller_start_ts=now,
                current_ts=now))
            if r["status"] == "committed":
                st.sched_txn_command(cmds.ResolveLockLite(
                    start_ts, r["ts"], rec["keys"]))
                rec["commit_ts"] = r["ts"]
                self.acked.append(rec)
                self._apply_deltas(rec)
                return
            if r["status"] in ("rolled_back", "ttl_expired"):
                st.sched_txn_command(cmds.ResolveLockLite(
                    start_ts, 0, rec["keys"]))
                return
            # still "locked": the commit never landed (we are the only
            # client and nothing is in flight) — roll it back
        st.sched_txn_command(cmds.Rollback(rec["keys"], start_ts))

    def resolve_indeterminate(self) -> int:
        """Settle every indeterminate txn; → number settled.  Call on a
        healed, quiesced cluster (nothing may be in flight)."""
        settled = 0
        remaining = []
        for rec in self.indeterminate:
            try:
                st = self._storage()
                self._resolve_one(st, rec)
                settled += 1
            except Exception:   # noqa: BLE001 — retried next round
                remaining.append(rec)
        self.indeterminate = remaining
        return settled

    # -------------------------------------------------------- copr reads

    def copr_query(self) -> Optional[int]:
        """SUM(balance) through the coprocessor executor pipeline over a
        consistent leader snapshot; → total or None when the read could
        not complete under the active fault.  A non-None result is
        checked against conservation on the spot: any committed
        snapshot must show the conserved total."""
        from ..storage.txn_types import encode_key
        sid = self._leader_sid()
        if sid is None:
            return None
        ts = self._tso()
        try:
            kv = RaftKv(self.c.stores[sid], driver=self._driver)
            snap = kv.snapshot(SnapContext(
                key_hint=encode_key(self.keys[0])))
            sel = DagSelect.from_table(self.table)
            dag = sel.sum(sel.col("c0")).build(start_ts=ts)
            res = BatchExecutorsRunner(
                dag, MvccScanStorage(MvccReader(snap), ts)
            ).handle_request()
            total = int(res.rows()[0][0])
        except Exception:   # noqa: BLE001 — turbulence: no read served
            return None
        self.copr_reads += 1
        assert total == self.expected_total, \
            f"copr SUM saw {total}, expected {self.expected_total} " \
            "(balance conservation violated)"
        return total
