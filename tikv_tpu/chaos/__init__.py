"""Deterministic chaos harness.

Reference: the role of tests/failpoints/cases/ + Jepsen-style nemesis
drivers — seeded fault schedules (partition, leader isolation,
crash-restart at failpoint crash boundaries, message delay/reorder/
duplication, disk stalls) applied against the in-process cluster while
a bank-transfer + coprocessor workload runs, then invariant checks
(balance conservation through MVCC, ComputeHash/VerifyHash replica
agreement, no lost acknowledged writes, raft log/apply monotonicity).

Everything is driven by seeded ``random.Random`` instances: the same
seed reproduces the same schedule, the same workload op stream, and the
same message scrambling decisions.
"""

from .invariants import (        # noqa: F401
    InvariantViolation,
    RaftStateTracker,
    check_bg_not_starved,
    check_conservation,
    check_fg_latency_bounded,
    check_goodput,
    check_hbm_within_budget,
    check_mesh_serves_degraded,
    check_no_cold_rebuild_on_serving_path,
    check_no_late_acks,
    check_no_lost_acks,
    check_no_quarantined_dispatch,
    check_no_remint_on_move,
    check_no_stale_epoch,
    check_remint_concurrency_bounded,
    check_read_correctness,
    check_replica_consistency,
    check_replica_read_correctness,
    check_scrub_clean,
)
from .nemesis import (           # noqa: F401
    CRASH_SITES,
    DEGRADE_SITES,
    DEVICE_FAULT_KINDS,
    ELASTIC_FAULT_KINDS,
    FASTPATH_FAULT_KINDS,
    FAULT_KINDS,
    PLAN_FAULT_KINDS,
    REPLICA_FAULT_KINDS,
    TENANT_FAULT_KINDS,
    Fault,
    Nemesis,
    generate_schedule,
    stabilize,
)
from .workload import BankWorkload      # noqa: F401
