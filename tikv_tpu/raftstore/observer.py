"""Coprocessor observer host — the apply-path event seam.

Reference: components/raftstore/src/coprocessor/mod.rs:98-594 — the
``CoprocessorHost`` that CDC (components/cdc/src/observer.rs),
resolved-ts (components/resolved_ts/src/lib.rs), backup-stream
(components/backup-stream/src/observer.rs) and the split checker all
register into.  Observers see committed apply events in order, plus
region/role changes, and must never fail the apply.

Events delivered:
- ``on_apply_write(region_id, index, ops)``: the data WriteOps of one
  applied entry, AFTER the engine write succeeded (ops carry raw cf/
  key/value exactly as applied);
- ``on_data_replaced(region_id, index)``: the region's data was
  replaced wholesale at ``index`` (snapshot apply) — incremental
  subscribers (the columnar delta log) must drop everything they
  derived from earlier applied writes;
- ``on_region_split(left, right, left_index, right_index)``: a split
  was just executed, BEFORE the generic ``on_region_changed`` fires for
  the surviving left region — subscribers that can serve the split
  incrementally (delta-log coverage carry-over, device-side line/feed
  slicing) act here; ``right_index`` is None when no right peer was
  materialized on this store.  The generic event still follows;
- ``on_region_changed(region)``: split/merge/conf-change/snapshot;
- ``on_role_change(region_id, is_leader)``: leadership transitions;
- ``on_peer_destroyed(region_id)``: the peer was removed from this
  store (merge-away / conf-change removal) — subscribers must drop
  every artifact derived from the region's local data.
"""

from __future__ import annotations

from typing import Callable, Sequence


class Observer:
    """Base observer: override what you need (BoxObserver analogs)."""

    def on_apply_write(self, region_id: int, index: int,
                       ops: Sequence) -> None:
        pass

    def on_data_replaced(self, region_id: int, index: int) -> None:
        pass

    def on_region_split(self, left, right, left_index, right_index) -> None:
        pass

    def on_region_changed(self, region) -> None:
        pass

    def on_role_change(self, region_id: int, is_leader: bool) -> None:
        pass

    def on_peer_destroyed(self, region_id: int) -> None:
        pass


class CoprocessorHost:
    """Observer registry attached to one RaftStore (dispatcher.rs:451).

    Dispatch is synchronous on the apply path (the reference's apply
    poller calls observers inline too); observers do their heavy work on
    their own workers, treating these callbacks as mailbox pushes.
    Observer exceptions are swallowed — a broken subscriber must never
    fail consensus.
    """

    def __init__(self):
        self._observers: list[Observer] = []

    def register(self, obs: Observer) -> None:
        self._observers.append(obs)

    def unregister(self, obs: Observer) -> None:
        try:
            self._observers.remove(obs)
        except ValueError:
            pass

    # -- dispatch --

    def notify_apply_write(self, region_id: int, index: int,
                           ops: Sequence) -> None:
        for obs in self._observers:
            try:
                obs.on_apply_write(region_id, index, ops)
            except Exception:   # noqa: BLE001
                pass

    def notify_data_replaced(self, region_id: int, index: int) -> None:
        for obs in self._observers:
            try:
                obs.on_data_replaced(region_id, index)
            except Exception:   # noqa: BLE001
                pass

    def notify_region_split(self, left, right, left_index,
                            right_index) -> None:
        for obs in self._observers:
            try:
                obs.on_region_split(left, right, left_index, right_index)
            except Exception:   # noqa: BLE001
                pass

    def notify_region_changed(self, region) -> None:
        for obs in self._observers:
            try:
                obs.on_region_changed(region)
            except Exception:   # noqa: BLE001
                pass

    def notify_role_change(self, region_id: int, is_leader: bool) -> None:
        for obs in self._observers:
            try:
                obs.on_role_change(region_id, is_leader)
            except Exception:   # noqa: BLE001
                pass

    def notify_peer_destroyed(self, region_id: int) -> None:
        for obs in self._observers:
            try:
                obs.on_peer_destroyed(region_id)
            except Exception:   # noqa: BLE001
                pass
