"""Peer — one replica of one region: raft driving + apply.

Reference: components/raftstore/src/store/peer.rs (Peer: propose :3612,
handle_raft_ready_append :2565) and fsm/apply.rs (exec_raft_cmd
:1370-1740 — write commands, and admin commands: split :1692,
change peer, compact log).  Like the reference, raft-ready handling and
apply run on SEPARATE pollers (SURVEY.md §2.8 item 3): the store's
batch-system poller drives ready/append and hands committed entries to
a second apply batch-system (batch_system.py, wired in store.py — the
fsm/apply.rs analog); a synchronous single-threaded drive mode remains
for tests and the in-process cluster harness.

Read path, fastest first: leader LEASE local reads
(store/worker/read.rs LocalReader — ``local_read`` here, served by
raftkv.py without a proposal or log barrier while the lease holds),
then follower STALE reads (``stale_snapshot`` — any replica, no
consensus round trip, gated on ``read_ts ≤ resolved_ts`` by the
service layer; the replicated device-serving path answers coprocessor
reads from the follower's own delta-patched columnar feed through this
snapshot), then ReadIndex barriers (``propose_read`` /
``replica_read`` for followers), which remain the correctness backstop
whenever neither the lease nor the watermark can vouch.
"""

from __future__ import annotations

import struct
from dataclasses import dataclass
from typing import Callable, Optional

from ..engine.traits import CF_RAFT, KvEngine
from ..raft.messages import (
    ConfChange,
    ConfChangeType,
    ConfChangeV2,
    EntryType,
    HardState,
    Message,
)
from ..raft.raw_node import LEADER, NotLeader, RawNode
from .cmd import AdminCmd, RaftCmd, WriteOp
from .metapb import (
    EpochNotMatch,
    KeyNotInRegion,
    NotLeaderError,
    Peer as PeerMeta,
    Region,
    RegionEpoch,
    RegionMerging,
)
from .peer_storage import PeerStorage, data_key


@dataclass
class Proposal:
    index: int
    term: int
    cb: Callable            # cb(result | Exception)
    is_read: bool = False   # read barrier: snapshot served at apply time


class RegionSnapshot:
    """Engine snapshot clamped to one region, with the data-key prefix
    applied transparently (reference: raftstore RegionSnapshot).

    ``data_index`` stamps the last applied *data-mutating* entry index —
    the snapshot's data version for columnar/copr caches (read barriers
    and leader noops do not bump it, so repeated reads share a version).
    """

    data_index: Optional[int] = None
    apply_index: Optional[int] = None

    def __init__(self, snap, region: Region):
        self._snap = snap
        self.region = region

    def _check(self, key: bytes) -> bytes:
        if not self.region.contains(key):
            raise KeyNotInRegion(key, self.region)
        return data_key(key)

    def get_value_cf(self, cf: str, key: bytes):
        return self._snap.get_value_cf(cf, self._check(key))

    def get_value(self, key: bytes):
        from ..engine.traits import CF_DEFAULT
        return self.get_value_cf(CF_DEFAULT, key)

    def iterator_cf(self, cf: str, lower: Optional[bytes] = None,
                    upper: Optional[bytes] = None):
        from .peer_storage import region_data_bounds
        rlo, rhi = region_data_bounds(self.region)
        lo = rlo if lower is None else max(rlo, data_key(lower))
        hi = rhi if upper is None else min(rhi, data_key(upper))
        return _PrefixStripIterator(self._snap.iterator_cf(cf, lo, hi))

    def range_cf(self, cf: str, lower: bytes, upper: bytes):
        """Bulk range read clamped to the region; keys keep the data-key
        prefix — the extra prefix_skip tells the native builder how many
        leading bytes to ignore instead of re-slicing every key."""
        rng = getattr(self._snap, "range_cf", None)
        if rng is None:
            return None
        from .peer_storage import region_data_bounds
        rlo, rhi = region_data_bounds(self.region)
        lo = max(rlo, data_key(lower))
        hi = min(rhi, data_key(upper))
        if lo >= hi:
            return [], [], 0
        keys, vals, skip = rng(cf, lo, hi)
        return keys, vals, skip + 1


class _PrefixStripIterator:
    """Strips the data-key prefix so layers above see user keys."""

    def __init__(self, it):
        self._it = it

    def valid(self):
        return self._it.valid()

    def seek(self, key: bytes):
        return self._it.seek(data_key(key))

    def seek_for_prev(self, key: bytes):
        return self._it.seek_for_prev(data_key(key))

    def seek_to_first(self):
        return self._it.seek_to_first()

    def seek_to_last(self):
        return self._it.seek_to_last()

    def next(self):
        return self._it.next()

    def prev(self):
        return self._it.prev()

    def key(self) -> bytes:
        return self._it.key()[1:]

    def value(self) -> bytes:
        return self._it.value()


class RaftPeer:
    def __init__(self, store, region: Region, peer_meta: PeerMeta,
                 engine: KvEngine, initial: bool = False, **raft_cfg):
        import threading as _threading
        # serializes poller processing against lease reads from handler
        # threads in pooled mode (the LocalReader seam); uncontended in
        # the synchronous drive mode
        self.mu = _threading.RLock()
        self.store = store
        self.meta = peer_meta
        self.engine = engine
        self.peer_storage = PeerStorage(engine, region)
        ms, applied = self.peer_storage.load()
        if initial and ms.last_index() == 0:
            # fresh bootstrap/split peer: in-memory marker matching
            # write_initial_state (the engine copy is in the same batch)
            from ..raft.messages import HardState, Snapshot, SnapshotMetadata
            from .peer_storage import RAFT_INIT_LOG_INDEX, RAFT_INIT_LOG_TERM
            meta0 = ms.snapshot.metadata
            ms.snapshot = Snapshot(SnapshotMetadata(
                RAFT_INIT_LOG_INDEX, RAFT_INIT_LOG_TERM,
                meta0.voters, meta0.learners))
            ms.set_hard_state(HardState(RAFT_INIT_LOG_TERM, 0,
                                        RAFT_INIT_LOG_INDEX))
            applied = RAFT_INIT_LOG_INDEX
        ms.snapshot_provider = self._make_snapshot
        self.node = RawNode(peer_meta.id, ms, **raft_cfg)
        self.node.applied = max(self.node.applied, applied)
        # last applied entry that mutated data; restart conservatively
        # re-stamps at applied (one-time cache invalidation per restart).
        # data_index advances while a write batch is still being BUILT;
        # data_index_engine advances only after the batch hits the
        # engine — snapshots must stamp the engine-durable version or a
        # lease read racing the apply pool could stamp a version whose
        # rows it cannot see yet (and the columnar delta cache would
        # then pin wrong data under that version forever)
        self.data_index = self.node.applied
        self.data_index_engine = self.node.applied
        self.proposals: list[Proposal] = []
        self.pending_destroy = False
        # PrepareMerge in flight: the prepare entry's apply index, or
        # None.  Persisted (merge_state_key) so a restarted source peer
        # keeps rejecting writes until commit/rollback.
        from .peer_storage import merge_state_key
        raw = engine.get_value_cf(CF_RAFT, merge_state_key(region.id))
        self.merging: Optional[int] = \
            int.from_bytes(raw, "big") if raw else None
        # sender metas seen on incoming messages — lets an uninitialized
        # peer route responses before it learns the region's peer list
        # (reference: peer.rs Peer::peer_cache)
        self.peer_cache: dict[int, PeerMeta] = {}
        # applied-but-not-yet-notified observer events + role tracking
        self._pending_obs: list = []
        self._last_role = False
        # (index, crc32) of the last applied ComputeHash
        self.consistency_state: Optional[tuple] = None
        # an async raft-log write is in flight (batch_system write pool)
        self._ready_inflight = False
        # sub-region bucket boundaries (split-check pass computes them)
        self.buckets: list = []
        # split-check bookkeeping (fsm/apply.rs size_diff_hint +
        # SplitCheckTask): apply accumulates written bytes; the checker
        # only re-scans the region once the delta crosses
        # region_split_check_diff — a full region scan per tick would
        # stall the store (and contend every lease read) at scale
        self.approximate_size = 0
        self.size_diff_hint = 0
        # apply-pool decoupling (fsm/apply.rs ApplyFsm on its own
        # batch-system): plain-write entry batches apply on a second
        # poller pool; applied_engine tracks what the ENGINE holds —
        # node.applied may run ahead while a batch is queued, and reads
        # must gate on engine state, not raft bookkeeping
        self.applied_engine = self.node.applied
        # proposals are appended by the raft poller and consumed by
        # whichever thread applies — their own lock keeps the apply
        # pool off peer.mu (the whole point of the second pool)
        self._prop_mu = _threading.Lock()
        # hibernation (store/hibernate_state.rs): quiet peers stop
        # ticking; any traffic wakes them
        self._idle_ticks = 0
        self.hibernated = False
        # replica reads (ReadIndex): ctx -> (cb, read_ts, age), plus
        # reads whose commit index the leader confirmed but we have not
        # applied up to yet
        self._replica_reads: dict[int, list] = {}
        self._replica_read_ctx = 0
        self._replica_waiting: list = []    # (index, cb)

    # ------------------------------------------------------------- props

    @property
    def region(self) -> Region:
        return self.peer_storage.region

    def is_leader(self) -> bool:
        return self.node.state == LEADER

    def leader_peer(self) -> Optional[PeerMeta]:
        lid = self.node.leader_id
        for p in self.region.peers:
            if p.id == lid:
                return p
        return None

    # ------------------------------------------------------------- propose

    def _check_header(self, cmd: RaftCmd) -> None:
        region = self.region
        if cmd.epoch.version != region.epoch.version or \
                (cmd.admin is not None and
                 cmd.epoch.conf_ver != region.epoch.conf_ver):
            raise EpochNotMatch(region)
        for op in cmd.ops:
            if op.op == "ingest":
                # the SST's sorted first/last keys were range-checked
                # against this epoch before proposing (node.
                # ingest_sst_blob); a split in between fails the epoch
                # check at apply
                continue
            if not region.contains(op.key):
                raise KeyNotInRegion(op.key, region)

    def propose(self, cmd: RaftCmd, cb: Callable) -> int:
        with self.mu:
            self.wake()
            return self._propose_locked(cmd, cb)

    def _propose_locked(self, cmd: RaftCmd, cb: Callable) -> int:
        from ..utils.failpoint import fail_point
        fail_point("peer::before_propose")
        if not self.is_leader():
            raise NotLeaderError(self.region.id, self.leader_peer())
        if self.merging is not None and (
                cmd.admin is None or
                cmd.admin.kind not in ("rollback_merge",)):
            # a merging source accepts only the rollback; everything
            # else retries after commit/rollback (ProposalInMergingMode)
            raise RegionMerging(self.region.id)
        self._check_header(cmd)
        from ..utils.metrics import RAFT_PROPOSE_COUNTER
        RAFT_PROPOSE_COUNTER.labels(
            cmd.admin.kind if cmd.admin is not None else "write").inc()
        if cmd.admin is not None and cmd.admin.kind == "change_peer":
            a = cmd.admin
            cc_type = {"add": ConfChangeType.ADD_NODE,
                       "add_learner": ConfChangeType.ADD_LEARNER,
                       "remove": ConfChangeType.REMOVE_NODE}[a.change_type]
            index = self.node.propose_conf_change(
                ConfChange(cc_type, a.peer.id, cmd.to_bytes()))
        elif cmd.admin is not None and cmd.admin.kind == "change_peer_v2":
            from .cmd import decode_change_peer_v2
            meta = decode_change_peer_v2(cmd.admin.extra)
            tmap = {"add": ConfChangeType.ADD_NODE,
                    "add_learner": ConfChangeType.ADD_LEARNER,
                    "remove": ConfChangeType.REMOVE_NODE}
            changes = tuple((tmap[c["t"]], c["peer"]["id"])
                            for c in meta["changes"])
            index = self.node.propose_conf_change_v2(ConfChangeV2(
                changes, cmd.to_bytes(),
                leave_joint=meta.get("leave", False)))
        else:
            index = self.node.propose(cmd.to_bytes())
        with self._prop_mu:
            self.proposals.append(Proposal(index, self.node.term, cb))
        return index

    def _inspected_engine_write(self, wb) -> None:
        """Write-path latency inspector (store/async_io/write.rs:24
        LatencyInspector): every apply/persist engine write is timed
        into the store's HealthController, so a degrading disk raises
        the slow score long before it fails outright.  The store's
        fail-slow injection knob (chaos nemesis) adds its delay INSIDE
        the measured window — an injected brownout must look exactly
        like a real one to the health loop."""
        import time as _time
        from ..utils.failpoint import fail_point
        fail_point("store::write_inspect")
        t0 = _time.perf_counter()
        stall = getattr(self.store, "inject_write_delay_s", 0.0)
        if stall > 0:
            _time.sleep(stall)
        self.engine.write(wb)
        health = getattr(self.store, "health", None)
        if health is not None:
            health.record_write(_time.perf_counter() - t0)

    def stale_snapshot(self) -> RegionSnapshot:
        """Engine snapshot with NO consensus round trip — only safe for
        reads at or below the region's resolved-ts watermark (closed
        timestamps: no commit at ts ≤ resolved_ts can newly appear), a
        gate the SERVICE layer enforces before calling this.  Serves
        from any replica, leader or not (kvproto Context stale_read)."""
        with self.mu:
            snap = RegionSnapshot(self.engine.snapshot(), self.region)
            snap.data_index = self.data_index_engine
            snap.apply_index = self.applied_engine
            return snap

    def local_read(self) -> Optional[RegionSnapshot]:
        """Lease-based local read: serve an engine snapshot with NO raft
        round-trip when the leader lease is valid and this leader has
        applied into its own term (reference: store/worker/read.rs
        LocalReader + ReadDelegate — applied_term == term guarantees all
        writes acked by previous leaders are in the applied state; writes
        acked by THIS leader were applied before their ack fired)."""
        with self.mu:
            return self._local_read_locked()

    def _local_read_locked(self) -> Optional[RegionSnapshot]:
        from ..utils.failpoint import fail_point
        # a "return" action forces the lease miss path (read barrier)
        if fail_point("read::before_local_read") is not None:
            return None
        node = self.node
        if not self.is_leader() or not node.in_lease():
            return None
        # gate on what the ENGINE holds: with the apply pool,
        # node.applied may run ahead of a queued batch, and a lease
        # read must never serve a snapshot missing acked writes
        if node.storage.term(self.applied_engine) != node.term:
            return None     # fresh leader: noop not applied yet
        snap = RegionSnapshot(self.engine.snapshot(), self.region)
        snap.data_index = self.data_index_engine
        snap.apply_index = self.applied_engine
        return snap

    def replica_read(self, cb: Callable, read_ts: int = 0) -> None:
        """Follower/replica read (store read parallelism, SURVEY §2.8.4;
        reference: test_replica_read.rs flow over raft ReadIndex).  The
        snapshot is served once this peer has applied up to the commit
        index the LEADER confirmed — same consistency as a leader
        lease read, no leader load.  Dropped requests (no leader yet,
        leader lease pending, message loss) are re-sent from tick() and
        expire after ~2 election timeouts."""
        from ..utils.failpoint import fail_point
        fail_point("read::before_replica_read")
        with self.mu:
            self._replica_read_ctx += 1
            ctx = self._replica_read_ctx
            self._replica_reads[ctx] = [cb, read_ts, 0]
            self.node.request_read_index(ctx, read_ts)

    def _serve_replica_reads(self) -> None:
        """Drain ReadIndex answers + reads unblocked by new applies."""
        node = self.node
        if node.read_states:
            states, node.read_states = node.read_states, []
            for index, ctx in states:
                ent = self._replica_reads.pop(ctx, None)
                if ent is not None:
                    self._replica_waiting.append((index, ent[0]))
        if not self._replica_waiting:
            return
        still = []
        for index, cb in self._replica_waiting:
            # the ReadIndex contract is "applied up to the leader's
            # commit point IN THE ENGINE" — node.applied may run ahead
            # of a queued apply batch
            if self.applied_engine >= index:
                snap = RegionSnapshot(self.engine.snapshot(),
                                      self.region)
                snap.data_index = self.data_index_engine
                snap.apply_index = self.applied_engine
                cb(snap)
            else:
                still.append((index, cb))
        self._replica_waiting = still

    def propose_read(self, cb: Callable) -> int:
        """Read barrier through the log (see module docstring)."""
        with self.mu:
            return self._propose_read_locked(cb)

    def _propose_read_locked(self, cb: Callable) -> int:
        if not self.is_leader():
            raise NotLeaderError(self.region.id, self.leader_peer())
        index = self.node.propose(b"")

        def on_applied(_result):
            if isinstance(_result, Exception):
                cb(_result)
            else:
                snap = RegionSnapshot(self.engine.snapshot(), self.region)
                snap.data_index = self.data_index_engine
                snap.apply_index = index
                cb(snap)
        with self._prop_mu:
            self.proposals.append(Proposal(index, self.node.term,
                                           on_applied,
                                       is_read=True))
        return index

    # ------------------------------------------------------------- ready

    def handle_ready(self, async_writer=None, on_persisted=None,
                     on_persist_failed=None,
                     apply_ctx=None) -> list[Message]:
        """Persist, apply, return messages to send.  Reference:
        handle_raft_ready_append + the apply poller, collapsed.

        ``async_writer`` (store/async_io/write.rs): append-only readies
        (log entries + hard state, no apply, no snapshot) hand their
        WAL batch to the write-worker pool and return WITHOUT their
        messages — the append ack must not leave before the fsync.  The
        pool persists (group-committed across peers) then calls
        ``on_persisted(rd)`` from a poller-routed context, which sends
        the messages and advances.  While one async persist is in
        flight the peer produces no further ready (the _ready_inflight
        gate), preserving the ready/advance protocol.
        """
        from ..utils.failpoint import fail_point
        out: list[Message] = []
        while self.node.has_ready():
            if self._ready_inflight:
                break       # awaiting the async log write
            from ..utils.metrics import RAFT_READY_COUNTER
            RAFT_READY_COUNTER.inc()
            fail_point("peer::handle_ready")
            rd = self.node.ready()
            if async_writer is not None and \
                    not getattr(async_writer, "failed", False) and \
                    rd.snapshot is None and \
                    not rd.committed_entries and rd.entries:
                fail_point("raftlog::before_persist")
                wb = self.engine.write_batch()
                meta = self.node.storage.snapshot.metadata
                self.peer_storage.persist(
                    wb, rd.entries, rd.hard_state,
                    truncated=(meta.index, meta.term))
                self._ready_inflight = True
                async_writer.submit(
                    wb, lambda rd=rd: on_persisted(self.region.id, rd),
                    fail_cb=(None if on_persist_failed is None else
                             (lambda: on_persist_failed(self.region.id))))
                break
            if apply_ctx is not None and rd.snapshot is None and \
                    rd.committed_entries and \
                    all(self._is_plain_write(e)
                        for e in rd.committed_entries):
                # decoupled apply (fsm/apply.rs: ApplyFsm runs on its
                # own batch-system): persist the log, queue the
                # committed plain-write batch on the apply pool, and
                # advance — a slow apply (bulk ingest, big writes)
                # never stalls this poller's raft ticks or elections.
                # Only plain writes decouple: admin/conf-change/read
                # barriers mutate raft-side state and stay inline,
                # ordered behind the queue by the drain below.
                fail_point("raftlog::before_persist")
                wb = self.engine.write_batch()
                meta = self.node.storage.snapshot.metadata
                self.peer_storage.persist(wb, rd.entries, rd.hard_state,
                                          truncated=(meta.index,
                                                     meta.term))
                if not wb.is_empty():
                    self._inspected_engine_write(wb)
                apply_ctx.send(self.region.id, rd.committed_entries)
                out.extend(rd.messages)
                self.node.advance(rd)
                continue
            if apply_ctx is not None and (rd.committed_entries or
                                          rd.snapshot is not None):
                # complex batch OR snapshot: every queued plain apply
                # must land first — entries for commit order, snapshots
                # because a queued pre-snapshot write batch applied
                # AFTER apply_snapshot would clobber post-snapshot data
                # and regress the apply state
                apply_ctx.drain(self.region.id)
            wb = self.engine.write_batch()
            if rd.snapshot is not None:
                fail_point("snapshot::before_apply")
                region = self.peer_storage.apply_snapshot(wb, rd.snapshot)
                # a snapshot replaces all region data: stamp the data
                # version so columnar/copr caches can never serve
                # pre-snapshot entries, and tell observers the data was
                # replaced WHOLESALE — committed-write delta logs cover
                # nothing at or before this index
                self.data_index = max(self.data_index,
                                      rd.snapshot.metadata.index)
                self.applied_engine = max(self.applied_engine,
                                          rd.snapshot.metadata.index)
                self._pending_obs.append(
                    (rd.snapshot.metadata.index, None))
                self.store.on_region_changed(self, region)
                fail_point("snapshot::after_apply")
            fail_point("raftlog::before_persist")
            meta = self.node.storage.snapshot.metadata
            self.peer_storage.persist(wb, rd.entries, rd.hard_state,
                                      truncated=(meta.index, meta.term))
            fail_point("apply::before_entries")
            if rd.committed_entries:
                from ..utils.metrics import RAFT_APPLY_COUNTER
                RAFT_APPLY_COUNTER.inc(len(rd.committed_entries))
            cbs: list = []
            for entry in rd.committed_entries:
                if not entry.data and not wb.is_empty() and \
                        self._pending_read_at(entry.index, entry.term):
                    # flush the applied prefix so the read barrier's
                    # engine snapshot includes every earlier entry of
                    # this same ready batch (apply state rides along so
                    # a crash here never re-applies admin commands)
                    self.peer_storage.persist_apply(wb, entry.index - 1)
                    self.engine.write(wb)
                    self.data_index_engine = self.data_index
                    wb = self.engine.write_batch()
                elif not wb.is_empty() and self._is_compute_hash(entry):
                    # ComputeHash digests the ENGINE state: earlier
                    # writes of this same ready batch must be flushed
                    # first or replicas batching differently would
                    # digest different visible prefixes at one index
                    self.peer_storage.persist_apply(wb, entry.index - 1)
                    self.engine.write(wb)
                    self.data_index_engine = self.data_index
                    wb = self.engine.write_batch()
                self._apply_entry(wb, entry, cbs)
            if rd.committed_entries:
                self.peer_storage.persist_apply(
                    wb, rd.committed_entries[-1].index)
            fail_point("apply::before_write")
            if not wb.is_empty():
                self._inspected_engine_write(wb)
            fail_point("apply::after_write")
            if rd.committed_entries or rd.snapshot is not None:
                # only paths that actually applied may publish: these
                # drained the apply pool first, so data_index is fully
                # durable here.  A message-only ready must NOT copy a
                # data_index the apply-pool thread bumped mid-batch —
                # that would re-open the stale-stamp race the
                # data_index_engine split closes (and flush the pool's
                # pending observer events before their write lands).
                self.data_index_engine = self.data_index
                # observers run AFTER the engine write so they only ever
                # see durable state (coprocessor/mod.rs post-apply hooks)
                self._dispatch_obs()
            if rd.committed_entries:
                self.applied_engine = rd.committed_entries[-1].index
            # ACKs leave only now — after the engine write (see
            # _apply_entry)
            for prop, res in cbs:
                prop.cb(res)
            out.extend(rd.messages)
            self.node.advance(rd)
        self._serve_replica_reads()
        role = self.is_leader()
        if role != self._last_role:
            self._last_role = role
            if role and self.node.in_joint() and \
                    self.node._pending_conf_index <= self.node.applied:
                # the previous leader died between enter and leave: a
                # NEW leader re-proposes the bare leave or the cluster
                # stays joint forever (raft-rs auto transition)
                try:
                    self.node.propose_conf_change_v2(
                        ConfChangeV2((), b"", leave_joint=True),
                        force=True)
                except Exception:   # noqa: BLE001 — retried next ready
                    pass
            self.store.coprocessor_host.notify_role_change(
                self.region.id, role)
        return out

    @staticmethod
    def _is_compute_hash(entry) -> bool:
        if not entry.data or entry.entry_type is EntryType.CONF_CHANGE:
            return False
        return RaftCmd.peek_admin_kind(entry.data) == "compute_hash"

    @staticmethod
    def _is_plain_write(entry) -> bool:
        """Entries the apply pool may execute concurrently with raft
        driving: KV writes only — no admin (mutates region/raft meta),
        no conf change, no read barrier (serves an engine snapshot that
        must reflect every earlier entry)."""
        if not entry.data or entry.entry_type is EntryType.CONF_CHANGE:
            return False
        return RaftCmd.peek_admin_kind(entry.data) is None

    def apply_plain_entries(self, entries) -> None:
        """Apply one committed plain-write batch on the APPLY pool
        (fsm/apply.rs ApplyDelegate::handle_raft_committed_entries).

        Runs WITHOUT peer.mu: region meta is stable (admin entries
        execute inline behind an apply-queue drain), proposals have
        their own lock, and ``applied_engine`` advances last so reads
        gate on durable engine state."""
        from ..utils.failpoint import fail_point
        from ..utils.metrics import RAFT_APPLY_COUNTER
        RAFT_APPLY_COUNTER.inc(len(entries))
        fail_point("apply::before_entries")
        wb = self.engine.write_batch()
        cbs: list = []
        for entry in entries:
            self._apply_entry(wb, entry, cbs)
        self.peer_storage.persist_apply(wb, entries[-1].index)
        fail_point("apply::before_write")
        if not wb.is_empty():
            self._inspected_engine_write(wb)
        self.data_index_engine = self.data_index
        fail_point("apply::after_write")
        self._dispatch_obs()
        self.applied_engine = entries[-1].index
        for prop, res in cbs:
            prop.cb(res)

    def _dispatch_obs(self) -> None:
        """Flush applied-entry observer events, post-engine-write.
        ``ops is None`` marks a wholesale data replacement (snapshot
        apply) — delta subscribers must drop their coverage."""
        if not self._pending_obs:
            return
        host = self.store.coprocessor_host
        for index, ops in self._pending_obs:
            if ops is None:
                host.notify_data_replaced(self.region.id, index)
            else:
                host.notify_apply_write(self.region.id, index, ops)
        self._pending_obs.clear()

    def on_log_persisted(self, rd) -> list[Message]:
        """Async-IO completion: the log batch hit disk — now the acks
        may leave and the ready advances (write.rs persisted callback).
        Runs serialized with other peer work (poller mailbox)."""
        from ..utils.failpoint import fail_point
        fail_point("raftlog::after_persist")
        self._ready_inflight = False
        self.node.advance(rd)
        return list(rd.messages)

    # ------------------------------------------------------------- apply

    def _pending_read_at(self, index: int, term: int) -> bool:
        with self._prop_mu:
            for p in self.proposals:
                if p.index >= index:
                    return p.index == index and p.term == term \
                        and p.is_read
        return False

    def _take_proposal(self, index: int, term: int) -> Optional[Proposal]:
        stale = []
        got = None
        with self._prop_mu:
            while self.proposals and self.proposals[0].index <= index:
                p = self.proposals.pop(0)
                if p.index == index and p.term == term:
                    got = p
                    break
                stale.append(p)
        for p in stale:     # callbacks run outside the lock
            p.cb(NotLeaderError(self.region.id, self.leader_peer()))
        return got

    def _apply_entry(self, wb, entry, out_cbs: list) -> None:
        """Execute one committed entry into ``wb``; the proposal
        callback (the client's ACK) is APPENDED to ``out_cbs``, not
        fired — acks must not leave before the batch's engine write
        lands, or a concurrent lease read could miss an acked write
        (the apply pool made that window real; the reference invokes
        apply callbacks after the write batch commits the same way)."""
        prop = self._take_proposal(entry.index, entry.term)
        if not entry.data:
            if prop is not None:
                out_cbs.append((prop, {}))  # read barrier / leader noop
            return
        if entry.entry_type is EntryType.CONF_CHANGE:
            if ConfChangeV2.is_v2(entry.data):
                cc2 = ConfChangeV2.from_bytes(entry.data)
                if cc2.context:
                    cmd = RaftCmd.from_bytes(cc2.context)
                    admin = cmd.admin
                else:       # bare leave from a new leader
                    admin = AdminCmd("change_peer_v2")
                result = self._exec_change_peer_v2(wb, admin, cc2)
            else:
                cc = ConfChange.from_bytes(entry.data)
                cmd = RaftCmd.from_bytes(cc.context)
                result = self._exec_admin(wb, cmd.admin, cc=cc,
                                          index=entry.index)
        else:
            cmd = RaftCmd.from_bytes(entry.data)
            try:
                self._check_epoch_at_apply(cmd)
            except EpochNotMatch as e:
                if prop is not None:
                    out_cbs.append((prop, e))
                return
            if cmd.admin is not None:
                result = self._exec_admin(wb, cmd.admin,
                                          index=entry.index)
            else:
                # only actual KV mutations bump the data version —
                # admin commands (compact_log, change_peer) leave table
                # data untouched and splits bump epoch.version, so the
                # columnar cache key (which includes both) stays exact
                # without spurious invalidation on log GC
                self.data_index = entry.index
                result = self._exec_write(wb, cmd)
                self._pending_obs.append((entry.index, cmd.ops))
        if prop is not None:
            out_cbs.append((prop, result))

    def _check_epoch_at_apply(self, cmd: RaftCmd) -> None:
        region = self.region
        if cmd.epoch.version != region.epoch.version:
            raise EpochNotMatch(region)

    def _exec_write(self, wb, cmd: RaftCmd) -> dict:
        for op in cmd.ops:
            # size_diff_hint: written bytes accumulate until the split
            # checker consumes them (deletes count too — they change
            # the region's size estimate in the same direction the
            # reference's apply metrics do)
            self.size_diff_hint += len(op.key) + len(op.value)
            if op.op == "put":
                wb.put_cf(op.cf, data_key(op.key), op.value)
            elif op.op == "delete":
                wb.delete_cf(op.cf, data_key(op.key))
            elif op.op == "delete_range":
                wb.delete_range_cf(op.cf, data_key(op.key),
                                   data_key(op.value))
            elif op.op == "ingest":
                from ..utils.failpoint import fail_point
                fail_point("apply::before_ingest")
                # bulk SST ingest (fsm/apply.rs IngestSst): op.value is
                # a v2 SST container; whole sorted runs bulk-merge into
                # the engine instead of replaying per-key ops.  Like
                # the reference's file ingest, rows land WITHOUT
                # passing the CDC observer — BR/Lightning require
                # no-import during replication for the same reason.
                from ..sst_importer import read_sst_cf
                # memo=True: hand this decode to the streaming cold
                # pipeline's observer read of the same blob object
                for cf, (keys, vals) in read_sst_cf(
                        op.value, memo=True).items():
                    wb.ingest_cf(cf, [data_key(k) for k in keys], vals)
            else:   # pragma: no cover
                raise ValueError(op.op)
        return {}

    def _exec_admin(self, wb, admin: AdminCmd,
                    cc: Optional[ConfChange] = None,
                    index: int = 0) -> dict:
        from ..utils.failpoint import fail_point
        if admin.kind == "split":
            fail_point("apply::before_split")
            return self._exec_split(wb, admin)
        if admin.kind == "change_peer":
            fail_point("apply::before_conf_change")
            return self._exec_change_peer(wb, admin, cc)
        if admin.kind == "compact_log":
            fail_point("apply::before_compact_log")
            return self._exec_compact_log(wb, admin)
        if admin.kind == "prepare_merge":
            fail_point("apply::before_prepare_merge")
            return self._exec_prepare_merge(wb, admin, index)
        if admin.kind == "commit_merge":
            fail_point("apply::before_commit_merge")
            return self._exec_commit_merge(wb, admin)
        if admin.kind == "rollback_merge":
            return self._exec_rollback_merge(wb, admin)
        if admin.kind == "compute_hash":
            return self._exec_compute_hash(index, admin)
        if admin.kind == "verify_hash":
            return self._exec_verify_hash(admin)
        raise ValueError(admin.kind)    # pragma: no cover

    # -- consistency check (worker/consistency_check.rs + fsm/apply.rs
    #    exec_compute_hash/exec_verify_hash) --
    #
    # The leader proposes ComputeHash; EVERY replica, applying it at the
    # same log index over the same replicated data, computes an identical
    # digest of the region's data CFs.  The leader then proposes
    # VerifyHash(index, its own digest); a replica whose stored digest
    # for that index differs has diverged — the reference panics the
    # node, here InconsistentRegion surfaces through the drive loop.

    def _exec_compute_hash(self, index: int,
                           admin: Optional[AdminCmd] = None) -> dict:
        import zlib
        from ..engine.traits import CF_DEFAULT, CF_LOCK, CF_WRITE
        from .peer_storage import region_data_bounds
        # GC via each node's LOCAL compaction filter legitimately drops
        # versions at/below the safe point at node-local times — raw
        # bytes of two healthy replicas may differ below it.  The
        # leader pins its safe point into the proposal; every replica
        # hashes only versions ABOVE it, so the digest is deterministic
        # whether or not a replica has compacted yet.
        safe_point = 0
        if admin is not None and len(admin.extra) == 8:
            (safe_point,) = struct.unpack(">Q", admin.extra)
        lo, hi = region_data_bounds(self.region)
        crc = 0
        for cf in (CF_DEFAULT, CF_LOCK, CF_WRITE):
            crc = zlib.crc32(cf.encode(), crc)
            it = self.engine.iterator_cf(cf, lo, hi)
            ok = it.seek_to_first()
            while ok:
                key = it.key()
                if safe_point and cf in (CF_DEFAULT, CF_WRITE) and \
                        len(key) > 9:
                    from ..storage.txn_types import split_ts
                    _, ts = split_ts(key[1:])
                    if ts <= safe_point:
                        ok = it.next()
                        continue
                crc = zlib.crc32(key, crc)
                crc = zlib.crc32(it.value(), crc)
                ok = it.next()
        # region state participates too (apply.rs hashes the region state
        # key): replicas at the same index must agree on the epoch
        ep = self.region.epoch
        crc = zlib.crc32(struct.pack(">QII", self.region.id, ep.conf_ver,
                                     ep.version), crc)
        self.consistency_state = (index, crc)
        return {"compute_hash": {"index": index, "hash": crc}}

    def _exec_verify_hash(self, admin: AdminCmd) -> dict:
        expect_index, expect_hash = struct.unpack(">QI", admin.extra)
        st = self.consistency_state
        if st is None or st[0] != expect_index:
            # stale/missed ComputeHash (e.g. this replica restarted or
            # caught up via snapshot past the compute index): the
            # reference logs and skips — a later round re-checks
            return {"verify_hash": "skipped"}
        if st[1] != expect_hash:
            from .metapb import InconsistentRegion
            raise InconsistentRegion(
                f"region {self.region.id} hash mismatch at index "
                f"{expect_index}: local {st[1]:#x} != leader "
                f"{expect_hash:#x}")
        return {"verify_hash": "ok"}

    def _exec_prepare_merge(self, wb, admin: AdminCmd,
                            index: int) -> dict:
        """fsm/apply.rs exec_prepare_merge: epoch bump + persisted merge
        state; the source stops accepting proposals until commit or
        rollback."""
        from dataclasses import replace
        from .peer_storage import merge_state_key
        region = self.region
        new_region = replace(region, epoch=RegionEpoch(
            region.epoch.conf_ver, region.epoch.version + 1))
        self.peer_storage.persist_region(wb, new_region)
        wb.put_cf(CF_RAFT, merge_state_key(region.id),
                  index.to_bytes(8, "big"))
        self.merging = index
        self.store.on_region_changed(self, new_region)
        return {"region": new_region, "prepare_index": index}

    def _exec_rollback_merge(self, wb, admin: AdminCmd) -> dict:
        """fsm/apply.rs exec_rollback_merge: clear the merge state and
        bump the epoch so stale CommitMerge attempts epoch-fail."""
        from dataclasses import replace
        from .peer_storage import merge_state_key
        region = self.region
        new_region = replace(region, epoch=RegionEpoch(
            region.epoch.conf_ver, region.epoch.version + 1))
        self.peer_storage.persist_region(wb, new_region)
        wb.delete_cf(CF_RAFT, merge_state_key(region.id))
        self.merging = None
        self.store.on_region_changed(self, new_region)
        return {"region": new_region}

    def _exec_commit_merge(self, wb, admin: AdminCmd) -> dict:
        """fsm/apply.rs exec_commit_merge (simplified to the coordinated
        protocol): the TARGET absorbs the adjacent source region.

        Data never moves — both regions share this store's engine; only
        the region boundary and the source's raft-local state change.
        Safety precondition (the coordinator enforced it before
        proposing, node.merge_region): every source peer has applied the
        PrepareMerge, so the local source peer's data is complete up to
        the merge point.  The reference instead ships the source log
        tail inside CommitMerge — the coordinated wait is the
        in-process/PD-scheduler equivalent.
        """
        from dataclasses import replace
        from .peer_storage import decode_region
        source = decode_region(admin.extra)
        region = self.region
        speer = self.store.peers.get(source.id)
        if speer is not None:
            # drain any committed-but-unapplied source entries first
            # (messages are dropped; the group is being destroyed)
            if speer.node.applied < admin.merge_index:
                speer.handle_ready()
            if speer.node.applied < admin.merge_index:
                raise AssertionError(
                    f"commit_merge: source {source.id} applied "
                    f"{speer.node.applied} < prepare {admin.merge_index}")
        # b"" as end_key means +infinity — it must never compare equal
        # to a b"" start_key (-infinity)
        if source.end_key and source.end_key == region.start_key:
            new_start, new_end = source.start_key, region.end_key
        elif region.end_key and region.end_key == source.start_key:
            new_start, new_end = region.start_key, source.end_key
        else:
            raise AssertionError("commit_merge: regions not adjacent")
        new_region = replace(
            region, start_key=new_start, end_key=new_end,
            epoch=RegionEpoch(
                max(region.epoch.conf_ver, source.epoch.conf_ver),
                max(region.epoch.version, source.epoch.version) + 1))
        self.peer_storage.persist_region(wb, new_region)
        self.store.destroy_peer(source.id)
        self.store.on_region_changed(self, new_region)
        return {"region": new_region}

    def _exec_split(self, wb, admin: AdminCmd) -> dict:
        """fsm/apply.rs exec_batch_split: left keeps the id, right is the
        new region [split_key, end); both bump epoch.version."""
        region = self.region
        from dataclasses import replace
        new_epoch = RegionEpoch(region.epoch.conf_ver,
                                region.epoch.version + 1)
        right_peers = tuple(
            PeerMeta(pid, p.store_id, p.is_learner)
            for pid, p in zip(admin.new_peer_ids, region.peers))
        right = Region(admin.new_region_id, admin.split_key,
                       region.end_key, new_epoch, right_peers)
        left = replace(region, end_key=admin.split_key, epoch=new_epoch)
        self.peer_storage.persist_region(wb, left)
        self.store.create_split_peer(wb, right, was_leader=self.is_leader())
        # split-aware observers (delta-log carry-over, device-side
        # line/feed slicing) act BEFORE the generic region_changed
        # sweep tears the parent's cache lines down.  Admin entries
        # never bump data_index, so self.data_index IS the last
        # pre-split write — the exact stamp for both children
        right_peer = self.store.peers.get(right.id)
        self.store.coprocessor_host.notify_region_split(
            left, right, self.data_index,
            right_peer.data_index if right_peer is not None else None)
        self.store.on_region_changed(self, left)
        return {"left": left, "right": right}

    def _exec_change_peer(self, wb, admin: AdminCmd,
                          cc: Optional[ConfChange]) -> dict:
        region = self.region
        peers = list(region.peers)
        p = admin.peer
        if admin.change_type in ("add", "add_learner"):
            peers = [x for x in peers if x.id != p.id]
            peers.append(PeerMeta(p.id, p.store_id,
                                  admin.change_type == "add_learner"))
        else:
            peers = [x for x in peers if x.id != p.id]
        new_region = region.with_peers(peers)
        self.peer_storage.persist_region(wb, new_region)
        if cc is not None:
            self.node.apply_conf_change(cc)
        self.store.on_region_changed(self, new_region)
        if admin.change_type == "remove" and p.id == self.meta.id:
            self.pending_destroy = True
        return {"region": new_region}

    def _exec_change_peer_v2(self, wb, admin: AdminCmd, cc2) -> dict:
        """Joint membership change apply (fsm/apply.rs ChangePeerV2 +
        raft §6).  Enter: region carries the UNION of old and new peer
        sets while raft enforces both majorities; the leader then
        auto-proposes the LEAVE, whose apply installs the target set.
        """
        import struct as _struct

        from dataclasses import replace
        from .cmd import decode_change_peer_v2
        from .peer_storage import joint_state_key
        meta = decode_change_peer_v2(admin.extra) if admin.extra else             {"changes": [], "leave": True, "target": None}
        region = self.region
        self.node.apply_conf_change_v2(cc2)
        # persist the joint state (BOTH sets: the incoming voters can't
        # be derived from region.peers, which holds the union) so a
        # restart mid-joint keeps the both-majority rules
        node = self.node
        if node.voters_outgoing:
            out_s = sorted(node.voters_outgoing)
            in_s = sorted(node.voters)
            wb.put_cf(CF_RAFT, joint_state_key(region.id),
                      _struct.pack(">II", len(out_s), len(in_s)) +
                      b"".join(_struct.pack(">Q", v)
                               for v in out_s + in_s))
        else:
            wb.delete_cf(CF_RAFT, joint_state_key(region.id))
        if cc2.leave_joint:
            if meta.get("target"):
                target = tuple(PeerMeta(p["id"], p["store_id"],
                                        p.get("learner", False))
                               for p in meta["target"])
            else:
                # bare leave (new-leader re-proposal): the target is the
                # post-leave raft membership filtered from the union
                member = self.node.voters | self.node.learners
                target = tuple(p for p in region.peers
                               if p.id in member)
            new_region = replace(
                region, peers=target,
                epoch=RegionEpoch(region.epoch.conf_ver + 1,
                                  region.epoch.version))
            self.peer_storage.persist_region(wb, new_region)
            self.store.on_region_changed(self, new_region)
            if not any(p.id == self.meta.id for p in target):
                self.pending_destroy = True
            return {"region": new_region}
        # enter joint: union of old peers and the incoming changes
        peers = {p.id: p for p in region.peers}
        target = dict(peers)
        for c in meta["changes"]:
            p = c["peer"]
            pm = PeerMeta(p["id"], p["store_id"], c["t"] == "add_learner")
            if c["t"] == "remove":
                target.pop(p["id"], None)
            else:
                target[p["id"]] = pm
                peers[p["id"]] = pm
        new_region = replace(
            region, peers=tuple(peers.values()),
            epoch=RegionEpoch(region.epoch.conf_ver + 1,
                              region.epoch.version))
        self.peer_storage.persist_region(wb, new_region)
        self.store.on_region_changed(self, new_region)
        if self.is_leader():
            # auto-leave (raft-rs ConfChangeV2 auto transition): the
            # leave entry carries the TARGET peer set for the meta
            from .cmd import encode_change_peer_v2
            leave_cmd = RaftCmd(
                new_region.id, new_region.epoch,
                admin=AdminCmd("change_peer_v2",
                               extra=encode_change_peer_v2(
                                   leave=True,
                                   target=list(target.values()))))
            self.node.propose_conf_change_v2(
                ConfChangeV2((), leave_cmd.to_bytes(), leave_joint=True),
                force=True)
        return {"region": new_region, "joint": True}

    def _exec_compact_log(self, wb, admin: AdminCmd) -> dict:
        index = min(admin.compact_index, self.node.applied)
        if index > self.node.storage.snapshot.metadata.index:
            self.node.storage.compact(index)
            self.peer_storage.compact_log(wb, index)
            # Rewrite raft_state with the POST-compact truncated marker in
            # the same batch: handle_ready persisted it with the marker
            # captured before this apply, and a crash between the two
            # writes would leave trunc_idx pointing below log entries that
            # this batch just deleted — an unrecoverable, non-contiguous
            # log on restart (reference: fsm/apply.rs exec_compact_log
            # updates RaftTruncatedState atomically with the deletion).
            meta = self.node.storage.snapshot.metadata
            self.peer_storage.persist(
                wb, [],
                HardState(self.node.term, self.node.vote, self.node.commit),
                truncated=(meta.index, meta.term))
        return {}

    # ------------------------------------------------------------- misc

    def _make_snapshot(self, index: int, term: int):
        # Generate at the APPLIED index, not the compaction marker: the
        # engine data + region meta reflect exactly node.applied, and a
        # lower stamp would make the receiver re-apply entries (e.g. conf
        # changes double-bumping conf_ver).  Reference: peer_storage.rs
        # do_snapshot uses the apply state's applied_index.
        from ..utils.failpoint import fail_point
        fail_point("snapshot::before_generate")
        applied = self.node.applied
        t = self.node.storage.term(applied)
        if t is None:
            t = term
        # raft-level conf travels verbatim: while JOINT, a receiver
        # must apply both-majority rules — deriving voters from the
        # region's peer union would weaken elections to a single
        # union-majority (unsafe: {old majority} can outvote there)
        node = self.node
        conf = (sorted(node.voters), sorted(node.learners),
                sorted(node.voters_outgoing))
        return self.peer_storage.generate_snapshot(applied, t,
                                                   self.region, conf)

    def step(self, msg: Message) -> None:
        # heartbeat chatter is not activity — counting it would keep
        # every region awake forever; real entries/votes/snapshots wake
        from ..raft.messages import MsgType as _MT
        if msg.msg_type not in (_MT.HEARTBEAT, _MT.HEARTBEAT_RESPONSE) \
                or msg.entries:
            self.wake()
        elif self.hibernated:
            # a heartbeat reaching a hibernated peer means some peer is
            # still awake (e.g. a rejoining follower): answer it
            self.wake()
        self.node.step(msg)

    HIBERNATE_IDLE_TICKS = 30   # ~3 election timeouts of quiet

    def tick(self) -> None:
        if getattr(self.store.config, "hibernate_regions", False):
            # hibernate (store/hibernate_state.rs:88): after sustained
            # quiet the leader stops heartbeating entirely, and
            # followers SLOW their election clocks 8× instead of
            # stopping them — a crashed hibernating leader is still
            # detected (pre-vote fires eventually and wakes the region)
            # without per-tick chatter from thousands of idle regions.
            self._idle_ticks += 1
            if self._idle_ticks > self.HIBERNATE_IDLE_TICKS:
                self.hibernated = True
                if self.is_leader() or self._idle_ticks % 8 != 0:
                    return
        self.node.tick()
        if self._replica_reads:
            self._retry_replica_reads()

    def wake(self) -> None:
        self._idle_ticks = 0
        self.hibernated = False

    def _retry_replica_reads(self) -> None:
        """Re-send pending ReadIndex requests (dropped request, leader
        without a lease yet, election churn) and expire hopeless ones."""
        expire_at = 4 * self.node._election_tick
        dead = []
        for ctx, ent in self._replica_reads.items():
            ent[2] += 1
            if ent[2] >= expire_at:
                dead.append(ctx)
            elif ent[2] % 2 == 0:
                self.node.request_read_index(ctx, ent[1])
        for ctx in dead:
            cb, _ts, _age = self._replica_reads.pop(ctx)
            cb(NotLeaderError(self.region.id, self.leader_peer()))
