"""RaftKv — the kv.Engine implemented by raft proposal + apply wait.

Reference: src/server/raftkv/mod.rs (RaftKv: async_snapshot :603 routes
a read through the consensus/lease path; async_write :472 proposes a
RaftCmdRequest and resolves when applied).  The synchronous surface here
blocks on a ``driver`` callable that pumps the in-process cluster (or the
standalone store loop) until the callback fires — the same shape as the
reference blocking on the apply callback.
"""

from __future__ import annotations

from typing import Callable, Optional

from ..kv.engine import SnapContext, WriteData
from .cmd import RaftCmd, WriteOp
from .metapb import NotLeaderError
from .store import RaftStore


class RaftKv:
    def __init__(self, store: RaftStore,
                 driver: Optional[Callable[[Callable[[], bool]], None]] = None,
                 lock=None, latency_inspector=None):
        self.store = store
        self._driver = driver if driver is not None else self._local_drive
        # serializes lease reads against the apply loop so the engine
        # snapshot and its data_index stamp are taken atomically
        self._lock = lock
        self.lease_reads = 0
        self.barrier_reads = 0
        self.stale_reads = 0
        # write-path latency inspector feeding the health controller's
        # slow score (store/async_io/write.rs:24 LatencyInspector)
        self._latency_inspector = latency_inspector
        # read-traffic hook feeding the load-split controller
        # (split_controller.rs: reads report their keys per region)
        self.on_read = None

    def _local_drive(self, done: Callable[[], bool]) -> None:
        for _ in range(10000):
            if done():
                return
            if self.store.drive() == 0 and done():
                return
            self.store.tick()
        raise TimeoutError("raft command did not complete")

    def _wait(self, box: dict) -> None:
        self._driver(lambda: "result" in box)
        result = box["result"]
        if isinstance(result, Exception):
            raise result

    # -- kv.Engine --

    def snapshot(self, ctx: SnapContext):
        # fail-slow injection (chaos): a browned-out store serves reads
        # slowly but correctly — the shed/hedge machinery above must
        # route around it, nothing below here misbehaves
        stall = getattr(self.store, "inject_read_delay_s", 0.0)
        if stall > 0:
            import time as _time
            _time.sleep(stall)
        peer = self._route(ctx)
        if self.on_read is not None and ctx.key_hint:
            self.on_read(peer.region.id, ctx.key_hint)
        if ctx.stale_read:
            # resolved-ts-gated local snapshot: correctness rests on the
            # caller's read_ts ≤ resolved_ts check (service layer) —
            # below the watermark no new commit can appear, so any
            # replica's applied state answers the MVCC read exactly
            self.stale_reads += 1
            return peer.stale_snapshot()
        if ctx.replica_read and not peer.is_leader():
            # follower read via ReadIndex (SURVEY §2.8.4): consistent at
            # the leader's commit point, zero leader load.  In the
            # synchronous drive mode registration must hold the node
            # lock — the drive thread touches the same read state
            # without peer.mu there.
            box: dict = {}
            cb = lambda r: box.__setitem__("result", r)  # noqa: E731
            if self._lock is not None and not self.store.pooled():
                with self._lock:
                    peer.replica_read(cb, ctx.read_ts)
            else:
                peer.replica_read(cb, ctx.read_ts)
            self._wait(box)
            return box["result"]
        # lease fast path (LocalReader): no proposal, no log barrier.
        # local_read serializes on the peer mutex; the extra node lock
        # covers the synchronous drive mode where pollers don't exist
        if self._lock is not None and not self.store.pooled():
            with self._lock:
                snap = peer.local_read()
        else:
            snap = peer.local_read()
        if snap is not None:
            self.lease_reads += 1
            return snap
        self.barrier_reads += 1
        box: dict = {}
        if self.store.pooled():
            if not self.store._route_peer_msg(
                    peer.region.id,
                    ("read", lambda r: box.__setitem__("result", r))):
                raise NotLeaderError(peer.region.id)    # mailbox gone
        else:
            peer.propose_read(lambda r: box.__setitem__("result", r))
        self._wait(box)
        return box["result"]

    def write(self, ctx: SnapContext, data: WriteData) -> None:
        key_hint = data.modifies[0][2] if data.modifies else b""
        peer = self._route(ctx, key_hint)
        ops = []
        for op, cf, key, value in data.modifies:
            if op == "put":
                ops.append(WriteOp("put", cf, key, value))
            else:
                ops.append(WriteOp("delete", cf, key))
        cmd = RaftCmd(peer.region.id, peer.region.epoch, tuple(ops))
        import time as _time
        t0 = _time.perf_counter()
        box: dict = {}
        if self.store.pooled():
            # proposals ride the mailbox: the peer's poller serializes
            # them with ready handling (fsm/peer.rs PeerMsg::RaftCommand)
            if not self.store._route_peer_msg(
                    peer.region.id,
                    ("cmd", cmd,
                     lambda r: box.__setitem__("result", r))):
                raise NotLeaderError(peer.region.id)    # mailbox gone
        else:
            peer.propose(cmd, lambda r: box.__setitem__("result", r))
        try:
            self._wait(box)
        finally:
            if self._latency_inspector is not None:
                self._latency_inspector(_time.perf_counter() - t0)

    def kv_engine(self):
        return self.store.engine

    # -- routing --

    def _route(self, ctx: SnapContext, key_hint: bytes = b""):
        if ctx.region_id:
            return self.store.region_peer(ctx.region_id)
        key = key_hint or ctx.key_hint
        if key:
            return self.store.peer_by_key(key)
        # single-region stores (tests / fresh clusters) route trivially
        peers = list(self.store.peers.values())
        leaders = [p for p in peers if p.is_leader()]
        if len(leaders) == 1:
            return leaders[0]
        if len(peers) == 1:
            return peers[0]
        raise NotLeaderError(0)
