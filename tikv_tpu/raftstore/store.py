"""RaftStore — all the peers living on one store.

Reference: components/raftstore/src/store/fsm/store.rs (StoreFsm +
store meta: region ranges → peers) and fsm/peer.rs message dispatch; the
batch-system actor runtime (components/batch-system) is collapsed into a
synchronous ``drive()`` loop — the reference's poll loop shape
(batch.rs:340) without threads, which the in-process cluster fixture and
the standalone server both pump.

Peer lifecycle handled here: bootstrap, create-on-message (a raft message
for an unknown region creates an uninitialized peer that a leader
snapshot then initializes — store/fsm/store.rs maybe_create_peer), split
(create_split_peer), and destroy on conf-change removal.
"""

from __future__ import annotations

import struct
from typing import Callable, Optional

from ..engine.traits import CF_RAFT, KvEngine
from ..raft.messages import Message, MsgType
from .cmd import AdminCmd, RaftCmd
from .metapb import Peer as PeerMeta, Region, RegionNotFound
from .peer import RaftPeer
from .peer_storage import (
    REGION_PREFIX,
    decode_region,
    region_state_key,
)


class Transport:
    """Store-to-store raft message channel.

    Reference: src/server/raft_client.rs (buffered per-peer connections)
    — here an interface; the in-process cluster and the network server
    provide impls.  ``send(to_store, region_id, to_peer, from_peer, msg)``.
    """

    def send(self, to_store: int, region_id: int, to_peer: PeerMeta,
             from_peer: PeerMeta, msg: Message) -> None:
        raise NotImplementedError


class _ApplyCtx:
    """Routing seam handed to peers' handle_ready: queue a committed
    plain-write batch on the apply pool, or drain a region's queue so
    complex entries (admin/conf-change/read barriers) keep commit
    order (fsm/apply.rs: PeerFsm -> ApplyRouter -> ApplyFsm)."""

    def __init__(self, store):
        self._store = store

    def send(self, region_id: int, entries) -> None:
        st = self._store
        if not st.apply_router.send(region_id, ("apply", entries)):
            # mailbox missing (register race on a fresh shell peer):
            # apply inline on this poller — nothing is queued, so
            # same-thread execution keeps commit order
            peer = st.peers.get(region_id)
            if peer is not None:
                peer.apply_plain_entries(entries)

    def drain(self, region_id: int, timeout: float = 10.0) -> None:
        import threading as _t
        st = self._store
        ev = _t.Event()
        if not st.apply_router.send(region_id, ("barrier", ev)):
            return
        if not ev.wait(timeout):
            raise TimeoutError(
                f"apply queue drain stalled for region {region_id}")


class RaftStore:
    def __init__(self, store_id: int, engine: KvEngine,
                 transport: Transport, election_tick: int = 10,
                 heartbeat_tick: int = 2, pre_vote: bool = True,
                 seed: int = 0, tick_interval: float | None = None):
        self.store_id = store_id
        self.engine = engine
        self.transport = transport
        self.peers: dict[int, RaftPeer] = {}
        self._raft_cfg = dict(election_tick=election_tick,
                              heartbeat_tick=heartbeat_tick,
                              pre_vote=pre_vote, seed=seed,
                              tick_interval=tick_interval)
        self._campaign_on_create: set[int] = set()
        # live raftstore knobs (split/gc thresholds); Node swaps in the
        # config-file section so online changes flow through
        from ..config import RaftstoreConfig
        self.config = RaftstoreConfig()
        # observer host: CDC/resolved-ts/backup hook the apply path here
        # (coprocessor/mod.rs:98-594)
        from .observer import CoprocessorHost
        self.coprocessor_host = CoprocessorHost()
        # write-path health (health_controller): every inspected engine
        # write feeds the slow score; store heartbeats carry it to PD so
        # scheduling steers leaders away from a fail-slow store
        from ..utils.health import HealthController
        self.health = HealthController(timeout_s=0.05,
                                       store_id=store_id)
        # fail-slow injection knobs (chaos fail_slow nemesis): persistent
        # per-store latency added inside the inspected write path /
        # the read snapshot path — a brownout, not an outage
        self.inject_write_delay_s = 0.0
        self.inject_read_delay_s = 0.0
        # guards self.peers mutations: pooled-mode pollers create/destroy
        # peers (split/merge/conf-change) while other threads iterate
        import threading as _threading
        self.meta_mu = _threading.Lock()

    def slow_down(self, seconds: float) -> None:
        """Inject persistent per-store latency (fail-slow brownout):
        applied inside every inspected engine write and every snapshot
        read until cleared with slow_down(0)."""
        self.inject_write_delay_s = seconds
        self.inject_read_delay_s = seconds

    # ------------------------------------------------------------- lifecycle

    def load_peers(self) -> None:
        """Restart path: recreate every peer persisted in the engine."""
        from ..utils.failpoint import fail_point
        fail_point("store::before_load_peers")
        it = self.engine.iterator_cf(
            CF_RAFT, REGION_PREFIX,
            REGION_PREFIX[:-1] + bytes([REGION_PREFIX[-1] + 1]))
        regions = []
        state_key_len = len(REGION_PREFIX) + 8 + 1
        ok = it.seek_to_first()
        while ok:
            k = it.key()
            # exact region_state_key shape: prefix + region_id(8) + "m".
            # A suffix check alone is wrong — raft_log_key ends with the
            # entry index whose low byte can be 0x6d ("m", e.g. index 109)
            if len(k) == state_key_len and k.endswith(b"m"):
                regions.append(decode_region(it.value()))
            ok = it.next()
        for region in regions:
            meta = region.peer_on_store(self.store_id)
            if meta is not None:
                self._add_peer(region, meta)

    def bootstrap_region(self, region: Region) -> None:
        """First-start path: persist + create the initial region's peer."""
        meta = region.peer_on_store(self.store_id)
        assert meta is not None, (region, self.store_id)
        peer = self._add_peer(region, meta, initial=True)
        wb = self.engine.write_batch()
        peer.peer_storage.write_initial_state(wb)
        peer.peer_storage.persist_region(wb, region)
        self.engine.write(wb)

    # set by the node: leader-side async-commit check for ReadIndex
    read_index_hook = None

    def _new_peer(self, region: Region, meta: PeerMeta,
                  initial: bool = False) -> RaftPeer:
        """THE single peer constructor: every creation path (bootstrap,
        restart load, split, shell-on-message) flows through here so
        per-peer wiring (the ReadIndex async-commit hook) exists in one
        place."""
        peer = RaftPeer(self, region, meta, self.engine, initial=initial,
                        **self._raft_cfg)
        if self.read_index_hook is not None:
            peer.node.read_index_hook = \
                (lambda ts, p=peer: self.read_index_hook(ts, p.region))
        return peer

    def _add_peer(self, region: Region, meta: PeerMeta,
                  initial: bool = False) -> RaftPeer:
        peer = self._new_peer(region, meta, initial=initial)
        with self.meta_mu:
            self.peers[region.id] = peer
        return peer

    def peers_snapshot(self) -> list:
        """Stable peer list for iteration from any thread."""
        with self.meta_mu:
            return list(self.peers.values())

    def create_split_peer(self, wb, right: Region,
                          was_leader: bool) -> None:
        """Apply-time creation of the right half of a split."""
        meta = right.peer_on_store(self.store_id)
        if meta is None or right.id in self.peers:
            return
        peer = self._add_peer(right, meta, initial=True)
        peer.peer_storage.write_initial_state(wb)
        peer.peer_storage.persist_region(wb, right)
        if self.pooled():
            self.router.register(right.id)
            if getattr(self, "_apply_pool", None) is not None:
                self.apply_router.register(right.id)
        if was_leader:
            # the parent's leader store campaigns the new region at once
            # so it gets a leader without waiting an election timeout
            if self.pooled():
                self.router.send(right.id, ("campaign",))
            else:
                self._campaign_on_create.add(right.id)

    def destroy_peer(self, region_id: int) -> None:
        from ..utils.failpoint import fail_point
        fail_point("store::before_destroy_peer")
        with self.meta_mu:
            peer = self.peers.pop(region_id, None)
        if peer is not None:
            wb = self.engine.write_batch()
            peer.peer_storage.destroy(wb)
            self.engine.write(wb)
            # lifecycle teardown: subscribers (delta sink, device-state
            # supervisor) drop every artifact derived from this region
            self.coprocessor_host.notify_peer_destroyed(region_id)

    # ------------------------------------------------------------- routing

    def region_peer(self, region_id: int) -> RaftPeer:
        peer = self.peers.get(region_id)
        if peer is None:
            raise RegionNotFound(region_id)
        return peer

    def peer_by_key(self, key: bytes) -> RaftPeer:
        for peer in self.peers_snapshot():
            if peer.region.contains(key):
                return peer
        raise RegionNotFound(-1)

    def on_region_changed(self, peer: RaftPeer, region: Region) -> None:
        """Metadata hook (split/conf change/snapshot) — the observer
        host's region-change event (raftstore/src/coprocessor)."""
        for obs in getattr(self, "observers", ()):
            obs(self.store_id, region)
        self.coprocessor_host.notify_region_changed(region)

    # ------------------------------------------------------------- messages

    def on_raft_message(self, region_id: int, to_peer: PeerMeta,
                        from_peer: PeerMeta, msg: Message) -> None:
        from ..utils.failpoint import fail_point
        # a "return" action models inbound message loss at this store
        if fail_point("store::drop_raft_message") is not None:
            return
        fail_point("store::on_raft_message")
        if self.pooled():
            if region_id not in self.peers and \
                    msg.msg_type in (MsgType.APPEND, MsgType.HEARTBEAT,
                                     MsgType.SNAPSHOT):
                # shell creation is check-then-act from concurrent
                # transport threads: atomic under meta_mu, or two
                # racers would clobber each other's peer + mailbox
                with self.meta_mu:
                    if region_id not in self.peers:
                        peer = self._new_peer(Region(region_id,
                                                     peers=()), to_peer)
                        self.peers[region_id] = peer
                        self.router.register(region_id)
                        if getattr(self, "_apply_pool", None) is not None:
                            self.apply_router.register(region_id)
            self._route_peer_msg(region_id,
                                 ("raft", to_peer, from_peer, msg))
            return
        peer = self.peers.get(region_id)
        if peer is None:
            # a message for a peer we don't have yet (add-peer or slow
            # split): create an uninitialized shell; the leader's snapshot
            # initializes it (maybe_create_peer)
            if msg.msg_type in (MsgType.APPEND, MsgType.HEARTBEAT,
                                MsgType.SNAPSHOT):
                # Empty peer list: the shell must NOT see itself as a
                # voter, else once leader contact lapses it self-elects
                # in a single-voter group and inflates terms (reference:
                # store/fsm/store.rs maybe_create_peer replicates with an
                # empty peer list; the leader snapshot installs the real
                # membership).  to_peer rides peer_cache/meta for routing.
                region = Region(region_id, peers=())
                peer = self._add_peer(region, to_peer)
            else:
                return
        if to_peer.id != peer.meta.id:
            return      # stale peer id
        peer.peer_cache[from_peer.id] = from_peer
        peer.step(msg)

    # --------------------------------------------------- pooled driving
    #
    # The batch-system mode (components/batch-system): each peer is an
    # FSM with a mailbox; a poller pool drains them with reschedule
    # fairness; append-only readies persist on the async write pool
    # (group-committed fsyncs).  The synchronous drive() below remains
    # the in-process fixture's deterministic single-threaded mode —
    # the reference keeps both shapes too (test_raftstore's node
    # simulator vs the real poll loops).

    def start_pool(self, n_pollers: int = 2, n_writers: int = 1,
                   n_appliers: int = 1) -> None:
        from .batch_system import PollerPool, Router, WriteWorkerPool
        self.router = Router()
        self.write_pool = WriteWorkerPool(self.engine, n_writers)
        for region_id in self.peers:
            self.router.register(region_id)
        self._pool = PollerPool(self.router, self._handle_fsm,
                                name=f"store-{self.store_id}")
        self._pool.spawn(n_pollers)
        # second batch-system for apply (fsm/apply.rs:3906 ApplyBatchSystem):
        # plain-write entry batches execute here so a slow apply (bulk
        # ingest, big writes) never stalls raft ticks/elections on the
        # raft pollers
        if n_appliers > 0:
            self.apply_router = Router()
            for region_id in self.peers:
                self.apply_router.register(region_id)
            self._apply_pool = PollerPool(
                self.apply_router, self._handle_apply_fsm,
                name=f"apply-{self.store_id}")
            self._apply_pool.spawn(n_appliers)
            self._apply_ctx = _ApplyCtx(self)
        else:
            self._apply_pool = None
            self._apply_ctx = None

    def stop_pool(self) -> None:
        pool = getattr(self, "_pool", None)
        if pool is not None:
            pool.shutdown()
            self.write_pool.shutdown()
            self._pool = None
        apool = getattr(self, "_apply_pool", None)
        if apool is not None:
            apool.shutdown()
            self._apply_pool = None
            self._apply_ctx = None

    def pooled(self) -> bool:
        return getattr(self, "_pool", None) is not None

    def _route_peer_msg(self, region_id: int, msg) -> bool:
        return self.router.send(region_id, msg)

    def _handle_fsm(self, region_id: int, msgs) -> None:
        """Poller handler: one peer's message batch (mailbox held)."""
        peer = self.peers.get(region_id)
        if peer is None:
            return
        with peer.mu:
            self._handle_fsm_locked(peer, region_id, msgs)

    def _handle_fsm_locked(self, peer, region_id: int, msgs) -> None:
        for m in msgs:
            kind = m[0]
            try:
                if kind == "raft":
                    _k, to_peer, from_peer, rmsg = m
                    if to_peer.id == peer.meta.id:
                        peer.peer_cache[from_peer.id] = from_peer
                        peer.step(rmsg)
                elif kind == "cmd":
                    _k, cmd, cb = m
                    try:
                        peer.propose(cmd, cb)
                    except Exception as e:      # noqa: BLE001
                        cb(e)
                elif kind == "read":
                    _k, cb = m
                    try:
                        peer.propose_read(cb)
                    except Exception as e:      # noqa: BLE001
                        cb(e)
                elif kind == "tick":
                    peer.tick()
                elif kind == "campaign":
                    peer.node.campaign(force=True)
                elif kind == "persisted":
                    _k, rd = m
                    self._send_all(peer, peer.on_log_persisted(rd))
                elif kind == "persist_failed":
                    # async log write failed: clear the gate so the next
                    # ready retries the persist synchronously, where the
                    # engine error surfaces per-FSM
                    peer._ready_inflight = False
            except Exception:   # noqa: BLE001 — one bad msg, not the fsm
                pass
        self._send_all(peer, peer.handle_ready(
            async_writer=self.write_pool,
            on_persisted=self._on_persisted,
            on_persist_failed=self._on_persist_failed,
            apply_ctx=getattr(self, "_apply_ctx", None)))
        if peer.pending_destroy:
            self.destroy_peer(region_id)
            self.router.close(region_id)
            apool = getattr(self, "_apply_pool", None)
            if apool is not None:
                self.apply_router.close(region_id)
        self.transport.flush()

    def _handle_apply_fsm(self, region_id: int, msgs) -> None:
        """Apply-pool handler: committed plain-write batches + drain
        barriers, FIFO per region (the mailbox IS the commit order)."""
        peer = self.peers.get(region_id)
        applied_any = False
        for m in msgs:
            kind = m[0]
            if kind == "apply":
                if peer is not None:
                    try:
                        peer.apply_plain_entries(m[1])
                        applied_any = True
                    except Exception:   # noqa: BLE001 — poison guard
                        import logging
                        logging.getLogger(__name__).exception(
                            "apply batch failed for region %d",
                            region_id)
            elif kind == "barrier":
                m[1].set()
        if applied_any:
            # kick the raft FSM: replica reads waiting on
            # applied_engine are served from its next handle_ready
            self.router.send(region_id, ("applied",))

    def _on_persisted(self, region_id: int, rd) -> None:
        # runs on a writer thread: route back through the mailbox so the
        # advance happens under the FSM invariant
        self.router.send(region_id, ("persisted", rd))

    def _on_persist_failed(self, region_id: int) -> None:
        self.router.send(region_id, ("persist_failed",))

    def _send_all(self, peer: RaftPeer, msgs) -> None:
        from ..utils.failpoint import fail_point
        for msg in msgs:
            if fail_point("store::drop_send") is not None:
                continue
            target = self._peer_meta(peer.region, msg.to) or \
                peer.peer_cache.get(msg.to)
            if target is None:
                continue
            self.transport.send(target.store_id, peer.region.id, target,
                                peer.meta, msg)

    # ------------------------------------------------------------- driving

    def tick(self) -> None:
        if self.pooled():
            self.router.broadcast(("tick",))
            return
        for peer in self.peers_snapshot():
            peer.tick()

    def drive(self) -> int:
        """Handle all pending ready work; send messages.  Returns the
        number of messages sent (0 = quiescent)."""
        if self.pooled():
            return 0        # the poller pool owns peer processing
        sent = 0
        for region_id in list(self.peers):
            peer = self.peers.get(region_id)
            if peer is None:
                continue
            if region_id in self._campaign_on_create:
                self._campaign_on_create.discard(region_id)
                peer.node.campaign(force=True)
            for msg in peer.handle_ready():
                target = self._peer_meta(peer.region, msg.to) or \
                    peer.peer_cache.get(msg.to)
                if target is None:
                    continue
                self.transport.send(target.store_id, region_id, target,
                                    peer.meta, msg)
                sent += 1
            if peer.pending_destroy:
                self.destroy_peer(region_id)
        return sent

    @staticmethod
    def _peer_meta(region: Region, peer_id: int) -> Optional[PeerMeta]:
        for p in region.peers:
            if p.id == peer_id:
                return p
        return None

    # ------------------------------------------------------- split checker

    def _scan_region(self, peer: RaftPeer):
        """ONE bulk pass over the region's data CFs → (total_bytes,
        sorted [(bare_key, bytes)]) — size and split-key candidates
        from the same scan.  The reference reads RocksDB
        table-properties instead (engine_rocks/src/properties.rs); a
        scan is exact and cheap at this engine's scale."""
        from ..engine.traits import CF_DEFAULT, CF_LOCK, CF_WRITE
        from .peer_storage import region_data_bounds
        rng = getattr(self.engine, "range_cf", None)
        lo, hi = region_data_bounds(peer.region)
        total = 0
        entries: list[tuple[bytes, int]] = []
        for cf, splittable in ((CF_WRITE, True), (CF_DEFAULT, True),
                               (CF_LOCK, False)):
            if rng is not None:
                keys, vals, _skip = rng(cf, lo, hi)
                for k, v in zip(keys, vals):
                    sz = len(k) + len(v)
                    total += sz
                    if splittable:
                        uk = k[1:]              # strip data prefix
                        if uk[:1] == b"x" and len(uk) > 8:
                            uk = uk[:-8]        # versions stay together
                        entries.append((uk, sz))
            else:   # pragma: no cover - engines without bulk range
                it = self.engine.iterator_cf(cf, lo, hi)
                ok = it.seek_to_first()
                while ok:
                    total += len(it.key()) + len(it.value())
                    ok = it.next()
        entries.sort()
        return total, entries

    def region_approximate_size(self, peer: RaftPeer) -> int:
        return self._scan_region(peer)[0]

    def find_split_key(self, peer: RaftPeer,
                       entries=None) -> Optional[bytes]:
        """The key where cumulative size crosses half the region —
        worker/split_check.rs's half-split policy.  Versioned keys
        (txn keyspace 'x', 8-byte ts suffix in write/default CFs) are
        truncated to the bare encoded key so one user key's versions
        never straddle the boundary."""
        if entries is None:
            entries = self._scan_region(peer)[1]
        if len(entries) < 2:
            return None
        total = sum(sz for _, sz in entries)
        acc = 0
        region = peer.region
        for uk, sz in entries:
            acc += sz
            if acc >= total // 2:
                if uk > region.start_key and \
                        (not region.end_key or uk < region.end_key):
                    return uk
                # keep walking: the midpoint key may equal start_key
                continue
        return None

    def _bucket_bounds(self, entries) -> list:
        """Sub-region bucket boundaries every region_bucket_size_mb of
        data (pd_client buckets: finer copr parallelism units)."""
        bucket_bytes = int(getattr(self.config, "region_bucket_size_mb",
                                   32) * (1 << 20))
        if bucket_bytes <= 0 or not entries:
            return []
        out = []
        acc = 0
        for uk, sz in entries:
            acc += sz
            if acc >= bucket_bytes:
                out.append(uk)
                acc = 0
        return out

    def split_check(self, pd) -> int:
        """One split-checker pass (store/worker/split_check.rs): leader
        peers over ``region_split_size_mb`` propose a half-split with
        PD-allocated ids.  One bulk scan per region serves the size
        estimate, the split key, AND the bucket bounds — but a region
        is only re-scanned once apply has accumulated
        ``region_split_check_diff`` bytes of changes since the last
        scan (fsm/apply.rs size_diff_hint): scanning every region every
        pass would cost seconds per tick at bench scale and contend
        every lease read.  Returns splits proposed."""
        threshold = int(self.config.region_split_size_mb * (1 << 20))
        if threshold <= 0:
            return 0
        # reference default: split-size/16 (coprocessor config
        # region_split_check_diff); bucket bounds also come from this
        # scan, so the finer of the two granularities drives the
        # re-check trigger.  Scales down with tiny test thresholds so
        # small fixtures still re-check promptly.
        bucket_bytes = int(getattr(self.config, "region_bucket_size_mb",
                                   32) * (1 << 20))
        gran = min(threshold, bucket_bytes) if bucket_bytes > 0 \
            else threshold
        check_diff = max(gran // 16, 1)
        proposed = 0
        for peer in self.peers_snapshot():
            if not peer.is_leader() or peer.merging is not None:
                continue
            if peer.size_diff_hint < check_diff:
                continue
            peer.size_diff_hint = 0
            size, entries = self._scan_region(peer)
            peer.approximate_size = size
            peer.buckets = self._bucket_bounds(entries)
            if size < threshold:
                continue
            split_key = self.find_split_key(peer, entries)
            if split_key is None:
                continue
            new_id, new_peer_ids = pd.ask_split(peer.region)
            cmd = RaftCmd(peer.region.id, peer.region.epoch,
                          admin=AdminCmd(
                              "split", split_key=split_key,
                              new_region_id=new_id,
                              new_peer_ids=tuple(new_peer_ids)))
            try:
                peer.propose(cmd, lambda r: None)
                proposed += 1
            except Exception:   # not leader anymore / epoch raced
                continue
        return proposed
