"""Load-based split controller: hot regions shed load, not just size.

Reference: components/raftstore/src/store/worker/split_controller.rs —
the read path reports each request's key (or range) per region; a
recorder keeps a reservoir sample per window; when a region's QPS stays
above ``qps_threshold`` for ``detect_times`` consecutive windows, the
controller picks a split key that balances the sampled accesses and
proposes a split exactly like the size checker.  Without this, a hot
SMALL region can never shed load — range sharding stays blind to skew
(SURVEY §2.8.1).

Design notes vs the reference:
- the reference samples whole key RANGES and scores candidate keys by
  (left, right, contained) counts over the sample; here requests are
  recorded by their first touched key and the split key is the sample
  median — same balance property for point-read and short-scan
  workloads, without the per-candidate scoring pass;
- recording is wait-free for readers: a bounded per-region reservoir
  behind one lock taken for a few appends per request, far off the
  read path's critical section;
- the controller runs from the store tick (the reference runs in the
  pd-worker's stats monitor) and routes proposals through the same
  PD ask_split → admin-cmd flow as size splits.
"""

from __future__ import annotations

import random
import threading
import time
from typing import Callable, Optional

# split_controller.rs defaults (QPS_THRESHOLD, DETECT_TIMES,
# SAMPLE_NUM scaled to this runtime's request rates)
DEFAULT_QPS_THRESHOLD = 3000
DEFAULT_DETECT_TIMES = 3
SAMPLE_NUM = 40


class _RegionRecorder:
    __slots__ = ("count", "samples", "hits")

    def __init__(self):
        self.count = 0
        self.samples: list[bytes] = []

    def record(self, key: bytes) -> None:
        self.count += 1
        if len(self.samples) < SAMPLE_NUM:
            self.samples.append(key)
        else:
            # reservoir: every request has SAMPLE_NUM/count odds
            j = random.randrange(self.count)
            if j < SAMPLE_NUM:
                self.samples[j] = key


class LoadSplitController:
    """Sliding-window QPS sampler + split proposer."""

    def __init__(self, qps_threshold: int = DEFAULT_QPS_THRESHOLD,
                 detect_times: int = DEFAULT_DETECT_TIMES,
                 window_s: float = 1.0):
        self.qps_threshold = qps_threshold
        self.detect_times = detect_times
        self.window_s = window_s
        self._mu = threading.Lock()
        self._recorders: dict[int, _RegionRecorder] = {}
        # region -> (consecutive hot windows, accumulated samples)
        self._hot: dict[int, tuple[int, list[bytes]]] = {}
        self._last_roll = time.monotonic()
        self.splits_proposed = 0

    # ---------------------------------------------------------- read path

    def record_read(self, region_id: int, key: bytes) -> None:
        """Called by every routed read (KvGet/Scan first key, copr
        first-range start) — a few appends under one short lock."""
        with self._mu:
            rec = self._recorders.get(region_id)
            if rec is None:
                rec = self._recorders[region_id] = _RegionRecorder()
            rec.record(key)

    # ------------------------------------------------------------- window

    def _roll_window(self, elapsed_s: Optional[float] = None
                     ) -> dict[int, list[bytes]]:
        """Close the current window → {region_id: samples} for regions
        hot for >= detect_times consecutive windows.

        ``elapsed_s`` is the ACTUAL wall time the window covered —
        tick() only guarantees at-least ``window_s``, and a late tick
        (stalled store loop, test fixture driving coarsely) that rolled
        with the nominal width would overestimate QPS and fire spurious
        load splits."""
        if elapsed_s is None:
            elapsed_s = self.window_s
        ready: dict[int, list[bytes]] = {}
        with self._mu:
            recorders, self._recorders = self._recorders, {}
            qps_floor = self.qps_threshold * max(elapsed_s, self.window_s)
            next_hot: dict[int, tuple[int, list[bytes]]] = {}
            for rid, rec in recorders.items():
                if rec.count < qps_floor:
                    continue        # streak broken: forget the region
                streak, acc = self._hot.get(rid, (0, []))
                acc = (acc + rec.samples)[-4 * SAMPLE_NUM:]
                streak += 1
                if streak >= self.detect_times:
                    ready[rid] = acc
                else:
                    next_hot[rid] = (streak, acc)
            self._hot = next_hot
        return ready

    def split_key_for(self, samples: list[bytes],
                      start_key: bytes, end_key: bytes) -> Optional[bytes]:
        """Median of the sampled keys, constrained strictly inside the
        region (split_controller.rs picks the best-balanced sample; the
        median IS the balance point of the sampled distribution)."""
        inside = sorted(k for k in samples
                        if k > start_key and (not end_key or k < end_key))
        if not inside:
            return None
        key = inside[len(inside) // 2]
        if key <= start_key or (end_key and key >= end_key):
            return None
        return key

    def tick(self, now: Optional[float] = None) -> dict[int, list[bytes]]:
        """→ {region_id: samples} due for a load split this window."""
        now = time.monotonic() if now is None else now
        elapsed = now - self._last_roll
        if elapsed < self.window_s:
            return {}
        self._last_roll = now
        return self._roll_window(elapsed)
