"""Cluster metadata types.

Reference: the kvproto ``metapb`` messages (Region, Peer, RegionEpoch,
Store) used throughout raftstore and pd_client.
"""

from __future__ import annotations

from dataclasses import dataclass, field, replace
from typing import Sequence


@dataclass(frozen=True)
class Peer:
    id: int
    store_id: int
    is_learner: bool = False


@dataclass(frozen=True)
class RegionEpoch:
    """conf_ver bumps on membership change; version on split/merge."""

    conf_ver: int = 1
    version: int = 1


@dataclass(frozen=True)
class Region:
    """A contiguous key range replicated by one raft group.

    ``start_key``/``end_key`` are user keys; empty end_key = +inf.
    """

    id: int
    start_key: bytes = b""
    end_key: bytes = b""
    epoch: RegionEpoch = RegionEpoch()
    peers: tuple = ()

    def contains(self, key: bytes) -> bool:
        if key < self.start_key:
            return False
        return not self.end_key or key < self.end_key

    def peer_on_store(self, store_id: int):
        for p in self.peers:
            if p.store_id == store_id:
                return p
        return None

    def with_peers(self, peers: Sequence[Peer],
                   bump_conf: bool = True) -> "Region":
        epoch = RegionEpoch(self.epoch.conf_ver + (1 if bump_conf else 0),
                            self.epoch.version)
        return replace(self, peers=tuple(peers), epoch=epoch)


@dataclass(frozen=True)
class Store:
    id: int
    address: str = ""


class EpochNotMatch(Exception):
    def __init__(self, current: Region):
        super().__init__(f"epoch not match; current {current.epoch}")
        self.current = current


class NotLeaderError(Exception):
    def __init__(self, region_id: int, leader=None):
        super().__init__(f"region {region_id}: not leader")
        self.region_id = region_id
        self.leader = leader


class KeyNotInRegion(Exception):
    def __init__(self, key: bytes, region: Region):
        super().__init__(f"{key!r} not in region {region.id}")
        self.key = key
        self.region = region


class RegionNotFound(Exception):
    def __init__(self, region_id: int):
        super().__init__(f"region {region_id} not found")
        self.region_id = region_id


class DataIsNotReady(Exception):
    """A stale read's read_ts is above this replica's resolved-ts
    watermark (kvproto errorpb DataIsNotReady): serving it could miss a
    commit still in flight below read_ts.  The client falls back to a
    leader or ReadIndex replica read."""

    def __init__(self, region_id: int, safe_ts: int, read_ts: int):
        super().__init__(f"region {region_id}: read_ts {read_ts} > "
                         f"resolved_ts {safe_ts}")
        self.region_id = region_id
        self.safe_ts = safe_ts
        self.read_ts = read_ts


class InconsistentRegion(Exception):
    """Consistency check failed: this replica's data digest differs from
    the leader's at the same applied index (the reference panics —
    fsm/apply.rs exec_verify_hash)."""


class RegionMerging(Exception):
    """Writes rejected while a PrepareMerge is in flight (reference:
    raftstore Error::ProposalInMergingMode) — retryable after the merge
    commits or rolls back."""

    def __init__(self, region_id: int):
        super().__init__(f"region {region_id} is merging")
        self.region_id = region_id
