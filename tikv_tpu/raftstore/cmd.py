"""Raft command payloads — what gets proposed into the raft log.

Reference: the kvproto ``raft_cmdpb`` messages (RaftCmdRequest with
either CmdType requests Put/Delete/DeleteRange or one AdminCmdType
request: Split / ChangePeer / CompactLog / TransferLeader —
components/raftstore/src/store/fsm/apply.rs exec_raft_cmd :1370-1740).

Serialization: a compact tagged binary format (length-prefixed fields) —
entries must be self-contained bytes so logs survive restarts and can
later cross the wire; no Python pickling.
"""

from __future__ import annotations

import struct
from dataclasses import dataclass, field
from typing import Optional, Sequence

from .metapb import Peer, Region, RegionEpoch


def _pack_bytes(b: bytes) -> bytes:
    return struct.pack(">I", len(b)) + b


def _unpack_bytes(buf: bytes, off: int) -> tuple[bytes, int]:
    (n,) = struct.unpack_from(">I", buf, off)
    off += 4
    return buf[off:off + n], off + n


@dataclass(frozen=True)
class WriteOp:
    """One KV mutation (CmdType::Put/Delete/DeleteRange)."""

    op: str         # put | delete | delete_range
    cf: str
    key: bytes
    value: bytes = b""

    def to_bytes(self) -> bytes:
        return (_pack_bytes(self.op.encode()) + _pack_bytes(self.cf.encode())
                + _pack_bytes(self.key) + _pack_bytes(self.value))

    @staticmethod
    def from_bytes(buf: bytes, off: int) -> tuple["WriteOp", int]:
        op, off = _unpack_bytes(buf, off)
        cf, off = _unpack_bytes(buf, off)
        key, off = _unpack_bytes(buf, off)
        value, off = _unpack_bytes(buf, off)
        return WriteOp(op.decode(), cf.decode(), key, value), off


@dataclass(frozen=True)
class AdminCmd:
    """Admin command.  kind: split | change_peer | compact_log |
    prepare_merge | commit_merge | rollback_merge.

    split: split_key + new_region_id + new_peer_ids
    change_peer: change_type(add|remove|add_learner) + peer
    compact_log: compact_index
    prepare_merge: target region id rides new_region_id
    commit_merge: extra = encoded source Region, merge_index = the
        source's prepare-merge apply index (fsm/apply.rs merge cmds)
    rollback_merge: merge_index = the prepare index being rolled back
    """

    kind: str
    split_key: bytes = b""
    new_region_id: int = 0
    new_peer_ids: tuple = ()
    change_type: str = ""
    peer: Optional[Peer] = None
    compact_index: int = 0
    merge_index: int = 0
    extra: bytes = b""          # commit_merge: encoded source region

    def to_bytes(self) -> bytes:
        parts = [_pack_bytes(self.kind.encode()), _pack_bytes(self.split_key),
                 struct.pack(">QQ", self.new_region_id, self.compact_index),
                 struct.pack(">I", len(self.new_peer_ids))]
        parts += [struct.pack(">Q", p) for p in self.new_peer_ids]
        parts.append(_pack_bytes(self.change_type.encode()))
        if self.peer is not None:
            parts.append(struct.pack(">BQQB", 1, self.peer.id,
                                     self.peer.store_id,
                                     int(self.peer.is_learner)))
        else:
            parts.append(struct.pack(">B", 0))
        # trailing fields: absent in pre-merge logs, decoder tolerates
        parts.append(struct.pack(">Q", self.merge_index))
        parts.append(_pack_bytes(self.extra))
        return b"".join(parts)

    @staticmethod
    def from_bytes(buf: bytes, off: int) -> tuple["AdminCmd", int]:
        kind, off = _unpack_bytes(buf, off)
        split_key, off = _unpack_bytes(buf, off)
        new_region_id, compact_index = struct.unpack_from(">QQ", buf, off)
        off += 16
        (n,) = struct.unpack_from(">I", buf, off)
        off += 4
        ids = []
        for _ in range(n):
            (pid,) = struct.unpack_from(">Q", buf, off)
            ids.append(pid)
            off += 8
        change_type, off = _unpack_bytes(buf, off)
        (has_peer,) = struct.unpack_from(">B", buf, off)
        off += 1
        peer = None
        if has_peer:
            pid, sid, learner = struct.unpack_from(">QQB", buf, off)
            off += 17
            peer = Peer(pid, sid, bool(learner))
        merge_index = 0
        extra = b""
        if off + 8 <= len(buf):     # logs from before the merge fields
            (merge_index,) = struct.unpack_from(">Q", buf, off)
            off += 8
            extra, off = _unpack_bytes(buf, off)
        return AdminCmd(kind.decode(), split_key, new_region_id, tuple(ids),
                        change_type.decode(), peer, compact_index,
                        merge_index, extra), off


def encode_change_peer_v2(changes=(), leave: bool = False,
                          target=None) -> bytes:
    """The ONE encoder for change_peer_v2 admin payloads: ``changes`` =
    [(type_str, Peer)], ``target`` = final peer list for LEAVE."""
    import msgpack
    return msgpack.packb({
        "changes": [{"t": t, "peer": {"id": p.id, "store_id": p.store_id,
                                      "learner": p.is_learner}}
                    for t, p in changes],
        "leave": leave,
        "target": [{"id": p.id, "store_id": p.store_id,
                    "learner": p.is_learner} for p in (target or ())],
    }, use_bin_type=True)


def decode_change_peer_v2(extra: bytes) -> dict:
    import msgpack
    return msgpack.unpackb(extra, raw=False)


@dataclass(frozen=True)
class RaftCmd:
    """One proposed command: header (routing + epoch check) + payload."""

    region_id: int
    epoch: RegionEpoch
    ops: tuple = ()                    # tuple[WriteOp]
    admin: Optional[AdminCmd] = None

    def to_bytes(self) -> bytes:
        head = struct.pack(">QII", self.region_id, self.epoch.conf_ver,
                           self.epoch.version)
        if self.admin is not None:
            return head + b"A" + self.admin.to_bytes()
        # join, never body += op_bytes: quadratic concat turns a 20k-op
        # batch proposal into seconds of memcpy
        parts = [head, b"W", struct.pack(">I", len(self.ops))]
        parts.extend(op.to_bytes() for op in self.ops)
        return b"".join(parts)

    @staticmethod
    def peek_admin_kind(buf: bytes):
        """Cheap wire peek: the admin kind string, or None for write
        commands — without decoding the payload.  Owns the layout
        knowledge (16-byte header + b"A" tag + length-prefixed kind) so
        callers never hardcode offsets."""
        if buf[16:17] != b"A":
            return None
        kind, _ = _unpack_bytes(buf, 17)
        return kind.decode()

    @staticmethod
    def from_bytes(buf: bytes) -> "RaftCmd":
        region_id, conf_ver, version = struct.unpack_from(">QII", buf, 0)
        off = 16
        tag = buf[off:off + 1]
        off += 1
        epoch = RegionEpoch(conf_ver, version)
        if tag == b"A":
            admin, _ = AdminCmd.from_bytes(buf, off)
            return RaftCmd(region_id, epoch, (), admin)
        (n,) = struct.unpack_from(">I", buf, off)
        off += 4
        ops = []
        for _ in range(n):
            op, off = WriteOp.from_bytes(buf, off)
            ops.append(op)
        return RaftCmd(region_id, epoch, tuple(ops))
