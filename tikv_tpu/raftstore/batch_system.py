"""Batch-system — the multi-raft actor runtime.

Reference: components/batch-system/src/ — thousands of region FSMs
multiplexed over a small poller pool: each FSM owns a ``BasicMailbox``
(batch.rs ``Fsm`` + mailbox state machine), senders ``notify`` the
scheduler queue on first message, pollers claim notified FSMs, drain a
bounded batch of messages, and REQUEUE an FSM that still has work
instead of spinning on it (reschedule fairness, batch.rs:292,340) — so
one hot region cannot starve the rest.

Python shape: the FSM invariant (one poller processes an FSM at a
time) comes from the mailbox state field flipping idle→notified→
processing under the mailbox lock; the GIL serializes bytecode but the
pool still overlaps the blocking stages (WAL fsync, gRPC sends) that
release it — exactly the IO the reference moves off the raft threads
(store/async_io/write.rs).
"""

from __future__ import annotations

import queue
import threading
from collections import deque
from typing import Callable, Optional

# mailbox states
_IDLE = 0           # no pending messages, not scheduled
_NOTIFIED = 1       # queued for a poller
_PROCESSING = 2     # a poller owns it right now


class Mailbox:
    """One FSM's inbox (batch-system BasicMailbox)."""

    def __init__(self, fsm_id):
        self.fsm_id = fsm_id
        self._msgs: deque = deque()
        self._mu = threading.Lock()
        self._state = _IDLE
        self.closed = False

    def push(self, msg) -> bool:
        """→ True if the FSM must be (re)scheduled."""
        with self._mu:
            if self.closed:
                return False
            self._msgs.append(msg)
            if self._state == _IDLE:
                self._state = _NOTIFIED
                return True
            return False

    def take(self, max_batch: int) -> list:
        """Poller claims the mailbox and drains up to max_batch."""
        with self._mu:
            self._state = _PROCESSING
            out = []
            while self._msgs and len(out) < max_batch:
                out.append(self._msgs.popleft())
            return out

    def finish(self) -> bool:
        """Poller releases; → True if messages arrived meanwhile (the
        FSM must requeue — the fairness hook)."""
        with self._mu:
            if self._msgs:
                self._state = _NOTIFIED
                return True
            self._state = _IDLE
            return False

    def close(self) -> None:
        with self._mu:
            self.closed = True
            self._msgs.clear()


class Router:
    """fsm_id → mailbox registry + the scheduler queue (router.rs)."""

    def __init__(self):
        self._mailboxes: dict = {}
        self._mu = threading.Lock()
        self.schedule_q: "queue.Queue" = queue.Queue()

    def register(self, fsm_id) -> Mailbox:
        mb = Mailbox(fsm_id)
        with self._mu:
            self._mailboxes[fsm_id] = mb
        return mb

    def close(self, fsm_id) -> None:
        with self._mu:
            mb = self._mailboxes.pop(fsm_id, None)
        if mb is not None:
            mb.close()

    def mailbox(self, fsm_id) -> Optional[Mailbox]:
        return self._mailboxes.get(fsm_id)

    def send(self, fsm_id, msg) -> bool:
        mb = self._mailboxes.get(fsm_id)
        if mb is None:
            return False
        if mb.push(msg):
            self.schedule_q.put(fsm_id)
        return True

    def broadcast(self, msg) -> None:
        with self._mu:
            ids = list(self._mailboxes)
        for fsm_id in ids:
            self.send(fsm_id, msg)


class PollerPool:
    """N poller threads draining the scheduler queue (batch.rs Poller).

    ``handler(fsm_id, msgs)`` runs with the FSM's mailbox held in
    PROCESSING state — the one-poller-per-FSM invariant the raftstore
    peer code relies on for mutation safety.
    """

    def __init__(self, router: Router, handler: Callable,
                 max_batch: int = 256, name: str = "poller"):
        self._router = router
        self._handler = handler
        self._max_batch = max_batch
        self._name = name
        self._threads: list = []
        self._stop = threading.Event()

    def spawn(self, n: int) -> None:
        for i in range(n):
            t = threading.Thread(target=self._poll, daemon=True,
                                 name=f"{self._name}-{i}")
            t.start()
            self._threads.append(t)

    def _poll(self) -> None:
        q = self._router.schedule_q
        while not self._stop.is_set():
            try:
                fsm_id = q.get(timeout=0.1)
            except queue.Empty:
                continue
            mb = self._router.mailbox(fsm_id)
            if mb is None or mb.closed:
                continue
            msgs = mb.take(self._max_batch)
            try:
                if msgs:
                    self._handler(fsm_id, msgs)
            except Exception:   # noqa: BLE001
                # one FSM's failure must not kill the poller thread —
                # log it and keep draining the rest of the store
                import logging
                logging.getLogger(__name__).exception(
                    "fsm %r handler failed", fsm_id)
            finally:
                if mb.finish():
                    # reschedule fairness: go to the BACK of the queue
                    q.put(fsm_id)

    def shutdown(self) -> None:
        self._stop.set()
        for t in self._threads:
            t.join(timeout=2)
        self._threads.clear()


class WriteWorkerPool:
    """Async raft-log IO (store/async_io/write.rs): WAL-bearing write
    batches from many peers funnel to dedicated writer threads; each
    worker GROUP-COMMITS everything queued at wake-up in one engine
    write (one fsync covers many regions), then runs the peers'
    post-persist callbacks (send messages, apply)."""

    def __init__(self, engine, n_workers: int = 1):
        self._engine = engine
        self._q: "queue.Queue" = queue.Queue()
        self._threads = []
        self._stop = threading.Event()
        # a failed log write poisons the pool: peers fall back to the
        # synchronous persist path where the error surfaces per-FSM
        # instead of stranding _ready_inflight gates forever
        self.failed = False
        for i in range(n_workers):
            t = threading.Thread(target=self._run, daemon=True,
                                 name=f"raftlog-writer-{i}")
            t.start()
            self._threads.append(t)

    def submit(self, wb, on_persisted: Callable,
               fail_cb: Optional[Callable] = None) -> None:
        if self.failed:
            if fail_cb is not None:
                fail_cb()
            return
        self._q.put((wb, on_persisted, fail_cb))

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                first = self._q.get(timeout=0.1)
            except queue.Empty:
                continue
            batch = [first]
            while True:
                try:
                    batch.append(self._q.get_nowait())
                except queue.Empty:
                    break
            # group commit: one engine write (one fsync) for the batch
            merged = self._engine.write_batch()
            for wb, _cb, _fail in batch:
                merged._ops.extend(wb._ops)
            try:
                if not merged.is_empty():
                    self._engine.write(merged)
            except Exception:
                # a failed raft-log write means NOTHING in this batch
                # may be acked (the reference panics here, write.rs);
                # poison the pool and tell each peer so its inflight
                # gate clears and the sync path surfaces the error
                import logging
                logging.getLogger(__name__).critical(
                    "raft-log write failed; async IO disabled",
                    exc_info=True)
                self.failed = True
                for _wb, _cb, fail_cb in batch:
                    if fail_cb is not None:
                        try:
                            fail_cb()
                        except Exception:   # noqa: BLE001
                            pass
                continue
            for _wb, cb, _fail in batch:
                try:
                    cb()
                except Exception:   # noqa: BLE001 — peer callbacks
                    pass

    def shutdown(self) -> None:
        self._stop.set()
        for t in self._threads:
            t.join(timeout=2)
        self._threads.clear()
