"""Multi-raft replication layer.

Reference: components/raftstore (69k LoC): peers multiplexed per store,
apply path, region lifecycle (split / conf change / snapshot catch-up),
and RaftKv — the consensus-backed kv.Engine.
"""

from .cmd import AdminCmd, RaftCmd, WriteOp
from .metapb import (
    EpochNotMatch,
    KeyNotInRegion,
    NotLeaderError,
    Peer,
    Region,
    RegionEpoch,
    RegionNotFound,
    Store,
)
from .peer import RaftPeer, RegionSnapshot
from .raftkv import RaftKv
from .store import RaftStore, Transport

__all__ = [
    "AdminCmd", "RaftCmd", "WriteOp", "EpochNotMatch", "KeyNotInRegion",
    "NotLeaderError", "Peer", "Region", "RegionEpoch", "RegionNotFound",
    "Store", "RaftPeer", "RegionSnapshot", "RaftKv", "RaftStore",
    "Transport",
]
