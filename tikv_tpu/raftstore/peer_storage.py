"""Peer storage — durable raft state in the engine.

Reference: components/raftstore/src/store/peer_storage.rs (RaftLocalState,
RaftApplyState, RegionLocalState persisted in CF_RAFT) and
components/keys/src/lib.rs (region raft key layout).  The RawNode runs on
an in-memory log (raft/storage.py); this class mirrors every persisted
Ready into the engine so a restarted store reconstructs the exact raft
state, and generates region snapshots for follower catch-up.
"""

from __future__ import annotations

import struct
from typing import Optional, Sequence

from ..engine.traits import CF_RAFT, DATA_CFS, KvEngine
from ..raft.messages import (
    Entry,
    EntryType,
    HardState,
    Snapshot,
    SnapshotMetadata,
)
from ..raft.storage import MemoryRaftStorage
from .cmd import _pack_bytes, _unpack_bytes
from .metapb import Peer, Region, RegionEpoch

LOCAL_PREFIX = b"\x01"
REGION_PREFIX = LOCAL_PREFIX + b"r"
DATA_PREFIX = b"z"

# New regions start their log *after* this index (reference:
# store/peer_storage.rs RAFT_INIT_LOG_INDEX/TERM = 5).  An empty shell
# peer (created on first message) can then never be served by log
# appends — the leader must ship a region snapshot, which carries the
# authoritative region metadata.  Catch-up via bare log replay would
# leave the shell's peer list permanently diverged.
RAFT_INIT_LOG_INDEX = 5
RAFT_INIT_LOG_TERM = 5


def raft_log_key(region_id: int, index: int) -> bytes:
    return REGION_PREFIX + struct.pack(">Q", region_id) + b"l" + \
        struct.pack(">Q", index)


def raft_state_key(region_id: int) -> bytes:
    return REGION_PREFIX + struct.pack(">Q", region_id) + b"s"


def apply_state_key(region_id: int) -> bytes:
    return REGION_PREFIX + struct.pack(">Q", region_id) + b"a"


def region_state_key(region_id: int) -> bytes:
    return REGION_PREFIX + struct.pack(">Q", region_id) + b"m"


def joint_state_key(region_id: int) -> bytes:
    """Persisted joint-consensus outgoing voter set (ConfState's
    voters_outgoing): non-empty between enter-joint and leave-joint so
    a restarted peer keeps enforcing BOTH majorities."""
    return REGION_PREFIX + struct.pack(">Q", region_id) + b"j"


def merge_state_key(region_id: int) -> bytes:
    """Persisted PrepareMerge state (raft_serverpb MergeState analog):
    value = >Q prepare-apply-index.  Lives under the region's CF_RAFT
    prefix so peer destruction cleans it up with everything else."""
    return REGION_PREFIX + struct.pack(">Q", region_id) + b"g"


def data_key(key: bytes) -> bytes:
    return DATA_PREFIX + key


def region_data_bounds(region: Region) -> tuple[bytes, Optional[bytes]]:
    lower = DATA_PREFIX + region.start_key
    upper = DATA_PREFIX + region.end_key if region.end_key else \
        bytes([DATA_PREFIX[0] + 1])
    return lower, upper


# -- serialization of the three local states --

def encode_region(region: Region) -> bytes:
    out = struct.pack(">QII", region.id, region.epoch.conf_ver,
                      region.epoch.version)
    out += _pack_bytes(region.start_key) + _pack_bytes(region.end_key)
    out += struct.pack(">I", len(region.peers))
    for p in region.peers:
        out += struct.pack(">QQB", p.id, p.store_id, int(p.is_learner))
    return out


def decode_region(buf: bytes) -> Region:
    rid, conf_ver, version = struct.unpack_from(">QII", buf, 0)
    off = 16
    start, off = _unpack_bytes(buf, off)
    end, off = _unpack_bytes(buf, off)
    (n,) = struct.unpack_from(">I", buf, off)
    off += 4
    peers = []
    for _ in range(n):
        pid, sid, learner = struct.unpack_from(">QQB", buf, off)
        off += 17
        peers.append(Peer(pid, sid, bool(learner)))
    return Region(rid, start, end, RegionEpoch(conf_ver, version),
                  tuple(peers))


def encode_entry(e: Entry) -> bytes:
    return struct.pack(">QQB", e.term, e.index,
                       1 if e.entry_type is EntryType.CONF_CHANGE else 0) \
        + e.data


def decode_entry(buf: bytes) -> Entry:
    term, index, is_cc = struct.unpack_from(">QQB", buf, 0)
    return Entry(term, index, buf[17:],
                 EntryType.CONF_CHANGE if is_cc else EntryType.NORMAL)


class PeerRaftStorage(MemoryRaftStorage):
    """MemoryRaftStorage whose *outgoing* snapshots are generated on
    demand from region data (leader side of follower catch-up); the
    compaction marker ``self.snapshot`` stays the log-arithmetic anchor."""

    def __init__(self, voters: Sequence[int] = ()):
        super().__init__(voters)
        self.snapshot_provider = None   # fn(index, term) -> Snapshot

    def snapshot_for_send(self):
        if self.snapshot_provider is not None:
            meta = self.snapshot.metadata
            return self.snapshot_provider(meta.index, meta.term)
        return self.snapshot


class PeerStorage:
    """Durability mirror of one peer's raft state."""

    def __init__(self, engine: KvEngine, region: Region):
        self.engine = engine
        self.region = region

    # -- restart/load --

    def load(self) -> tuple[PeerRaftStorage, int]:
        """→ (raft storage for RawNode, applied_index)."""
        rid = self.region.id
        ms = PeerRaftStorage(voters=tuple(
            p.id for p in self.region.peers if not p.is_learner))
        outgoing: tuple = ()
        incoming = None
        rawj = self.engine.get_value_cf(CF_RAFT, joint_state_key(rid))
        if rawj:
            n_out, n_in = struct.unpack_from(">II", rawj, 0)
            vals = struct.unpack_from(f">{n_out + n_in}Q", rawj, 8)
            outgoing = tuple(vals[:n_out])
            # the true INCOMING set: region.peers holds the old/new
            # UNION while joint, so deriving voters from it would
            # weaken decisions to a union majority
            incoming = tuple(vals[n_out:])
        ms.set_conf(
            incoming if incoming is not None else
            [p.id for p in self.region.peers if not p.is_learner],
            [p.id for p in self.region.peers if p.is_learner],
            outgoing)
        raw = self.engine.get_value_cf(CF_RAFT, raft_state_key(rid))
        applied = 0
        if raw is not None:
            term, vote, commit, trunc_idx, trunc_term = \
                struct.unpack_from(">QQQQQ", raw, 0)
            ms.set_hard_state(HardState(term, vote, commit))
            if trunc_idx:
                meta = ms.snapshot.metadata
                ms.snapshot = Snapshot(SnapshotMetadata(
                    trunc_idx, trunc_term, meta.voters, meta.learners,
                    meta.voters_outgoing))
            # replay the persisted log tail
            it = self.engine.iterator_cf(
                CF_RAFT, raft_log_key(rid, 0),
                raft_log_key(rid, 2**64 - 1))
            ok = it.seek_to_first()
            entries = []
            while ok:
                entries.append(decode_entry(it.value()))
                ok = it.next()
            if entries:
                ms.append(entries)
        rawa = self.engine.get_value_cf(CF_RAFT, apply_state_key(rid))
        if rawa is not None:
            (applied,) = struct.unpack_from(">Q", rawa, 0)
        return ms, applied

    # -- persist one Ready --

    def persist(self, wb, entries: Sequence[Entry],
                hard_state: Optional[HardState],
                truncated: tuple = (0, 0)) -> None:
        rid = self.region.id
        for e in entries:
            wb.put_cf(CF_RAFT, raft_log_key(rid, e.index), encode_entry(e))
        if entries:
            # drop any stale conflicting suffix beyond the new last entry
            wb.delete_range_cf(CF_RAFT,
                               raft_log_key(rid, entries[-1].index + 1),
                               raft_log_key(rid, 2**64 - 1))
        if hard_state is not None:
            wb.put_cf(CF_RAFT, raft_state_key(rid), struct.pack(
                ">QQQQQ", hard_state.term, hard_state.vote,
                hard_state.commit, truncated[0], truncated[1]))

    def write_initial_state(self, wb) -> None:
        """Bootstrap/split-time state: log begins at RAFT_INIT_LOG_INDEX."""
        rid = self.region.id
        wb.put_cf(CF_RAFT, raft_state_key(rid), struct.pack(
            ">QQQQQ", RAFT_INIT_LOG_TERM, 0, RAFT_INIT_LOG_INDEX,
            RAFT_INIT_LOG_INDEX, RAFT_INIT_LOG_TERM))
        self.persist_apply(wb, RAFT_INIT_LOG_INDEX)

    def persist_apply(self, wb, applied_index: int) -> None:
        wb.put_cf(CF_RAFT, apply_state_key(self.region.id),
                  struct.pack(">Q", applied_index))

    def persist_region(self, wb, region: Region) -> None:
        self.region = region
        wb.put_cf(CF_RAFT, region_state_key(region.id),
                  encode_region(region))

    def compact_log(self, wb, to_index: int) -> None:
        rid = self.region.id
        wb.delete_range_cf(CF_RAFT, raft_log_key(rid, 0),
                           raft_log_key(rid, to_index + 1))

    def destroy(self, wb) -> None:
        rid = self.region.id
        wb.delete_range_cf(CF_RAFT, REGION_PREFIX + struct.pack(">Q", rid),
                           REGION_PREFIX + struct.pack(">Q", rid + 1))

    # -- region snapshots (follower catch-up; store/snap.rs role) --

    def generate_snapshot(self, index: int, term: int,
                          region: Region, conf=None) -> Snapshot:
        snap = self.engine.snapshot()
        lower, upper = region_data_bounds(region)
        parts = [encode_region(region)]
        for cf in DATA_CFS:
            pairs = []
            it = snap.iterator_cf(cf, lower, upper)
            ok = it.seek_to_first()
            while ok:
                pairs.append((it.key(), it.value()))
                ok = it.next()
            body = struct.pack(">I", len(pairs))
            for k, v in pairs:
                body += _pack_bytes(k) + _pack_bytes(v)
            parts.append(_pack_bytes(cf.encode()) + body)
        if conf is not None:
            voters, learners, outgoing = conf
        else:
            voters = tuple(p.id for p in region.peers
                           if not p.is_learner)
            learners = tuple(p.id for p in region.peers if p.is_learner)
            outgoing = ()
        return Snapshot(SnapshotMetadata(index, term, tuple(voters),
                                         tuple(learners),
                                         tuple(outgoing)),
                        _pack_bytes(parts[0]) + b"".join(parts[1:]))

    def apply_snapshot(self, wb, snap: Snapshot) -> Region:
        """Install region data from a snapshot; returns the region meta."""
        buf = snap.data
        region_raw, off = _unpack_bytes(buf, 0)
        region = decode_region(region_raw)
        # Clear ALL persisted raft log entries for the region: a lagging
        # follower caught up by snapshot may hold stale entries below the
        # snapshot index, which restart-replay would then try to append
        # under the new (higher) compaction marker and assert.  Entries
        # after the snapshot are re-persisted by subsequent readies.
        # (reference: peer_storage.rs clear_meta deletes the raft log
        # range when applying a snapshot)
        wb.delete_range_cf(CF_RAFT, raft_log_key(region.id, 0),
                           raft_log_key(region.id, 2**64 - 1))
        lower, upper = region_data_bounds(region)
        for cf in DATA_CFS:
            wb.delete_range_cf(cf, lower, upper)
        for _ in range(len(DATA_CFS)):
            cf_raw, off = _unpack_bytes(buf, off)
            cf = cf_raw.decode()
            (n,) = struct.unpack_from(">I", buf, off)
            off += 4
            for _ in range(n):
                k, off = _unpack_bytes(buf, off)
                v, off = _unpack_bytes(buf, off)
                wb.put_cf(cf, k, v)
        self.persist_region(wb, region)
        self.persist_apply(wb, snap.metadata.index)
        # a mid-joint snapshot must leave the receiver JOINT across a
        # restart too: persist both voter sets, or clear (restore()
        # would otherwise derive a single union config from the peers —
        # the split-brain generate_snapshot's comment warns about)
        meta = snap.metadata
        outgoing = tuple(getattr(meta, "voters_outgoing", ()))
        if outgoing:
            out_s, in_s = sorted(outgoing), sorted(meta.voters)
            wb.put_cf(CF_RAFT, joint_state_key(region.id),
                      struct.pack(">II", len(out_s), len(in_s)) +
                      b"".join(struct.pack(">Q", v)
                               for v in out_s + in_s))
        else:
            wb.delete_cf(CF_RAFT, joint_state_key(region.id))
        return region
