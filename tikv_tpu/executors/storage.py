"""Executor-facing storage feed.

Reference: components/tidb_query_common/src/storage/mod.rs:21-32 — the
3-method ``Storage`` trait (``begin_scan`` / ``scan_next`` / ``get``) that
decouples executors from MVCC/engine details; implemented in production by
``TikvStorage`` over MVCC scanners (src/coprocessor/dag/storage_impl.rs:14)
and in tests by fixture stores (components/test_coprocessor).

TPU-first addition: ``scan_batch`` — pull up to N pairs at once so the host
decode loop is a single pass feeding pinned columnar buffers (SURVEY.md §7
"Decode on the hot path"); the per-pair ``scan_next`` remains for parity.
"""

from __future__ import annotations

import bisect
from typing import Iterable, Optional, Protocol, Sequence

from .ranges import KeyRange


class ScanStorage(Protocol):
    def begin_scan(self, ranges: Sequence[KeyRange], desc: bool = False) -> None: ...

    def scan_next(self) -> Optional[tuple[bytes, bytes]]: ...

    def scan_batch(self, n: int) -> list[tuple[bytes, bytes]]: ...

    def get(self, key: bytes) -> Optional[bytes]: ...


class FixtureStorage:
    """Sorted in-memory KV — the zero-Raft, zero-engine feed.

    Reference: test fixtures in components/test_coprocessor/src/fixture.rs
    (fixture store used by all executor benches) and the ``FixtureStorage``
    in tidb_query_executors tests.
    """

    def __init__(self, pairs: Iterable[tuple[bytes, bytes]] = ()):
        data = sorted(pairs)
        self._keys = [k for k, _ in data]
        self._vals = [v for _, v in data]
        self._ranges: list[KeyRange] = []
        self._desc = False
        self._range_idx = 0
        self._pos = 0
        self._stop = 0

    # -- construction helpers ------------------------------------------------

    def put(self, key: bytes, value: bytes) -> None:
        i = bisect.bisect_left(self._keys, key)
        if i < len(self._keys) and self._keys[i] == key:
            self._vals[i] = value
        else:
            self._keys.insert(i, key)
            self._vals.insert(i, value)

    def __len__(self) -> int:
        return len(self._keys)

    # -- ScanStorage ---------------------------------------------------------

    def begin_scan(self, ranges: Sequence[KeyRange], desc: bool = False) -> None:
        # desc scans walk the (sorted) range list in reverse so keys come
        # out in global reverse order (reference reverses ranges too)
        self._ranges = list(reversed(ranges)) if desc else list(ranges)
        self._desc = desc
        self._range_idx = 0
        self._load_range()

    def _load_range(self) -> None:
        while self._range_idx < len(self._ranges):
            r = self._ranges[self._range_idx]
            lo = bisect.bisect_left(self._keys, r.start)
            hi = bisect.bisect_left(self._keys, r.end)
            if lo < hi:
                if self._desc:
                    self._pos, self._stop = hi - 1, lo - 1
                else:
                    self._pos, self._stop = lo, hi
                return
            self._range_idx += 1
        self._pos = self._stop = 0

    def scan_next(self) -> Optional[tuple[bytes, bytes]]:
        while True:
            if self._range_idx >= len(self._ranges):
                return None
            if self._pos != self._stop:
                i = self._pos
                self._pos += -1 if self._desc else 1
                return self._keys[i], self._vals[i]
            self._range_idx += 1
            self._load_range()

    def scan_batch(self, n: int) -> list[tuple[bytes, bytes]]:
        out: list[tuple[bytes, bytes]] = []
        while len(out) < n:
            if self._range_idx >= len(self._ranges):
                break
            if self._pos == self._stop:
                self._range_idx += 1
                self._load_range()
                continue
            if self._desc:
                take = min(n - len(out), self._pos - self._stop)
                for i in range(self._pos, self._pos - take, -1):
                    out.append((self._keys[i], self._vals[i]))
                self._pos -= take
            else:
                take = min(n - len(out), self._stop - self._pos)
                out.extend(zip(self._keys[self._pos:self._pos + take],
                               self._vals[self._pos:self._pos + take]))
                self._pos += take
        return out

    def get(self, key: bytes) -> Optional[bytes]:
        i = bisect.bisect_left(self._keys, key)
        if i < len(self._keys) and self._keys[i] == key:
            return self._vals[i]
        return None
