"""TopN executor (host path).

Reference: tidb_query_executors/src/top_n_executor.rs — keeps a k-sized
heap of rows ordered by the ORDER BY expressions. Host implementation is
vectorized: per batch, evaluate sort keys, concatenate with the running
k candidate rows, lexsort, keep k. NULLs sort first ASC / last DESC
(MySQL), ties broken by arrival order (stable, like the reference's heap).
BYTES sort keys use a comparison sort (candidate set is bounded by
k + batch, so the Python comparison cost is O((k+1024) log) per fold).
"""

from __future__ import annotations

import functools

import numpy as np

from ..datatype import ColumnBatch, EvalType, FieldType
from ..expr import build_rpn, eval_rpn
from .interface import BatchExecuteResult, TimedExecutor


def eval_order_keys(rpns, batch: ColumnBatch) -> list[tuple]:
    """Evaluate ORDER BY expressions over one batch → per-key
    (values, validity) pairs broadcast to row length."""
    n = batch.num_rows
    cols = [(c.values, c.validity) for c in batch.columns]
    keys = []
    for rpn in rpns:
        v, ok = eval_rpn(rpn, cols, n, np)
        keys.append((np.broadcast_to(v, (n,)), np.broadcast_to(ok, (n,))))
    return keys


def order_indices(keys, descs, seq, gids=None) -> np.ndarray:
    """Stable best-first ordering over a candidate set.

    ``keys``: per ORDER BY column (values, validity); ``descs``: per-key
    DESC flags; ``seq``: arrival order (tie break). ``gids``, when given,
    sorts ascending as the most-significant key (partition grouping).
    NULLs sort first ASC / last DESC (MySQL).
    """
    has_obj = any(v.dtype == np.dtype(object) for v, _ in keys)
    if not has_obj:
        lex: list[np.ndarray] = [seq]
        for (v, ok), desc in zip(reversed(keys), reversed(descs)):
            if v.dtype.kind in "iu":
                # exact int ordering (f64 would collapse above 2^53);
                # reserve int64 min as the NULL sentinel
                iv = np.maximum(v.astype(np.int64, copy=False),
                                np.iinfo(np.int64).min + 2)
                if desc:
                    lex.append(np.where(ok, -iv, np.iinfo(np.int64).max))
                else:
                    lex.append(np.where(ok, iv, np.iinfo(np.int64).min))
                continue
            fv = v.astype(np.float64, copy=False)
            if desc:
                lex.append(np.where(ok, -fv, np.inf))   # NULL last
            else:
                lex.append(np.where(ok, fv, -np.inf))   # NULL first
        if gids is not None:
            lex.append(gids)
        return np.lexsort(tuple(lex))

    n = len(seq)

    def cmp(i: int, j: int) -> int:
        if gids is not None and gids[i] != gids[j]:
            return -1 if gids[i] < gids[j] else 1
        for (v, ok), desc in zip(keys, descs):
            a_null, b_null = not ok[i], not ok[j]
            if a_null or b_null:
                if a_null and b_null:
                    continue
                # ASC: NULL first (NULL is "smaller"); DESC: NULL last
                null_wins = not desc
                if a_null:
                    return -1 if null_wins else 1
                return 1 if null_wins else -1
            a, b = v[i], v[j]
            if a == b:
                continue
            lt = a < b
            if desc:
                lt = not lt
            return -1 if lt else 1
        return -1 if seq[i] < seq[j] else 1

    return np.asarray(sorted(range(n), key=functools.cmp_to_key(cmp)),
                      dtype=np.int64)


class BatchTopNExecutor(TimedExecutor):
    def __init__(self, child, desc):
        super().__init__()
        self._child = child
        self._desc = desc
        self._rpns = [build_rpn(e) for e, _ in desc.order_by]
        self._descs = [d for _, d in desc.order_by]
        self._k = desc.limit
        self._cand: ColumnBatch | None = None
        self._cand_keys: list | None = None   # per ORDER BY: (values, validity)
        self._cand_seq: np.ndarray | None = None
        self._next_seq = 0
        self._done = False

    @property
    def schema(self) -> list[FieldType]:
        return self._child.schema

    def _eval_keys(self, batch: ColumnBatch) -> list[tuple]:
        return eval_order_keys(self._rpns, batch)

    def _order(self, keys: list[tuple], seq: np.ndarray) -> np.ndarray:
        """Indices of the best-first ordering over the candidate set."""
        return order_indices(keys, self._descs, seq)[:self._k]

    def _fold(self, batch: ColumnBatch):
        if batch.num_rows == 0:
            return
        keys = self._eval_keys(batch)
        seq = np.arange(self._next_seq, self._next_seq + batch.num_rows,
                        dtype=np.int64)
        self._next_seq += batch.num_rows
        if self._cand is None:
            cand, ckeys, cseq = batch, keys, seq
        else:
            cand = ColumnBatch.concat([self._cand, batch])
            ckeys = [(np.concatenate([av, bv]), np.concatenate([am, bm]))
                     for (av, am), (bv, bm) in zip(self._cand_keys, keys)]
            cseq = np.concatenate([self._cand_seq, seq])
        order = self._order(ckeys, cseq)
        self._cand = cand.take(order)
        self._cand_keys = [(v[order], ok[order]) for v, ok in ckeys]
        self._cand_seq = cseq[order]

    def _next_batch(self, scan_rows: int) -> BatchExecuteResult:
        # one child batch per call so the driver's batch growth reaches
        # the scan below (see _HashAggBase._next_batch)
        if self._done:
            return BatchExecuteResult(ColumnBatch.empty(self.schema), True)
        r = self._child.next_batch(scan_rows)
        self._fold(r.batch)
        if r.is_drained:
            self._done = True
            out = self._cand if self._cand is not None \
                else ColumnBatch.empty(self.schema)
            return BatchExecuteResult(out, True, r.warnings)
        return BatchExecuteResult(ColumnBatch.empty(self.schema), False,
                                  r.warnings)


class BatchPartitionTopNExecutor(TimedExecutor):
    """Per-partition TopN — reference:
    tidb_query_executors/src/partition_top_n_executor.rs.

    The reference requires input grouped by the partition columns and
    flushes a heap at each partition-prefix change; this implementation
    dictionary-encodes partition keys (GroupKeyEncoder — same machinery
    as hash agg) so the result is correct for ANY input order, a strict
    superset of the reference contract. Per fold the candidate set is
    sorted by (partition id, order keys) in one lexsort and cut to the
    first k rows of each partition with a vectorized rank filter, so the
    retained state is O(P·k) rows.

    Output: partitions in first-seen order, rows best-first within each
    partition (the reference emits partitions in input order the same
    way)."""

    def __init__(self, child, desc):
        super().__init__()
        from .aggregation import GroupKeyEncoder
        self._child = child
        self._desc = desc
        self._enc = GroupKeyEncoder([build_rpn(e)
                                     for e in desc.partition_by])
        self._rpns = [build_rpn(e) for e, _ in desc.order_by]
        self._descs = [d for _, d in desc.order_by]
        self._k = desc.limit
        self._cand: ColumnBatch | None = None
        self._cand_keys: list | None = None
        self._cand_gids: np.ndarray | None = None
        self._cand_seq: np.ndarray | None = None
        self._next_seq = 0
        self._done = False

    @property
    def schema(self) -> list[FieldType]:
        return self._child.schema

    def _eval_keys(self, batch: ColumnBatch) -> list[tuple]:
        return eval_order_keys(self._rpns, batch)

    def _fold(self, batch: ColumnBatch):
        if batch.num_rows == 0 or self._k == 0:
            return
        keys = self._eval_keys(batch)
        gids = self._enc.gids(batch)
        seq = np.arange(self._next_seq, self._next_seq + batch.num_rows,
                        dtype=np.int64)
        self._next_seq += batch.num_rows
        if self._cand is None:
            cand, ckeys, cgids, cseq = batch, keys, gids, seq
        else:
            cand = ColumnBatch.concat([self._cand, batch])
            ckeys = [(np.concatenate([av, bv]), np.concatenate([am, bm]))
                     for (av, am), (bv, bm) in zip(self._cand_keys, keys)]
            cgids = np.concatenate([self._cand_gids, gids])
            cseq = np.concatenate([self._cand_seq, seq])
        order = order_indices(ckeys, self._descs, cseq, gids=cgids)
        g_sorted = cgids[order]
        m = len(order)
        pos = np.arange(m, dtype=np.int64)
        new_grp = np.empty(m, dtype=bool)
        new_grp[0] = True
        new_grp[1:] = g_sorted[1:] != g_sorted[:-1]
        start = np.maximum.accumulate(np.where(new_grp, pos, 0))
        keep = order[pos - start < self._k]
        self._cand = cand.take(keep)
        self._cand_keys = [(v[keep], ok[keep]) for v, ok in ckeys]
        self._cand_gids = cgids[keep]
        self._cand_seq = cseq[keep]

    def _next_batch(self, scan_rows: int) -> BatchExecuteResult:
        if self._done:
            return BatchExecuteResult(ColumnBatch.empty(self.schema), True)
        r = self._child.next_batch(scan_rows)
        self._fold(r.batch)
        if r.is_drained:
            self._done = True
            out = self._cand if self._cand is not None \
                else ColumnBatch.empty(self.schema)
            return BatchExecuteResult(out, True, r.warnings)
        return BatchExecuteResult(ColumnBatch.empty(self.schema), False,
                                  r.warnings)
