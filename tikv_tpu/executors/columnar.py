"""Columnar table snapshots — the vectorized scan feed.

Reference: TiKV decodes row-encoded KV pairs lazily per column
(tidb_query_datatype/src/codec/batch/lazy_column.rs:27) because its unit of
work is a CPU cache tile.  On TPU the scan feed must produce dense columnar
blocks without a per-row Python decode loop (SURVEY.md §7 "Decode on the hot
path"), so the storage layer can hand the executor a *columnar snapshot*:
sorted handle array + dense value/validity arrays per column — the moral
equivalent of the reference's Chunk encode_type
(tidb_query_executors/src/runner.rs:71-76) applied at rest.

``ColumnarTable`` implements the scan feed consumed by both the host
executors (``BatchColumnarTableScanExecutor``) and the device runner, and
can also materialize row-encoded KV pairs for parity tests against the
row-codec path.
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

from ..codec.keys import _RECORD_SEP, _TABLE_PREFIX  # type: ignore
from ..codec.number import decode_i64, encode_i64
from ..copr.dag import TableScanDesc
from ..datatype import Column, ColumnBatch, EvalType, FieldType
from .interface import BatchExecuteResult, TimedExecutor
from .ranges import KeyRange

_I64_MIN = -(2**63)
_I64_MAX = 2**63 - 1


def _record_prefix(table_id: int) -> bytes:
    return _TABLE_PREFIX + encode_i64(table_id) + _RECORD_SEP


def handle_bounds(r: KeyRange, table_id: int) -> tuple[int, int]:
    """Map a record-key range to an inclusive-exclusive handle interval.

    Record keys are exactly prefix+8 bytes; longer keys sort between handle
    and handle+1, so a long start key starts *after* its handle and a long
    end key ends *after* its handle (inclusive of it).
    """
    prefix = _record_prefix(table_id)
    plen = len(prefix)

    def lo_of(k: bytes) -> int:
        if k <= prefix:
            return _I64_MIN
        if not k.startswith(prefix):
            return _I64_MAX  # starts past every record of this table
        if len(k) < plen + 8:
            # short key: pad with 0x00 → sorts before the first handle with
            # this prefix byte pattern; conservative: decode what we can
            h = decode_i64(k[plen:].ljust(8, b"\x00"), 0)
            return h
        h = decode_i64(k, plen)
        # long key sorts after its handle: python ints are unbounded, so
        # h+1 may exceed i64 (the caller treats bounds > i64::MAX as "all")
        return h if len(k) == plen + 8 else h + 1

    def hi_of(k: bytes) -> int:
        if k <= prefix:
            return _I64_MIN
        if not k.startswith(prefix):
            return _I64_MAX + 1
        if len(k) < plen + 8:
            h = decode_i64(k[plen:].ljust(8, b"\x00"), 0)
            return h
        h = decode_i64(k, plen)
        return h if len(k) == plen + 8 else h + 1

    return lo_of(r.start), hi_of(r.end)


class ColumnarTable:
    """Immutable columnar snapshot of one table's committed rows.

    ``handles`` must be sorted ascending (the physical key order of record
    keys).  ``columns`` maps col_id → Column aligned with ``handles``.
    """

    def __init__(self, table, handles: np.ndarray, columns: dict):
        self.table = table
        self.handles = np.asarray(handles, dtype=np.int64)
        assert np.all(self.handles[1:] > self.handles[:-1]), \
            "handles must be strictly increasing"
        self.columns = columns

    @staticmethod
    def from_arrays(table, handles, named_columns: dict) -> "ColumnarTable":
        """named_columns: {column name: np.ndarray | Column}."""
        handles = np.asarray(handles, dtype=np.int64)
        order = np.argsort(handles, kind="stable")
        handles = handles[order]
        cols: dict = {}
        for name, data in named_columns.items():
            tc = table[name]
            if isinstance(data, Column):
                col = Column(data.eval_type, data.values[order],
                             data.validity[order])
            else:
                arr = np.asarray(data)[order]
                col = Column.from_values(tc.field_type.eval_type, arr)
            cols[tc.col_id] = col
        return ColumnarTable(table, handles, cols)

    def __len__(self) -> int:
        return len(self.handles)

    def estimated_rows(self) -> int:
        return len(self.handles)

    # -- columnar scan -------------------------------------------------------

    def _range_slices(self, ranges: Sequence[KeyRange]) -> list[tuple[int, int]]:
        out = []
        n = len(self.handles)
        for r in ranges:
            lo, hi = handle_bounds(r, self.table.table_id)
            i = n if lo > _I64_MAX else \
                int(np.searchsorted(self.handles, max(lo, _I64_MIN),
                                    side="left"))
            j = n if hi > _I64_MAX else \
                int(np.searchsorted(self.handles, hi, side="left"))
            if i < j:
                out.append((i, j))
        return out

    def count_rows(self, ranges: Sequence[KeyRange]) -> int:
        return sum(j - i for i, j in self._range_slices(ranges))

    def scan_columns(self, desc: TableScanDesc,
                     ranges: Sequence[KeyRange]) -> ColumnBatch:
        """Vectorized range scan → ColumnBatch in ``desc.columns`` order."""
        slices = self._range_slices(ranges)
        if desc.desc:
            slices = [(i, j) for i, j in reversed(slices)]

        def gather(values: np.ndarray, validity: np.ndarray):
            if len(slices) == 1 and not desc.desc:
                i, j = slices[0]
                return values[i:j], validity[i:j]
            vparts, mparts = [], []
            for i, j in slices:
                if desc.desc:
                    vparts.append(values[i:j][::-1])
                    mparts.append(validity[i:j][::-1])
                else:
                    vparts.append(values[i:j])
                    mparts.append(validity[i:j])
            if not vparts:
                return values[:0], validity[:0]
            return np.concatenate(vparts), np.concatenate(mparts)

        out_cols = []
        for info in desc.columns:
            if info.is_pk_handle:
                v, m = gather(self.handles,
                              np.ones(len(self.handles), dtype=np.bool_))
                out_cols.append(Column(EvalType.INT, v, m))
                continue
            col = self.columns.get(info.col_id)
            if col is None:
                # absent column → all default_value/NULL
                n = sum(j - i for i, j in slices)
                out_cols.append(Column.from_list(
                    info.field_type.eval_type, [info.default_value] * n))
                continue
            v, m = gather(col.values, col.validity)
            out_cols.append(Column(col.eval_type, v, m))
        return ColumnBatch([c.field_type for c in desc.columns], out_cols)

    # -- row-codec materialization (parity tests only) -----------------------

    def to_kv_pairs(self) -> list[tuple[bytes, bytes]]:
        from ..codec import encode_row, table_record_key
        pairs = []
        by_id = self.columns
        for i, h in enumerate(self.handles):
            payload = {}
            for col_id, col in by_id.items():
                v = col.get(i)
                if v is not None:
                    payload[col_id] = v
            pairs.append((table_record_key(self.table.table_id, int(h)),
                          encode_row(payload)))
        return pairs


class BatchColumnarTableScanExecutor(TimedExecutor):
    """Host scan executor over a ColumnarTable — no row decode.

    Slices the vectorized scan result progressively so the pull-model
    pipeline above it is unchanged (interface.rs:21 contract).
    """

    def __init__(self, snapshot: ColumnarTable, desc: TableScanDesc,
                 ranges: Sequence[KeyRange]):
        super().__init__()
        self._batch = snapshot.scan_columns(desc, ranges)
        self._pos = 0
        self._schema = list(desc.schema)

    @property
    def schema(self) -> list[FieldType]:
        return self._schema

    def _next_batch(self, scan_rows: int) -> BatchExecuteResult:
        start = self._pos
        stop = min(start + scan_rows, self._batch.num_rows)
        self._pos = stop
        chunk = self._batch.slice(start, stop)
        return BatchExecuteResult(chunk, stop >= self._batch.num_rows)
