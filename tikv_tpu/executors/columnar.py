"""Columnar table snapshots — the vectorized scan feed.

Reference: TiKV decodes row-encoded KV pairs lazily per column
(tidb_query_datatype/src/codec/batch/lazy_column.rs:27) because its unit of
work is a CPU cache tile.  On TPU the scan feed must produce dense columnar
blocks without a per-row Python decode loop (SURVEY.md §7 "Decode on the hot
path"), so the storage layer can hand the executor a *columnar snapshot*:
sorted handle array + dense value/validity arrays per column — the moral
equivalent of the reference's Chunk encode_type
(tidb_query_executors/src/runner.rs:71-76) applied at rest.

``ColumnarTable`` implements the scan feed consumed by both the host
executors (``BatchColumnarTableScanExecutor``) and the device runner, and
can also materialize row-encoded KV pairs for parity tests against the
row-codec path.
"""

from __future__ import annotations

import struct
from typing import Optional, Sequence

import numpy as np

from ..codec.keys import _RECORD_SEP, _TABLE_PREFIX, index_key_prefix  # type: ignore
from ..codec.number import decode_i64, encode_i64
from ..copr.dag import IndexScanDesc, TableScanDesc
from ..datatype import Column, ColumnBatch, EvalType, FieldType
from .interface import BatchExecuteResult, TimedExecutor
from .ranges import KeyRange

_I64_MIN = -(2**63)
_I64_MAX = 2**63 - 1


def _record_prefix(table_id: int) -> bytes:
    return _TABLE_PREFIX + encode_i64(table_id) + _RECORD_SEP


def handle_bounds(r: KeyRange, table_id: int) -> tuple[int, int]:
    """Map a record-key range to an inclusive-exclusive handle interval.

    Record keys are exactly prefix+8 bytes; longer keys sort between handle
    and handle+1, so a long start key starts *after* its handle and a long
    end key ends *after* its handle (inclusive of it).
    """
    prefix = _record_prefix(table_id)
    plen = len(prefix)

    def lo_of(k: bytes) -> int:
        if k <= prefix:
            return _I64_MIN
        if not k.startswith(prefix):
            return _I64_MAX  # starts past every record of this table
        if len(k) < plen + 8:
            # short key: pad with 0x00 → sorts before the first handle with
            # this prefix byte pattern; conservative: decode what we can
            h = decode_i64(k[plen:].ljust(8, b"\x00"), 0)
            return h
        h = decode_i64(k, plen)
        # long key sorts after its handle: python ints are unbounded, so
        # h+1 may exceed i64 (the caller treats bounds > i64::MAX as "all")
        return h if len(k) == plen + 8 else h + 1

    def hi_of(k: bytes) -> int:
        if k <= prefix:
            return _I64_MIN
        if not k.startswith(prefix):
            return _I64_MAX + 1
        if len(k) < plen + 8:
            h = decode_i64(k[plen:].ljust(8, b"\x00"), 0)
            return h
        h = decode_i64(k, plen)
        return h if len(k) == plen + 8 else h + 1

    return lo_of(r.start), hi_of(r.end)


class ColumnarTable:
    """Immutable columnar snapshot of one table's committed rows.

    ``handles`` must be sorted ascending (the physical key order of record
    keys).  ``columns`` maps col_id → Column aligned with ``handles``.

    ``alive``: optional boolean mask aligned with ``handles`` — False
    rows are delete tombstones left in place by incremental cache
    maintenance (copr/region_cache.py) and are invisible to every
    logical accessor (scans, counts, kv materialization).  ``None``
    means every row is live and scans stay zero-copy views.
    """

    def __init__(self, table, handles: np.ndarray, columns: dict,
                 alive: Optional[np.ndarray] = None):
        self.table = table
        self.handles = np.asarray(handles, dtype=np.int64)
        assert np.all(self.handles[1:] > self.handles[:-1]), \
            "handles must be strictly increasing"
        self.columns = columns
        self.alive = alive
        self._n_alive = len(self.handles) if alive is None \
            else int(alive.sum())

    @staticmethod
    def from_arrays(table, handles, named_columns: dict) -> "ColumnarTable":
        """named_columns: {column name: np.ndarray | Column}."""
        handles = np.asarray(handles, dtype=np.int64)
        order = np.argsort(handles, kind="stable")
        handles = handles[order]
        cols: dict = {}
        for name, data in named_columns.items():
            tc = table[name]
            if isinstance(data, Column):
                col = Column(data.eval_type, data.values[order],
                             data.validity[order])
            else:
                arr = np.asarray(data)[order]
                col = Column.from_values(tc.field_type.eval_type, arr)
            cols[tc.col_id] = col
        return ColumnarTable(table, handles, cols)

    def __len__(self) -> int:
        return self._n_alive

    def estimated_rows(self) -> int:
        return self._n_alive

    # -- columnar scan -------------------------------------------------------

    def _range_slices(self, ranges: Sequence[KeyRange]) -> list[tuple[int, int]]:
        out = []
        n = len(self.handles)
        if not ranges:
            # no ranges = the whole snapshot (the device runner's
            # bucket-tile path keys its region feed this way)
            return [(0, n)] if n else []
        for r in ranges:
            lo, hi = handle_bounds(r, self.table.table_id)
            i = n if lo > _I64_MAX else \
                int(np.searchsorted(self.handles, max(lo, _I64_MIN),
                                    side="left"))
            j = n if hi > _I64_MAX else \
                int(np.searchsorted(self.handles, hi, side="left"))
            if i < j:
                out.append((i, j))
        return out

    def count_rows(self, ranges: Sequence[KeyRange]) -> int:
        if self.alive is None:
            return sum(j - i for i, j in self._range_slices(ranges))
        return sum(int(self.alive[i:j].sum())
                   for i, j in self._range_slices(ranges))

    def row_slices(self, ranges: Sequence[KeyRange]) -> list:
        """Public seam for the device runner's bucket-tile mapping.

        Spans are PHYSICAL row indices; with pending delete tombstones
        they would include dead rows the device kernels cannot skip, so
        the bucket-tile path is refused until the next compaction.
        """
        if self.alive is not None:
            raise ValueError("row spans unavailable under tombstones")
        return self._range_slices(ranges)

    def _ones(self, n: int) -> np.ndarray:
        """Cached all-true validity, grown monotonically and sliced —
        pk-handle columns are NOT NULL by construction and a fresh
        100M-row bool array per scan costs ~50ms."""
        ones = getattr(self, "_ones_validity", None)
        if ones is None or len(ones) < n:
            ones = np.ones(max(n, len(self.handles)), dtype=np.bool_)
            # slices of this buffer are handed out as Column.validity;
            # freeze it so an in-place mutation raises instead of
            # corrupting every later scan's all-true mask
            ones.flags.writeable = False
            self._ones_validity = ones
        return ones[:n]

    def scan_columns(self, desc,
                     ranges: Sequence[KeyRange]) -> ColumnBatch:
        """Vectorized range scan → ColumnBatch in ``desc.columns`` order."""
        if isinstance(desc, IndexScanDesc):
            return self._scan_index_columns(desc, ranges)
        slices = self._range_slices(ranges)
        if desc.desc:
            slices = [(i, j) for i, j in reversed(slices)]
        alive = self.alive

        def gather(values: np.ndarray, validity: np.ndarray):
            if alive is None and len(slices) == 1 and not desc.desc:
                i, j = slices[0]
                return values[i:j], validity[i:j]
            vparts, mparts = [], []
            for i, j in slices:
                v, m = values[i:j], validity[i:j]
                if alive is not None:
                    keep = alive[i:j]
                    v, m = v[keep], m[keep]
                if desc.desc:
                    v, m = v[::-1], m[::-1]
                vparts.append(v)
                mparts.append(m)
            if not vparts:
                return values[:0], validity[:0]
            if len(vparts) == 1:
                return vparts[0], mparts[0]
            return np.concatenate(vparts), np.concatenate(mparts)

        out_cols = []
        for info in desc.columns:
            if info.is_pk_handle:
                v, m = gather(self.handles, self._ones(len(self.handles)))
                out_cols.append(Column(EvalType.INT, v, m))
                continue
            col = self.columns.get(info.col_id)
            if col is None:
                # absent column → all default_value/NULL
                if alive is None:
                    n = sum(j - i for i, j in slices)
                else:
                    n = sum(int(alive[i:j].sum()) for i, j in slices)
                out_cols.append(Column.from_list(
                    info.field_type.eval_type, [info.default_value] * n))
                continue
            v, m = gather(col.values, col.validity)
            out_cols.append(Column(col.eval_type, v, m))
        return ColumnBatch([c.field_type for c in desc.columns], out_cols)

    # -- late-materialized gather (device selection vector → rows) ----------

    def _feed_positions(self, slices: tuple, desc: bool) -> np.ndarray:
        """Memoized map from scan-output position → physical row index,
        reproducing ``scan_columns``'s exact ordering (alive filtering,
        slice order, descending reversal).  The device selection path
        addresses rows by scan-output position, so this is the bridge
        back to the snapshot's physical arrays."""
        cache = getattr(self, "_feed_pos_cache", None)
        if cache is None:
            cache = self._feed_pos_cache = {}
        key = (slices, desc)
        pos = cache.get(key)
        if pos is None:
            parts = []
            for i, j in (reversed(slices) if desc else slices):
                ids = np.arange(i, j, dtype=np.int64)
                if self.alive is not None:
                    ids = ids[self.alive[i:j]]
                if desc:
                    ids = ids[::-1]
                parts.append(ids)
            pos = parts[0] if len(parts) == 1 else (
                np.concatenate(parts) if parts
                else np.empty(0, np.int64))
            cache[key] = pos
        return pos

    def gather_rows(self, desc, ranges: Sequence[KeyRange],
                    rows) -> ColumnBatch:
        """Vectorized take of ``rows`` from the scan output WITHOUT
        materializing the full scan first (the late-materialization
        gather: the device ships a compact selection vector, the host
        touches only the k surviving rows of the resident columnar
        snapshot).

        ``rows``: a bool mask over the scan output, or an int array of
        ascending scan-output positions.  Alive-mask tombstones and
        multi-range/descending scans are honored via the memoized
        position map; the common full-range ascending no-tombstone case
        gathers straight off the physical arrays.
        """
        if isinstance(desc, IndexScanDesc):
            raise ValueError("gather_rows serves table scans; index "
                             "scans use the sorted-view path")
        slices = tuple(self._range_slices(ranges))
        rows = np.asarray(rows)
        if self.alive is None and not desc.desc and len(slices) <= 1:
            lo = slices[0][0] if slices else 0
            phys = (np.flatnonzero(rows) + lo) if rows.dtype == np.bool_ \
                else rows + lo
        else:
            phys = self._feed_positions(slices, desc.desc)[rows]
        out_cols = []
        for info in desc.columns:
            if info.is_pk_handle:
                out_cols.append(Column(EvalType.INT, self.handles[phys],
                                       self._ones(len(phys))))
                continue
            col = self.columns.get(info.col_id)
            if col is None:
                out_cols.append(Column.from_list(
                    info.field_type.eval_type,
                    [info.default_value] * len(phys)))
                continue
            out_cols.append(Column(col.eval_type, col.values[phys],
                                   col.validity[phys]))
        return ColumnBatch([c.field_type for c in desc.columns], out_cols)

    def _index_sorted(self, col_id: int):
        """Memoized (value, handle)-sorted view of one indexed column:
        → (svals, svalid, shandles, n_nulls).  MySQL NULLs sort first."""
        cache = getattr(self, "_index_order_cache", None)
        if cache is None:
            cache = self._index_order_cache = {}
        got = cache.get(col_id)
        if got is None:
            col = self.columns[col_id]
            values, validity, handles = col.values, col.validity, \
                self.handles
            if self.alive is not None:
                keep = self.alive
                values, validity, handles = \
                    values[keep], validity[keep], handles[keep]
            nulls = ~validity
            order = np.lexsort((handles, values, nulls * -1))
            got = (values[order], validity[order],
                   handles[order], int(nulls.sum()))
            # single-slice scans hand out zero-copy views of these;
            # freeze so downstream mutation can't corrupt the memo
            for a in got[:3]:
                a.flags.writeable = False
            cache[col_id] = got
        return got

    def _index_bound(self, key: bytes, prefix: bytes, svals, shandles,
                     n_nulls: int) -> int:
        """Encoded index key → offset into the sorted index view.

        Index keys are ``prefix + mc_datum(value) [+ mc_datum(handle)]``;
        rows at or after the returned offset have encoded keys >= ``key``.
        """
        from ..codec.mc_datum import decode_mc_datum
        n = len(svals)
        if key <= prefix:
            return 0
        if not key.startswith(prefix):
            return 0 if key < prefix else n
        try:
            v, off = decode_mc_datum(key, len(prefix))
        except (ValueError, IndexError, struct.error):
            return n        # e.g. the 0xff… full-range sentinel: past all
        if v is None:       # NULL datum: the NULLs-first block
            i0, i1 = 0, n_nulls
        else:
            i0 = n_nulls + int(np.searchsorted(svals[n_nulls:], v, "left"))
            i1 = n_nulls + int(np.searchsorted(svals[n_nulls:], v, "right"))
        if off < len(key):  # handle datum tie-break within the value run
            try:
                h, _ = decode_mc_datum(key, off)
            except (ValueError, IndexError, struct.error):
                return i1   # junk after the value datum: past the run
            return i0 + int(np.searchsorted(shandles[i0:i1], h, "left"))
        return i0

    def _scan_index_columns(self, desc: IndexScanDesc,
                            ranges: Sequence[KeyRange]) -> ColumnBatch:
        """Covering-index scan: indexed column + handle in index order,
        range- and direction-aware (reference: index_scan_executor.rs).
        """
        infos = desc.columns
        want_handle = bool(infos) and infos[-1].is_pk_handle
        idx_infos = infos[:-1] if want_handle else infos
        if len(idx_infos) != 1:
            raise ValueError("columnar index scan supports single-column "
                             "indexes; use the row-decode path")
        info = idx_infos[0]
        col = self.columns[info.col_id]
        svals, svalid, shandles, n_nulls = self._index_sorted(info.col_id)
        prefix = index_key_prefix(self.table.table_id, desc.index_id)
        slices = []
        for r in ranges:
            i = self._index_bound(r.start, prefix, svals, shandles, n_nulls)
            j = self._index_bound(r.end, prefix, svals, shandles, n_nulls)
            if i < j:
                slices.append((i, j))
        if desc.desc:
            slices = [(i, j) for i, j in reversed(slices)]

        def gather(a: np.ndarray) -> np.ndarray:
            parts = [a[i:j][::-1] if desc.desc else a[i:j]
                     for i, j in slices]
            if not parts:
                return a[:0]
            # single-slice scans (the common full/point-range case) stay
            # zero-copy views of the memoized sorted arrays
            return parts[0] if len(parts) == 1 else np.concatenate(parts)

        out_cols = [Column(col.eval_type, gather(svals), gather(svalid))]
        if want_handle:
            gh = gather(shandles)
            out_cols.append(Column(EvalType.INT, gh, self._ones(len(gh))))
        return ColumnBatch([c.field_type for c in infos], out_cols)

    # -- row-codec materialization (parity tests only) -----------------------

    def to_kv_pairs(self, ranges=None) -> list[tuple[bytes, bytes]]:
        from ..codec import encode_row, table_record_key
        if ranges is None:
            indices = range(len(self.handles))
        else:
            indices = [i for lo, hi in self._range_slices(ranges)
                       for i in range(lo, hi)]
        if self.alive is not None:
            indices = [i for i in indices if self.alive[i]]
        pairs = []
        by_id = self.columns
        for i in indices:
            h = self.handles[i]
            payload = {}
            for col_id, col in by_id.items():
                v = col.get(i)
                if v is not None:
                    payload[col_id] = v
            pairs.append((table_record_key(self.table.table_id, int(h)),
                          encode_row(payload)))
        return pairs


class BatchColumnarTableScanExecutor(TimedExecutor):
    """Host scan executor over a ColumnarTable — no row decode.

    Slices the vectorized scan result progressively so the pull-model
    pipeline above it is unchanged (interface.rs:21 contract).
    """

    def __init__(self, snapshot: ColumnarTable, desc: TableScanDesc,
                 ranges: Sequence[KeyRange]):
        super().__init__()
        self._batch = snapshot.scan_columns(desc, ranges)
        self._pos = 0
        self._schema = list(desc.schema)
        self._src = (snapshot, desc, ranges)
        self._hcache = None

    @property
    def schema(self) -> list[FieldType]:
        return self._schema

    # -- paging hooks (endpoint.rs streaming/paged requests) --
    #
    # Unary pages resume by the LAST RETURNED HANDLE, not a row offset:
    # each page may see a fresh snapshot (writes land between pages),
    # and a key-based token stays exact while an offset silently skips
    # or duplicates rows when earlier handles appear/disappear.

    def _handles_for_batch(self):
        if getattr(self, "_hcache", None) is None:
            snap, desc, ranges = self._src
            tbl = snap if hasattr(snap, "_range_slices") else \
                getattr(snap, "_tbl", None)     # MvccColumnarSnapshot
            if tbl is None or isinstance(desc, IndexScanDesc) or \
                    desc.desc:
                self._hcache = False        # no resume token
            else:
                slices = tbl._range_slices(ranges)
                alive = getattr(tbl, "alive", None)
                parts = [tbl.handles[i:j] if alive is None
                         else tbl.handles[i:j][alive[i:j]]
                         for i, j in slices]
                self._hcache = parts[0] if len(parts) == 1 else (
                    np.concatenate(parts) if parts
                    else tbl.handles[:0])
        return None if self._hcache is False else self._hcache

    def resume_handle(self):
        """Token for the next page: the last consumed row's handle, or
        None when nothing was consumed / the scan cannot resume."""
        h = self._handles_for_batch()
        if h is None or self._pos == 0:
            return None
        return int(h[self._pos - 1])

    def skip_after_handle(self, token: int) -> None:
        h = self._handles_for_batch()
        if h is None:
            raise ValueError("scan does not support handle resume")
        self._pos = int(np.searchsorted(h, token, side="right"))

    def supports_resume(self) -> bool:
        return self._handles_for_batch() is not None

    def rows_consumed(self) -> int:
        return self._pos

    def _next_batch(self, scan_rows: int) -> BatchExecuteResult:
        start = self._pos
        stop = min(start + scan_rows, self._batch.num_rows)
        self._pos = stop
        chunk = self._batch.slice(start, stop)
        return BatchExecuteResult(chunk, stop >= self._batch.num_rows)
