"""Key ranges.

Reference: tidb_query_common/src/storage/range.rs — ``IntervalRange`` /
``PointRange`` / ``Range``. A scan request carries a sorted list of these.
"""

from __future__ import annotations

from dataclasses import dataclass


@dataclass(frozen=True)
class KeyRange:
    """[start, end) byte range; a point range has end == start + NUL."""

    start: bytes
    end: bytes

    @staticmethod
    def point(key: bytes) -> "KeyRange":
        return KeyRange(key, key + b"\x00")

    @property
    def is_point(self) -> bool:
        return self.end == self.start + b"\x00"
