"""Pipeline builder + driver — the host half of a PER-FRAGMENT route.

Reference: tidb_query_executors/src/runner.rs — ``build_executors`` (:181)
maps tipb Executor descriptors to BatchExecutor impls (scan must be first;
agg picks simple/fast-hash/slow-hash/stream by plan shape, :293-318), and
``BatchExecutorsRunner::handle_request`` (:498,:641) drives the pipeline
with batch sizes growing 32 → (×2) → 1024 (:38-45), collecting exec
summaries and encoding result chunks.

Routing granularity: a whole request no longer picks host OR device
once.  The endpoint's linear path still routes per DAGRequest, but
under the plan IR (copr/plan_ir.py) this runner executes individual
LEAF FRAGMENTS of a larger operator DAG — a device scan+join plan can
hand its aggregation finalize here, and a faulted device fragment
degrades to this pipeline per fragment, not per plan.  The executors
themselves also run above in-memory batches (plan_ir.run_host_ops
feeds them through a batch-source adapter) for the post-join/sort/
window host finalize.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional, Sequence

from ..copr.dag import (
    AggregationDesc,
    DAGRequest,
    IndexScanDesc,
    LimitDesc,
    ProjectionDesc,
    SelectionDesc,
    TableScanDesc,
    PartitionTopNDesc,
    TopNDesc,
)
from ..datatype import ColumnBatch, EvalType
from .aggregation import (
    BatchFastHashAggExecutor,
    BatchSimpleAggExecutor,
    BatchSlowHashAggExecutor,
    BatchStreamAggExecutor,
)
from .interface import BatchExecutor, ExecSummary
from .scan import BatchIndexScanExecutor, BatchTableScanExecutor
from .simple import (
    BatchLimitExecutor,
    BatchProjectionExecutor,
    BatchSelectionExecutor,
)
from .storage import ScanStorage
from .top_n import BatchTopNExecutor

BATCH_INITIAL_SIZE = 32
BATCH_MAX_SIZE = 1024
BATCH_GROW_FACTOR = 2
# Columnar snapshots are whole-column numpy arrays: every executor is
# vectorized, so the batch cap exists only to bound the Python driver
# loop, not CPU cache footprint (the reference's 1024 cap is a cache
# heuristic for its row-at-a-time scan feed, runner.rs:38-45).  Wide
# batches cut the per-batch interpreter overhead ~1000x on 10M+ row
# scans.
BATCH_MAX_SIZE_COLUMNAR = 1 << 20


def build_executors(dag: DAGRequest, storage: ScanStorage) -> BatchExecutor:
    """Reference: runner.rs build_executors — first descriptor must be a
    scan; aggregation executor choice mirrors runner.rs:293-318."""
    descs = dag.executors
    if not descs:
        raise ValueError("empty executor list")
    head = descs[0]
    if isinstance(head, TableScanDesc):
        if hasattr(storage, "scan_columns"):
            # columnar snapshot feed — no row decode (executors/columnar.py)
            from .columnar import BatchColumnarTableScanExecutor
            ex: BatchExecutor = BatchColumnarTableScanExecutor(
                storage, head, dag.ranges)
        else:
            ex = BatchTableScanExecutor(storage, head, dag.ranges)
    elif isinstance(head, IndexScanDesc):
        if hasattr(storage, "scan_columns"):
            # columnar snapshots serve covering-index scans directly
            from .columnar import BatchColumnarTableScanExecutor
            ex = BatchColumnarTableScanExecutor(storage, head, dag.ranges)
        else:
            ex = BatchIndexScanExecutor(storage, head, dag.ranges)
    else:
        raise ValueError(f"pipeline must start with a scan, got {head}")
    for d in descs[1:]:
        if isinstance(d, SelectionDesc):
            ex = BatchSelectionExecutor(ex, d)
        elif isinstance(d, ProjectionDesc):
            ex = BatchProjectionExecutor(ex, d)
        elif isinstance(d, AggregationDesc):
            if not d.group_by:
                ex = BatchSimpleAggExecutor(ex, d)
            elif d.streamed:
                ex = BatchStreamAggExecutor(ex, d)
            elif len(d.group_by) == 1 and _is_fast_key(d.group_by[0]):
                ex = BatchFastHashAggExecutor(ex, d)
            else:
                ex = BatchSlowHashAggExecutor(ex, d)
        elif isinstance(d, TopNDesc):
            ex = BatchTopNExecutor(ex, d)
        elif isinstance(d, PartitionTopNDesc):
            from .top_n import BatchPartitionTopNExecutor
            ex = BatchPartitionTopNExecutor(ex, d)
        elif isinstance(d, LimitDesc):
            ex = BatchLimitExecutor(ex, d)
        else:
            raise ValueError(f"unsupported executor {d}")
    return ex


def _is_fast_key(e) -> bool:
    # fast hash agg: single column ref or int-typed expression
    et = e.eval_type if e.kind != "call" else None
    from ..expr.functions import FUNCTIONS
    if e.kind == "call":
        et = FUNCTIONS[e.sig].ret
    return et in (EvalType.INT, EvalType.REAL)


@dataclass
class SelectResult:
    """Decoded response: final columns + per-executor summaries.

    Paging (endpoint.rs:760-823): ``is_drained=False`` means more pages
    follow; ``resume_token`` is the last returned row's handle — stable
    across snapshots, unlike a row offset (concurrent writes shift
    offsets but never reorder handles).
    """

    batch: ColumnBatch
    exec_summaries: list
    warnings: list = field(default_factory=list)
    is_drained: bool = True
    resume_token: Optional[int] = None

    def rows(self):
        return self.batch.rows()


class BatchExecutorsRunner:
    """Drives the pipeline to completion (unary request) or one page.

    Reference: runner.rs handle_request/internal_handle_request; the
    paged variant mirrors handle_streaming_request — stop once the page
    budget fills, report the key-based resume token so the next request
    (possibly over a NEWER snapshot) continues exactly after the last
    returned row.
    """

    def __init__(self, dag: DAGRequest, storage: ScanStorage,
                 resume_token: Optional[int] = None):
        self._dag = dag
        self._out = build_executors(dag, storage)
        self._max_batch = BATCH_MAX_SIZE_COLUMNAR \
            if hasattr(storage, "scan_columns") else BATCH_MAX_SIZE
        if resume_token is not None:
            scan = self._scan_executor()
            if scan is None or not hasattr(scan, "skip_after_handle"):
                raise ValueError("plan does not support paging resume")
            scan.skip_after_handle(resume_token)

    def _scan_executor(self):
        cur = self._out
        while cur is not None:
            nxt = getattr(cur, "_child", None)
            if nxt is None:
                return cur
            cur = nxt
        return None

    def handle_request(self, max_rows: Optional[int] = None) -> SelectResult:
        scan = self._scan_executor()
        supports = getattr(scan, "supports_resume", None)
        if max_rows is not None and \
                not (callable(supports) and supports()):
            # a scan without a resume token cannot page: serve the full
            # result as one drained page rather than looping the client
            # on page 1 forever
            max_rows = None
        from ..utils.deadline import check_current as _dl_check
        batch_size = BATCH_INITIAL_SIZE
        chunks: list[ColumnBatch] = []
        warnings: list = []
        n_rows = 0
        drained = False
        while True:
            # deadline gate between executor batches (endpoint.rs checks
            # max_execution_duration the same way): a long scan whose
            # caller has stopped waiting is abandoned mid-pipeline
            # instead of running to completion
            _dl_check("executor_batch")
            r = self._out.next_batch(batch_size)
            if r.batch.num_rows:
                chunks.append(r.batch)
                n_rows += r.batch.num_rows
            warnings.extend(r.warnings)
            if r.is_drained:
                drained = True
                break
            if max_rows is not None and n_rows >= max_rows:
                break
            if batch_size < self._max_batch:
                batch_size = min(batch_size * BATCH_GROW_FACTOR,
                                 self._max_batch)
        schema = self._out.schema
        batch = ColumnBatch.concat(chunks) if chunks \
            else ColumnBatch.empty(schema)
        if self._dag.output_offsets is not None:
            batch = ColumnBatch(
                [batch.schema[i] for i in self._dag.output_offsets],
                [batch.columns[i] for i in self._dag.output_offsets])
        summaries = _collect_summaries(self._out)
        token_fn = getattr(scan, "resume_handle", None)
        token = token_fn() if callable(token_fn) else None
        return SelectResult(batch, summaries, warnings,
                            is_drained=drained, resume_token=token)


def _collect_summaries(ex) -> list[ExecSummary]:
    out = []
    cur = ex
    while cur is not None:
        out.append(cur.summary)
        cur = getattr(cur, "_child", None)
    return list(reversed(out))  # scan first, like the reference
