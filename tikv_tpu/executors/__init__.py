"""Batch executor pipeline.

Rebuild of components/tidb_query_executors (16k LoC): the pull-based
vectorized Volcano model — ``BatchExecutor::next_batch(scan_rows)``
(interface.rs:21-31) pulling ColumnBatches up a pipeline of
TableScan/IndexScan → Selection → Projection → Agg/TopN/Limit, driven by
``BatchExecutorsRunner`` (runner.rs).

Two execution paths share the plan and the expression engine:

- **host path** (this package, numpy): exact reference semantics, serves
  small/latency-bound requests and all general cases;
- **device path** (device_runner.py): pattern-matched plan shapes compiled
  to fused JAX tile kernels with psum-merged partial aggregates (the
  TPU north star, BASELINE.md).
"""

from .interface import BatchExecutor, BatchExecuteResult, ExecSummary
from .ranges import KeyRange
from .storage import ScanStorage, FixtureStorage
from .runner import BatchExecutorsRunner, build_executors

__all__ = [
    "BatchExecutor",
    "BatchExecuteResult",
    "ExecSummary",
    "KeyRange",
    "ScanStorage",
    "FixtureStorage",
    "BatchExecutorsRunner",
    "build_executors",
]
