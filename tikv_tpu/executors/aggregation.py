"""Aggregation executors (host path).

Reference: tidb_query_executors/src/simple_aggr_executor.rs,
fast_hash_aggr_executor.rs (single int/bytes key — specialised hashmap),
slow_hash_aggr_executor.rs (general multi-key), stream_aggr_executor.rs
(input sorted by group key). Output schema follows the reference: aggregate
result columns first, then group-by columns
(util/aggr_executor.rs schema layout).

Host implementations are vectorized numpy (np.unique dictionary-encoding +
np.add.at scatter) rather than per-row state structs; the device analogues
live in ops/agg.py and are selected by the device runner.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from ..datatype import Column, ColumnBatch, EvalType, FieldType
from ..expr import build_rpn, eval_rpn
from ..ops import agg as _agg
from .interface import BatchExecuteResult, TimedExecutor


def _agg_ret_ft(kind: str, arg_et: Optional[EvalType],
                elems: tuple = ()) -> FieldType:
    if kind in ("count", "count_star"):
        return FieldType.long(not_null=True)
    if kind in _agg.BIT_KINDS:
        # MySQL BIT_* returns unsigned BIGINT and never NULL (identity
        # for empty groups): BIT_AND() of no rows = 2^64-1
        return FieldType.long(unsigned=True, not_null=True)
    if arg_et is EvalType.DECIMAL and kind not in _agg.VAR_KINDS:
        # MySQL SUM/AVG/MIN/MAX over DECIMAL stay DECIMAL
        return FieldType.new_decimal()
    if kind in ("min", "max", "first"):
        # order-preserving aggregates return the argument's original
        # field type (reference: AggrFnDefinitionParser keeps the arg
        # FieldType for min/max) — without this, clients would see the
        # raw packed u64 time core typed as BIGINT
        from .. import datatype as _dt
        if arg_et is EvalType.DATETIME:
            return FieldType(tp=_dt.FieldTypeTp.DATETIME)
        if arg_et is EvalType.DURATION:
            return FieldType(tp=_dt.FieldTypeTp.DURATION)
        if arg_et is EvalType.ENUM:
            return FieldType.enum(elems)
        if arg_et is EvalType.SET:
            return FieldType.set_(elems)
    if kind == "avg" or kind in _agg.VAR_KINDS:
        return FieldType.double()
    if arg_et is EvalType.REAL:
        return FieldType.double()
    if arg_et is EvalType.BYTES:
        return FieldType.var_char()
    return FieldType.long()


def _arg_elems(e) -> tuple:
    """First non-empty enum/set name table in an agg-arg expr tree."""
    stack = [e]
    while stack:
        n = stack.pop()
        if n.elems:
            return tuple(n.elems)
        stack.extend(n.children)
    return ()


class _AggState:
    """Per-group growable state arrays for one agg spec."""

    def __init__(self, kind: str, et: Optional[EvalType]):
        self.kind = kind
        self.et = et
        if et is EvalType.REAL:
            dtype = np.float64
        elif et in (EvalType.DATETIME, EvalType.ENUM, EvalType.SET):
            # unsigned cores: mixing them with int64 identities would
            # silently promote to float64 (and round above 2^53)
            dtype = np.uint64
        else:
            dtype = np.int64
        self.dec = et is EvalType.DECIMAL
        # obj: per-row python loops for order-sensitive states (BYTES
        # and DECIMAL both compare as python objects)
        self.obj = et is EvalType.BYTES or self.dec
        if self.dec:
            # DECIMAL sums stay exact decimals (np.add.at object loop;
            # int 0 init is a valid Decimal addend)
            self.sum = np.zeros(0, dtype=object)
        else:
            self.sum = np.zeros(0, dtype=dtype) if not self.obj else None
        self.count = np.zeros(0, dtype=np.int64)
        if kind in ("min", "max"):
            if self.obj:
                self.vals: list = []
            else:
                if dtype == np.float64:
                    ident = np.inf if kind == "min" else -np.inf
                else:
                    info = np.iinfo(dtype)
                    ident = info.max if kind == "min" else info.min
                self.ident = dtype(ident)
                self.vals = np.zeros(0, dtype=dtype)
        if kind == "first":
            self.first_vals: list = []
            self.first_set: list = []
        if kind in _agg.VAR_KINDS:
            self.sum = np.zeros(0, dtype=np.float64)
            self.sumsq = np.zeros(0, dtype=np.float64)
        if kind in _agg.BIT_KINDS:
            self.bit_ident = np.int64(_agg._BIT_IDENT[kind])
            self.bits = np.zeros(0, dtype=np.int64)

    def grow(self, n_groups: int):
        cur = len(self.count)
        if n_groups <= cur:
            return
        extra = n_groups - cur
        self.count = np.concatenate([self.count, np.zeros(extra, np.int64)])
        if self.sum is not None:
            self.sum = np.concatenate([self.sum,
                                       np.zeros(extra, self.sum.dtype)])
        if self.kind in ("min", "max"):
            if self.obj:
                self.vals.extend([None] * extra)
            else:
                self.vals = np.concatenate(
                    [self.vals, np.full(extra, self.ident, self.vals.dtype)])
        if self.kind == "first":
            self.first_vals.extend([None] * extra)
            self.first_set.extend([False] * extra)
        if self.kind in _agg.VAR_KINDS:
            self.sumsq = np.concatenate(
                [self.sumsq, np.zeros(extra, np.float64)])
        if self.kind in _agg.BIT_KINDS:
            self.bits = np.concatenate(
                [self.bits, np.full(extra, self.bit_ident, np.int64)])

    def keep_only(self, idx: int) -> None:
        """Retain ONLY group ``idx`` (stream agg emitted the rest)."""
        sl = slice(idx, idx + 1)
        self.count = self.count[sl].copy()
        if self.sum is not None:
            self.sum = self.sum[sl].copy()
        if self.kind in ("min", "max"):
            self.vals = self.vals[sl] if self.obj \
                else self.vals[sl].copy()
        if self.kind == "first":
            self.first_vals = self.first_vals[sl]
            self.first_set = self.first_set[sl]
        if self.kind in _agg.VAR_KINDS:
            self.sumsq = self.sumsq[sl].copy()
        if self.kind in _agg.BIT_KINDS:
            self.bits = self.bits[sl].copy()

    def update(self, gids: np.ndarray, values, validity):
        """Scatter one batch into group states. gids: int group id per row."""
        kind = self.kind
        if kind == "count_star":
            np.add.at(self.count, gids, 1)
            return
        ok = validity
        oki = ok.astype(np.int64)
        if kind == "count":
            np.add.at(self.count, gids, oki)
        elif kind in ("sum", "avg"):
            np.add.at(self.count, gids, oki)
            if self.dec:
                import decimal as _d
                from ..datatype import mydecimal as _md
                with _d.localcontext(_md.CTX):   # 65-digit sums
                    np.add.at(self.sum, gids,
                              np.where(ok, values, _md.ZERO))
            else:
                masked = np.where(ok, values, 0).astype(self.sum.dtype)
                np.add.at(self.sum, gids, masked)
        elif kind in ("min", "max"):
            np.add.at(self.count, gids, oki)
            if self.obj:
                for g, v, o in zip(gids, values, ok):
                    if o:
                        cur = self.vals[g]
                        if cur is None or (v < cur if kind == "min" else v > cur):
                            self.vals[g] = v
            else:
                filled = np.where(ok, values, self.ident)
                (np.minimum if kind == "min" else np.maximum).at(
                    self.vals, gids, filled)
        elif kind == "first":
            for g, v, o in zip(gids, values, ok):
                if not self.first_set[g]:
                    self.first_set[g] = True
                    if not o:
                        self.first_vals[g] = None
                    else:
                        self.first_vals[g] = v.item() if hasattr(v, "item") else v
        elif kind in _agg.VAR_KINDS:
            np.add.at(self.count, gids, oki)
            v64 = np.where(ok, values.astype(np.float64), 0.0)
            np.add.at(self.sum, gids, v64)
            np.add.at(self.sumsq, gids, v64 * v64)
        elif kind in _agg.BIT_KINDS:
            filled = np.where(ok, _agg._bit_int64(values), self.bit_ident)
            _agg._bit_ufunc(kind).at(self.bits, gids, filled)
        else:
            raise ValueError(kind)

    def finalize_column(self, n_groups: int) -> Column:
        kind = self.kind
        if kind in ("count", "count_star"):
            return Column.from_values(EvalType.INT, self.count[:n_groups].copy())
        if kind == "sum":
            validity = self.count[:n_groups] > 0
            if self.dec:
                return Column(EvalType.DECIMAL,
                              self.sum[:n_groups].copy(), validity)
            et = EvalType.REAL if self.sum.dtype == np.float64 else EvalType.INT
            return Column(et, self.sum[:n_groups].copy(), validity)
        if kind == "avg":
            validity = self.count[:n_groups] > 0
            if self.dec:
                from ..datatype import mydecimal as _md
                vals = np.empty(n_groups, dtype=object)
                for g in range(n_groups):
                    c = int(self.count[g])
                    vals[g] = _md.div(self.sum[g], _md.from_int(c)) \
                        if c else _md.ZERO
                return Column(EvalType.DECIMAL, vals, validity)
            denom = np.maximum(self.count[:n_groups], 1)
            return Column(EvalType.REAL,
                          self.sum[:n_groups] / denom, validity)
        if kind in ("min", "max"):
            validity = self.count[:n_groups] > 0
            if self.obj:
                return Column.from_list(self.et, self.vals[:n_groups])
            vals = np.where(validity, self.vals[:n_groups], 0)
            if self.et in (EvalType.DATETIME, EvalType.DURATION,
                           EvalType.ENUM, EvalType.SET):
                et = self.et     # keep the argument's eval type
            elif vals.dtype == np.float64:
                et = EvalType.REAL
            else:
                et = EvalType.INT
            return Column(et, vals.astype(self.vals.dtype), validity)
        if kind == "first":
            et = self.et or EvalType.INT
            return Column.from_list(et, self.first_vals[:n_groups])
        if kind in _agg.VAR_KINDS:
            var, validity = _agg.var_arrays(
                kind, self.sum[:n_groups], self.sumsq[:n_groups],
                self.count[:n_groups])
            return Column(EvalType.REAL, var, validity)
        if kind in _agg.BIT_KINDS:
            return Column.from_list(
                EvalType.INT,
                [b & 0xFFFFFFFFFFFFFFFF
                 for b in self.bits[:n_groups].tolist()],
                unsigned=True)
        raise ValueError(kind)


def _appearance_order(inverse: np.ndarray, local_keys: list, n: int):
    """Remap batch-local ids to first-seen input order.

    The int/float fast paths below produce ids in VALUE order (that is
    what makes them sort-free/cheap); the reference's hashmaps assign
    ids in insertion = input order (fast_hash_aggr_executor.rs), and
    stream agg / partition TopN emission order depends on it — a
    DESC-sorted or NULL-first input must stream groups out in input
    order, not reversed.  O(n + k log k)."""
    k = len(local_keys)
    if k <= 1:
        return inverse, local_keys
    first_pos = np.full(k, n, dtype=np.int64)
    np.minimum.at(first_pos, inverse, np.arange(n, dtype=np.int64))
    order = np.argsort(first_pos, kind="stable")
    rank = np.empty(k, dtype=np.int64)
    rank[order] = np.arange(k, dtype=np.int64)
    return rank[inverse], [local_keys[j] for j in order]


def _encode_gids(enc: GroupKeyEncoder, batch: ColumnBatch) -> np.ndarray:
    """Map each row to a global group id (assigning new ids in
    first-seen input order)."""
    n = batch.num_rows
    cols = [(c.values, c.validity) for c in batch.columns]
    key_cols = []
    for rpn in enc.rpns:
        v, ok = eval_rpn(rpn, cols, n, np)
        key_cols.append((np.broadcast_to(v, (n,)),
                         np.broadcast_to(ok, (n,))))
    # batch-local dictionary encode: single int key fast path
    value_ordered = False
    if len(key_cols) == 1 and key_cols[0][0].dtype.kind in "iu":
        v, ok = key_cols[0]
        any_null = not ok.all()
        valid = v[ok] if any_null else v
        if valid.size == 0:
            inverse = np.zeros(n, dtype=np.int64)
            local_keys = [(None,)]
        else:
            m = int(valid.min())
            span = int(valid.max()) - m + 1
            # O(n)-bounded: no absolute floor — early 32-row batches
            # must not pay a span-sized table per batch
            if span <= 4 * n:
                # dense key domain: O(n) direct-index encode — no
                # sort (fast_hash_aggr_executor.rs specialises the
                # single-int-key case the same way)
                idx = np.where(ok, v - m, span) if any_null \
                    else v - m
                seen = np.zeros(span + (2 if any_null else 1),
                                np.bool_)
                seen[idx] = True
                local_of = np.cumsum(seen, dtype=np.int64) - 1
                inverse = local_of[idx]
                uniq_off = np.flatnonzero(seen[:span])
                # rebuild keys in v's dtype: a uint64 domain above
                # 2^63 overflows int64 + python-int addition
                uniq_vals = uniq_off.astype(v.dtype) + v.dtype.type(m)
                local_keys = [(x,) for x in uniq_vals.tolist()]
                if any_null and seen[span]:
                    local_keys.append((None,))
                value_ordered = True
            else:
                # sparse domain: one sort over the valid rows only
                uniq, inv_valid = np.unique(valid,
                                            return_inverse=True)
                local_keys = [(x,) for x in uniq.tolist()]
                if any_null:
                    inverse = np.full(n, len(local_keys), np.int64)
                    inverse[ok] = inv_valid
                    local_keys.append((None,))
                else:
                    inverse = inv_valid.astype(np.int64, copy=False)
                value_ordered = True
    elif len(key_cols) == 1 and key_cols[0][0].dtype.kind == "f":
        v, ok = key_cols[0]
        uniq, inverse = np.unique(
            np.stack([np.where(ok, v, 0), ok.astype(v.dtype)]),
            axis=1, return_inverse=True)
        local_keys = [((uniq[0, j].item() if uniq[1, j] else None),)
                      for j in range(uniq.shape[1])]
        value_ordered = True
    else:
        rows = list(zip(*[
            [vv.item() if o and hasattr(vv, "item") else (vv if o else None)
             for vv, o in zip(v, ok)] for v, ok in key_cols]))
        uniq_map: dict = {}
        inverse = np.empty(n, dtype=np.int64)
        local_keys = []
        for i, key in enumerate(rows):
            j = uniq_map.get(key)
            if j is None:
                j = len(local_keys)
                uniq_map[key] = j
                local_keys.append(key)
            inverse[i] = j
    if value_ordered:
        inverse, local_keys = _appearance_order(inverse, local_keys, n)
    # local id -> global id
    l2g = np.empty(len(local_keys), dtype=np.int64)
    for j, key in enumerate(local_keys):
        g = enc.index.get(key)
        if g is None:
            g = len(enc.keys)
            enc.index[key] = g
            enc.keys.append(key)
        l2g[j] = g
    return l2g[inverse]


class GroupKeyEncoder:
    """Dictionary-encodes group/partition key expressions into stable
    global group ids (first-seen order). Shared by the hash-agg executors
    and BatchPartitionTopNExecutor (reference assigns group ids through
    its hashmaps the same way)."""

    def __init__(self, group_rpns):
        self.rpns = group_rpns
        self.index: dict = {}       # key tuple -> group id
        self.keys: list = []        # group id -> key tuple

    def gids(self, batch: ColumnBatch) -> np.ndarray:
        return _encode_gids(self, batch)


class _HashAggBase(TimedExecutor):
    """Shared machinery: dictionary-encode group keys per batch, scatter
    into growable per-group states, emit on drain."""

    def __init__(self, child, desc):
        super().__init__()
        self._child = child
        self._desc = desc
        self._group_rpns = [build_rpn(e) for e in desc.group_by]
        self._agg_rpns = [build_rpn(a.arg) if a.arg is not None else None
                          for a in desc.aggs]
        arg_ets = [r.ret_type if r else None for r in self._agg_rpns]
        self._states = [_AggState(a.kind, et)
                        for a, et in zip(desc.aggs, arg_ets)]
        self._enc = GroupKeyEncoder(self._group_rpns)
        self._done = False
        group_fts = []
        for rpn in self._group_rpns:
            et = rpn.ret_type
            group_fts.append(
                FieldType.double() if et is EvalType.REAL
                else FieldType.var_char() if et is EvalType.BYTES
                else FieldType.new_decimal() if et is EvalType.DECIMAL
                else FieldType.long())
        self._schema = [
            _agg_ret_ft(a.kind, et,
                        _arg_elems(a.arg) if a.arg is not None else ())
            for a, et in zip(desc.aggs, arg_ets)] + group_fts

    @property
    def schema(self) -> list[FieldType]:
        return self._schema

    def _update(self, batch: ColumnBatch):
        n = batch.num_rows
        if n == 0 and self._desc.group_by:
            return
        gids = self._enc.gids(batch) if self._desc.group_by else \
            np.zeros(n, dtype=np.int64)
        if n:
            # the group still RECEIVING rows (stream agg's retained
            # group) is the last row's; with appearance-order ids this
            # equals keys[-1] for sorted input, but gids[-1] stays
            # correct even for unsorted feeds
            self._last_gid = int(gids[-1])
        if not self._desc.group_by and not self._enc.keys:
            self._enc.keys.append(())
        n_groups = len(self._enc.keys)
        cols = [(c.values, c.validity) for c in batch.columns]
        for st, rpn in zip(self._states, self._agg_rpns):
            st.grow(n_groups)
            if rpn is None:
                st.update(gids, None, None)
            else:
                v, ok = eval_rpn(rpn, cols, n, np)
                st.update(gids, np.broadcast_to(v, (n,)),
                          np.broadcast_to(ok, (n,)))

    def _emit(self) -> ColumnBatch:
        n_groups = len(self._enc.keys)
        agg_cols = [st.finalize_column(n_groups) for st in self._states]
        group_cols = []
        for k in range(len(self._group_rpns)):
            et = self._group_rpns[k].ret_type
            group_cols.append(Column.from_list(
                et, [key[k] for key in self._enc.keys]))
        return ColumnBatch(self._schema, agg_cols + group_cols)

    def _next_batch(self, scan_rows: int) -> BatchExecuteResult:
        # one child batch per call (reference: util/aggr_executor.rs
        # handle_next_batch) so the driver's 32→2×→max batch growth
        # reaches the executor below — draining the child in a private
        # loop would pin it at the initial 32-row batches forever
        if self._done:
            return BatchExecuteResult(ColumnBatch.empty(self._schema), True)
        r = self._child.next_batch(scan_rows)
        self._update(r.batch)
        if r.is_drained:
            self._done = True
            return BatchExecuteResult(self._emit(), True, r.warnings)
        return BatchExecuteResult(ColumnBatch.empty(self._schema), False,
                                  r.warnings)


class BatchFastHashAggExecutor(_HashAggBase):
    """Reference: fast_hash_aggr_executor.rs — single group-by key."""


class BatchSlowHashAggExecutor(_HashAggBase):
    """Reference: slow_hash_aggr_executor.rs — multi-column group keys."""


class BatchSimpleAggExecutor(_HashAggBase):
    """Reference: simple_aggr_executor.rs — no group by; exactly one
    output row even for empty input (COUNT()=0, SUM()=NULL)."""

    def _next_batch(self, scan_rows: int) -> BatchExecuteResult:
        if self._done:
            return BatchExecuteResult(ColumnBatch.empty(self._schema), True)
        if not self._enc.keys:
            self._enc.keys.append(())
        r = self._child.next_batch(scan_rows)
        self._update(r.batch)
        if r.is_drained:
            self._done = True
            for st in self._states:
                st.grow(1)
            return BatchExecuteResult(self._emit(), True, r.warnings)
        return BatchExecuteResult(ColumnBatch.empty(self._schema), False,
                                  r.warnings)


class BatchStreamAggExecutor(_HashAggBase):
    """Reference: stream_aggr_executor.rs — input sorted by group key:
    every group except the one still receiving rows is COMPLETE at each
    batch boundary, so completed groups stream out per batch and the
    retained state is O(1) groups (what makes paged/streamed responses
    memory-bounded over arbitrarily many groups).

    Sortedness is the plan builder's contract (as in the reference); an
    unsorted feed would re-open an emitted group and produce duplicate
    key rows downstream."""

    def _flush_completed(self) -> ColumnBatch:
        """Emit every group EXCEPT the one the last row belongs to,
        then rebase state onto that single in-progress group."""
        keep = self._last_gid
        n = len(self._enc.keys)
        done = np.array([g for g in range(n) if g != keep],
                        dtype=np.int64)
        out = self._emit().take(done)
        kept_key = self._enc.keys[keep]
        for st in self._states:
            st.keep_only(keep)
        self._enc.keys = [kept_key]
        self._enc.index = {kept_key: 0}
        self._last_gid = 0
        return out

    def _next_batch(self, scan_rows: int) -> BatchExecuteResult:
        if self._done:
            return BatchExecuteResult(ColumnBatch.empty(self._schema),
                                      True)
        r = self._child.next_batch(scan_rows)
        self._update(r.batch)
        n_groups = len(self._enc.keys)
        if r.is_drained:
            self._done = True
            return BatchExecuteResult(self._emit(), True, r.warnings)
        if n_groups > 1:
            return BatchExecuteResult(self._flush_completed(), False,
                                      r.warnings)
        return BatchExecuteResult(ColumnBatch.empty(self._schema),
                                  False, r.warnings)
