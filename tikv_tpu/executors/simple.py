"""Selection / Projection / Limit executors (host path).

Reference: tidb_query_executors/src/selection_executor.rs,
projection_executor.rs, limit_executor.rs.
"""

from __future__ import annotations

import numpy as np

from ..datatype import Column, ColumnBatch, FieldType
from ..expr import build_rpn, eval_rpn
from .interface import BatchExecuteResult, TimedExecutor


class BatchSelectionExecutor(TimedExecutor):
    def __init__(self, child, desc):
        super().__init__()
        self._child = child
        self._rpns = [build_rpn(c) for c in desc.conditions]

    @property
    def schema(self) -> list[FieldType]:
        return self._child.schema

    def _next_batch(self, scan_rows: int) -> BatchExecuteResult:
        r = self._child.next_batch(scan_rows)
        batch = r.batch
        n = batch.num_rows
        if n:
            cols = [(c.values, c.validity) for c in batch.columns]
            mask = np.ones(n, dtype=np.bool_)
            for rpn in self._rpns:
                v, ok = eval_rpn(rpn, cols, n, np)
                # SQL WHERE keeps rows where predicate is TRUE (not NULL)
                mask &= ok & (v != 0)
            batch = batch.filter(mask)
        return BatchExecuteResult(batch, r.is_drained, r.warnings)


class BatchProjectionExecutor(TimedExecutor):
    def __init__(self, child, desc):
        super().__init__()
        self._child = child
        self._rpns = [build_rpn(e) for e in desc.exprs]
        self._schema = [_ft_of(rpn) for rpn in self._rpns]

    @property
    def schema(self) -> list[FieldType]:
        return self._schema

    def _next_batch(self, scan_rows: int) -> BatchExecuteResult:
        r = self._child.next_batch(scan_rows)
        batch = r.batch
        n = batch.num_rows
        cols = [(c.values, c.validity) for c in batch.columns]
        out = []
        for rpn, ft in zip(self._rpns, self._schema):
            v, ok = eval_rpn(rpn, cols, n, np)
            v = np.broadcast_to(v, (n,)).astype(ft.eval_type.np_dtype, copy=False)
            ok = np.broadcast_to(ok, (n,)).astype(np.bool_, copy=False)
            out.append(Column(ft.eval_type, np.ascontiguousarray(v),
                              np.ascontiguousarray(ok)))
        return BatchExecuteResult(ColumnBatch(self._schema, out),
                                  r.is_drained, r.warnings)


class BatchLimitExecutor(TimedExecutor):
    def __init__(self, child, desc):
        super().__init__()
        self._child = child
        self._remaining = desc.limit

    @property
    def schema(self) -> list[FieldType]:
        return self._child.schema

    def _next_batch(self, scan_rows: int) -> BatchExecuteResult:
        if self._remaining <= 0:
            return BatchExecuteResult(ColumnBatch.empty(self.schema), True)
        r = self._child.next_batch(scan_rows)
        batch = r.batch
        if batch.num_rows >= self._remaining:
            batch = batch.slice(0, self._remaining)
            self._remaining = 0
            return BatchExecuteResult(batch, True, r.warnings)
        self._remaining -= batch.num_rows
        return BatchExecuteResult(batch, r.is_drained, r.warnings)


def _ft_of(rpn) -> FieldType:
    from ..datatype import EvalType
    et = rpn.ret_type
    if et is EvalType.REAL:
        return FieldType.double()
    if et is EvalType.BYTES:
        return FieldType.var_char()
    if et is EvalType.DECIMAL:
        return FieldType.new_decimal()
    if et is EvalType.JSON:
        return FieldType.json()
    return FieldType.long()
