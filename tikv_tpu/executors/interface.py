"""Executor interfaces.

Reference: tidb_query_executors/src/interface.rs — ``BatchExecutor`` trait
(:21): ``schema()``, ``next_batch(scan_rows) -> BatchExecuteResult``
(physical columns + logical rows + is_drained), and exec-summary collection
(:45, ExecSummaryCollector). We fold logical-rows into the batch itself
(executors emit already-filtered batches — simpler, and the device path
works on masks anyway).
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Optional, Protocol

from ..datatype import ColumnBatch, FieldType


@dataclass
class ExecSummary:
    """Per-operator execution summary.

    Reference: tipb ExecutorExecutionSummary, filled by runner.rs
    (collect_exec_stats): rows produced, #next_batch calls, wall time.
    """

    num_produced_rows: int = 0
    num_iterations: int = 0
    time_processed_ns: int = 0

    def record(self, rows: int, elapsed_ns: int):
        self.num_produced_rows += rows
        self.num_iterations += 1
        self.time_processed_ns += elapsed_ns


@dataclass
class BatchExecuteResult:
    batch: ColumnBatch
    is_drained: bool
    # warnings carried upward (reference: EvalContext warnings)
    warnings: list = field(default_factory=list)


class BatchExecutor(Protocol):
    summary: ExecSummary

    @property
    def schema(self) -> list[FieldType]: ...

    def next_batch(self, scan_rows: int) -> BatchExecuteResult: ...


class TimedExecutor:
    """Base class handling exec-summary timing around next_batch."""

    def __init__(self):
        self.summary = ExecSummary()

    @property
    def schema(self) -> list[FieldType]:
        raise NotImplementedError

    def _next_batch(self, scan_rows: int) -> BatchExecuteResult:
        raise NotImplementedError

    def next_batch(self, scan_rows: int) -> BatchExecuteResult:
        t0 = time.perf_counter_ns()
        r = self._next_batch(scan_rows)
        self.summary.record(r.batch.num_rows, time.perf_counter_ns() - t0)
        return r
