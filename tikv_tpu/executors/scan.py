"""Table / index scan executors.

Reference: tidb_query_executors/src/table_scan_executor.rs and
index_scan_executor.rs (+ util/scan_executor.rs): pull raw KV pairs from
the storage feed, decode row payloads lazily into columns, surface the PK
handle from the key. Here decode is eager-but-batched (one pass per batch
into dense columns) because the device path wants columnar tiles, not
per-value lazy cells.
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

from ..codec import decode_record_handle, decode_row
from ..codec.mc_datum import decode_mc_datum
from ..codec.number import decode_i64
from ..datatype import Column, ColumnBatch, EvalType, FieldType
from .interface import BatchExecuteResult, TimedExecutor
from .ranges import KeyRange
from .storage import ScanStorage


class BatchTableScanExecutor(TimedExecutor):
    """Reference: table_scan_executor.rs (BatchTableScanExecutor)."""

    def __init__(self, storage: ScanStorage, desc, ranges: Sequence[KeyRange]):
        super().__init__()
        self._storage = storage
        self._desc = desc
        self._storage.begin_scan(ranges, desc.desc)
        self._drained = False
        self._schema = desc.schema

    @property
    def schema(self) -> list[FieldType]:
        return self._schema

    def _next_batch(self, scan_rows: int) -> BatchExecuteResult:
        pairs = self._storage.scan_batch(scan_rows)
        if len(pairs) < scan_rows:
            self._drained = True
        cols_info = self._desc.columns
        n = len(pairs)
        # one decoded python-list per output column; None = NULL
        out: list[list] = [[None] * n for _ in cols_info]
        for r, (key, value) in enumerate(pairs):
            row = decode_row(value) if value else {}
            for c, info in enumerate(cols_info):
                if info.is_pk_handle:
                    out[c][r] = decode_record_handle(key)
                else:
                    v = row.get(info.col_id, info.default_value)
                    out[c][r] = v
        columns = [Column.from_list(info.field_type.eval_type, vals,
                                    unsigned=info.field_type.is_unsigned)
                   for info, vals in zip(cols_info, out)]
        return BatchExecuteResult(ColumnBatch(list(self._schema), columns),
                                  is_drained=self._drained)


class BatchIndexScanExecutor(TimedExecutor):
    """Reference: index_scan_executor.rs.

    Index key layout (codec/keys.py): prefix(t{tid}_i{iid}) + mc-datums of
    the indexed columns + mc-int handle (non-unique). Unique index: handle
    lives in the value (8-byte big-endian). Output columns are the indexed
    columns in order, plus the handle if the last ColumnInfo is pk_handle.
    """

    def __init__(self, storage: ScanStorage, desc, ranges: Sequence[KeyRange]):
        super().__init__()
        self._storage = storage
        self._desc = desc
        self._storage.begin_scan(ranges, desc.desc)
        self._drained = False
        self._schema = desc.schema
        self._prefix_len = 1 + 8 + 2 + 8  # t + tid + _i + iid

    @property
    def schema(self) -> list[FieldType]:
        return self._schema

    def _next_batch(self, scan_rows: int) -> BatchExecuteResult:
        pairs = self._storage.scan_batch(scan_rows)
        if len(pairs) < scan_rows:
            self._drained = True
        cols_info = self._desc.columns
        want_handle = bool(cols_info) and cols_info[-1].is_pk_handle
        n_idx_cols = len(cols_info) - (1 if want_handle else 0)
        n = len(pairs)
        out: list[list] = [[None] * n for _ in cols_info]
        for r, (key, value) in enumerate(pairs):
            off = self._prefix_len
            for c in range(n_idx_cols):
                v, off = decode_mc_datum(key, off)
                out[c][r] = v
            if want_handle:
                if self._desc.unique:
                    out[-1][r] = decode_i64(value, 0)
                else:
                    h, _ = decode_mc_datum(key, off)
                    out[-1][r] = h
        columns = [Column.from_list(info.field_type.eval_type, vals,
                                    unsigned=info.field_type.is_unsigned)
                   for info, vals in zip(cols_info, out)]
        return BatchExecuteResult(ColumnBatch(list(self._schema), columns),
                                  is_drained=self._drained)
