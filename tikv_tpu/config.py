"""Configuration tree + online reconfig dispatch.

Reference: src/config/mod.rs (``TikvConfig`` — one serde-TOML tree
embedding every subsystem's config), components/online_config
(``OnlineConfig`` derive + ``ConfigManager`` trait, lib.rs:137) and the
``ConfigController`` that routes live changes to registered managers;
POST /config on the status server feeds it (status_server/mod.rs:699).

Python shape: dataclass tree loaded from TOML (stdlib ``tomllib``),
validated, diffed for online updates.  Fields marked in
``_ONLINE_FIELDS`` may change at runtime; everything else is rejected
with the same "not an online-config field" contract the reference
enforces.
"""

from __future__ import annotations

import dataclasses
import threading
from dataclasses import dataclass, field, fields
from typing import Callable, Optional


@dataclass
class ServerConfig:
    addr: str = "127.0.0.1:20160"
    status_addr: str = ""               # "" = status server disabled
    grpc_concurrency: int = 8


@dataclass
class StorageConfig:
    data_dir: str = ""                  # "" = in-memory engine
    scheduler_concurrency: int = 4
    # encryption at rest ([security.encryption] in the reference's
    # config): path to a 64-hex-char master key file; "" = plaintext.
    # Data keys + the encrypted file dictionary live in the data dir
    # (components/encryption manager/ + file_dict_file.rs).
    master_key_file: str = ""


@dataclass
class RaftstoreConfig:
    raft_base_tick_interval_ms: int = 10
    raft_heartbeat_ticks: int = 2
    raft_election_timeout_ticks: int = 10
    region_split_size_mb: int = 96      # split-check threshold
    region_max_size_mb: int = 144
    region_split_check_ticks: int = 10  # split check every N ticks
    raft_log_gc_threshold: int = 1024
    hibernate_regions: bool = False
    # batch-system pollers (0 = synchronous drive loop) and async
    # raft-log writer threads (store-pool-size / store-io-pool-size)
    store_pool_size: int = 0
    store_io_pool_size: int = 1
    # apply-pool size (reference apply-pool-size, fsm/apply.rs second
    # batch-system); 0 = apply inline on the raft pollers
    apply_pool_size: int = 2
    region_bucket_size_mb: float = 32.0
    # load-based splitting (split_controller.rs): a region sustaining
    # >= split_qps_threshold reads/s for split_detect_times windows
    # splits at the sampled-access median key; 0 disables
    split_qps_threshold: int = 3000
    split_detect_times: int = 3


@dataclass
class CoprocessorConfig:
    # device routing crossover — rationale at
    # copr/endpoint.py Endpoint.DEFAULT_DEVICE_ROW_THRESHOLD; raise to
    # ~2^22 for tunneled (high-RTT) device transports
    device_row_threshold: int = 131072
    region_cache_capacity: int = 8
    # paged response budget (endpoint.rs paging)
    response_page_rows: int = 1 << 20
    # incremental columnar cache maintenance (copr/region_cache.py):
    # per-region committed-write delta log bounds — a data-version gap
    # wider than the retained log rebuilds instead of patching
    delta_log_entries: int = 1024
    delta_log_rows: int = 1 << 16
    # compact a delta-maintained line when pending delete tombstones
    # exceed this fraction of its rows
    tombstone_compact_ratio: float = 0.25
    # device-state integrity (device/supervisor.py): HBM budget for the
    # runner's feed arena in MiB (0 = unlimited — accounting only) and
    # the background scrub cadence in seconds (0 = scrub on demand).
    # scrub_digests records per-plane content digests at feed build
    # (one vectorized host pass per plane) and patch time (one tiny
    # device reduction per plane) — the audit the scrubber compares
    # against; disable to shave the cold-upload/patch overhead on
    # deployments that never scrub
    device_hbm_budget_mb: int = 0
    scrub_interval_s: float = 0.0
    scrub_digests: bool = True
    # cross-request device batching (server/coalescer.py): concurrent
    # requests sharing a compile class + resident feed coalesce into
    # one stacked dispatch under a bounded, deadline-aware collection
    # window.  coalesce_window_ms = 0 disables the subsystem entirely
    # (every device request dispatches solo); coalesce_max_group caps
    # group size (also the stacked kernel's largest lane bucket)
    coalesce_window_ms: float = 2.0
    coalesce_max_group: int = 16
    # cold-path kill (device/mvcc.py + copr/stream_build.py):
    # device_cold_build enables the device rung of the columnar build
    # ladder (flat-plane parse + on-device MVCC version resolution, the
    # feed born resident); cold_stream additionally parses + uploads
    # CF_WRITE planes of bulk-ingested SST chunks WHILE the load runs,
    # so the first query's build degenerates to one resolve dispatch.
    # cold_stream=None (the default) is AUTO: on iff the process has a
    # spare core to run the parse worker on — the overlap premise is a
    # second core, and on a single-CPU box the worker only steals
    # cycles from the very ingest it shadows (measured: -20% loader
    # throughput and a stalled first query).  True/False force it.
    # cold_stream_max_mb bounds the retained host planes per region
    # (device planes shed first at half the cap); 0 = unlimited
    device_cold_build: bool = True
    cold_stream: Optional[bool] = None
    cold_stream_max_mb: int = 1024
    # multi-chip scale-out (parallel/mesh.py, device/placement.py):
    # mesh_shape pins the ("range", "tile") mesh factorization
    # ("2x4"; default None lets _factor2 pick the squarest split —
    # note a PRIME device count then degenerates to 1xN).  Fixed at
    # runner construction; the live shape is visible in /health
    # device_mesh.  device_placement turns on hot-region → slice
    # routing: small regions pin to single-device slices spread by
    # load (PD's balance-region policy one level down), feeds at or
    # above placement_rows shard over the whole mesh.
    mesh_shape: Optional[str] = None
    device_placement: bool = False
    placement_rows: int = 1 << 22
    # chip failure domains (device/supervisor.py SliceHealth): strikes
    # to quarantine a mesh slice (dispatch/fetch faults and scrub
    # quarantines weigh 1.0, launch-latency outliers 0.25; served
    # requests decay 0.5), the half-open canary-probe cooldown after a
    # trip, and the round-trip latency above which a served request
    # still counts as an outlier strike (0 disables the latency feed —
    # cold compiles on slow transports would otherwise strike healthy
    # slices)
    slice_trip_strikes: float = 3.0
    slice_probe_cooldown_s: float = 0.25
    slice_latency_outlier_s: float = 0.0
    # causal request tracing (utils/trace.py): trace_sample is the
    # fraction of read RPCs recording full span trees (a client-sent
    # trace_id always samples; TimeDetail stays on the wire for every
    # request regardless), trace_buffer bounds the /debug/trace
    # retention ring (tail-biased: slowest-per-class + errored/late
    # requests pin past ring eviction), slow_log_threshold_ms fires
    # the redacted slow-query log line (TiKV slow_log! analog; 0
    # disables), flight_recorder_depth bounds the device launch ring
    trace_sample: float = 1.0
    trace_buffer: int = 256
    slow_log_threshold_ms: float = 1000.0
    flight_recorder_depth: int = 256
    # microsecond warm path (server/fastpath.py + server/coalescer.py):
    # fastpath_classes bounds the learned wire-template cache (0
    # disables the compiled request fast path entirely — every request
    # takes the full decode pipeline); dispatch_pipeline enables the
    # coalescer's back-to-back dispatcher (collection overlaps the
    # in-flight launch, and a drained device is fed the oldest open
    # group early instead of waiting out its window)
    fastpath_classes: int = 64
    dispatch_pipeline: bool = True
    # re-mint storm control (device/supervisor.py RemintGovernor):
    # remint_concurrency bounds concurrent cold columnar_build
    # re-mints after a mass invalidation (0 = unthrottled — the
    # pre-storm-control behavior); excess builds park in a priority
    # queue (hot regions first, RU-debt tenants last) of at most
    # remint_queue, past which the worst-priority waiter is shed with
    # a ServerIsBusy carrying remint_retry_after_ms
    remint_concurrency: int = 0
    remint_queue: int = 32
    remint_retry_after_ms: int = 50


@dataclass
class ReadPoolConfig:
    concurrency: int = 8


@dataclass
class ResourceMeteringConfig:
    """[resource-metering]: device-aware RU attribution
    (resource_metering.py + ru_model.py).  Every field is
    online-updatable and visible in /health.

    The windowed recorder rolls per-tag/per-region charges every
    ``window_s``; the last window's top-``topk`` hot-tenant/hot-region
    report serves /resource_metering and rides the store heartbeat to
    PD every ``report_interval_s``.  ``max_resource_groups`` bounds
    the live tag map (overflow + idle tags fold into "other").  The
    ``ru_per_*`` weights are the linear cost model — see
    ru_model.RuModel's table for the defaults' rationale."""

    window_s: float = 5.0
    topk: int = 8
    max_resource_groups: int = 64
    report_interval_s: float = 5.0
    # RU weights (0 disables an axis); None in a TOML would be odd, so
    # the dataclass carries the model defaults verbatim
    ru_per_launch_s: float = 1000.0 / 3.0
    ru_per_host_s: float = 1000.0 / 3.0
    ru_per_d2h_mb: float = 16.0
    ru_per_mb_s: float = 0.05
    ru_per_read_key: float = 1.0 / 2048.0
    ru_per_request: float = 0.125


@dataclass
class ResourceControlConfig:
    """[resource-control]: multi-tenant enforcement of the RU charges
    ``[resource-metering]`` measures (resource_control.py).  Every
    field is online-updatable and visible in /health and at
    /resource_control.

    ``groups`` maps resource-group names to ``{share, burst,
    priority}`` specs: ``share`` is the group's token-bucket refill
    rate in RU/s (the unit the ru_model prices every measured charge
    in), ``burst`` the bucket cap in RU (0 = 2× share), ``priority``
    one of low/medium/high (high never sheds at the read pool and
    never counts as throttled in the coalescer's DWFQ).  Groups not
    named here get ``default_share``/``default_burst``.  A typo'd
    group key, a non-positive share, or an unknown priority tier
    fails validation (the negative-RU-weight guard applied to group
    specs)."""

    enabled: bool = False
    default_share: float = 500.0
    default_burst: float = 0.0          # 0 = 2x share
    groups: dict = field(default_factory=dict)


@dataclass
class SecurityConfig:
    """[security]: TLS for every gRPC channel (components/security).
    The ONE definition — server/security.py builds its manager from
    this same dataclass."""

    ca_path: str = ""
    cert_path: str = ""
    key_path: str = ""

    @property
    def enabled(self) -> bool:
        return bool(self.ca_path or self.cert_path)


@dataclass
class TikvConfig:
    """The full config tree (config/mod.rs TikvConfig analog)."""

    server: ServerConfig = field(default_factory=ServerConfig)
    storage: StorageConfig = field(default_factory=StorageConfig)
    raftstore: RaftstoreConfig = field(default_factory=RaftstoreConfig)
    coprocessor: CoprocessorConfig = field(
        default_factory=CoprocessorConfig)
    readpool: ReadPoolConfig = field(default_factory=ReadPoolConfig)
    resource_metering: ResourceMeteringConfig = field(
        default_factory=ResourceMeteringConfig)
    resource_control: ResourceControlConfig = field(
        default_factory=ResourceControlConfig)
    security: SecurityConfig = field(default_factory=SecurityConfig)

    @staticmethod
    def from_file(path: str) -> "TikvConfig":
        import tomllib
        with open(path, "rb") as f:
            raw = tomllib.load(f)
        return TikvConfig.from_dict(raw)

    @staticmethod
    def from_dict(raw: dict) -> "TikvConfig":
        cfg = TikvConfig()
        for f in fields(cfg):
            sub = raw.get(f.name.replace("_", "-"), raw.get(f.name))
            if sub is None:
                continue
            target = getattr(cfg, f.name)
            for sf in fields(target):
                key = sf.name.replace("_", "-")
                if key in sub or sf.name in sub:
                    setattr(target, sf.name, sub.get(key, sub.get(sf.name)))
        cfg.validate()
        return cfg

    def validate(self) -> None:
        r = self.raftstore
        if r.raft_heartbeat_ticks >= r.raft_election_timeout_ticks:
            raise ValueError("heartbeat ticks must be < election ticks")
        if r.region_split_size_mb > r.region_max_size_mb:
            raise ValueError("region-split-size must be <= region-max-size")
        if self.readpool.concurrency < 1:
            raise ValueError("readpool concurrency must be >= 1")
        rm = self.resource_metering
        if rm.window_s <= 0:
            raise ValueError("resource-metering window-s must be > 0")
        if rm.topk < 1 or rm.max_resource_groups < 1:
            raise ValueError(
                "resource-metering topk/max-resource-groups must be "
                ">= 1")
        if rm.report_interval_s < 0:
            raise ValueError(
                "resource-metering report-interval-s must be >= 0")
        for f in dataclasses.fields(rm):
            if f.name.startswith("ru_per_") and \
                    getattr(rm, f.name) < 0:
                # a negative weight would DECREMENT RU counters and
                # corrupt every downstream total/report
                raise ValueError(
                    f"resource-metering {f.name} must be >= 0")
        rc = self.resource_control
        if rc.default_share <= 0:
            raise ValueError(
                "resource-control default-share must be > 0")
        if rc.default_burst < 0:
            raise ValueError(
                "resource-control default-burst must be >= 0")
        # group-spec vocabulary guard: a typo'd key, non-positive
        # share, or unknown priority tier fails HERE, never silently
        # mis-configures an enforcement site (resource_control.py
        # owns the one validator both paths share)
        from .resource_control import validate_group_specs
        validate_group_specs(rc.groups)

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


# fields changeable at runtime ("section.field" — OnlineConfig markers)
_ONLINE_FIELDS = {
    "raftstore.region_split_size_mb",
    "raftstore.region_max_size_mb",
    "raftstore.region_split_check_ticks",
    "raftstore.raft_log_gc_threshold",
    "raftstore.hibernate_regions",
    "coprocessor.device_row_threshold",
    "coprocessor.region_cache_capacity",
    "coprocessor.response_page_rows",
    "coprocessor.tombstone_compact_ratio",
    "coprocessor.device_hbm_budget_mb",
    "coprocessor.coalesce_window_ms",
    "coprocessor.coalesce_max_group",
    "coprocessor.device_cold_build",
    "coprocessor.trace_sample",
    "coprocessor.trace_buffer",
    "coprocessor.slow_log_threshold_ms",
    "coprocessor.flight_recorder_depth",
    "coprocessor.fastpath_classes",
    "coprocessor.dispatch_pipeline",
    "coprocessor.remint_concurrency",
    "readpool.concurrency",
    "resource_metering.window_s",
    "resource_metering.topk",
    "resource_metering.max_resource_groups",
    "resource_metering.report_interval_s",
    "resource_metering.ru_per_launch_s",
    "resource_metering.ru_per_host_s",
    "resource_metering.ru_per_d2h_mb",
    "resource_metering.ru_per_mb_s",
    "resource_metering.ru_per_read_key",
    "resource_metering.ru_per_request",
    "resource_control.enabled",
    "resource_control.default_share",
    "resource_control.default_burst",
    "resource_control.groups",
}


class ConfigController:
    """Live-change router (online_config ConfigController analog).

    Subsystems register a manager callback per section; ``update``
    validates the diff against _ONLINE_FIELDS, applies it to the config
    tree, and dispatches {changed field: value} to the section manager.
    """

    def __init__(self, cfg: TikvConfig):
        self.cfg = cfg
        self._managers: dict[str, Callable[[dict], None]] = {}
        self._lock = threading.Lock()

    def register(self, section: str,
                 manager: Callable[[dict], None]) -> None:
        self._managers[section] = manager

    def update(self, changes: dict) -> dict:
        """changes: {"raftstore.region-split-size-mb": 64, ...} →
        {applied field: value}.  Raises ValueError on unknown or
        non-online fields (nothing is applied)."""
        with self._lock:
            parsed = []
            for dotted, value in changes.items():
                section, _, name = dotted.replace("-", "_").partition(".")
                if not name:
                    raise ValueError(f"bad config key {dotted!r}")
                if f"{section}.{name}" not in _ONLINE_FIELDS:
                    raise ValueError(
                        f"{dotted!r} is not an online-config field")
                target = getattr(self.cfg, section, None)
                if target is None or not hasattr(target, name):
                    raise ValueError(f"unknown config field {dotted!r}")
                cur = getattr(target, name)
                if cur is not None and value is not None and \
                        not isinstance(value, type(cur)):
                    if isinstance(cur, bool) or not (
                            isinstance(cur, (int, float)) and
                            isinstance(value, (int, float))):
                        raise ValueError(
                            f"{dotted!r}: want {type(cur).__name__}")
                parsed.append((section, name, value))
            # validate the tree with changes applied before committing
            # (deep copy: replace() would share the nested sections)
            import copy
            trial = copy.deepcopy(self.cfg)
            for section, name, value in parsed:
                setattr(getattr(trial, section), name, value)
            trial.validate()
            applied: dict = {}
            by_section: dict[str, dict] = {}
            for section, name, value in parsed:
                setattr(getattr(self.cfg, section), name, value)
                applied[f"{section}.{name}"] = value
                by_section.setdefault(section, {})[name] = value
        for section, diff in by_section.items():
            mgr = self._managers.get(section)
            if mgr is not None:
                mgr(diff)
        return applied
