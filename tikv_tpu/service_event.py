"""Service lifecycle event channel.

Reference: components/service/src/service_event.rs — an embedding
process (or the status server) posts PAUSE_GRPC / CONTINUE_GRPC / EXIT
onto a channel; the server loop reacts without the poster knowing the
server's internals.
"""

from __future__ import annotations

import enum
import queue
import threading


class ServiceEvent(enum.Enum):
    PAUSE_GRPC = "pause"
    CONTINUE_GRPC = "continue"
    EXIT = "exit"


class ServiceEventChannel:
    def __init__(self):
        self._q: "queue.Queue[ServiceEvent]" = queue.Queue()

    def post(self, event: ServiceEvent) -> None:
        self._q.put(event)

    def get(self, timeout=None):
        try:
            return self._q.get(timeout=timeout)
        except queue.Empty:
            return None


def attach(channel: ServiceEventChannel, server) -> threading.Thread:
    """Drive a TikvServer from the channel: pause rejects new RPCs with
    server_is_busy, continue resumes, exit stops the server.  Returns
    the (daemon) dispatcher thread."""

    def run():
        while True:
            ev = channel.get(timeout=0.2)
            if ev is None:
                if getattr(server, "_stopped", False):
                    return
                continue
            if ev is ServiceEvent.PAUSE_GRPC:
                server.service.paused = True
            elif ev is ServiceEvent.CONTINUE_GRPC:
                server.service.paused = False
            elif ev is ServiceEvent.EXIT:
                server.stop()
                return

    t = threading.Thread(target=run, daemon=True)
    t.start()
    return t
