"""Device (TPU) execution backend for the coprocessor layer."""

from .runner import DeviceRunner

__all__ = ["DeviceRunner"]
