"""Device (TPU) execution backend for the coprocessor layer."""

from .runner import DeferredResult, DeviceRunner

__all__ = ["DeviceRunner", "DeferredResult"]
