"""Device (TPU) execution backend for the coprocessor layer.

Lazy exports (PEP 562): importing a sibling like
``tikv_tpu.device.supervisor`` — which every server Node does for
lifecycle teardown, device runner or not — must not drag in the
accelerator runtime; ``DeviceRunner`` pulls jax only when first
touched.
"""

__all__ = ["DeviceRunner", "DeferredResult"]


def __getattr__(name):
    if name in __all__:
        from . import runner
        return getattr(runner, name)
    raise AttributeError(name)
