"""Hot-region → mesh-slice placement (the PD loop, one level down).

A multi-chip node has two ways to use its mesh (parallel/mesh.py):
shard one big feed over every chip (scale-up — a single request's
kernel runs as per-shard partials + tree-reduce), or pin many small
regions' feeds to single-device slices (scale-out — many concurrent
requests each run whole on one chip).  Left alone, the second mode
degenerates: every region lands wherever the runner happens to live
and one chip saturates while seven idle — exactly the hot-store
problem PD's balance-region scheduler exists to prevent.

:class:`SlicePlacer` closes that loop locally.  It owns one
single-device sub-runner per mesh slice and routes each feed anchor
(region lineage / snapshot) to a slice chosen by the PD policy
(pd/scheduler.pick_slice) over a blended score:

- **occupancy** — the slice arena's resident HBM bytes (PR 6's
  accounting), normalized across slices; and
- **load** — a decayed per-slice dispatch rate (PR 3's slow-score
  discipline: recent traffic dominates, history fades), so a Zipfian
  mix's hot regions spread by the traffic they actually draw, not
  just by bytes.

Placement is STICKY (a placed anchor keeps its slice — its HBM feed,
request memos, and compile classes live there) until the opportunistic
rebalance step (pd/scheduler.rebalance_donor) finds the spread
unjustifiable; then the hottest slice's coldest anchor MIGRATES to the
coolest slice over ICI (:meth:`SlicePlacer.migrate`): its resident
feeds travel between chips via ``device_put`` with their lineage
versions and scrub digests, the destination re-verifies every plane on
arrival before it serves, and only when migration is impossible (no
digests, arrival divergence) does the move degrade to the old
drop-and-re-mint over the narrow host link.  Feeds above ``whole_mesh_rows`` bypass
placement and shard over the full mesh (scale-up wins past the point
where one chip's HBM pass dominates the launch overhead).

A slice is NOT assumed healthy forever.  The placer shares the
runner's :class:`~.supervisor.SliceHealthBoard` (dispatch/fetch
faults, scrub quarantines and latency outliers strike per-slice
scores, PR 3's slow-store shape): a QUARANTINED slice stops being
scored — ``pick_slice`` excludes it, and its sticky anchors DRAIN
onto healthy slices (spread via ``pd.scheduler.drain_receivers``, the
evict-slow-store shape) by ICI migration first: the condemned chip's
planes usually still verify, so the drain is a device copy per feed,
not a recovery storm of host re-mints.  A feed that fails arrival
verify (or carries no digests) drops through the PR 6 retirement path
instead, and the draining slice's joiner build-side dictionaries
retire explicitly so its HBM frees immediately.  Routing that still
finds an anchor pinned to a dead slice fails it over on the spot.  Half-open canary
probes re-admit the slice with a DECAYED (not reset) score, so the
health penalty in the placement blend lets anchors trickle back —
never a thundering re-pin.

JOIN CO-LOCATION (plan IR, copr/plan_ir.py): every served join plan
records its two feed anchors as a decayed PAIR FREQUENCY
(:meth:`SlicePlacer.note_join`).  Once a pair's affinity clears
``COLOCATE_AFFINITY``, a new placement for either anchor pins to the
other's slice instead of the coolest one — "these two regions join
often" expressed in the same decayed-score vocabulary as load — so
the device hash join's build dictionary and probe feed co-reside and
the probe dispatch mints zero cross-slice transfers.

The placer is OFF by default (``DeviceRunner(placement=False)``) —
single-chip deployments and whole-mesh benches never pay the routing
indirection; ``coprocessor.device_placement`` turns it on for serving
nodes.
"""

from __future__ import annotations

import threading
import time
import weakref
from typing import Optional

from ..parallel import make_mesh, mesh_slices
from ..pd.scheduler import (
    drain_receivers,
    pick_slice,
    rebalance_donor,
    slice_scores,
)

# feeds at or above this many rows shard over the WHOLE mesh instead of
# pinning to one slice: one chip's HBM pass over 4M+ rows costs more
# than the cross-chip launch + tree-reduce overhead it would save
DEFAULT_WHOLE_MESH_ROWS = 1 << 22

# decayed-load half life in seconds: recent dispatches dominate the
# traffic score, minutes-old history fades (the slow-score shape)
LOAD_HALFLIFE_S = 30.0

# run the rebalance check every N routed requests — placement decisions
# stay O(1) per request, the O(slices·anchors) scan amortizes
REBALANCE_EVERY = 64

# decayed pair-frequency (served join plans, copr/plan_ir.py) above
# which two anchors are treated as a JOIN PAIR: a new placement for
# one prefers the other's slice, so the device join's build and probe
# feeds co-reside and the probe dispatch mints zero cross-slice
# transfers.  Decays with the same half-life as the load score.
COLOCATE_AFFINITY = 2.0


class SlicePlacer:
    """Per-slice sub-runners + the placement policy over them.

    ``parent`` is the whole-mesh :class:`DeviceRunner`; sub-runners are
    built from its mesh's single-device slices with the parent's tuning
    (chunk override, capacities, per-slice share of the HBM budget).
    """

    def __init__(self, parent, whole_mesh_rows: int =
                 DEFAULT_WHOLE_MESH_ROWS):
        self._parent = parent
        self.whole_mesh_rows = whole_mesh_rows
        self._mu = threading.Lock()
        self._slices = [parent._make_slice_runner(make_mesh(devs),
                                                  slice_indices=(i,),
                                                  bind_health=True)
                        for i, devs in
                        enumerate(mesh_slices(parent._mesh))]
        if parent._arena.budget_bytes > 0:
            # a budget passed at parent CONSTRUCTION must bind the
            # slices too, not only the set_hbm_budget() path
            self.set_hbm_budget(parent._arena.budget_bytes)
        self._load = [0.0] * len(self._slices)
        self._load_t = time.monotonic()
        # id(anchor) -> slice index; weakref finalizers prune entries
        # for anchors that die without an explicit drop
        self._placed: dict[int, int] = {}
        self._refs: dict[int, object] = {}
        self._routes = 0
        self.places = 0
        self.moves = 0
        self.whole_mesh_routes = 0
        # co-location hints: decayed pair-frequency of anchors that
        # JOIN each other (note_join, fed by served join plans) —
        # placement prefers pinning a join pair to ONE slice
        self._pair_aff: dict[tuple[int, int], float] = {}
        self._pair_t = time.monotonic()
        self.colocation_pins = 0
        # chip failure domains: the parent's health board scores these
        # same slices; a trip drains the dead slice's anchors here
        self._board = parent._board
        self.failovers = 0
        self.drained = 0
        # ICI feed migration (the move path that skips the host link):
        # total moves, cumulative/last wall time, children adopted at
        # device-side splits, and moves that degraded to drop+re-mint
        self.migrations = 0
        self.migration_ms = 0.0
        self.last_migration_ms = 0.0
        self.migration_failures = 0
        self.adoptions = 0
        if self._board is not None:
            self._board.add_trip_listener(self._on_slice_trip)

    def __len__(self) -> int:
        return len(self._slices)

    @property
    def slices(self) -> list:
        return list(self._slices)

    # -- scoring ------------------------------------------------------

    def _decay_locked(self) -> None:
        now = time.monotonic()
        dt = now - self._load_t
        if dt <= 0:
            return
        f = 0.5 ** (dt / LOAD_HALFLIFE_S)
        self._load = [v * f for v in self._load]
        self._load_t = now

    def _scores_locked(self) -> list:
        self._decay_locked()
        occ = {i: r._arena.resident_bytes()
               for i, r in enumerate(self._slices)}
        mx_b = max(occ.values(), default=0) or 1
        mx_l = max(self._load, default=0.0) or 1.0
        scores = slice_scores({i: b / mx_b for i, b in occ.items()},
                              {i: v / mx_l
                               for i, v in enumerate(self._load)},
                              len(self._slices))
        if self._board is not None:
            # health penalty: a freshly-readmitted slice carries a
            # decayed-but-high strike score, so new placements trickle
            # back instead of thundering onto a chip that just flapped
            scores = [s + self._board.penalty(i)
                      for i, s in enumerate(scores)]
        return scores

    def _dead_locked(self) -> frozenset:
        return self._board.quarantined_set() \
            if self._board is not None else frozenset()

    # -- co-location hints (served join plans → pair affinity) --------

    def _decay_pairs_locked(self) -> None:
        now = time.monotonic()
        dt = now - self._pair_t
        if dt <= 0:
            return
        f = 0.5 ** (dt / LOAD_HALFLIFE_S)
        if f < 0.999:
            self._pair_aff = {k: v * f
                              for k, v in self._pair_aff.items()
                              if v * f > 0.05}
            self._pair_t = now

    def note_join(self, a, b) -> None:
        """Record one served join between anchors ``a`` and ``b`` —
        the decayed pair frequency the placement blend reads as 'these
        two regions join often, pin them together'.  The affinity
        CROSSING the co-location threshold while both anchors sit on
        different healthy slices triggers an active pull: one side's
        feeds migrate over ICI to the other's slice, so an
        already-placed hot pair co-resides without waiting for a drop
        or an LRU eviction to re-place it."""
        if a is b:
            return
        key = (min(id(a), id(b)), max(id(a), id(b)))
        pull = None
        with self._mu:
            self._decay_pairs_locked()
            old = self._pair_aff.get(key, 0.0)
            self._pair_aff[key] = old + 1.0
            if old < COLOCATE_AFFINITY <= old + 1.0:
                ia = self._placed.get(id(a))
                ib = self._placed.get(id(b))
                dead = self._dead_locked()
                if ia is not None and ib is not None and ia != ib and \
                        ia not in dead and ib not in dead:
                    pull = (a, ia, ib)
            while len(self._pair_aff) > 256:
                # drop the weakest OTHER pair — never the pair just
                # recorded, or at capacity a new hot pair would be
                # evicted in the same call forever and its affinity
                # could never accumulate past the co-location threshold
                weakest = min((k for k in self._pair_aff if k != key),
                              key=self._pair_aff.get)
                del self._pair_aff[weakest]
        if pull is not None and self.migrate(*pull, reason="colocate"):
            from ..utils import metrics as m
            m.DEVICE_PLACEMENT_COUNTER.labels("colocate").inc()
            with self._mu:
                self.colocation_pins += 1

    def _partner_slice_locked(self, key: int,
                              dead: frozenset) -> Optional[int]:
        """The strongest join partner's placed slice (affinity ≥
        COLOCATE_AFFINITY, partner placed, slice healthy) — where a
        new placement for ``key`` should land."""
        self._decay_pairs_locked()
        best, best_aff = None, COLOCATE_AFFINITY
        for (a, b), aff in self._pair_aff.items():
            if aff < best_aff:
                continue
            other = b if a == key else (a if b == key else None)
            if other is None:
                continue
            idx = self._placed.get(other)
            if idx is not None and idx not in dead:
                best, best_aff = idx, aff
        return best

    def colocated(self, a, b) -> bool:
        """Are both anchors currently pinned to ONE healthy slice?"""
        with self._mu:
            ia = self._placed.get(id(a))
            ib = self._placed.get(id(b))
            return ia is not None and ia == ib and \
                ia not in self._dead_locked()

    # -- routing ------------------------------------------------------

    def route(self, storage, n_hint: Optional[int] = None):
        """→ the runner that should serve this request: a placed slice
        sub-runner, or the whole-mesh parent for large feeds and
        untrackable anchors."""
        from ..utils import metrics as m
        anchor = self._parent._feed_anchor(storage)
        if n_hint is None:
            est = getattr(storage, "estimated_rows", None)
            if callable(est):
                try:
                    n_hint = est()
                except Exception:   # noqa: BLE001 — hint only
                    n_hint = None
        if n_hint is not None and n_hint >= self.whole_mesh_rows:
            key = id(anchor)
            with self._mu:
                self.whole_mesh_routes += 1
                # an anchor that GREW past the threshold graduates to
                # the whole mesh: its stale slice feed would otherwise
                # sit unpatched (and unevicted under no budget) forever
                idx = self._placed.pop(key, None)
                self._refs.pop(key, None)
            if idx is not None:
                self._slices[idx].drop_feed(anchor, reason="placement")
            m.DEVICE_PLACEMENT_COUNTER.labels("whole_mesh").inc()
            return self._parent
        # half-open probing rides routing: a quarantined slice whose
        # cooldown elapsed gets its canary now (bounded by the board's
        # per-slice probe gate — cheap when nothing is due)
        self._parent.probe_quarantined()
        key = id(anchor)
        failover_from = None
        with self._mu:
            dead = self._dead_locked()
            idx = self._placed.get(key)
            if idx is not None and idx in dead and \
                    len(dead) < len(self._slices):
                # the anchor's slice died since it was placed (or the
                # trip-time drain raced this request): fail it over to
                # a healthy slice NOW — its feed rebuilds there.
                # Total mesh death keeps the pin instead: pick_slice's
                # all-excluded fallback would just re-pin onto another
                # dead slice every request (a failover storm in the
                # counters); the refusal gate host-serves until a
                # probe re-admits something
                failover_from = idx
                idx = None
            if idx is None:
                # co-location hint first: a join pair's new member
                # lands on its partner's slice (decayed affinity from
                # served join plans), score-blind by design — the join
                # saves more than a marginally cooler chip would
                idx = self._partner_slice_locked(key, dead)
                if idx is not None:
                    self.colocation_pins += 1
                    m.DEVICE_PLACEMENT_COUNTER.labels("colocate").inc()
                else:
                    idx = pick_slice(self._scores_locked(), exclude=dead)
                try:
                    self._refs[key] = weakref.ref(
                        anchor, lambda _r, k=key: self._forget(k))
                except TypeError:
                    return self._parent      # untrackable anchor
                self._placed[key] = idx
                if failover_from is None:
                    self.places += 1
                    m.DEVICE_PLACEMENT_COUNTER.labels("place").inc()
                else:
                    self.failovers += 1
            self._load[idx] += 1.0
            self._routes += 1
            rebalance = self._routes % REBALANCE_EVERY == 0
        if failover_from is not None:
            self._slices[failover_from].drop_feed(anchor,
                                                  reason="failover")
            m.DEVICE_FAILOVER_COUNTER.labels("failover").inc()
        if rebalance:
            self.rebalance()
        return self._slices[idx]

    def owner(self, anchor):
        """The sub-runner currently holding ``anchor``, or None."""
        with self._mu:
            idx = self._placed.get(id(anchor))
        return None if idx is None else self._slices[idx]

    def _forget(self, key: int) -> None:
        with self._mu:
            self._placed.pop(key, None)
            self._refs.pop(key, None)
            # a dead anchor's join-pair affinities die with it: a NEW
            # object reusing the id must never inherit another
            # region's co-location hint (same id-reuse guard as the
            # joiner's weakref pruning)
            if self._pair_aff:
                self._pair_aff = {k: v
                                  for k, v in self._pair_aff.items()
                                  if key not in k}

    def forget(self, anchor) -> None:
        self._forget(id(anchor))

    # -- ICI feed migration -------------------------------------------

    def migrate(self, anchor, src: int, dst: int,
                reason: str = "placement") -> bool:
        """Move ``anchor``'s resident feeds from slice ``src`` to
        ``dst`` over the device interconnect → True when the
        destination serves the moved feeds.

        The feeds travel with their lineage versions and scrub
        digests (``extract_feeds``); the destination re-hashes every
        plane on arrival BEFORE installing (``install_feeds``) — a
        divergent plane quarantines the source copy and the move
        reports False so the caller falls back to drop+re-mint from
        host truth.  In-flight requests need no rescue choreography:
        the source feeds are not dropped until after the pin flips,
        and a request that raced onto the destination and re-minted a
        NEWER generation there is never clobbered by the arriving
        copy."""
        from ..utils import metrics as m
        from ..utils import tracker
        if src == dst or not (0 <= src < len(self._slices)) or \
                not (0 <= dst < len(self._slices)):
            return False
        src_r, dst_r = self._slices[src], self._slices[dst]
        t0 = time.perf_counter()
        with tracker.phase("feed_migrate"):
            try:
                feeds, skipped = src_r.extract_feeds(anchor)
            except Exception:   # noqa: BLE001 — migration is best-effort
                feeds, skipped = None, 0
            if not feeds:
                m.DEVICE_FEED_MIGRATION_COUNTER.labels(
                    "no_digests").inc()
                with self._mu:
                    self.migration_failures += 1
                return False
            try:
                verdict = dst_r.install_feeds(anchor, feeds)
            except Exception:   # noqa: BLE001 — same contract
                verdict = "corrupt"
            if verdict != "moved":
                # arrival verify caught divergence: never serve it —
                # drop whatever landed and condemn the source copy
                # (quarantine-and-rebuild, the scrub discipline)
                dst_r.drop_feed(anchor, reason="migrate_verify")
                try:
                    src_r.quarantine(anchor, reason="migrate divergence")
                except Exception:   # noqa: BLE001
                    pass
                m.DEVICE_FEED_MIGRATION_COUNTER.labels("corrupt").inc()
                with self._mu:
                    self.migration_failures += 1
                return False
            key = id(anchor)
            ms = (time.perf_counter() - t0) * 1e3
            with self._mu:
                if key not in self._placed:
                    try:
                        self._refs[key] = weakref.ref(
                            anchor, lambda _r, k=key: self._forget(k))
                    except TypeError:
                        pass    # untrackable: feeds moved, pin didn't
                if key in self._refs:
                    self._placed[key] = dst
                self.migrations += 1
                self.migration_ms += ms
                self.last_migration_ms = ms
        # the pin now points at dst: drop the source copy LAST so a
        # dispatch already in flight on src finishes against resident
        # planes (arena pins keep them alive through the kernel)
        src_r.drop_feed(anchor, reason=reason)
        m.DEVICE_FEED_MIGRATION_COUNTER.labels(
            "partial" if skipped else "moved").inc()
        return True

    def adopt(self, parent, children) -> None:
        """Pin device-split children to their parent's slice.  The
        child feeds were sliced from the parent's resident planes ON
        that slice (split_stash), so the children's first requests
        must route there to consume them — anywhere else re-uploads
        from host."""
        from ..utils import metrics as m
        with self._mu:
            idx = self._placed.get(id(parent))
            if idx is None or idx in self._dead_locked():
                return
            n = 0
            for ch in children:
                if ch is None:
                    continue
                k = id(ch)
                try:
                    self._refs[k] = weakref.ref(
                        ch, lambda _r, kk=k: self._forget(kk))
                except TypeError:
                    continue
                self._placed[k] = idx
                n += 1
            self.adoptions += n
        if n:
            m.DEVICE_PLACEMENT_COUNTER.labels("adopt").inc(n)

    # -- failure-domain drain -----------------------------------------

    def _on_slice_trip(self, idx: int, reason: str) -> None:
        """Board trip listener: drain every anchor stuck to the dead
        slice — MIGRATE each onto a healthy slice over ICI
        (least-loaded-first round-robin via ``drain_receivers``, the
        evict-slow-store spread, NOT a single-receiver dump).  A
        condemned chip's planes usually still verify, so the drain is
        a device copy per feed and the receivers serve warm; a feed
        that can't travel (no digests, arrival divergence) drops
        through the retirement path and its next request rebuilds cold
        — answers stay correct throughout because a rebuild is just a
        cold hit.  The dead slice's joiner build-side dictionaries
        retire explicitly too: waiting for weakref GC would strand
        HBM on a chip the budget still accounts."""
        from ..utils import metrics as m
        with self._mu:
            victims = [k for k, v in self._placed.items() if v == idx]
            if not victims:
                return
            dead = self._dead_locked() | {idx}
            targets = drain_receivers(self._scores_locked(),
                                      exclude=dead, k=len(victims))
            moves = []
            for j, k in enumerate(victims):
                tgt = targets[j] if targets else None
                # no healthy receiver (total mesh death): keep the
                # pin — route-time failover re-pins when a slice
                # re-admits — but the feeds below STILL drop: HBM
                # state on a condemned chip is garbage either way
                ref = self._refs.get(k)
                a = ref() if ref is not None else None
                if a is not None:
                    moves.append((a, tgt))
            self.drained += len(victims)
        for a, tgt in moves:
            with self._mu:
                if self._placed.get(id(a)) != idx:
                    continue    # route-time failover won the race
            moved = False
            if tgt is not None:
                try:
                    moved = self.migrate(a, idx, tgt, reason="failover")
                except Exception:   # noqa: BLE001 — drain must finish
                    moved = False
            if not moved:
                if tgt is not None:
                    with self._mu:
                        if self._placed.get(id(a)) == idx:
                            self._placed[id(a)] = tgt
                self._slices[idx].drop_feed(a, reason="failover")
        joiner = getattr(self._slices[idx], "_joiner", None)
        if joiner is not None:
            joiner.drop_all()
        m.DEVICE_FAILOVER_COUNTER.labels("drain").inc(len(victims))

    # -- rebalance ----------------------------------------------------

    def rebalance(self) -> bool:
        """One balance step: when the hottest slice carries an
        unjustifiable share of the blended score, drop its COLDEST
        anchor's feed and re-pin the anchor to the coolest slice (the
        next request rebuilds there).  Coldest-first keeps the move
        cheap — the hot anchor's warm feed and compile classes stay
        put, mirroring how PD drains a hot store by moving replicas,
        not leaders, first.  Returns True when a move happened."""
        from ..utils import metrics as m
        with self._mu:
            pair = rebalance_donor(self._scores_locked(), min_ratio=2.0,
                                   min_gap=0.25)
            if pair is None:
                return False
            hot, cool = pair
            if cool in self._dead_locked():
                # never balance ONTO a quarantined slice (its health
                # penalty usually keeps it off the cool end, but a
                # fully-loaded mesh can tie) — the drain already moved
                # its anchors the other way
                return False
            donor = self._slices[hot]
            victim = None
            v_stats = None
            for anchor, nbytes, hits, tick, pins in \
                    donor._arena.entry_stats():
                if pins > 0 or self._placed.get(id(anchor)) != hot:
                    continue
                st = (hits, tick)
                if v_stats is None or st < v_stats:
                    victim, v_stats = anchor, st
            if victim is None:
                return False
            self.moves += 1
        # outside the lock: the move itself is a device-side ICI copy
        # (verify-on-arrival), falling back to the old drop+re-pin when
        # the feeds can't travel (no digests / divergence)
        if not self.migrate(victim, hot, cool, reason="placement"):
            with self._mu:
                self._placed[id(victim)] = cool
            donor.drop_feed(victim, reason="placement")
        m.DEVICE_PLACEMENT_COUNTER.labels("move").inc()
        return True

    # -- fan-out helpers (parent delegation) --------------------------

    def drop_feed_all(self, anchor, reason: str) -> int:
        freed = 0
        for r in self._slices:
            freed += r.drop_feed(anchor, reason=reason)
        self.forget(anchor)
        return freed

    def set_hbm_budget(self, parent_budget: int) -> None:
        """Per-slice share of the node budget: slices split it evenly
        (each owns a disjoint anchor set), the parent keeps the full
        figure for whole-mesh feeds."""
        share = parent_budget // len(self._slices) \
            if parent_budget > 0 else 0
        for r in self._slices:
            r.set_hbm_budget(share)

    # -- observability ------------------------------------------------

    def publish_metrics(self) -> None:
        from ..utils import metrics as m
        with self._mu:
            self._decay_locked()
            loads = list(self._load)
        for i, r in enumerate(self._slices):
            m.DEVICE_SLICE_RESIDENT_BYTES.labels(str(i)).set(
                r._arena.resident_bytes())
            m.DEVICE_SLICE_LOAD.labels(str(i)).set(round(loads[i], 3))

    def stats(self) -> dict:
        self.publish_metrics()
        with self._mu:
            loads = [round(v, 3) for v in self._load]
            placed = [0] * len(self._slices)
            for idx in self._placed.values():
                if 0 <= idx < len(placed):
                    placed[idx] += 1
            dead = self._dead_locked()
            out = {
                "slices": [
                    {"resident_bytes": r._arena.resident_bytes(),
                     "resident_lines": r._arena.resident_lines(),
                     "load": loads[i],
                     "placed_anchors": placed[i],
                     "quarantined": i in dead}
                    for i, r in enumerate(self._slices)],
                "places": self.places,
                "moves": self.moves,
                "whole_mesh_routes": self.whole_mesh_routes,
                "failovers": self.failovers,
                "drained": self.drained,
                "colocation_pins": self.colocation_pins,
                "join_pairs": len(self._pair_aff),
                "migrations": self.migrations,
                "migration_ms": round(self.migration_ms, 3),
                "last_migration_ms": round(self.last_migration_ms, 3),
                "migration_failures": self.migration_failures,
                "adoptions": self.adoptions,
            }
        return out
