"""Device hash join, sort and window fragments — the plan-IR kernels.

The operator boundary the reference never crosses (copr/plan_ir.py):
these kernels serve the three fragment kinds the tipb vocabulary
omits, over the same single-device substrate the selection/topn
kernels use (padded HBM-resident planes, pow-bucketed compile
classes, hoisted constants).

JOIN — an inner equi-join between two co-located region feeds:

- The BUILD side rides the dictionary discipline of the PR 2 sparse-
  slot kernels: the key column uploads once per (anchor, data version)
  and ONE build dispatch (``join_build``) sorts it into a device-
  resident dictionary — ``(sorted keys, permutation, valid-prefix
  sums)`` — with NULL/padded rows sentineled to ``int64.max`` and
  ordered valid-first within equal keys, so duplicate and
  sentinel-colliding keys resolve EXACTLY (the valid-prefix sum bounds
  each probe run to its valid entries).  The structure is cached in
  HBM across requests and dies with the anchor (``drop_anchor`` rides
  the runner's ``drop_feed`` teardown path).

- The PROBE side fuses the probe fragment's selection predicates into
  the probe dispatch (``join_probe``): predicate RPNs evaluate over
  the uploaded probe planes with constants hoisted into traced scalar
  parameters (device/selection.split_params — the same const-blind
  compile-class discipline), the surviving rows binary-search the
  build dictionary, and pair counts prefix-sum into a capacity-
  bucketed emission — ONE dispatch total.

- The output is LATE-MATERIALIZED (Abadi et al.): row-index PAIRS
  (int32), never joined rows.  D2H ships 8 bytes/pair; the host
  gathers only the columns the parent operator demands, from the
  columnar snapshots already resident host-side.  An undersized pair
  capacity is detected by the on-device total and re-dispatched at
  the EXACT pow2 bucket — never a truncated result — and the observed
  multiplicity feeds an EWMA that sizes the next request's bucket.

SORT — the permutation, not the rows: the transformed sort keys
(plan_ir.sort_key_i64/f64, shared with the host twin so results are
bit-identical) upload, one dispatch composes stable argsorts (padding
pushed strictly last by a leading pad key), and 4·n bytes of
permutation cross D2H; the host ``take``s the resident batch.

WINDOW — shifted segmented scans over the (partition, order)-sorted
view: segment ids from boundary flags, running count/sum as
``cumsum − segment-start offset``, row_number from the segment-start
index, lag/lead as segment-bounded shifted gathers.  REAL running
sums stay host (device cumsum is an associative scan whose float
rounding forks bit-parity; integer arithmetic is exact on both).

All three are SINGLE-DEVICE by construction (the join's build
dictionary and the sort's permutation are committed to one chip);
on a multi-chip node the plan executor runs them on the SlicePlacer
slice that co-locates both feeds (the co-location hint loop,
device/placement.py).  ``device::join_dispatch`` faults the probe
dispatch for failpoint-driven per-fragment host degrade.
"""

from __future__ import annotations

import threading
import weakref
from collections import OrderedDict
from typing import Optional, Sequence

import numpy as np

import jax
import jax.numpy as jnp

from ..copr.plan_ir import WindowNode, eval_order_keys
from ..datatype import EvalType
from ..expr import build_rpn
from ..expr.eval import eval_rpn
from ..utils.failpoint import fail_point

_I64 = np.iinfo(np.int64)

# build/probe cache bounds: entries are per-(anchor, version, columns)
# device planes; the LRU keeps reruns warm while churn stays bounded
_MAX_ENTRIES = 64
_DEFAULT_CACHE_BYTES = 1 << 28

_DEVICE_KEY_ETS = (EvalType.INT,)


class JoinDeviceUnavailable(Exception):
    """The device join cannot serve this fragment (failpoint, shape
    outside the envelope at dispatch time) — the plan executor degrades
    the FRAGMENT to the host join, nothing else."""


from .selection import _next_pow2  # noqa: E402 — shared pow2 bucketing


def join_supported(probe_scan, probe_conds, left_key: int,
                   build_scan, right_key: int) -> bool:
    """Static device-join envelope: ascending table scans, signed-INT
    (or pk-handle) keys, device-safe probe predicates.  The plan
    executor checks this BEFORE recording co-location affinity, so
    join pairs that can never be device-served don't earn score-blind
    placement pins."""
    from .runner import _rpn_device_safe
    from ..copr.dag import TableScanDesc
    for scan, key in ((probe_scan, left_key), (build_scan, right_key)):
        if not isinstance(scan, TableScanDesc) or scan.desc:
            return False
        if key >= len(scan.columns):
            return False
        info = scan.columns[key]
        if not info.is_pk_handle and (
                info.field_type.eval_type not in _DEVICE_KEY_ETS or
                info.field_type.is_unsigned):
            return False
    scan_ets = [c.field_type.eval_type for c in probe_scan.columns]
    for cond in probe_conds:
        if not _rpn_device_safe(build_rpn(cond), scan_ets):
            return False
    return True


class DeviceJoiner:
    """Join/sort/window kernel owner for ONE single-device runner."""

    MULT_ALPHA = 0.3

    def __init__(self, runner, cache_bytes: int = _DEFAULT_CACHE_BYTES):
        self._runner = runner
        self._mu = threading.Lock()
        self._cache: OrderedDict = OrderedDict()
        self._cache_bytes = 0
        self._cache_budget = cache_bytes
        # id(anchor) → weakref: a dead anchor's entries are pruned at
        # finalization, so a NEW object reusing the id can never be
        # served another snapshot's build dictionary (entries are
        # keyed by id, not by the object — the arena's weak-keying
        # discipline applied here)
        self._anchor_refs: dict = {}
        self._kernels: dict = {}
        # observed pairs-per-probe-row EWMA keyed by (probe table,
        # build table): sizes the emission capacity bucket
        self._mult: dict = {}
        # counters (under _mu)
        self.device_joins = 0
        self.overflow_redispatches = 0
        self.build_cache_hits = 0
        self.build_cache_builds = 0
        self.sorts = 0
        self.windows = 0

    # ------------------------------------------------------------ cache

    def _cache_get(self, key):
        with self._mu:
            ent = self._cache.get(key)
            if ent is not None:
                self._cache.move_to_end(key)
            return ent

    def _cache_put(self, key, ent, anchor=None) -> None:
        with self._mu:
            old = self._cache.pop(key, None)
            if old is not None:
                self._cache_bytes -= old["nbytes"]
            self._cache[key] = ent
            self._cache_bytes += ent["nbytes"]
            while len(self._cache) > _MAX_ENTRIES or \
                    (self._cache_bytes > self._cache_budget and
                     len(self._cache) > 1):
                _k, dead = self._cache.popitem(last=False)
                self._cache_bytes -= dead["nbytes"]
            if anchor is not None and key[1] not in self._anchor_refs:
                aid = key[1]
                try:
                    self._anchor_refs[aid] = weakref.ref(
                        anchor, lambda _r, a=aid: self._drop_id(a))
                except TypeError:
                    pass        # unweakreffable anchors keep LRU bounds

    def _drop_id(self, aid: int) -> None:
        with self._mu:
            self._anchor_refs.pop(aid, None)
            for k in [k for k in self._cache if k[1] == aid]:
                ent = self._cache.pop(k)
                self._cache_bytes -= ent["nbytes"]

    def set_budget(self, nbytes: int) -> None:
        """Bound the join cache's device-resident bytes and enforce
        NOW.  Wired from ``DeviceRunner.set_hbm_budget`` (the joiner
        takes a fixed slice of the node budget) so the operator's HBM
        cap bounds join state too, not only the feed arena."""
        with self._mu:
            self._cache_budget = max(1 << 20, int(nbytes))
            while self._cache_bytes > self._cache_budget and \
                    len(self._cache) > 0:
                _k, dead = self._cache.popitem(last=False)
                self._cache_bytes -= dead["nbytes"]

    def resident_bytes(self) -> int:
        with self._mu:
            return self._cache_bytes

    def drop_all(self) -> int:
        """Retire EVERY cached build dictionary — the quarantine-drain
        teardown (placement.py ``_on_slice_trip``): a condemned slice's
        joiner entries would otherwise die only by anchor weakref while
        the budget still accounts their HBM on a chip nothing will
        dispatch to again."""
        with self._mu:
            freed = self._cache_bytes
            self._cache.clear()
            self._anchor_refs.clear()
            self._cache_bytes = 0
        return freed

    def drop_anchor(self, anchor) -> int:
        """Feed teardown hook (runner.drop_feed): the anchor's build/
        probe planes die with its feed — stale-epoch join state must
        not survive a region lifecycle event."""
        freed = 0
        with self._mu:
            self._anchor_refs.pop(id(anchor), None)
            for k in [k for k in self._cache if k[1] == id(anchor)]:
                ent = self._cache.pop(k)
                self._cache_bytes -= ent["nbytes"]
                freed += ent["nbytes"]
        return freed

    @staticmethod
    def _anchor_version(storage):
        lineage = getattr(storage, "feed_lineage", None)
        anchor = storage if lineage is None else lineage
        v = getattr(storage, "feed_version", None)
        if lineage is not None and v is None:
            v = lineage.version
        return anchor, v

    # ---------------------------------------------------------- kernels

    def _kern(self, key, build):
        fn = self._kernels.get(key)
        if fn is None:
            fn = self._kernels[key] = build()
        return fn

    def _pad(self, n: int) -> int:
        return self._runner._pad_rows(max(1, n))

    @staticmethod
    def _pad_plane(arr: np.ndarray, n_pad: int):
        if len(arr) == n_pad:
            return jnp.asarray(np.ascontiguousarray(arr))
        p = np.zeros(n_pad, dtype=arr.dtype)
        p[:len(arr)] = arr
        return jnp.asarray(p)

    def _build_kernel(self, n_pad: int):
        def build():
            def fn(n_scalar, keys, valid):
                iota = jnp.arange(n_pad, dtype=jnp.int64)
                sv = valid & (iota < n_scalar)
                skey = jnp.where(sv, keys, _I64.max)
                # valid-first within equal keys: stable argsort
                # composition (the sentinel-collision exactness trick)
                perm0 = jnp.argsort(~sv)
                perm = perm0[jnp.argsort(skey[perm0])]
                sk = skey[perm]
                svs = sv[perm]
                prefix = jnp.concatenate(
                    [jnp.zeros(1, jnp.int64),
                     jnp.cumsum(svs.astype(jnp.int64))])
                return sk, perm.astype(jnp.int32), prefix
            return jax.jit(fn)
        return self._kern(("join_build", n_pad), build)

    def _probe_kernel(self, np_probe: int, np_build: int, k_cap: int,
                      rpns, null_like_sig, n_params: int):
        def build():
            def fn(n_scalar, sk, perm, prefix, pkeys, pvalid, *args):
                params = args[:n_params]
                flat = args[n_params:]
                iota = jnp.arange(np_probe, dtype=jnp.int64)
                rowmask = iota < n_scalar
                pmask = pvalid & rowmask
                if rpns:
                    pairs = []
                    fi = 0
                    while fi < len(flat):
                        pairs.append((flat[fi], flat[fi + 1]))
                        fi += 2
                    one = jnp.ones((), jnp.bool_)
                    for p in params:
                        pairs.append((p, one))
                    for rpn in rpns:
                        v, ok = eval_rpn(rpn, pairs, np_probe, jnp)
                        pmask = pmask & ok & (v != 0)
                lo = jnp.searchsorted(sk, pkeys, side="left")
                hi = jnp.searchsorted(sk, pkeys, side="right")
                cntv = prefix[hi] - prefix[lo]
                cnt = jnp.where(pmask, cntv, 0)
                csum = jnp.cumsum(cnt)
                total = csum[-1]
                j = jnp.arange(k_cap, dtype=jnp.int64)
                probe_of = jnp.clip(
                    jnp.searchsorted(csum, j, side="right"),
                    0, np_probe - 1)
                base = csum[probe_of] - cnt[probe_of]
                within = j - base
                bpos = jnp.clip(lo[probe_of] + within, 0, np_build - 1)
                bidx = perm[bpos]
                ok_pair = j < total
                pi = jnp.where(ok_pair, probe_of, -1).astype(jnp.int32)
                bi = jnp.where(ok_pair, bidx, -1).astype(jnp.int32)
                return pi, bi, total
            return jax.jit(fn)
        return self._kern(("join_probe", np_probe, np_build, k_cap,
                           null_like_sig, n_params), build)

    # ------------------------------------------------------------- join

    def _host_key_column(self, scan, ranges, storage, offset: int):
        """One-column scan → (values int64, validity) at scan-output
        positions (the alive mask and range slicing applied by the
        snapshot, exactly like the full scan)."""
        info = scan.columns[offset]
        sub = type(scan)(scan.table_id, (info,))
        col = storage.scan_columns(sub, ranges).columns[0]
        return np.asarray(col.values, dtype=np.int64), \
            np.asarray(col.validity, dtype=np.bool_)

    def _probe_planes(self, scan, ranges, storage, used: list):
        batch = storage.scan_columns(
            type(scan)(scan.table_id,
                       tuple(scan.columns[i] for i in used)), ranges)
        return batch

    def supports_join(self, probe_scan, probe_conds, left_key: int,
                      build_scan, right_key: int) -> bool:
        return join_supported(probe_scan, probe_conds, left_key,
                              build_scan, right_key)

    def join(self, probe_scan, probe_ranges, probe_storage, probe_conds,
             left_key: int, build_scan, build_ranges, build_storage,
             right_key: int) -> Optional[tuple]:
        """→ ``(probe_idx, build_idx)`` numpy arrays (scan-output
        positions, probe-major order), or None when the fragment is
        outside the device envelope.  Raises on device faults — the
        plan executor owns the per-fragment host degrade."""
        from ..utils import tracker
        if not self.supports_join(probe_scan, probe_conds, left_key,
                                  build_scan, right_key):
            return None
        # ---- build side: device-resident sorted dictionary ----
        banchor, bver = self._anchor_version(build_storage)
        bkey = ("build", id(banchor), bver, build_scan.columns[
            right_key].col_id, tuple(build_ranges))
        ent = self._cache_get(bkey)
        if ent is None:
            with tracker.phase("join_build"):
                vals, valid = self._host_key_column(
                    build_scan, build_ranges, build_storage, right_key)
                nb = len(vals)
                nb_pad = self._pad(nb)
                kfn = self._build_kernel(nb_pad)
                with self._runner._dispatch_phase(
                        "join_build", key=("join_build", nb_pad)):
                    sk, perm, prefix = kfn(
                        jnp.asarray(nb, jnp.int64),
                        self._pad_plane(vals, nb_pad),
                        self._pad_plane(valid, nb_pad))
                ent = {"sk": sk, "perm": perm, "prefix": prefix,
                       "n": nb, "n_pad": nb_pad,
                       "nbytes": int(sk.nbytes + perm.nbytes +
                                     prefix.nbytes)}
            self._cache_put(bkey, ent, anchor=banchor)
            with self._mu:
                self.build_cache_builds += 1
        else:
            with self._mu:
                self.build_cache_hits += 1
        # ---- probe side: key + fused predicate planes ----
        rpns = [build_rpn(c) for c in probe_conds]
        from .runner import _remap_rpn, _rpn_col_indices
        used = sorted({i for r in rpns
                       for i in _rpn_col_indices(r)})
        panchor, pver = self._anchor_version(probe_storage)
        pkey_id = probe_scan.columns[left_key].col_id
        pkey_cache = ("probe", id(panchor), pver, pkey_id,
                      tuple(probe_scan.columns[i].col_id for i in used),
                      tuple(probe_ranges))
        pent = self._cache_get(pkey_cache)
        if pent is None:
            kvals, kvalid = self._host_key_column(
                probe_scan, probe_ranges, probe_storage, left_key)
            npr = len(kvals)
            np_pad = self._pad(npr)
            planes = []
            nbytes = 0
            if used:
                batch = self._probe_planes(probe_scan, probe_ranges,
                                           probe_storage, used)
                for c in batch.columns:
                    v = self._pad_plane(
                        np.ascontiguousarray(c.values), np_pad)
                    m = self._pad_plane(
                        np.ascontiguousarray(c.validity), np_pad)
                    planes.extend((v, m))
                    nbytes += int(v.nbytes + m.nbytes)
            kv = self._pad_plane(kvals, np_pad)
            km = self._pad_plane(kvalid, np_pad)
            nbytes += int(kv.nbytes + km.nbytes)
            pent = {"keys": kv, "valid": km, "planes": tuple(planes),
                    "n": npr, "n_pad": np_pad, "nbytes": nbytes}
            self._cache_put(pkey_cache, pent, anchor=panchor)
        # hoisted predicate constants → traced scalar params (compile
        # classes stay const-blind, selection.py discipline)
        from . import selection as selmod
        remapped = [_remap_rpn(r, {old: new
                               for new, old in enumerate(used)})
                    for r in rpns]
        param_rpns, param_vals, param_dts = selmod.split_params(
            remapped, len(used))
        # ---- probe dispatch (fused selection + dictionary probe) ----
        if fail_point("device::join_dispatch") is not None:
            raise JoinDeviceUnavailable("device::join_dispatch")
        tkey = (probe_scan.table_id, build_scan.table_id)
        with self._mu:
            mult = self._mult.get(tkey, 1.0)
        k_cap = _next_pow2(int(max(
            64, min(pent["n"] * max(1.0, mult) * 1.5 + 64, 1 << 27))))
        rpn_sig = (tuple(r.fingerprint() for r in param_rpns),
                   param_dts)
        total = None
        for attempt in range(3):
            kkey = ("join_probe", pent["n_pad"], ent["n_pad"], k_cap,
                    rpn_sig, len(param_vals))
            kfn = self._probe_kernel(pent["n_pad"], ent["n_pad"], k_cap,
                                     param_rpns, rpn_sig,
                                     len(param_vals))
            with tracker.phase("join_probe"):
                with self._runner._dispatch_phase("join_probe",
                                                  key=kkey):
                    pi, bi, tot = kfn(
                        jnp.asarray(pent["n"], jnp.int64),
                        ent["sk"], ent["perm"], ent["prefix"],
                        pent["keys"], pent["valid"],
                        *[jnp.asarray(v, dt) for v, dt in
                          zip(param_vals, param_dts)],
                        *pent["planes"])
                total = int(tot)
                if total <= k_cap:
                    pi = np.asarray(pi)
                    bi = np.asarray(bi)
                    break
            # capacity overflow: the on-device total is exact — one
            # re-dispatch at the exact pow2 bucket, never truncation
            k_cap = _next_pow2(max(64, total))
            with self._mu:
                self.overflow_redispatches += 1
            from ..utils import metrics as m
            m.DEVICE_JOIN_ROUTE_COUNTER.labels(
                "overflow_redispatch").inc()
        else:
            raise JoinDeviceUnavailable("pair capacity did not settle")
        with self._mu:
            self.device_joins += 1
            obs = total / max(1, pent["n"])
            self._mult[tkey] = obs if tkey not in self._mult else (
                self.MULT_ALPHA * obs +
                (1 - self.MULT_ALPHA) * self._mult[tkey])
            while len(self._mult) > 128:
                self._mult.pop(next(iter(self._mult)))
        pi = pi[:total].astype(np.int64)
        bi = bi[:total].astype(np.int64)
        return pi, bi

    # ------------------------------------------------------------- sort

    def sort_perm(self, keys: Sequence[np.ndarray], n: int) -> np.ndarray:
        """Stable composed argsort on device → host permutation (the
        sort fragment's ONLY D2H payload); padding rows are pushed
        strictly last by a leading pad key so ``perm[:n]`` is exact."""
        n_pad = self._pad(n)
        dts = tuple(str(np.asarray(k).dtype) for k in keys)

        def build():
            def fn(n_scalar, *ks):
                iota = jnp.arange(n_pad, dtype=jnp.int64)
                pad_key = (iota >= n_scalar).astype(jnp.int8)
                perm = jnp.arange(n_pad, dtype=jnp.int64)
                for k in list(ks)[::-1] + [pad_key]:
                    perm = perm[jnp.argsort(k[perm])]
                return perm.astype(jnp.int32)
            return jax.jit(fn)
        kfn = self._kern(("sort", n_pad, dts), build)
        with self._runner._dispatch_phase("sort_perm",
                                          key=("sort", n_pad, dts)):
            perm = kfn(jnp.asarray(n, jnp.int64),
                       *[self._pad_plane(np.asarray(k), n_pad)
                         for k in keys])
            out = np.asarray(perm)[:n].astype(np.int64)
        with self._mu:
            self.sorts += 1
        return out

    # ----------------------------------------------------------- window

    def window(self, batch, node: WindowNode):
        """Device window fragment over a host batch: keys/args upload,
        one dispatch sorts + segmented-scans, the host gathers the
        sorted batch by the returned permutation and appends the
        returned window columns.  → ColumnBatch, or None when a func/
        arg is outside the device envelope (REAL running sums stay
        host — associative-scan rounding would fork parity)."""
        from ..datatype import Column, ColumnBatch, FieldType
        from ..copr import plan_ir as pir
        n = batch.num_rows
        cols = [(c.values, c.validity) for c in batch.columns]
        funcs = []
        for f in node.funcs:
            if f.kind == "row_number":
                funcs.append((f.kind, None, None, 0))
                continue
            if f.kind not in ("count", "sum", "avg", "lag", "lead"):
                return None
            rpn = build_rpn(f.arg)
            if rpn.ret_type is not EvalType.INT and \
                    not (f.kind in ("lag", "lead", "count") and
                         rpn.ret_type is EvalType.REAL):
                return None
            v, ok = eval_rpn(rpn, cols, n, np)
            v = np.ascontiguousarray(np.broadcast_to(v, (n,)))
            ok = np.ascontiguousarray(np.broadcast_to(ok, (n,)))
            funcs.append((f.kind, v, ok, max(1, int(f.offset))))
        part_keys = pir.eval_order_keys(
            batch, tuple((e, False) for e in node.partition_by))
        order_keys = pir.eval_order_keys(batch, node.order_by)
        n_pad = self._pad(n)
        sig = (n_pad, len(part_keys),
               tuple(str(k.dtype) for k in part_keys + order_keys),
               tuple((f[0], None if f[1] is None else str(f[1].dtype),
                      f[3]) for f in funcs))

        def build():
            n_part = len(part_keys)
            n_order = len(order_keys)
            fsig = sig[3]

            def fn(n_scalar, *args):
                pks = args[:n_part]
                oks = args[n_part:n_part + n_order]
                rest = args[n_part + n_order:]
                iota = jnp.arange(n_pad, dtype=jnp.int64)
                pad_key = (iota >= n_scalar).astype(jnp.int8)
                perm = jnp.arange(n_pad, dtype=jnp.int64)
                for k in (list(pks) + list(oks))[::-1] + [pad_key]:
                    perm = perm[jnp.argsort(k[perm])]
                if n_part:
                    boundary = jnp.zeros(n_pad, jnp.bool_).at[0].set(True)
                    for k in pks:
                        sp = k[perm]
                        boundary = boundary.at[1:].set(
                            boundary[1:] | (sp[1:] != sp[:-1]))
                else:
                    boundary = jnp.zeros(n_pad, jnp.bool_).at[0].set(True)
                seg_id = jnp.cumsum(boundary.astype(jnp.int64))
                seg_start = jnp.searchsorted(seg_id, seg_id, side="left")
                seg_end = jnp.searchsorted(seg_id, seg_id, side="right")
                rn = iota - seg_start + 1
                outs = [perm.astype(jnp.int32)]
                ai = 0
                for kind, has_arg, off in [(f[0], f[1] is not None, f[2])
                                           for f in fsig]:
                    if kind == "row_number":
                        outs.append(rn)
                        continue
                    v = rest[ai][perm]
                    ok = rest[ai + 1][perm]
                    ai += 2
                    if kind in ("count", "sum", "avg"):
                        oki = ok.astype(jnp.int64)
                        ccs = jnp.cumsum(oki)
                        ccnt = ccs - (ccs[seg_start] - oki[seg_start])
                        if kind == "count":
                            outs.append(ccnt)
                            continue
                        vv = jnp.where(ok, v, 0).astype(jnp.int64)
                        cs = jnp.cumsum(vv)
                        csum = cs - (cs[seg_start] - vv[seg_start])
                        outs.append(csum)
                        outs.append(ccnt)
                    else:       # lag / lead
                        src = iota - off if kind == "lag" else iota + off
                        in_seg = (src >= seg_start) if kind == "lag" \
                            else (src < seg_end)
                        safe = jnp.clip(src, 0, n_pad - 1)
                        valid = in_seg & (src >= 0) & (src < n_pad) & \
                            ok[safe]
                        outs.append(jnp.where(valid, v[safe],
                                              jnp.zeros((), v.dtype)))
                        outs.append(valid)
                return tuple(outs)
            return jax.jit(fn)
        kfn = self._kern(("window",) + sig, build)
        args = [self._pad_plane(np.asarray(k), n_pad)
                for k in part_keys + order_keys]
        for kind, v, ok, _off in funcs:
            if v is not None:
                args.append(self._pad_plane(v, n_pad))
                args.append(self._pad_plane(ok, n_pad))
        with self._runner._dispatch_phase("window",
                                          key=("window",) + sig):
            outs = kfn(jnp.asarray(n, jnp.int64), *args)
            outs = [np.asarray(o)[:n] for o in outs]
        perm = outs[0].astype(np.int64)
        sorted_batch = batch.take(perm)
        out_cols = list(sorted_batch.columns)
        out_schema = list(sorted_batch.schema)
        ones = np.ones(n, np.bool_)
        oi = 1
        for (kind, v, ok, _off), f in zip(funcs, node.funcs):
            if kind == "row_number":
                outs_rn = outs[oi]
                oi += 1
                out_cols.append(Column(EvalType.INT,
                                       outs_rn.astype(np.int64),
                                       ones.copy()))
                out_schema.append(FieldType.long())
            elif kind == "count":
                ccnt = outs[oi]
                oi += 1
                out_cols.append(Column(EvalType.INT,
                                       ccnt.astype(np.int64),
                                       ones.copy()))
                out_schema.append(FieldType.long())
            elif kind in ("sum", "avg"):
                csum, ccnt = outs[oi], outs[oi + 1]
                oi += 2
                if kind == "sum":
                    out_cols.append(Column(EvalType.INT,
                                           csum.astype(np.int64),
                                           ccnt > 0))
                    out_schema.append(FieldType.long())
                else:
                    with np.errstate(divide="ignore", invalid="ignore"):
                        avg = csum.astype(np.float64) / ccnt
                    out_cols.append(Column(
                        EvalType.REAL, np.where(ccnt > 0, avg, 0.0),
                        ccnt > 0))
                    out_schema.append(FieldType.double())
            else:       # lag / lead
                vals, valid = outs[oi], outs[oi + 1]
                oi += 2
                et = EvalType.INT if vals.dtype.kind in "iu" \
                    else EvalType.REAL
                out_cols.append(Column(
                    et, vals.astype(np.int64)
                    if et is EvalType.INT else vals.astype(np.float64),
                    valid.astype(np.bool_)))
                out_schema.append(FieldType.long()
                                  if et is EvalType.INT
                                  else FieldType.double())
        with self._mu:
            self.windows += 1
        return ColumnBatch(out_schema, out_cols)

    # ------------------------------------------------------------ stats

    def stats(self) -> dict:
        with self._mu:
            return {
                "device_joins": self.device_joins,
                "build_cache_hits": self.build_cache_hits,
                "build_cache_builds": self.build_cache_builds,
                "overflow_redispatches": self.overflow_redispatches,
                "sorts": self.sorts,
                "windows": self.windows,
                "cache_entries": len(self._cache),
                "cache_bytes": self._cache_bytes,
                "multiplicity_ewma": {f"{k[0]}x{k[1]}": round(v, 3)
                                      for k, v in
                                      list(self._mult.items())[-8:]},
            }


