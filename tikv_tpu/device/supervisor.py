"""Device-state supervisor — bounded, lifecycle-correct, audited HBM.

PRs 4-5 made the device path fast by keeping derived state resident:
lineage-anchored HBM feeds, patch journals, compile-class caches.  This
module defends that state along three axes the reference treats as
table stakes for any cache layered over a log (the region-cache memory
engine + ARIES-style verify-derived-state-against-the-source recovery
discipline, PAPERS.md):

- **bounded** — :class:`FeedArena` owns every device-resident feed
  explicitly (no GC-timing-dependent ``WeakKeyDictionary`` reclamation):
  per-anchor byte accounting, a configurable HBM budget, and
  frequency+recency eviction that never evicts a line pinned by an
  in-flight deferred dispatch.  ``device::hbm_oom`` squeezes the
  effective budget for fault injection.

- **lifecycle-correct** — :class:`DeviceStateSupervisor` registers on
  the raftstore's CoprocessorHost: split/merge/epoch change
  (``on_region_changed``), snapshot apply (``on_data_replaced``) and
  peer destroy (``on_peer_destroyed``) eagerly invalidate the matching
  ``RegionColumnarCache`` lines, whose retirement callback drops the
  device feeds — stale-epoch state is torn down at the event, not aged
  out.  Role flips (``on_role_change``) instead drive the REPLICA-FEED
  state machine: a demoted leader's lines stay resident as follower
  feeds (same delta stream patches them; the resolved-ts gate serves
  them), and a leader gain over a warm feed is a PROMOTION — a
  scrub-digest re-verify, never a ``columnar_build``.

- **audited** — per-plane content digests recorded at feed build/patch
  time (position-weighted sums, odd weights so any single-element
  corruption is detected) are re-checked by a low-priority scrubber
  that re-hashes the resident planes ON DEVICE and compares.  On
  divergence the line is quarantined: its feeds drop, the next request
  for that region serves from the host backend, and the one after
  rebuilds a fresh feed from host truth.  ``device::feed_corrupt``
  injects the bit-flip the scrubber exists to catch.

- **failure-domain-aware** — :class:`SliceHealth` /
  :class:`SliceHealthBoard` treat each mesh slice (one chip) the way
  the store-level slow-score loop treats a store: dispatch faults,
  fetch faults, scrub quarantines and launch-latency outliers strike a
  per-slice score that decays on success; a slice crossing the trip
  threshold is QUARANTINED — placement stops scoring it, its sticky
  anchors drain onto healthy slices, whole-mesh sharded feeds rebuild
  on the largest healthy submesh (``parallel.mesh.healthy_submesh``)
  — and a half-open canary probe re-admits it with score decay, never
  a thundering re-pin.  ``device::slice_dead`` injects the persistent
  chip death this machinery exists to survive.

This module imports no jax at module scope — a Node without a device
runner can host the supervisor (it still drives columnar cache
lifecycle teardown) without paying the accelerator runtime import.
"""

from __future__ import annotations

import threading
import time
import weakref
from typing import Optional

import numpy as np

from ..raftstore.observer import Observer
from ..utils.failpoint import fail_point


# ----------------------------------------------------------- digests
#
# digest(plane, n) = sum_{i<n} (bits(plane[i]) * (2i+1)) mod 2^64.
# Odd weights make every single-position change detectable: a delta d
# at position i shifts the digest by d*(2i+1) mod 2^64, which is zero
# only when d = 0 (an odd factor cannot supply the 2^64's powers of
# two).  The same formula runs host-side (numpy, recorded at upload
# from the host truth) and device-side (the runner's jitted scrub
# kernel, recomputed after in-place patches and during scrub passes).


def host_plane_digest(arr: np.ndarray, n: int) -> int:
    """Host reference digest over the live prefix of one feed plane."""
    a = np.ascontiguousarray(arr[:n])
    if a.dtype == np.bool_:
        u = a.astype(np.uint64)
    else:
        u = a.view(np.dtype(f"u{a.dtype.itemsize}")).astype(np.uint64)
    idx = np.arange(n, dtype=np.uint64)
    with np.errstate(over="ignore"):
        return int((u * (2 * idx + 1)).sum(dtype=np.uint64))


def _bucket_nbytes(bucket: dict) -> int:
    """Device bytes held by one anchor's cache bucket: feed planes plus
    cached sparse-slot columns inside request memos."""
    total = 0
    for v in bucket.values():
        if not isinstance(v, dict):
            continue
        for a in v.get("flat", ()):
            total += int(getattr(a, "nbytes", 0))
        ss = v.get("sparse_slots")
        if ss is not None:
            total += int(getattr(ss[3], "nbytes", 0))
    return total


# ----------------------------------------------------- flight recorder

DEFAULT_FLIGHT_RECORDER_DEPTH = 256


class FlightRecorder:
    """Bounded ring of recent device launches — the black box an
    operator (or the /debug/trace surface) reads after a latency spike:
    per-launch wall, compile class, whether this launch was the class's
    FIRST (compile-vs-cached — the difference between a 0.6ms warm
    enqueue and a multi-second XLA compile), mesh shape, slice id for
    placement-routed launches, and arena-pinned bytes at dispatch.

    One recorder per PHYSICAL runner: placement slices and degraded
    submesh sub-runners share their parent's ring (their entries carry
    the slice id), so the box records the whole chip's launch history
    in order.  Entries feed the ``device_dispatch`` span's attributes,
    so a trace's launch carries its flight record inline.
    """

    CLASS_SEEN_MAX = 4096       # first-launch memory (LRU-bounded)

    def __init__(self, depth: int = DEFAULT_FLIGHT_RECORDER_DEPTH):
        from collections import OrderedDict, deque
        self._mu = threading.Lock()
        self._ring: "deque" = deque(maxlen=max(1, int(depth)))
        self._seen: "OrderedDict" = OrderedDict()
        self.launches = 0
        self.first_launches = 0
        self.faults = 0
        # cumulative measured launch wall: the resource-metering
        # attribution-coverage denominator (every _dispatch_phase wall
        # lands both here and in the RU recorder — charged wall /
        # recorded wall is the ≥95% acceptance figure)
        self.wall_s_total = 0.0

    def note(self, klass: str, key=None, wall_s: float = 0.0,
             mesh: str = "", slice_id=None, pinned_bytes: int = 0,
             ok: bool = True) -> dict:
        ck = (klass, key)
        with self._mu:
            first = ck not in self._seen
            self._seen[ck] = True
            self._seen.move_to_end(ck)
            while len(self._seen) > self.CLASS_SEEN_MAX:
                self._seen.popitem(last=False)
            self.launches += 1
            self.wall_s_total += wall_s
            if first:
                self.first_launches += 1
            if not ok:
                self.faults += 1
            entry = {"t_unix_s": round(time.time(), 6),
                     "launch_ms": round(wall_s * 1e3, 3),
                     "compile_class": klass,
                     "first_launch": first,
                     "mesh": mesh,
                     "slice": slice_id,
                     "pinned_bytes": int(pinned_bytes),
                     "ok": ok}
            self._ring.append(entry)
        return entry

    def set_depth(self, depth: int) -> None:
        """Online-resize the ring, keeping the newest tail."""
        from collections import deque
        with self._mu:
            self._ring = deque(self._ring, maxlen=max(1, int(depth)))

    def items(self, limit: int = 0) -> list:
        with self._mu:
            out = list(self._ring)
        return out[-limit:] if limit > 0 else out

    def stats(self) -> dict:
        with self._mu:
            return {"depth": self._ring.maxlen,
                    "recorded": len(self._ring),
                    "launches": self.launches,
                    "first_launches": self.first_launches,
                    "faults": self.faults,
                    "wall_s_total": self.wall_s_total}


# ------------------------------------------------- slice failure domains
#
# The store-level control loop (utils/health.py SlowScore rise/decay +
# CircuitBreaker trip/half-open, pd/scheduler.py evict-slow-store) one
# level down: each mesh slice — one chip — is a failure domain.  The
# board is deliberately DUMB policy-wise: it scores, trips and gates
# probes; the consumers (SlicePlacer drain/exclusion, DeviceRunner's
# elastic mesh degrade) read ``quarantined_set()`` and act.

# strikes to quarantine.  1.0 per dispatch/fetch fault or scrub
# quarantine, 0.25 per launch-latency outlier; each clean fetch decays
# the score by 0.5 — a healthy slice absorbs isolated faults, a dead
# chip trips within three requests.
DEFAULT_TRIP_STRIKES = 3.0
# half-open probe cooldown after a trip (and after a failed probe)
DEFAULT_PROBE_COOLDOWN_S = 0.25

# live boards, for the tier-1 leak guard (tests/conftest.py): a test
# must not leave a slice quarantined behind for the next test to trip
# over.  WeakSet: boards die with their runners.
_LIVE_BOARDS: "weakref.WeakSet" = weakref.WeakSet()


def live_boards() -> list:
    """Snapshot of every live SliceHealthBoard (conftest leak guard)."""
    return list(_LIVE_BOARDS)


class SliceHealth:
    """Strike/recovery health score for ONE mesh slice.

    State machine (the trip/drain/probe cycle, README "Device failure
    domains"):

      healthy --(score >= trip)--> quarantined
      quarantined --(cooldown, one canary at a time)--> probing
      probing --success--> healthy (score decayed to trip-1, so the
                           placement penalty stays high and re-pinning
                           is gradual — never a thundering herd)
      probing --failure--> quarantined (cooldown restarts)

    Fault feeds: dispatch faults, fetch faults, scrub quarantines
    (weight 1.0) and launch-latency outliers (weight 0.25, only when
    the owner configures ``latency_outlier_s``).  Success decays the
    score by 0.5 — the SlowScore rise-fast/decay-slow discipline.
    """

    __slots__ = ("idx", "_mu", "score", "state", "trip_strikes",
                 "cooldown_s", "latency_outlier_s", "strikes", "trips",
                 "readmits", "refusals", "probe_failures",
                 "launched_quarantined", "_opened_at", "_probe_inflight")

    def __init__(self, idx: int,
                 trip_strikes: float = DEFAULT_TRIP_STRIKES,
                 cooldown_s: float = DEFAULT_PROBE_COOLDOWN_S,
                 latency_outlier_s: Optional[float] = None):
        self.idx = idx
        self._mu = threading.Lock()
        self.score = 0.0
        self.state = "healthy"          # healthy | quarantined
        self.trip_strikes = trip_strikes
        self.cooldown_s = cooldown_s
        self.latency_outlier_s = latency_outlier_s
        self.strikes: dict = {}
        self.trips = 0
        self.readmits = 0
        # dispatches REFUSED because the slice was quarantined (the
        # request degraded/rescued instead of launching on a dead chip)
        self.refusals = 0
        self.probe_failures = 0
        # dispatches that LAUNCHED while quarantined — the invariant
        # chaos asserts stays zero (check_no_quarantined_dispatch)
        self.launched_quarantined = 0
        self._opened_at = 0.0
        self._probe_inflight = False

    # -- fault/success feeds ------------------------------------------

    def note_fault(self, kind: str, weight: float = 1.0) -> bool:
        """One strike; → True when this strike TRIPPED the slice."""
        with self._mu:
            self.strikes[kind] = self.strikes.get(kind, 0) + 1
            self.score += weight
            return self._maybe_trip_locked()

    def trip(self, kind: str) -> bool:
        """Decisive quarantine (a targeted persistent chip death needs
        no three-strike deliberation); → True on the transition."""
        with self._mu:
            self.strikes[kind] = self.strikes.get(kind, 0) + 1
            self.score = max(self.score, self.trip_strikes)
            return self._maybe_trip_locked()

    def _maybe_trip_locked(self) -> bool:
        if self.state != "healthy" or self.score < self.trip_strikes:
            return False
        self.state = "quarantined"
        self.trips += 1
        self._opened_at = time.monotonic()
        self._probe_inflight = False
        return True

    def note_ok(self, latency_s: Optional[float] = None) -> bool:
        """A served request: decay the score — or strike fractionally
        when the launch latency is an outlier (the fail-slow feed).
        → True when the outlier strike TRIPPED the slice (the caller
        must fire the board's trip listeners, exactly as for
        note_fault — a latency-induced quarantine drains like any
        other)."""
        # a threshold of None OR <= 0 disables the latency feed (the
        # config default is 0.0 = off — cold compiles on slow
        # transports must never strike a healthy slice)
        if latency_s is not None and self.latency_outlier_s and \
                self.latency_outlier_s > 0 and \
                latency_s >= self.latency_outlier_s:
            return self.note_fault("latency", weight=0.25)
        with self._mu:
            if self.state == "healthy":
                self.score = max(0.0, self.score - 0.5)
        return False

    # -- half-open probing --------------------------------------------

    def quarantined(self) -> bool:
        return self.state == "quarantined"

    def try_probe(self) -> bool:
        """→ True when a canary probe may run NOW: quarantined, the
        cooldown elapsed, and no other probe is in flight (the
        CircuitBreaker half-open single-probe discipline)."""
        with self._mu:
            if self.state != "quarantined" or self._probe_inflight:
                return False
            if time.monotonic() - self._opened_at < self.cooldown_s:
                return False
            self._probe_inflight = True
            return True

    def probe_result(self, ok: bool) -> None:
        with self._mu:
            self._probe_inflight = False
            if self.state != "quarantined":
                return
            if ok:
                self.state = "healthy"
                # decay, don't reset: the slice re-enters scoring with
                # a high (but sub-trip) score, so placement re-pins
                # anchors gradually and one fresh fault re-trips
                self.score = max(0.0, self.trip_strikes - 1.0)
                self.readmits += 1
            else:
                self.probe_failures += 1
                self._opened_at = time.monotonic()

    def penalty(self) -> float:
        """Normalized score for the placement blend (0 healthy …
        ~1 at the trip threshold)."""
        with self._mu:
            return self.score / self.trip_strikes \
                if self.trip_strikes > 0 else 0.0

    def reset(self) -> None:
        with self._mu:
            self.score = 0.0
            self.state = "healthy"
            self._probe_inflight = False

    def stats(self) -> dict:
        with self._mu:
            return {"slice": self.idx,
                    "score": round(self.score, 3),
                    "state": self.state,
                    "strikes": dict(self.strikes),
                    "trips": self.trips,
                    "readmits": self.readmits,
                    "refusals": self.refusals,
                    "probe_failures": self.probe_failures,
                    "probe_inflight": self._probe_inflight,
                    "launched_quarantined": self.launched_quarantined}


class SliceHealthBoard:
    """Per-slice health for one device mesh.

    Owned by the mesh's whole-mesh :class:`~..runner.DeviceRunner`;
    shared with its :class:`~.placement.SlicePlacer` (the slices are
    the same chips) and struck by degraded submesh runners through
    their ``_failover_parent`` back-pointer, so every observation about
    a chip lands on ONE score wherever it was made.
    """

    def __init__(self, n_slices: int,
                 trip_strikes: float = DEFAULT_TRIP_STRIKES,
                 cooldown_s: float = DEFAULT_PROBE_COOLDOWN_S,
                 latency_outlier_s: Optional[float] = None):
        self._slices = [SliceHealth(i, trip_strikes=trip_strikes,
                                    cooldown_s=cooldown_s,
                                    latency_outlier_s=latency_outlier_s)
                        for i in range(n_slices)]
        self._mu = threading.Lock()
        self._listeners: list = []
        _LIVE_BOARDS.add(self)

    def __len__(self) -> int:
        return len(self._slices)

    def slice(self, i: int) -> SliceHealth:
        return self._slices[i]

    def add_trip_listener(self, fn) -> None:
        """``fn(idx, reason)`` fires on every healthy→quarantined
        transition, OUTSIDE any board/slice lock (listeners take their
        own — the placer drains under its placement lock)."""
        with self._mu:
            self._listeners.append(fn)

    def _fire_trip(self, idx: int, reason: str) -> None:
        from ..utils.metrics import DEVICE_FAILOVER_COUNTER
        DEVICE_FAILOVER_COUNTER.labels("quarantine").inc()
        with self._mu:
            listeners = list(self._listeners)
        for fn in listeners:
            try:
                fn(idx, reason)
            except Exception:   # noqa: BLE001 — a listener must not
                pass            # poison the scoring path

    def note_fault(self, idx: int, kind: str,
                   weight: float = 1.0) -> None:
        if 0 <= idx < len(self._slices) and \
                self._slices[idx].note_fault(kind, weight=weight):
            self._fire_trip(idx, kind)

    def trip(self, idx: int, reason: str) -> None:
        if 0 <= idx < len(self._slices) and \
                self._slices[idx].trip(reason):
            self._fire_trip(idx, reason)

    def quarantined_set(self) -> frozenset:
        return frozenset(i for i, s in enumerate(self._slices)
                         if s.quarantined())

    def penalty(self, i: int) -> float:
        return self._slices[i].penalty()

    def maybe_probe(self, canary) -> int:
        """Run ``canary(idx) -> bool`` for every quarantined slice
        whose cooldown elapsed (one probe per slice at a time); feed
        the results back.  → probes run.  Cheap when nothing is due —
        the callers (placement routing, mesh-degrade routing, the
        supervisor's scrub loop) invoke it opportunistically."""
        from ..utils.metrics import DEVICE_FAILOVER_COUNTER
        ran = 0
        for s in self._slices:
            if not s.try_probe():
                continue
            ran += 1
            try:
                ok = bool(canary(s.idx))
            except Exception:   # noqa: BLE001 — a crashed canary is a
                ok = False      # failed probe, not a crashed caller
            s.probe_result(ok)
            if ok:
                DEVICE_FAILOVER_COUNTER.labels("readmit").inc()
            else:
                DEVICE_FAILOVER_COUNTER.labels("probe_fail").inc()
        return ran

    def reset(self) -> None:
        for s in self._slices:
            s.reset()

    def publish_metrics(self) -> None:
        from ..utils.metrics import DEVICE_SLICE_HEALTH
        for s in self._slices:
            DEVICE_SLICE_HEALTH.labels(str(s.idx)).set(
                round(s.penalty(), 4))

    def stats(self) -> list:
        self.publish_metrics()
        return [s.stats() for s in self._slices]


class _ArenaEntry:
    __slots__ = ("ref", "bucket", "nbytes", "hits", "tick", "pins",
                 "gen", "owner_tag", "owner_region", "res_t0")

    def __init__(self, ref, gen: int):
        self.ref = ref
        self.bucket: dict = {}
        self.nbytes = 0
        self.hits = 0
        self.tick = 0
        self.pins = 0
        # entry generation: pin tokens embed it so an unpin issued
        # against a dropped-and-rebuilt entry (same anchor, new entry)
        # can never strip a different dispatch's pin
        self.gen = gen
        # RU residency attribution: the (resource_group, source) tag
        # that last touched this anchor under a metering context owns
        # its bytes-resident-seconds from res_t0 forward
        self.owner_tag = None
        self.owner_region = None
        self.res_t0 = time.monotonic()


class FeedArena:
    """Explicitly-owned HBM feed cache with budget + eviction.

    One entry per feed anchor (a FeedLineage for delta-maintained
    lines, the snapshot itself otherwise).  The primary reclamation
    path is EXPLICIT: region cache line teardown calls the runner's
    ``drop_feed``.  A weakref finalizer is kept only as a backstop for
    anchors that never see a lifecycle event (ad-hoc test snapshots) —
    accounting never depends on it.

    Eviction: least-frequently-used first, least-recently-used among
    ties, skipping pinned entries (an in-flight deferred dispatch has
    device buffers in use; evicting its line would free HBM the
    accounting still owes).  ``budget_bytes <= 0`` disables the budget
    (accounting and gauges stay live).
    """

    def __init__(self, budget_bytes: int = 0):
        self._entries: dict[int, _ArenaEntry] = {}
        self._mu = threading.RLock()
        self._tick = 0
        self._gen = 0
        # residency charges settled under _mu, flushed to the metering
        # recorder OUTSIDE it: (owner_tag, owner_region, byte_seconds)
        self._pending_res: list = []
        # window-roll settlement: the recorder sweeps registered arenas
        # so an idle feed still pays rent every metering window
        from .. import resource_metering as _rm
        _rm.GLOBAL_RECORDER.register_residency_source(self)
        # running resident-byte total, maintained at admit/drop/evict:
        # the per-request paths (admit, unpin) must not pay an
        # O(anchors) sum at the thousands-of-regions scale
        self._resident = 0
        # running pinned-byte total, same discipline: the flight
        # recorder stamps it on EVERY kernel launch, so it must be
        # O(1), not an O(entries) sum under the arena mutex
        self._pinned = 0
        self.budget_bytes = int(budget_bytes)
        self.evictions = 0
        self.rejections = 0
        self.drops = 0

    # -- bucket access ------------------------------------------------

    def bucket(self, anchor, create: bool = True) -> Optional[dict]:
        """The per-anchor cache dict (feeds + request memos), or None
        when the anchor cannot be tracked (not weak-referenceable)."""
        from .. import resource_metering as _rm
        ctx = _rm.current_context()
        key = id(anchor)
        with self._mu:
            ent = self._entries.get(key)
            if ent is not None:
                self._tick += 1
                ent.hits += 1
                ent.tick = self._tick
                self._own_locked(ent, ctx, anchor)
                return ent.bucket
            if not create:
                return None
            try:
                ref = weakref.ref(anchor,
                                  lambda _r, k=key: self._gc_drop(k))
            except TypeError:
                return None
            self._tick += 1
            self._gen += 1
            ent = _ArenaEntry(ref, self._gen)
            ent.hits = 1
            ent.tick = self._tick
            self._own_locked(ent, ctx, anchor)
            self._entries[key] = ent
            return ent.bucket

    # -- residency metering -------------------------------------------

    def _own_locked(self, ent: _ArenaEntry, ctx, anchor) -> None:
        """A tagged toucher takes ownership of the anchor's residency;
        accrual up to now settles to the PREVIOUS owner first (the
        tag that parked the bytes pays for the parking)."""
        if ctx is None or ctx.tag is None:
            return
        if ent.owner_tag != ctx.tag:
            self._settle_entry_locked(ent, time.monotonic())
            ent.owner_tag = ctx.tag
        region = ctx.region if ctx.region is not None else \
            getattr(anchor, "region_hint", None)
        if region is not None:
            ent.owner_region = region

    def _settle_entry_locked(self, ent: _ArenaEntry,
                             now: float) -> None:
        dt = now - ent.res_t0
        ent.res_t0 = now
        if dt > 0 and ent.nbytes > 0:
            self._pending_res.append(
                (ent.owner_tag, ent.owner_region, ent.nbytes * dt))

    def _flush_residency(self) -> None:
        """Charge settled byte-seconds OUTSIDE the arena mutex."""
        with self._mu:
            if not self._pending_res:
                return
            pending, self._pending_res = self._pending_res, []
        from .. import resource_metering as _rm
        for tag, region, byte_s in pending:
            _rm.GLOBAL_RECORDER.charge(
                "arena::residency", byte_seconds=byte_s,
                tag=tag if tag is not None else _rm.UNTAGGED,
                region=region)

    def settle_residency(self, recorder=None) -> None:
        """Settle every entry's accrued bytes-resident-seconds up to
        now — the metering window roll's sweep (``recorder`` is the
        caller's handle, unused: charges flow through the global
        recorder the arena registered with)."""
        now = time.monotonic()
        with self._mu:
            for ent in self._entries.values():
                self._settle_entry_locked(ent, now)
        self._flush_residency()

    def _gc_drop(self, key: int) -> None:
        # backstop only: anchors with lifecycle owners are dropped
        # explicitly long before their refcount hits zero
        with self._mu:
            ent = self._entries.pop(key, None)
            if ent is not None:
                self._settle_entry_locked(ent, time.monotonic())
                self._resident -= ent.nbytes
                if ent.pins > 0:
                    self._pinned = max(0, self._pinned - ent.nbytes)
        # deliberately NO residency flush here: this is a weakref GC
        # callback and may fire on a thread already inside the
        # metering recorder's lock (an allocation-triggered collection
        # mid-charge) — the settlement stays queued in _pending_res
        # and the next pin/drop/window-roll flush charges it
        self._publish()

    # -- pinning ------------------------------------------------------

    def pin(self, anchor):
        """Pin the anchor's CURRENT entry; returns an opaque token for
        :meth:`unpin`, or None when the anchor is not resident.  The
        token embeds the entry generation: if the entry is dropped and
        rebuilt before the unpin arrives, the stale token is a no-op
        instead of stripping the new dispatch's pin."""
        with self._mu:
            ent = self._entries.get(id(anchor))
            if ent is None:
                return None
            # pin-time sampling: settle accrued residency at every
            # dispatch pin so a hot feed's rent lands in the same
            # metering window its traffic does
            self._settle_entry_locked(ent, time.monotonic())
            if ent.pins == 0:
                self._pinned += ent.nbytes
            ent.pins += 1
            token = (id(anchor), ent.gen)
        self._flush_residency()
        return token

    def unpin(self, token) -> None:
        if token is None:
            return
        key, gen = token
        with self._mu:
            ent = self._entries.get(key)
            if ent is not None and ent.gen == gen and ent.pins > 0:
                ent.pins -= 1
                if ent.pins == 0:
                    self._pinned = max(0, self._pinned - ent.nbytes)
            # a pin release may be what the budget was waiting for
            # (a pinned entry admitted over the cap): sweep now
            if self.budget_bytes > 0:
                self._evict_until_locked(self.budget_bytes)
        self._flush_residency()
        self._publish()

    # -- admission / eviction ----------------------------------------

    def admit(self, anchor) -> bool:
        """Re-account ``anchor``'s bucket and enforce the budget,
        evicting other unpinned entries (lowest frequency, then oldest
        recency) until resident bytes fit.  Returns False when the
        entry could not fit even alone — its bucket is dropped and the
        caller serves the request from its transient feed, uncached."""
        key = id(anchor)
        from ..utils.metrics import DEVICE_FEED_EVICTION_COUNTER
        with self._mu:
            ent = self._entries.get(key)
            if ent is None:
                return False
            fresh = _bucket_nbytes(ent.bucket)
            # settle at the OLD byte count before re-accounting: each
            # residency interval is charged at the bytes actually held
            self._settle_entry_locked(ent, time.monotonic())
            self._resident += fresh - ent.nbytes
            if ent.pins > 0:
                # re-accounting a pinned entry moves the pinned total
                # with it, or the pair of counters drifts apart
                self._pinned = max(0, self._pinned + fresh - ent.nbytes)
            ent.nbytes = fresh
            budget = self.budget_bytes
            fp = fail_point("device::hbm_oom")
            if fp is not None:
                try:
                    squeeze = int(getattr(fp, "value", None) or 0)
                except (TypeError, ValueError):
                    squeeze = 0
                budget = squeeze if budget <= 0 else min(budget, squeeze)
                # a fired squeeze always enforces: return(0) means "no
                # HBM at all", not "unlimited"
                budget = max(1, budget)
            admitted = True
            if budget > 0:
                self._evict_until_locked(budget, protect_key=key)
                if self._total_locked() > budget and ent.pins == 0:
                    # still over: either the entry exceeds the budget
                    # alone, or pinned in-flight lines hold the rest.
                    # The budget is a HARD cap on resident bytes, so
                    # the newcomer serves uncached either way (pinned
                    # space frees at fetch completion; the next access
                    # re-admits).  A PINNED newcomer is never popped —
                    # its HBM is in use by a launched kernel, so
                    # dropping the entry would only falsify the
                    # accounting (and strand the pin)
                    self._entries.pop(key, None)
                    self._resident -= ent.nbytes
                    self.rejections += 1
                    DEVICE_FEED_EVICTION_COUNTER.labels("reject").inc()
                    admitted = False
        self._flush_residency()
        self._publish()
        return admitted

    def _evict_until_locked(self, budget: int,
                            protect_key: Optional[int] = None) -> int:
        """Evict unpinned entries until resident bytes fit ``budget``.
        Caller holds ``_mu``.  Returns entries evicted.

        Victim order is lowest-frequency, then oldest-recency — unless
        multi-tenant resource control is on (resource_control.py), in
        which case the owning tag's standing is folded in FIRST: an
        entry whose tenant is OVER its HBM residency share (the
        ``arena::residency`` owners the metering records) evicts
        before any under-share tenant's entry, ranked by the owner's
        RU debt within each class — a background scanner's feeds die
        first and a latency tenant's hot set is protected up to its
        share.  Work-conserving by construction: the bias only
        engages under budget pressure, so an over-share tenant keeps
        using slack capacity until someone actually needs it."""
        from ..utils.metrics import DEVICE_FEED_EVICTION_COUNTER
        evicted = 0
        rc = tenant_bytes = standing = None
        if self._total_locked() > budget:
            from ..resource_control import GLOBAL_CONTROLLER
            from ..resource_metering import ResourceTagFactory as _rtf
            if GLOBAL_CONTROLLER.enabled:
                rc = GLOBAL_CONTROLLER
                tenant_bytes = {}
                for e in self._entries.values():
                    if e.nbytes > 0:
                        t = _rtf.tenant(e.owner_tag)
                        tenant_bytes[t] = \
                            tenant_bytes.get(t, 0) + e.nbytes
                # ONE controller-lock round trip per sweep: per-tenant
                # (byte limit, RU debt) snapshot — per-entry scoring
                # below is pure dict math under the arena mutex, and
                # only the victim's tenant needs bytes re-tallied
                standing = rc.hbm_standing(tenant_bytes, budget)
        evicted_by_tenant: dict = {}
        while self._total_locked() > budget:
            victim_key = victim = victim_rank = None
            for k, e in self._entries.items():
                if k == protect_key or e.pins > 0 or e.nbytes <= 0:
                    continue
                if standing is not None:
                    t = _rtf.tenant(e.owner_tag)
                    limit, debt = standing.get(t, (float("inf"), 0.0))
                    rank = (0 if tenant_bytes.get(t, 0) > limit
                            else 1, -debt, e.hits, e.tick)
                else:
                    rank = (e.hits, e.tick)
                if victim_rank is None or rank < victim_rank:
                    victim_key, victim, victim_rank = k, e, rank
            if victim is None:
                break
            self._settle_entry_locked(victim, time.monotonic())
            self._entries.pop(victim_key, None)
            self._resident -= victim.nbytes
            self.evictions += 1
            evicted += 1
            DEVICE_FEED_EVICTION_COUNTER.labels("budget").inc()
            if standing is not None:
                t = _rtf.tenant(victim.owner_tag)
                tenant_bytes[t] = max(
                    0, tenant_bytes.get(t, 0) - victim.nbytes)
                evicted_by_tenant[t] = \
                    evicted_by_tenant.get(t, 0) + 1
        if standing is not None and evicted:
            # one controller-lock round trip for the whole sweep's
            # eviction telemetry (mirrors the hbm_standing read side)
            rc.note_evictions(evicted_by_tenant)
            # the protection figure: under-share tenants' bytes still
            # resident after a sweep that evicted over-share state
            protected = sum(
                b for t, b in tenant_bytes.items()
                if b > 0 and b <= standing.get(
                    t, (float("inf"), 0.0))[0])
            rc.note_protected(protected)
        return evicted

    def enforce(self) -> int:
        """Eviction sweep against the CURRENT budget with no protected
        newcomer — the online budget-shrink path (set_hbm_budget).
        Returns entries evicted."""
        with self._mu:
            evicted = self._evict_until_locked(self.budget_bytes) \
                if self.budget_bytes > 0 else 0
        self._flush_residency()
        self._publish()
        return evicted

    def drop(self, anchor, reason: str = "drop") -> int:
        """Explicit teardown — the lifecycle/quarantine path.  Ignores
        pins (correctness teardown must win over budget bookkeeping;
        in-flight dispatches keep their own buffer references alive).
        Returns the bytes released from the accounting."""
        from ..utils.metrics import DEVICE_FEED_EVICTION_COUNTER
        with self._mu:
            ent = self._entries.pop(id(anchor), None)
            freed = ent.nbytes if ent is not None else 0
            if ent is not None:
                self._settle_entry_locked(ent, time.monotonic())
                self._resident -= ent.nbytes
                if ent.pins > 0:
                    self._pinned = max(0, self._pinned - ent.nbytes)
                self.drops += 1
                DEVICE_FEED_EVICTION_COUNTER.labels(reason).inc()
        self._flush_residency()
        self._publish()
        return freed

    def drop_all(self, reason: str = "drop") -> int:
        """Drop EVERY entry, pins included — the mesh-degrade and node
        teardown path: a feed sharded over a chip that just died (or a
        runner being torn down) holds nothing worth protecting, and
        in-flight dispatches keep their own buffer references alive.
        Stale pin tokens no-op at unpin (entry gone).  → bytes freed."""
        from ..utils.metrics import DEVICE_FEED_EVICTION_COUNTER
        with self._mu:
            now = time.monotonic()
            for ent in self._entries.values():
                self._settle_entry_locked(ent, now)
            freed = self._resident
            n = len(self._entries)
            self._entries.clear()
            self._resident = 0
            self._pinned = 0
            self.drops += n
            if n:
                DEVICE_FEED_EVICTION_COUNTER.labels(reason).inc(n)
        self._flush_residency()
        self._publish()
        return freed

    # -- observability ------------------------------------------------

    def _total_locked(self) -> int:
        return self._resident

    def resident_bytes(self) -> int:
        with self._mu:
            return self._total_locked()

    def pinned_bytes(self) -> int:
        """Bytes held by entries pinned by in-flight dispatches (the
        flight recorder stamps this per launch — O(1) running total,
        maintained at pin/unpin/re-account/drop)."""
        with self._mu:
            return self._pinned

    def resident_lines(self) -> int:
        with self._mu:
            return len(self._entries)

    def residency_by_tenant(self) -> dict:
        """Resident bytes per owning tenant (the resource_group half
        of the ``arena::residency`` owner tags) — the enforcement
        surface's per-group HBM view, rolled up into the runner's
        hbm_stats and the /resource_control route."""
        from ..resource_metering import ResourceTagFactory
        with self._mu:
            out: dict = {}
            for e in self._entries.values():
                if e.nbytes <= 0:
                    continue
                t = ResourceTagFactory.tenant(e.owner_tag)
                out[t] = out.get(t, 0) + e.nbytes
            return out

    def items(self) -> list:
        """Snapshot of (anchor, bucket) pairs with live anchors — the
        scrubber's iteration surface."""
        with self._mu:
            pairs = [(e.ref(), e.bucket)
                     for e in list(self._entries.values())]
        return [(a, b) for a, b in pairs if a is not None]

    def entry_stats(self) -> list:
        """(anchor, nbytes, hits, tick, pins) snapshot with live
        anchors — the placement rebalancer's victim-selection surface
        (device/placement.py picks the coldest unpinned anchor)."""
        with self._mu:
            rows = [(e.ref(), e.nbytes, e.hits, e.tick, e.pins)
                    for e in list(self._entries.values())]
        return [(a, nb, h, t, p) for a, nb, h, t, p in rows
                if a is not None]

    def _publish(self) -> None:
        from ..utils.metrics import (
            DEVICE_FEED_LINES,
            DEVICE_HBM_RESIDENT_BYTES,
        )
        with self._mu:
            DEVICE_HBM_RESIDENT_BYTES.set(self._total_locked())
            DEVICE_FEED_LINES.set(len(self._entries))

    def stats(self) -> dict:
        with self._mu:
            return {
                "budget_bytes": self.budget_bytes,
                "resident_bytes": self._total_locked(),
                "resident_lines": len(self._entries),
                "pinned_lines": sum(1 for e in self._entries.values()
                                    if e.pins > 0),
                # bytes the budget cannot reclaim right now (in use by
                # launched kernels) — check_hbm_within_budget allows
                # resident to exceed the cap by at most this much
                "pinned_bytes": self._pinned,
                "evictions": self.evictions,
                "rejections": self.rejections,
                "drops": self.drops,
            }


class _RemintWaiter:
    __slots__ = ("key", "shed", "region_id")

    def __init__(self, key, region_id):
        self.key = key
        self.shed = False
        self.region_id = region_id


class RemintGovernor:
    """Bounded, priority-ordered admission for cold ``columnar_build``
    re-mints — the storm-control half of the elastic feed lifecycle.

    When migration/split isn't possible (total slice death, digest
    divergence, delta-envelope misses) every invalidated region wants a
    host rebuild at once, and the narrow host link is exactly where a
    recovery storm hurts.  The governor caps concurrent builds at
    ``max_concurrent`` and parks the rest in a priority queue ordered
    hot-regions-first (the cache's decayed request rate) with RU-debt
    tenants last; past ``max_queue`` waiters, the WORST-priority waiter
    is shed with ``ServerIsBusy(retry_after_ms=...)`` so cold-tail work
    backs off instead of piling onto the link.

    Wired as ``RegionColumnarCache.remint_gate`` (server/node.py);
    ``max_concurrent <= 0`` disables admission entirely (the default —
    tier-1 behavior is unchanged unless configured on).
    """

    def __init__(self, max_concurrent: int = 2, max_queue: int = 32,
                 retry_after_ms: int = 50):
        self.max_concurrent = int(max_concurrent)
        self.max_queue = max(1, int(max_queue))
        self.retry_after_ms = int(retry_after_ms)
        self._cv = threading.Condition(threading.Lock())
        self._active = 0
        self._waiters: list = []
        self._seq = 0
        self.admitted = 0
        self.queued = 0
        self.shed = 0
        self.observed_max = 0       # peak concurrent builds ever granted
        self.peak_depth = 0         # deepest the wait queue ever got

    @staticmethod
    def _ru_debt() -> bool:
        """Is the CURRENT request's tenant in RU debt?  Debtors rebuild
        last: their burst already overdrew the shared budget."""
        try:
            from .. import resource_metering
            from ..resource_control import GLOBAL_CONTROLLER, \
                ResourceTagFactory
            ctx = resource_metering.current_context()
            tag = ctx.tag if ctx is not None else None
            if tag is None:
                return False
            return GLOBAL_CONTROLLER.debt(
                ResourceTagFactory.tenant(tag)) > 0
        except Exception:   # noqa: BLE001 — priority hints never fail a build
            return False

    def acquire(self, region_id: int, heat: float = 0.0):
        """Block until a build permit is granted; raises ServerIsBusy
        (with the retry hint) when this waiter is shed.  Returns a
        ticket for :meth:`release`."""
        if self.max_concurrent <= 0:
            return None             # disabled: free admission
        from ..server.read_pool import ServerIsBusy
        from ..utils.metrics import DEVICE_REMINT_QUEUE_DEPTH
        with self._cv:
            if self._active < self.max_concurrent and not self._waiters:
                self._active += 1
                self.admitted += 1
                self.observed_max = max(self.observed_max, self._active)
                return True
            # smaller key = admitted sooner: debt-free before debtors,
            # then hottest region, then FIFO
            self._seq += 1
            w = _RemintWaiter((1 if self._ru_debt() else 0, -heat,
                               self._seq), region_id)
            self._waiters.append(w)
            self.queued += 1
            if len(self._waiters) > self.max_queue:
                worst = max(self._waiters, key=lambda x: x.key)
                self._waiters.remove(worst)
                worst.shed = True
                self.shed += 1
                self._cv.notify_all()
            DEVICE_REMINT_QUEUE_DEPTH.set(len(self._waiters))
            self.peak_depth = max(self.peak_depth, len(self._waiters))
            while True:
                if w.shed:
                    raise ServerIsBusy(
                        "re-mint queue overloaded",
                        retry_after_ms=self.retry_after_ms)
                if self._active < self.max_concurrent and \
                        min(self._waiters, key=lambda x: x.key) is w:
                    self._waiters.remove(w)
                    self._active += 1
                    self.admitted += 1
                    self.observed_max = max(self.observed_max,
                                            self._active)
                    DEVICE_REMINT_QUEUE_DEPTH.set(len(self._waiters))
                    # others re-check: more slots may still be free
                    self._cv.notify_all()
                    return True
                self._cv.wait()

    def release(self, ticket) -> None:
        if ticket is None:
            return
        with self._cv:
            self._active -= 1
            self._cv.notify_all()

    def stats(self) -> dict:
        with self._cv:
            return {
                "max_concurrent": self.max_concurrent,
                "active": self._active,
                "depth": len(self._waiters),
                "admitted": self.admitted,
                "queued": self.queued,
                "shed": self.shed,
                "observed_max": self.observed_max,
                "peak_depth": self.peak_depth,
            }


class DeviceStateSupervisor(Observer):
    """Lifecycle teardown + background scrub over device-resident state.

    Registered on the raftstore's CoprocessorHost next to CDC and the
    DeltaSink.  Also installed as the RegionColumnarCache's
    ``on_line_retired`` callback, closing the loop: any line the cache
    drops (lifecycle event, LRU eviction, rebuild replacement, failed
    bridge) explicitly drops its device feed via ``runner.drop_feed``
    instead of waiting for GC.

    ``runner`` may be None — the supervisor still drives columnar-cache
    lifecycle invalidation on host-only nodes.
    """

    def __init__(self, runner=None, copr_cache=None, delta_sink=None,
                 scrub_interval: float = 0.0, scrub_max_lines: int = 0):
        self._runner = runner
        self._cache = copr_cache
        self._sink = delta_sink
        self._interval = scrub_interval
        self._scrub_max_lines = scrub_max_lines     # 0 = unbounded
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._mu = threading.Lock()
        # rotates a bounded pass's starting point so every resident
        # line is eventually scrubbed, not just the first N
        self._scrub_cursor = 0
        self.scrub_passes = 0
        self.scrub_divergences = 0
        self.quarantines = 0
        self.lifecycle_invalidations = 0
        self._last_scrub: dict = {}
        # replica-feed state machine (warm failover): regions whose
        # lines this store keeps as follower feeds — demoted leaders
        # plus regions that served a stale device read
        self._replica_feed_regions: set = set()
        self.promotions = 0             # leader gains over a warm feed
        self.promotion_rebuilds = 0     # promotions that failed verify
        self.demotions = 0              # leader losses (feed retained)
        # device-side split state machine
        self.splits = 0                 # parent lines sliced on device
        self.split_fallbacks = 0        # splits that fell back to re-mint
        # the storm-control governor (wired by node.py onto the cache's
        # remint_gate too; kept here so /health and chaos invariants
        # read one rollup)
        self.remint_governor = None

    # -- lifecycle events (CoprocessorHost observer) ------------------
    #
    # These run inline on the apply/drive path; each is dict surgery
    # plus reference drops — no device work, no blocking fetches.

    def on_region_changed(self, region) -> None:
        """Split/merge/epoch change: lines keyed at superseded epochs
        can never be hit again — drop them (and their feeds) now."""
        if self._cache is None:
            return
        n = self._cache.invalidate_region(
            region.id, keep_epoch=region.epoch.version)
        if n:
            self._note_invalidations(n)

    def on_region_split(self, left, right, left_index,
                        right_index) -> None:
        """A split is a slice, not a rebuild: the cache slices its
        parent lines into child lines at the children's epochs (zero
        ``columnar_build``), then the runner slices the resident parent
        FEEDS into digest-verified child feeds on device (zero
        ``feed_upload``).  This runs BEFORE the generic
        ``on_region_changed`` retires the superseded parent lines —
        peer.py orders the two events — so the parent planes are still
        resident when the device split reads them.  The
        ``device::device_split`` failpoint (and any slicing failure)
        falls back to host re-mint for THIS split only."""
        from ..utils import tracker
        from ..utils.metrics import DEVICE_FEED_MIGRATION_COUNTER
        if self._cache is None or \
                not hasattr(self._cache, "split_lines"):
            return
        if fail_point("device::device_split") is not None:
            DEVICE_FEED_MIGRATION_COUNTER.labels("split_fallback").inc()
            with self._mu:
                self.split_fallbacks += 1
            return
        with tracker.phase("device_split"):
            try:
                specs = self._cache.split_lines(left, right, left_index,
                                                right_index)
            except Exception:   # noqa: BLE001 — split must never fail apply
                import logging
                logging.getLogger(__name__).warning(
                    "device-side split failed; falling back to re-mint",
                    exc_info=True)
                specs = []
            runner = self._runner
            child_anchors = []
            parent = None
            for spec in specs:
                parent = spec["parent_lineage"]
                ok = False
                if runner is not None and \
                        hasattr(runner, "split_resident_feeds"):
                    try:
                        ok = runner.split_resident_feeds(spec) == "split"
                    except Exception:   # noqa: BLE001 — same contract
                        ok = False
                DEVICE_FEED_MIGRATION_COUNTER.labels(
                    "split" if ok else "split_fallback").inc()
                with self._mu:
                    if ok:
                        self.splits += 1
                    else:
                        self.split_fallbacks += 1
                for side in ("left", "right"):
                    ch = spec.get(side)
                    if ch is not None:
                        child_anchors.append(ch["lineage"])
            # children serve where the parent lived: pin them to its
            # slice so the first child request dispatches co-located
            placer = getattr(runner, "_placer", None) \
                if runner is not None else None
            if placer is not None and parent is not None and \
                    hasattr(placer, "adopt"):
                try:
                    placer.adopt(parent, child_anchors)
                except Exception:   # noqa: BLE001 — placement is advisory
                    pass

    def on_role_change(self, region_id: int, is_leader: bool) -> None:
        """Role flips drive the replica-feed state machine, not a
        teardown.

        **Leader loss** (demotion): the region's lines STAY resident
        as replica feeds.  The DeltaSink observes follower applies
        too, so the same per-region delta stream keeps them patched,
        and they serve any coprocessor read at ``read_ts ≤
        resolved_ts`` through the stale-read gate.  (Before replicated
        serving this eagerly invalidated — a leader transfer cost a
        multi-second cold re-mint on transfer back.)

        **Leader gain** over a warm feed (promotion): resolved-ts
        catch-up already happened continuously via the delta stream,
        so promotion is only a scrub-digest re-verify of the region's
        resident planes — never a ``columnar_build``.  Only a digest
        divergence (or the ``copr::replica_promote`` failpoint) falls
        back to invalidation + cold rebuild.
        """
        if self._cache is None:
            return
        if not is_leader:
            with self._mu:
                self.demotions += 1
                self._replica_feed_regions.add(region_id)
            self._publish_replica_feeds()
            return
        with self._mu:
            was_replica = region_id in self._replica_feed_regions
            self._replica_feed_regions.discard(region_id)
        self._publish_replica_feeds()
        if was_replica or (hasattr(self._cache, "region_resident") and
                           self._cache.region_resident(region_id)):
            self.promote_region(region_id)

    def note_replica_feed(self, region_id: int) -> None:
        """A stale device read served from this store's line: the line
        is now a live replica feed (node.py ``_note_replica_read``)."""
        with self._mu:
            self._replica_feed_regions.add(region_id)
        self._publish_replica_feeds()

    def _publish_replica_feeds(self) -> None:
        from ..utils.metrics import DEVICE_REPLICA_FEEDS
        with self._mu:
            n = len(self._replica_feed_regions)
        DEVICE_REPLICA_FEEDS.set(n)

    def promote_region(self, region_id: int) -> bool:
        """Warm promotion of an already-patched replica feed to leader
        serving state.  Returns True when the feed survived verify.

        The feed's content is re-verified against the digests recorded
        at build/patch time (the same audit the background scrubber
        runs) so a leader never serves from a silently-corrupted
        replica plane.  On divergence — or when chaos arms
        ``copr::replica_promote`` — the region's lines invalidate and
        the next request pays the cold rebuild, counted separately so
        the no-cold-rebuild invariant can tell a failed verify from a
        broken warm path."""
        from ..utils import tracker
        from ..utils.metrics import DEVICE_REPLICA_PROMOTION_COUNTER
        ok = fail_point("copr::replica_promote") is None
        if ok:
            with tracker.phase("replica_promote"):
                ok = self._verify_region_digests(region_id)
        with self._mu:
            self.promotions += 1
            if not ok:
                self.promotion_rebuilds += 1
        if ok:
            DEVICE_REPLICA_PROMOTION_COUNTER.labels("warm").inc()
            return True
        DEVICE_REPLICA_PROMOTION_COUNTER.labels("rebuild").inc()
        n = self._cache.invalidate_region(region_id)
        if n:
            self._note_invalidations(n)
        return False

    def _verify_region_digests(self, region_id: int) -> bool:
        """Digest re-verify of one region's resident feeds (the scrub
        audit, targeted): snapshot each feed's (planes, digests) pair
        under the runner's dispatch lock, re-hash on device, compare.
        A diverged anchor quarantines exactly as a scrub hit would.
        No runner (host-only node) → trivially clean."""
        runner = self._runner
        if runner is None or not hasattr(runner, "arena_items"):
            return True
        dispatch_mu = getattr(runner, "_dispatch_mu", None)
        out = {"lines": 0, "planes": 0, "divergences": 0,
               "quarantined_regions": []}
        clean = True
        for anchor, bucket in runner.arena_items():
            if getattr(anchor, "region_hint", None) != region_id:
                continue
            feeds = []
            if dispatch_mu is not None:
                dispatch_mu.acquire()
            try:
                for v in list(bucket.values()):
                    if isinstance(v, dict) and "flat" in v and \
                            v.get("digests") is not None:
                        feeds.append((v["flat"], v["digests"],
                                      v.get("n_live", 0)))
            finally:
                if dispatch_mu is not None:
                    dispatch_mu.release()
            diverged = False
            for flat, digests, n in feeds:
                for arr, want in zip(flat, digests):
                    got = int(np.asarray(runner.device_digest(arr, n)))
                    out["planes"] += 1
                    if got != int(np.asarray(want)):
                        diverged = True
                        break
                if diverged:
                    break
            if diverged:
                clean = False
                out["divergences"] += 1
                self._quarantine(runner, anchor, out)
        return clean

    def on_data_replaced(self, region_id: int, index: int) -> None:
        """Snapshot apply replaced the region's data wholesale: the
        DeltaSink already poisoned coverage; drop the derived lines
        eagerly too — they can only rebuild."""
        if self._cache is None:
            return
        n = self._cache.invalidate_region(region_id)
        if n:
            self._note_invalidations(n)

    def on_peer_destroyed(self, region_id: int) -> None:
        """Peer removed from this store (merge-away / conf change):
        every derived artifact for the region dies with it."""
        if self._cache is not None:
            n = self._cache.invalidate_region(region_id)
            if n:
                self._note_invalidations(n)
        if self._sink is not None and hasattr(self._sink, "drop_region"):
            self._sink.drop_region(region_id)

    def on_line_retired(self, lineage) -> None:
        """RegionColumnarCache retirement callback → explicit feed
        teardown (the drop_feed API replacing GC-timed reclamation)."""
        if self._runner is not None and lineage is not None:
            self._runner.drop_feed(lineage, reason="lifecycle")

    def _note_invalidations(self, n: int) -> None:
        with self._mu:
            self.lifecycle_invalidations += n

    # -- scrub --------------------------------------------------------

    def scrub(self, max_lines: Optional[int] = None) -> dict:
        """One scrub pass: re-hash resident device planes and compare
        against the digests recorded at build/patch time.  Divergence →
        quarantine the anchor (feeds drop; the next request for it
        degrades to host; the one after rebuilds from host truth).

        Low-priority by construction: digests are tiny reduction
        kernels over already-resident planes, dispatched one line at a
        time outside any runner lock, and ``max_lines`` bounds a pass
        so the scrubber never monopolizes the dispatch stream.
        """
        from ..utils.metrics import DEVICE_SCRUB_COUNTER
        out = {"lines": 0, "planes": 0, "divergences": 0,
               "quarantined_regions": []}
        runner = self._runner
        if runner is None or not hasattr(runner, "arena_items"):
            self._record_scrub(out, 0.0)
            return out
        limit = max_lines if max_lines is not None else \
            (self._scrub_max_lines or None)
        # the (flat, digests) pair is updated non-atomically by the
        # patch path under the runner's dispatch lock; snapshot each
        # feed's pair UNDER that lock so a concurrent patch can never
        # make a healthy line read as diverged (planes themselves are
        # immutable arrays — hashing proceeds outside the lock)
        dispatch_mu = getattr(runner, "_dispatch_mu", None)
        t0 = time.perf_counter()

        def hash_feeds(feeds) -> bool:
            diverged = False
            for flat, digests, n in feeds:
                for arr, want in zip(flat, digests):
                    got = int(np.asarray(runner.device_digest(arr, n)))
                    out["planes"] += 1
                    if got != int(np.asarray(want)):
                        diverged = True
                if diverged:
                    break
            return diverged

        items = runner.arena_items()
        if limit is not None and items:
            # bounded pass: rotate the start so lines beyond the first
            # ``limit`` are reached on later passes, never starved
            start = self._scrub_cursor % len(items)
            items = items[start:] + items[:start]
            self._scrub_cursor = start + limit
        for anchor, bucket in items:
            if limit is not None and out["lines"] >= limit:
                break
            feeds = []
            diverged = injected = False
            if dispatch_mu is not None:
                dispatch_mu.acquire()
            try:
                for k, v in list(bucket.items()):
                    if isinstance(v, dict) and "flat" in v and \
                            v.get("digests") is not None:
                        if fail_point("device::feed_corrupt") \
                                is not None:
                            # the injected fault: a bit flips on a
                            # resident plane (HBM corruption); this
                            # pass must catch it
                            runner.corrupt_resident_plane(v)
                            injected = True
                        feeds.append((v["flat"], v["digests"],
                                      v.get("n_live", 0)))
                if injected:
                    # we just flipped a bit on the LIVE feed: hash and
                    # quarantine before the lock drops, so no racing
                    # query can dispatch over the corrupted plane —
                    # zero wrong results by construction
                    diverged = hash_feeds(feeds)
                    if diverged:
                        self._quarantine(runner, anchor, out)
            finally:
                if dispatch_mu is not None:
                    dispatch_mu.release()
            if not injected:
                # single-device: hash outside the lock (concurrent jit
                # launches are safe there).  Sharded mesh: multi-device
                # launch interleaving can deadlock (the dispatch
                # lock's reason to exist), so the digest dispatches
                # serialize under it — a brief, bounded hold per line.
                serialize = dispatch_mu is not None and \
                    not getattr(runner, "_single", True)
                if serialize:
                    dispatch_mu.acquire()
                try:
                    diverged = hash_feeds(feeds)
                finally:
                    if serialize:
                        dispatch_mu.release()
                if diverged:
                    self._quarantine(runner, anchor, out)
            if not feeds:
                continue
            out["lines"] += 1
            if diverged:
                out["divergences"] += 1
                DEVICE_SCRUB_COUNTER.labels("divergence").inc()
            else:
                DEVICE_SCRUB_COUNTER.labels("clean").inc()
        self._record_scrub(out, time.perf_counter() - t0)
        return out

    def _quarantine(self, runner, anchor, out: dict) -> None:
        region = getattr(anchor, "region_hint", None)
        if region is not None:
            out["quarantined_regions"].append(region)
        runner.quarantine(anchor, reason="scrub divergence")
        with self._mu:
            self.quarantines += 1

    def _record_scrub(self, out: dict, elapsed_s: float) -> None:
        out["ms"] = round(elapsed_s * 1e3, 3)
        with self._mu:
            self.scrub_passes += 1
            self.scrub_divergences += out["divergences"]
            self._last_scrub = dict(out)

    # -- background thread --------------------------------------------

    def start(self) -> None:
        if self._interval <= 0 or self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="device-scrub")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2)
            self._thread = None

    def _loop(self) -> None:
        while not self._stop.wait(self._interval):
            try:
                self.scrub()
            except Exception:   # noqa: BLE001 — scrub must never crash
                import logging
                logging.getLogger(__name__).warning(
                    "device scrub pass failed", exc_info=True)
            # half-open probing for quarantined mesh slices rides the
            # same cadence: a re-admission must not wait for traffic
            # (the on-route probes) when the node has gone idle
            probe = getattr(self._runner, "probe_quarantined", None)
            if callable(probe):
                try:
                    probe()
                except Exception:   # noqa: BLE001 — same contract
                    import logging
                    logging.getLogger(__name__).warning(
                        "slice probe pass failed", exc_info=True)

    # -- observability ------------------------------------------------

    def stats(self) -> dict:
        with self._mu:
            out = {
                "scrub_passes": self.scrub_passes,
                "scrub_divergences": self.scrub_divergences,
                "quarantines": self.quarantines,
                "lifecycle_invalidations": self.lifecycle_invalidations,
                "replica_feeds": len(self._replica_feed_regions),
                "promotions": self.promotions,
                "promotion_rebuilds": self.promotion_rebuilds,
                "demotions": self.demotions,
                "splits": self.splits,
                "split_fallbacks": self.split_fallbacks,
                "last_scrub": dict(self._last_scrub),
            }
        if self.remint_governor is not None:
            out["remint"] = self.remint_governor.stats()
        if self._runner is not None and hasattr(self._runner,
                                                "hbm_stats"):
            out["hbm"] = self._runner.hbm_stats()
        return out
