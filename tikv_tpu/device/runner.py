"""Device (TPU) coprocessor backend — fused jit/shard_map pipelines.

This is the north-star slice (SURVEY.md §7, BASELINE.md): the CPU
``BatchExecutor`` hot loop (tidb_query_executors/src/runner.rs:641 —
scan → selection → aggregation per 1024-row batch) becomes ONE fused XLA
computation per plan over the whole HBM-resident feed:

- rows are sharded over the ("range", "tile") mesh (parallel/mesh.py) —
  TiKV's region/bucket sharding mapped to mesh axes;
- the feed is a set of flat padded column arrays cached in HBM across
  requests (the region-cache-engine analog); row-validity for non-NULL
  columns and the ragged tail is synthesized on device from an iota
  compare, so it never crosses PCIe or burns HBM;
- each request is ONE dispatch: a ``lax.scan`` over row blocks folds the
  aggregation carry on device (RpnExpression evaluation, the filter
  mask, and the aggregate kernels all trace into the same jit, so XLA
  fuses selection into the aggregation's HBM pass);
- group-by COUNT/SUM runs on the MXU as a *factorized* one-hot matmul
  (slot = hi·LO+lo, kernels.twolevel_partial) with exact int8 byte-split
  arithmetic — ~8× the straight one-hot matmul, which itself is ~10×
  XLA's scatter lowering on TPU;
- cross-shard merging happens ONCE after the scan, as a partial-agg →
  tree-reduce split on the interconnect (the TiDB partial-at-TiKV /
  final-at-TiDB architecture mapped onto mesh axes): psum for the
  count/sum/nonnull fields — TiKV's psum-mergeable partial aggregate
  states, tidb_query_aggr — and an all-to-all by key bucket for the
  order-sensitive hash-agg min/max slots (_finalize_hash_bucket_merge);
  simple-agg min/max/first come back as a per-shard (S,) stack for a
  scalar host reduce;
- the result returns in ONE packed uint8 buffer with the D2H transfer
  started asynchronously (through a tunneled TPU every blocking sync
  costs ~0.1s; r2's per-array readback spent 3+ RTTs per request).

On a 1-device mesh kernels compile as plain jit (no shard_map, no
NamedSharding transfers — both measurably degrade the tunneled session's
dispatch path). A SHARDED mesh is a first-class backend, not a degraded
one: feeds upload row-sharded and delta-PATCH in place
(GSPMD-partitioned dynamic_update_slice, _dus), the fused Pallas kernel
runs as per-shard partial grids psum-merged on ICI
(_pallas_sharded_wrap), selection mask/index routing is
shard-concatenable, and hot regions optionally pin to single-device
slices via the placement loop (device/placement.py) so a
many-small-regions mix scales OUT while a single big feed scales UP.
Host decode never appears on this path: the scan feed is a columnar
snapshot (executors/columnar.py). Small requests stay on the host numpy
path (copr/endpoint.py routing) so p99 latency never pays device
dispatch.

Routing is PER FRAGMENT, not per plan: under the plan IR
(copr/plan_ir.py) this runner serves individual leaf fragments of an
operator DAG — the same request may run its scan+selection here, its
join through the DeviceJoiner (device/join.py, reached via
``joiner()``), and its aggregation finalize on the host pipeline.  The
"whole plan picks one backend" framing this module's routing notes
used to assume holds only for the linear DAGRequest surface; any
degrade decision is now scoped to the fragment that faulted.
"""

from __future__ import annotations

import math
import threading
import time
from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import Optional, Sequence

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

try:                                    # jax >= 0.5 top-level alias
    _shard_map = jax.shard_map
except AttributeError:                  # 0.4.x: experimental home
    from jax.experimental.shard_map import shard_map as _shard_map

try:                                    # varying-manual-axes typing
    _pvary = lax.pvary
except AttributeError:                  # 0.4.x: replication is implicit
    def _pvary(x, axes):
        return x

from ..copr.dag import (
    AggregationDesc,
    DAGRequest,
    IndexScanDesc,
    LimitDesc,
    SelectionDesc,
    TableScanDesc,
    TopNDesc,
)
from ..datatype import Column, ColumnBatch, EvalType, FieldType
from ..datatype.tile import _device_dtype
from ..expr import build_rpn
from ..expr.eval import eval_rpn
from ..expr.rpn import RpnColumnRef, RpnConst, RpnExpression, RpnFnCall
from ..ops.agg import (
    AggSpec,
    finalize_hash,
    finalize_simple,
    hash_agg_tile,
    simple_agg_tile,
)
from ..parallel import ROW_AXES, make_mesh, num_shards, row_sharding

_BIG = np.iinfo(np.int64).max

# same-width unsigned views for bit-exact digest/corruption bitcasts
_UINT_BY_ITEMSIZE = {1: jnp.uint8, 2: jnp.uint16, 4: jnp.uint32,
                     8: jnp.uint64}

# scan-block granularity per kernel kind (rows per lax.scan step; the
# feed pads to a multiple of _FEED_UNIT per shard so any of these divide)
_FEED_BLOCK = 1 << 15
_CHUNK_AGG = 1 << 20
_CHUNK_TOPN = 1 << 23


class _FallbackToHost(Exception):
    """Raised when a runtime property (not the plan) forces the host path."""


def _fits_dtype(vals: np.ndarray, valid, dt: np.dtype) -> bool:
    """May ``vals`` be represented in the feed's established device
    dtype?  Floats narrow exactly like a fresh astype would; ints must
    fit the integer range (and uint64 stays below 2^63 — the same feed
    guard that routes beyond-int64 cores to the host)."""
    if dt.kind not in "iu":
        return True
    live = vals if valid is None or valid.all() else vals[valid]
    if not live.size:
        return True
    lo, hi = int(live.min()), int(live.max())
    if dt == np.dtype(np.uint64):
        return 0 <= lo and hi < (1 << 63)
    info = np.iinfo(dt)
    return info.min <= lo and hi <= info.max


def _fp_degrade(name: str) -> None:
    """Failpoint site that degrades to the host backend: a fired
    ``return`` action raises _FallbackToHost, so an injected device
    fault (or a real one steered in tests) downgrades the query instead
    of failing it — the runner's existing fallback machinery catches it.
    """
    from ..utils.failpoint import fail_point
    if fail_point(name) is not None:
        raise _FallbackToHost(name)
#  DATETIME (packed u64 core — the bit layout is order-preserving) and
#  DURATION (i64 ns) are device-native dense columns: comparisons, topN
#  and min/max/count ride the same kernels as INT.  Years >= 8192 pack
#  above 2^63 and would corrupt the int64 carries — the feed guard
#  routes such columns to host.
_DEVICE_ETS = (EvalType.INT, EvalType.REAL, EvalType.DATETIME,
               EvalType.DURATION)
_TIME_ETS = (EvalType.DATETIME, EvalType.DURATION)

# TopN sort-key sentinels (float64 keys; any real data is far inside these)
_EXCLUDED_ASC = 1e308
_EXCLUDED_DESC = -1e308
_NULL_KEY = -1e307          # MySQL: NULL sorts below every value


def _next_pow2(n: int) -> int:
    return 1 << max(0, (n - 1).bit_length())


def _rpn_col_indices(rpn: RpnExpression) -> set:
    return {n.col_idx for n in rpn.nodes if isinstance(n, RpnColumnRef)}


def _remap_rpn(rpn: RpnExpression, mapping: dict) -> RpnExpression:
    nodes = []
    for n in rpn.nodes:
        if isinstance(n, RpnColumnRef):
            nodes.append(RpnColumnRef(mapping[n.col_idx], n.eval_type))
        else:
            nodes.append(n)
    return RpnExpression(tuple(nodes))


def _rpn_device_safe(rpn: RpnExpression, scan_ets: Sequence[EvalType]) -> bool:
    for n in rpn.nodes:
        if isinstance(n, RpnConst):
            if n.value is not None and not isinstance(n.value, (int, float, bool)):
                return False
        elif isinstance(n, RpnColumnRef):
            if n.col_idx >= len(scan_ets) or scan_ets[n.col_idx] not in _DEVICE_ETS:
                return False
        elif isinstance(n, RpnFnCall):
            if n.meta.ret not in _DEVICE_ETS:
                return False
            if not n.meta.device_safe:
                # raw-numpy sig bodies (time extractors, string/json
                # families) crash on jit tracers — only pure-xp sigs
                # may enter a device plan; everything else runs host
                return False
    return True


@dataclass
class _Plan:
    """Analyzed device plan (rpns remapped onto ``used_cols`` positions)."""

    scan: TableScanDesc
    kind: str                        # scan | simple_agg | hash_agg | topn
    used_cols: list                  # original scan column offsets shipped to device
    sel_rpns: list = field(default_factory=list)
    specs: list = field(default_factory=list)        # AggSpec per agg
    agg_rpns: list = field(default_factory=list)     # RpnExpression | None
    key_rpn: Optional[RpnExpression] = None
    order_rpn: Optional[RpnExpression] = None
    order_desc: bool = False
    limit: int = 0
    # scan_sel only: every scan column rides the feed in a lossless
    # device dtype, so the compact route may materialize the output on
    # device (selection.py routing matrix)
    compact_ok: bool = False
    # lazy (param_rpns, values, dtypes) from selection.split_params
    sel_params: Optional[tuple] = None
    # lazy const-blind stat key (runner._sel_keys)
    sel_stat_key: Optional[tuple] = None


def _sum_parts(parts):
    """Merge per-tile packed partials (psum-partial semantics)."""
    packed = np.asarray(parts[0])
    for p in parts[1:]:
        packed = packed + np.asarray(p)
    return packed


class _GuardedMeta:
    """Request-scoped view of the shared lineage-anchored memo.

    Reads come from the shared dict only while it still reflects this
    request's snapshot generation (``fresh()``); writes always land in
    a request-local overlay and propagate to the shared dict only while
    fresh — a request (or deferred finalize) racing a newer
    generation's refresh must never repopulate the shared memo with
    stale derived constants (hash bounds, byte-plane widths, sparse
    recodes), which a newer request would then trust.
    """

    __slots__ = ("_meta", "_fresh", "_local")

    def __init__(self, meta: dict, fresh):
        self._meta = meta
        self._fresh = fresh
        self._local: dict = {}

    def __contains__(self, k) -> bool:
        return k in self._local or (self._fresh() and k in self._meta)

    def get(self, k, default=None):
        if k in self._local:
            return self._local[k]
        return self._meta.get(k, default) if self._fresh() else default

    def __getitem__(self, k):
        got = self.get(k, _GuardedMeta)
        if got is _GuardedMeta:
            raise KeyError(k)
        return got

    def __setitem__(self, k, v) -> None:
        self._local[k] = v
        if self._fresh():
            self._meta[k] = v

    def setdefault(self, k, v):
        got = self.get(k, _GuardedMeta)
        if got is not _GuardedMeta:
            return got
        self[k] = v
        return v


class _PinnedStager:
    """Pre-registered pinned-host D2H landing buffers.

    On TPU the blocking half of a readback is ``np.asarray(x)``: the
    runtime allocates fresh host memory and synchronously drains the
    transfer into it, per request.  This stager instead appends a
    jitted identity program with ``out_shardings`` pinned to the
    device's ``pinned_host`` memory space to the DISPATCH stream: the
    device→host copy executes asynchronously as part of the launch
    train, lands in runtime-managed pinned (page-locked) host buffers,
    and the later ``np.asarray`` at fetch time reads settled host
    memory instead of paying the sync round trip.  One staging program
    is compiled per (shape, dtype, device) — shapes are already
    pow2/9-8-geometric capacity buckets (``_pad_rows``), so the
    registration set is bounded exactly like the feed compile classes.

    Probed once per shape class: backends without the memories API
    (CPU jax — where ``np.asarray`` is zero-copy anyway) or sharded
    leaves disable themselves and the readback path is unchanged.
    """

    _MAX_CLASSES = 256

    def __init__(self, memory_kind: str = "pinned_host"):
        # "pinned_host" on TPU; tests exercise the staging mechanics on
        # CPU with "unpinned_host" (the only host space CPU jax has)
        self.memory_kind = memory_kind
        self._mu = threading.Lock()
        self._fns: dict = {}        # class key -> jitted fn | None
        self.enabled: Optional[bool] = None     # None = unprobed
        self.staged = 0
        self.staged_bytes = 0
        self.classes = 0

    def _fn_for(self, x):
        try:
            sharding = x.sharding
            devices = getattr(sharding, "_device_assignment", None) or \
                tuple(sharding.device_set)
            if len(devices) != 1:
                return None         # sharded leaf: leave to GSPMD
            dev = devices[0]
            key = (x.shape, str(x.dtype), dev.id)
        except Exception:   # noqa: BLE001 — not a jax array
            return None
        with self._mu:
            if key in self._fns:
                return self._fns[key]
            if len(self._fns) >= self._MAX_CLASSES:
                # registration full: pass the leaf through rather than
                # compiling (and immediately forgetting) a staging
                # program per request — the cap is a backstop far above
                # the bucketed shape population, so hitting it means a
                # shape explosion, not a workload to optimize
                return None
        fn = None
        try:
            from jax.sharding import SingleDeviceSharding
            out = SingleDeviceSharding(dev, memory_kind=self.memory_kind)
            fn = jax.jit(lambda a: a, out_shardings=out)
            fn(x)                   # probe: compiles + runs once
            self.enabled = True
        except Exception:   # noqa: BLE001 — memories API unsupported
            fn = None
            if self.enabled is None:
                self.enabled = False
        with self._mu:
            if len(self._fns) < self._MAX_CLASSES:
                self._fns[key] = fn
            if fn is not None:
                self.classes += 1
        return fn

    def stage(self, tree):
        """Stage every single-device leaf of ``tree`` to pinned host
        memory; leaves that cannot stage pass through untouched."""
        if self.enabled is False:
            return tree

        def one(x):
            fn = self._fn_for(x)
            if fn is None:
                return x
            try:
                y = fn(x)
            except Exception:   # noqa: BLE001 — degrade to direct D2H
                return x
            with self._mu:
                self.staged += 1
                self.staged_bytes += int(getattr(x, "nbytes", 0))
            return y

        return jax.tree.map(one, tree)

    def stats(self) -> dict:
        with self._mu:
            return {"enabled": bool(self.enabled),
                    "probed": self.enabled is not None,
                    "staged": self.staged,
                    "staged_bytes": self.staged_bytes,
                    "classes": self.classes}


# process-wide: pinned host memory is a per-device runtime resource,
# and the jit cache keys on the concrete device — safe to share across
# runners (slice sub-runners included)
HOST_STAGER = _PinnedStager()


class _Pending:
    """A dispatched device request: output pytree still on device plus
    the host finalize that turns the fetched numpy tree into a
    SelectResult.  Leaves are staged to pinned host memory at
    construction when the backend supports it (:class:`_PinnedStager`)
    and ``copy_to_host_async`` is issued for every leaf, so the D2H
    transfer streams while the caller decides when (and on which
    thread) to block — the seam the async serving path pipelines on.
    ``small``: the fetch is KBs (agg states), so a completion pool may
    prioritize it over bulk candidate readbacks.
    """

    __slots__ = ("tree", "finalize", "small")

    def __init__(self, tree, finalize, small: bool = True):
        tree = HOST_STAGER.stage(tree)
        self.tree = tree
        self.finalize = finalize
        self.small = small
        for x in jax.tree.leaves(tree):
            try:
                x.copy_to_host_async()
            except Exception:   # pragma: no cover - CPU arrays
                pass


class DeferredResult:
    """Handle for a device request whose D2H fetch + host finalize have
    not run yet (``DeviceRunner.handle_request(..., deferred=True)``).

    ``result()`` blocks on the transfer, runs the host finalize, and
    memoizes — safe to call from any thread, exactly-once semantics.
    The degrade contract survives deferral: a ``device::*`` failpoint
    (or any _FallbackToHost) firing inside the deferred fetch downgrades
    THIS request to the host pipeline instead of failing it, exactly as
    the synchronous path does.  Any other exception propagates to the
    caller (the endpoint applies its own degrade policy there).
    """

    __slots__ = ("_runner", "_pending", "_dag", "_storage", "_mu",
                 "_memo", "small", "_pin_anchor", "_meter_ctx")

    def __init__(self, runner, pending: _Pending, dag, storage,
                 pin_anchor=None):
        self._runner = runner
        self._pending = pending
        self._dag = dag             # original request (host fallback)
        self._storage = storage
        self._mu = threading.Lock()
        self._memo = None
        self.small = pending.small
        # feed-arena pin taken at dispatch; released exactly once when
        # the deferred fetch resolves (eviction must not race the D2H)
        self._pin_anchor = pin_anchor
        # dispatch-time metering context: fetch-side charges (D2H
        # bytes) attribute to the dispatching request/share-group no
        # matter which completion worker runs the fetch
        from .. import resource_metering as rm
        self._meter_ctx = rm.current_context()

    def result(self):
        from .. import resource_metering as rm
        with self._mu:
            if self._memo is None:
                try:
                    with rm.activate(self._meter_ctx):
                        self._memo = ("ok", self._resolve())
                except BaseException as e:      # noqa: BLE001 — memoized
                    self._memo = ("err", e)
                finally:
                    if self._pin_anchor is not None:
                        try:
                            self._runner._arena.unpin(self._pin_anchor)
                        except Exception:   # noqa: BLE001
                            pass
                        self._pin_anchor = None
            kind, val = self._memo
        if kind == "err":
            raise val
        return val

    def __del__(self):
        # backstop for an abandoned deferred (completion-pool submit
        # failure, dropped future): the arena pin must not outlive the
        # handle, or the line becomes unevictable under a budget
        if getattr(self, "_pin_anchor", None) is not None:
            try:
                self._runner._arena.unpin(self._pin_anchor)
            except Exception:   # noqa: BLE001 — interpreter teardown
                pass

    def _resolve(self):
        try:
            r = self._runner._finish(self._pending)
        except _FallbackToHost:
            # fetch-side fault: strike the slice's health score, then —
            # if the slice is actually DEAD (quarantined, or the
            # persistent slice_dead fault names it) — rescue the
            # request onto a healthy slice/submesh before falling to
            # the host rung.  The pin release in result()'s finally is
            # untouched either way: exactly-once, never doubled.
            self._runner._note_slice_fault("fetch")
            rescued = self._runner._rescue(self._dag, self._storage)
            if rescued is not None:
                return rescued
            from ..executors.runner import BatchExecutorsRunner
            return BatchExecutorsRunner(self._dag,
                                        self._storage).handle_request()
        return self._runner._apply_output_offsets(self._dag, r)


class _BatchUnavailable(Exception):
    """Raised when a cross-request batched dispatch cannot be served as
    one stacked launch (plan/feed edge case, degrade mid-dispatch).
    The coalescer catches it and retries every member as a SOLO
    dispatch — a failed group must never fail its members."""


class _GroupPending:
    """Shared fetch handle for ONE stacked group dispatch.

    Unlike :class:`DeferredResult` there is no built-in host fallback —
    the raw fetched tree serves N member resolutions, and a member-level
    failure must degrade THAT member (the endpoint's per-request
    contract), never substitute one member's answer for another's.
    ``fetch()`` blocks on the shared D2H once, memoizes, and releases
    the group's arena pin exactly once.
    """

    __slots__ = ("_runner", "_pending", "_mu", "_memo", "_pin_anchor",
                 "_meter_ctx")

    def __init__(self, runner, pending: _Pending, pin_anchor=None):
        self._runner = runner
        self._pending = pending
        self._mu = threading.Lock()
        self._memo = None
        self._pin_anchor = pin_anchor
        # group metering context captured at dispatch: the shared D2H
        # charge splits by occupancy share across member tags from
        # whichever member's completion worker joins the fetch first
        from .. import resource_metering as rm
        self._meter_ctx = rm.current_context()

    def fetch(self):
        from .. import resource_metering as rm
        with self._mu:
            if self._memo is None:
                try:
                    with rm.activate(self._meter_ctx):
                        self._memo = (
                            "ok", self._runner._finish(self._pending))
                except BaseException as e:  # noqa: BLE001 — memoized
                    if isinstance(e, _FallbackToHost):
                        # one strike for the shared fetch, not one per
                        # member resolution (the memo re-raises N times)
                        self._runner._note_slice_fault("fetch")
                    self._memo = ("err", e)
                finally:
                    self._unpin()
            kind, val = self._memo
        if kind == "err":
            raise val
        return val

    def _unpin(self) -> None:
        if self._pin_anchor is not None:
            try:
                self._runner._arena.unpin(self._pin_anchor)
            except Exception:   # noqa: BLE001
                pass
            self._pin_anchor = None

    def __del__(self):
        # abandoned group (every member solo-degraded before fetching):
        # the pin must not outlive the handle
        if getattr(self, "_pin_anchor", None) is not None:
            self._unpin()


class _BatchedSelectionGroup:
    """N per-request resolutions over one stacked selection dispatch.

    ``member_result(i)`` joins the SHARED fetch (one D2H sync for the
    whole group), slices member ``i``'s packed bitmask, seeds that
    member's selectivity EWMA, and runs the member's own host gather
    over its own snapshot — so concurrent members' gathers parallelize
    on the completion pool while the device round trip is paid once.
    """

    __slots__ = ("_runner", "_gp", "_members")

    def __init__(self, runner, gp: _GroupPending, members):
        self._runner = runner
        self._gp = gp
        self._members = members

    def __len__(self) -> int:
        return len(self._members)

    def member_result(self, i: int):
        from ..utils import tracker
        try:
            counts, packed, n = self._gp.fetch()
        except _FallbackToHost:
            # the group's slice died between dispatch and fetch: rescue
            # THIS member on a healthy slice — per member, so no member
            # ever fails (or host-degrades) for a group-mate's fault it
            # could survive; the shared pin was already released
            # exactly once inside the memoized fetch
            dag, storage = self._members[i]
            rescued = self._runner._rescue(dag, storage)
            if rescued is not None:
                return rescued
            raise       # the endpoint's per-member host degrade applies
        dag, storage = self._members[i]
        runner = self._runner
        plan = runner._analyze(dag)
        k = int(counts[i])
        runner._sel_observe(runner._sel_keys(dag, plan),
                            (k / n) if n else 0.0)
        mask = np.unpackbits(packed[i])[:n].astype(np.bool_)
        with tracker.phase("host_materialize"):
            if isinstance(plan.scan, TableScanDesc) and \
                    hasattr(storage, "gather_rows"):
                out = storage.gather_rows(plan.scan, dag.ranges, mask)
            else:
                b = runner._scan_batch(dag, plan, storage)
                out = b.filter(mask)
        result = runner._result(dag, out.schema, out.columns)
        return runner._apply_output_offsets(dag, result)


class DeviceRunner:
    """Executes supported DAG plans on the device mesh.

    Registered with copr.Endpoint the way coprocessor_v2 plugins register an
    alternate execution backend (coprocessor_plugin_api/src/lib.rs:5-43).
    """

    def __init__(self, mesh=None, chunk_rows: Optional[int] = None,
                 max_hash_capacity: int = 1 << 20,
                 max_topn_limit: int = 1 << 14,
                 hbm_budget_bytes: int = 0,
                 placement: bool = False,
                 placement_rows: Optional[int] = None,
                 slice_trip_strikes: Optional[float] = None,
                 slice_probe_cooldown_s: Optional[float] = None,
                 slice_latency_outlier_s: Optional[float] = None,
                 flight_recorder_depth: Optional[int] = None):
        # int64 accumulators are required for exact SUM/COUNT over 1e8
        # rows; jax defaults to 32-bit.  Values stay int32/float32 on
        # device, only accumulators widen.  (Set here, not at import, so
        # importing the package has no process-global side effect.)
        jax.config.update("jax_enable_x64", True)
        self._mesh = mesh if mesh is not None else make_mesh()
        self._max_hash_capacity = max_hash_capacity
        self._max_topn_limit = max_topn_limit
        self._row_sharding = row_sharding(self._mesh)
        self._repl = NamedSharding(self._mesh, P())
        # Single-device (the real-chip bench): plain jit + uncommitted
        # arrays.  Explicit NamedSharding transfers and shard_map wrappers
        # measurably degrade the tunneled-TPU session's dispatch path, and
        # a 1-device mesh gains nothing from them.
        self._single = num_shards(self._mesh) == 1
        # scan-block granularity (rows per shard per lax.scan step); the
        # chunk_rows override shrinks it so tests drive multi-step scans
        # on tiny fixtures
        S = num_shards(self._mesh)
        self._is_tpu = self._mesh.devices.flat[0].platform == "tpu"
        if chunk_rows is None:
            # feeds pad to the Pallas block so the fused hash kernel
            # (pallas_hash.BLOCK rows/grid step) divides the feed — per
            # SHARD on a sharded TPU mesh, since the sharded fast path
            # runs the same kernel per shard before the tree-reduce;
            # the XLA scan paths gcd down from this.  A sharded CPU
            # mesh (virtual-device parity tests) keeps the smaller
            # unit: no Mosaic lowering exists there and 8×2^18-row
            # minimum pads would swamp the fixtures.
            from .pallas_hash import BLOCK as _PL_BLOCK
            self._block_local = _PL_BLOCK \
                if (self._single or self._is_tpu) else _FEED_BLOCK
            self._chunk_override = False
        else:
            self._block_local = max(8, ((max(chunk_rows, 8) // S) // 8) * 8)
            self._chunk_override = True
        self._init_args = {"chunk_rows": chunk_rows,
                           "max_hash_capacity": max_hash_capacity,
                           "max_topn_limit": max_topn_limit}
        # -- chip failure domains (device/supervisor.py SliceHealth) --
        # The whole-mesh runner owns ONE health board covering its
        # slices; per-slice sub-runners (placement) and degraded
        # submesh runners strike the SAME board through these links:
        #   _health          this runner IS one slice (placement slice)
        #   _failover_parent the runner whose front door serves rescues
        #   _slice_indices   the PARENT-mesh flat indices of my devices
        #                    (what device::slice_dead's argument names)
        self._health = None
        self._failover_parent = None
        self._slice_indices = tuple(range(num_shards(self._mesh)))
        from .supervisor import (
            DEFAULT_PROBE_COOLDOWN_S,
            DEFAULT_TRIP_STRIKES,
            SliceHealthBoard,
        )
        self._board = SliceHealthBoard(
            num_shards(self._mesh),
            trip_strikes=slice_trip_strikes
            if slice_trip_strikes is not None else DEFAULT_TRIP_STRIKES,
            cooldown_s=slice_probe_cooldown_s
            if slice_probe_cooldown_s is not None
            else DEFAULT_PROBE_COOLDOWN_S,
            latency_outlier_s=slice_latency_outlier_s) \
            if not self._single else None
        # elastic mesh degrade: (frozenset(dead slices), sub-runner)
        # serving whole-mesh plans on the largest healthy submesh while
        # a chip is quarantined; None = full mesh healthy
        self._degraded: Optional[tuple] = None
        self._degrade_mu = threading.Lock()
        # keyed by const-SENSITIVE plan_key: rotating constants mint a
        # fresh analysis each, so the cache is bounded (FIFO) — the
        # const-blind kernel caches below are what keep compile classes
        # logarithmic; this only memoizes the host-side plan walk
        self._plan_cache: dict = {}
        self._plan_cache_max = 4096
        self._kernel_cache: dict = {}
        # dispatch serialization: two threads launching multi-device
        # executables concurrently can interleave their per-device
        # enqueues and deadlock the mesh (launch-order inversion), and
        # the cache dicts below are not thread-safe.  The lock spans
        # enqueue AND any cold work a request needs first (feed
        # upload, kernel build/compile) — warm requests hold it for
        # ~µs, but a request that goes cold serializes its peers
        # behind the rebuild; a deliberate simplicity tradeoff, since
        # cold builds are once-per-(data version, plan).  D2H fetches —
        # the expensive part the async serving path overlaps — always
        # block OUTSIDE it.
        self._dispatch_mu = threading.Lock()
        from collections import OrderedDict
        self._scalar_cache: "OrderedDict" = OrderedDict()
        # per-plan observed-selectivity EWMAs + aggregate route counts
        # (selection.py routing); LRU-bounded like the scalar cache
        self._sel_mu = threading.Lock()
        self._sel_stats: "OrderedDict" = OrderedDict()
        self._sel_route_counts: dict = {}
        # single-slot probe seam (probe_scan_kernel): last selection
        # dispatch's (plan_key, kernel key, params, n)
        self._selmask_last: Optional[tuple] = None
        # HBM-resident feed cache — the TPU-native analog of TiKV's
        # in-memory region cache engine (components/
        # region_cache_memory_engine: RangeCacheMemoryEngine layered over
        # RocksDB).  Owned EXPLICITLY by the feed arena (device/
        # supervisor.py): per-anchor byte accounting, a configurable HBM
        # budget with frequency+recency eviction, and drop_feed teardown
        # driven by region lifecycle events — reclamation no longer
        # depends on GC timing.
        from .supervisor import FeedArena
        self._arena = FeedArena(budget_bytes=hbm_budget_bytes)
        # device flight recorder (device/supervisor.py): bounded ring
        # of recent launches feeding the device_dispatch span's attrs
        # and the status server's /debug/trace surface.  One ring per
        # PHYSICAL runner — slice/submesh sub-runners share it
        # (_make_slice_runner), so the chip's launch history reads in
        # order with per-entry slice ids.
        from .supervisor import (
            DEFAULT_FLIGHT_RECORDER_DEPTH,
            FlightRecorder,
        )
        self.flight_recorder = FlightRecorder(
            flight_recorder_depth if flight_recorder_depth is not None
            else DEFAULT_FLIGHT_RECORDER_DEPTH)
        self._mesh_desc = "x".join(
            str(d) for d in self._mesh.devices.shape)
        # scrub-quarantined anchors: id(anchor) -> (anchor, reason).
        # The next request for a quarantined anchor serves from the
        # host pipeline (its feeds are already dropped); the one after
        # re-uploads from host truth.  Own lock: the background scrub
        # thread quarantines while request threads consume/drop.
        self._quarantined: dict = {}
        self._quar_mu = threading.Lock()
        # record per-plane content digests at feed build/patch time so
        # the background scrubber can audit resident planes against them
        self.scrub_digests = True
        # device-side MVCC resolution (device/mvcc.py): lazily built —
        # host-only deployments and sharded meshes never pay for it
        self._mvcc_resolver = None
        # plan-IR join/sort/window kernels (device/join.py): lazily
        # built — DAG-only deployments never pay for it.  Single-device
        # by construction (the build dictionary commits to one chip);
        # multi-chip nodes reach it through their placement slices.
        self._joiner = None
        # hot-region → slice placement (device/placement.py): sharded
        # meshes opt in to scale-OUT routing — small regions pin to
        # single-device sub-runners spread by load, large feeds still
        # shard over the whole mesh.  Off by default: single-chip
        # deployments and whole-mesh benches skip the indirection.
        self._placer = None
        if placement and not self._single:
            from .placement import DEFAULT_WHOLE_MESH_ROWS, SlicePlacer
            self._placer = SlicePlacer(
                self, whole_mesh_rows=placement_rows
                if placement_rows is not None
                else DEFAULT_WHOLE_MESH_ROWS)
        from ..utils.metrics import DEVICE_MESH_SHARDS
        DEVICE_MESH_SHARDS.set(num_shards(self._mesh))

    def _make_slice_runner(self, mesh, slice_indices=None,
                           bind_health: bool = False) -> "DeviceRunner":
        """A sub-runner over a subset of this runner's chips: one
        placement slice (single device) or a degraded healthy submesh.
        Tuned like the parent (chunk override, capacities); the placer
        owns per-slice HBM budget splits.  ``slice_indices`` are the
        PARENT-mesh flat indices of ``mesh``'s devices — the identity
        ``device::slice_dead`` targets and the health board scores; the
        sub-runner strikes the parent's board, never a private one.

        ``bind_health`` (placement slices only): attribute this
        runner's per-request faults/latency to its slice's score.  A
        DEGRADED submesh runner must NOT bind even at 1 device — its
        requests are whole-mesh plans squeezed onto survivors, whose
        inherently-higher latency would strike (and eventually condemn)
        the last healthy chip for doing its job."""
        sub = DeviceRunner(mesh=mesh, **self._init_args)
        sub._failover_parent = self
        # the PARENT's flight recorder records this slice's launches
        # (entries carry the slice id) — one black box per chip
        sub.flight_recorder = self.flight_recorder
        if slice_indices is not None:
            sub._slice_indices = tuple(slice_indices)
            if bind_health and len(slice_indices) == 1 and \
                    self._board is not None:
                sub._health = self._board.slice(slice_indices[0])
        # one board per PHYSICAL mesh: the sub-runner must not route
        # its own degrade ladder — the parent owns that decision
        sub._board = None
        return sub

    @property
    def placer(self):
        return self._placer

    # ------------------------------------------------ chip failure domains
    #
    # Each mesh slice is a failure domain, scored like PR 3 scores a
    # store (device/supervisor.py SliceHealth): dispatch faults, fetch
    # faults, scrub quarantines and launch-latency outliers strike; a
    # tripped slice is quarantined — placement drains its anchors,
    # whole-mesh sharded plans rebuild on the largest healthy submesh
    # (8→4→2→1; parallel.mesh.healthy_submesh), in-flight work rescues
    # onto survivors — and a half-open canary re-admits it.  Host is
    # the degrade ladder's FINAL rung only.

    def _strike_board(self):
        """The board slice-attributable faults land on: my own for the
        whole-mesh runner, the parent's for slice/submesh runners
        (``_health`` owners strike through the outer fault handler
        instead, so one request never double-counts)."""
        if self._board is not None:
            return self._board
        p = self._failover_parent
        return p._board if p is not None else None

    def _slice_dead_targets(self, indices=None) -> tuple:
        """My slice indices the ``device::slice_dead`` failpoint
        currently names, () when unarmed.  Argument grammar:
        ``return(i)`` / ``return(i j)`` kills specific slices, a bare
        ``return`` kills every slice (whole-device death); percent
        prefixes make the chip FLAP instead of staying dead."""
        from ..utils.failpoint import fail_point
        fp = fail_point("device::slice_dead")
        if fp is None:
            return ()
        mine = tuple(indices) if indices is not None \
            else self._slice_indices
        v = getattr(fp, "value", None)
        if v is None or not str(v).strip():
            return mine
        try:
            targets = {int(t) for t in
                       str(v).replace(",", " ").split()}
        except ValueError:
            return mine
        return tuple(i for i in mine if i in targets)

    def _note_slice_fault(self, kind: str) -> None:
        if self._health is not None:
            if self._health.note_fault(kind):
                board = self._strike_board()
                if board is not None:
                    board._fire_trip(self._health.idx, kind)

    def _note_slice_ok(self, latency_s: Optional[float] = None) -> None:
        h = self._health
        if h is not None:
            if h.note_ok(latency_s):
                # a latency-outlier strike can be the tripping one:
                # the drain/degrade listeners must fire for it exactly
                # as for a hard fault
                board = self._strike_board()
                if board is not None:
                    board._fire_trip(h.idx, "latency")
            return
        # whole-mesh / degraded-submesh runner: a served sharded
        # request ran on EVERY one of my slices — decay them all, so a
        # re-admitted chip earns its score back under mesh traffic too
        # (latency stays None here: a whole-mesh round trip cannot
        # attribute an outlier to one chip, and striking all of them
        # would let one slow request condemn the entire mesh)
        board = self._strike_board()
        if board is not None:
            for i in self._slice_indices:
                board.slice(i).note_ok()

    def _refuse_if_quarantined(self) -> bool:
        """Early dispatch gate: a QUARANTINED slice refuses the request
        before it touches ANY per-slice state (no arena bucket, no feed
        upload, no launch — launching on a dead chip would hang the
        stream; check_no_quarantined_dispatch counts on this gate).
        → True when the caller must serve from the host pipeline."""
        from ..utils import metrics as m
        h = self._health
        if h is not None and h.quarantined():
            h.refusals += 1
            m.DEVICE_FAILOVER_COUNTER.labels("refused_dispatch").inc()
            return True
        return False

    def _preflight_slice(self) -> None:
        """Dispatch-site gate: a slice the ``device::slice_dead``
        failpoint names fails the dispatch the way the dead chip
        would (the quarantine refusal ran earlier, before any
        per-slice state was touched)."""
        hit = self._slice_dead_targets()
        if hit:
            if self._health is None:
                board = self._strike_board()
                if board is not None:
                    for i in hit:
                        board.note_fault(i, "dispatch")
            # _health owners strike once in the outer fault handler
            raise _FallbackToHost("device::slice_dead")

    def _canary(self, idx: int) -> bool:
        """One cheap half-open probe of slice ``idx``: a trivial
        committed computation through the real runtime, gated by the
        same slice_dead failpoint a live dispatch would hit — a
        persistently-dead chip keeps failing its canary until the
        fault lifts."""
        try:
            if self._slice_dead_targets(indices=(idx,)):
                return False
            pos = self._slice_indices.index(idx) \
                if idx in self._slice_indices else idx
            dev = self._mesh.devices.flat[pos]
            x = jax.device_put(np.arange(8, dtype=np.int64), dev)
            return int(np.asarray(jnp.sum(x))) == 28
        except Exception:   # noqa: BLE001 — any runtime error = dead
            return False

    def probe_quarantined(self) -> int:
        """Half-open probing for quarantined slices (the supervisor's
        scrub loop and the routing paths call this opportunistically;
        the board's per-slice cooldown + single-probe gate bound the
        work).  → probes run."""
        if self._board is None:
            return 0
        return self._board.maybe_probe(self._canary)

    def _degraded_sub(self) -> Optional["DeviceRunner"]:
        """Locked snapshot of the current degraded-submesh runner (the
        one surface stats/budget/teardown fold it through), or None."""
        with self._degrade_mu:
            return self._degraded[1] if self._degraded is not None \
                else None

    def _degraded_target(self) -> Optional["DeviceRunner"]:
        """The runner whole-mesh plans should use right now: a sub-
        runner over the largest healthy submesh while any slice is
        quarantined (8→4→2→1 — re-minting sharded feeds from host
        truth onto the survivors), self's own mesh when healthy.
        Raises _FallbackToHost when no healthy submesh exists or the
        rebuild itself faults (``device::mesh_rebuild``) — host is the
        final rung of the ladder, never the first."""
        board = self._board
        if board is None:
            return None
        self.probe_quarantined()
        dead = board.quarantined_set()
        from ..utils import metrics as m
        from ..utils import tracker
        with self._degrade_mu:
            if not dead:
                if self._degraded is not None:
                    # every slice re-admitted: the full mesh takes over
                    # and the submesh feeds release their HBM (the full
                    # mesh re-mints from host truth on first touch)
                    old = self._degraded[1]
                    self._degraded = None
                    old._arena.drop_all(reason="drop")
                    m.DEVICE_FAILOVER_COUNTER.labels(
                        "mesh_restore").inc()
                return None
            key = frozenset(dead)
            if self._degraded is None or self._degraded[0] != key:
                _fp_degrade("device::mesh_rebuild")
                from ..parallel import healthy_submesh
                devs = healthy_submesh(self._mesh, dead)
                if devs is None:
                    raise _FallbackToHost("no healthy submesh")
                flat = list(self._mesh.devices.flat)
                gidx = tuple(flat.index(d) for d in devs)
                with tracker.phase("mesh_rebuild"):
                    sub = self._make_slice_runner(
                        make_mesh(devs), slice_indices=gidx)
                    sub._arena.budget_bytes = self._arena.budget_bytes
                if self._degraded is not None:
                    self._degraded[1]._arena.drop_all(reason="failover")
                # the full-mesh feeds span the dead chip — useless now;
                # in-flight dispatches keep their own buffer references
                self._arena.drop_all(reason="failover")
                self._degraded = (key, sub)
                m.DEVICE_FAILOVER_COUNTER.labels("mesh_downsize").inc()
            return self._degraded[1]

    def _rescue(self, dag: DAGRequest, storage):
        """In-flight rescue: a request whose slice died between
        dispatch and fetch retries ONCE through the failover root's
        front door — the placer re-pins its anchor onto a healthy
        slice, or the degraded submesh serves it — instead of burning
        the host rung on a provably-dead chip.  → a finished
        SelectResult, or None when this runner is not actually sick
        (the ordinary host-degrade contract then applies unchanged).
        Never touches this runner's pins: the caller's exactly-once
        unpin discipline stands."""
        from ..utils import metrics as m
        from ..utils import tracker
        try:
            h = self._health
            hit = self._slice_dead_targets()
            sick = h is not None and h.quarantined()
            if hit:
                sick = True
                if h is not None:
                    # a targeted persistent death needs no three-strike
                    # deliberation: trip now so the placer drains and
                    # the retry routes around this slice
                    board = self._strike_board()
                    if h.trip("slice_dead") and board is not None:
                        board._fire_trip(h.idx, "slice_dead")
                else:
                    board = self._strike_board()
                    if board is not None:
                        for i in hit:
                            board.trip(i, "slice_dead")
            if not sick and self._board is not None and \
                    self._board.quarantined_set():
                sick = True     # mesh already degraded: reroute
            if not sick:
                return None
            target = self._failover_parent
            if target is None:
                target = self if self._board is not None else None
            if target is None:
                return None
            m.DEVICE_FAILOVER_COUNTER.labels("rescue").inc()
            tracker.label("device_rescue", "slice_failover")
            return target.handle_request(dag, storage)
        except Exception:   # noqa: BLE001 — rescue is best-effort;
            return None     # the host rung follows

    def failure_domain_stats(self) -> dict:
        """Per-slice health + degrade rollup (/health device_health)."""
        out: dict = {"n_slices": len(self._slice_indices),
                     "slices": self._board.stats()
                     if self._board is not None else []}
        with self._degrade_mu:
            if self._degraded is not None:
                dead, sub = self._degraded
                out["degraded"] = {
                    "dead_slices": sorted(dead),
                    "healthy_devices": num_shards(sub._mesh)}
        return out

    def close(self) -> None:
        """Teardown: drop every device-resident line (node.stop()
        orders this after the endpoint/completion pool drain, so pins
        are already released), retire any degraded submesh runner, and
        clear quarantine state — an in-process restart starts clean
        with no leaked HBM accounting.  Idempotent."""
        if self._placer is not None:
            for r in self._placer.slices:
                r.close()
        with self._degrade_mu:
            if self._degraded is not None:
                self._degraded[1].close()
                self._degraded = None
        self._arena.drop_all(reason="drop")
        with self._quar_mu:
            self._quarantined.clear()
        if self._board is not None:
            self._board.reset()

    def mesh_stats(self) -> dict:
        """Mesh shape + placement rollup for /health."""
        shape = dict(zip(ROW_AXES,
                         (int(s) for s in self._mesh.devices.shape)))
        out = {"shape": shape,
               "n_devices": num_shards(self._mesh),
               "platform": self._mesh.devices.flat[0].platform}
        if self._placer is not None:
            out["placement"] = self._placer.stats()
        return out

    def mvcc_resolver(self, create: bool = True):
        """The runner's DeviceMvccResolver (the cold-path kill: flat
        CF_WRITE planes resolve newest-version-≤-read_ts on device and
        the feed is born resident).  Single-device only — a sharded
        mesh's cold builds keep the host upload pipeline (the resolve
        output is committed to one chip; re-laying it across shards
        would pay the D2H+H2D the device build exists to avoid)."""
        if self._mvcc_resolver is None and create and self._single:
            from .mvcc import DeviceMvccResolver
            self._mvcc_resolver = DeviceMvccResolver(self)
        return self._mvcc_resolver

    def joiner(self) -> "object":
        """The runner's DeviceJoiner (plan-IR join/sort/window kernels,
        device/join.py).  Single-device runners only — a whole-mesh
        sharded runner's joins route host or to a placement slice (the
        plan executor owns that choice)."""
        if self._joiner is None:
            from .join import DeviceJoiner
            self._joiner = DeviceJoiner(self)
            if self._arena.budget_bytes > 0:
                # a budget set before the joiner existed binds it too
                self._joiner.set_budget(self._arena.budget_bytes // 8)
        return self._joiner

    # ------------------------------------------------------------------ plan

    def supports(self, dag: DAGRequest) -> bool:
        return self._analyze(dag) is not None

    def profitable(self, dag: DAGRequest) -> bool:
        """Should auto-routing pick the device for this plan?

        Aggregations and TopN reduce on device (tiny D2H readback) and
        measure far above the host path.  Selections ride the device
        too since the late-materialization pass (selection.py): the
        predicate evaluates over the resident HBM feed and only a
        COMPACT selection vector crosses D2H — n/8 bytes of packed
        bitmask, 4·K bytes of compacted indices, or K rows of compacted
        low-width columns, whichever the router's cost model picks —
        so a selection's transfer now scales with SELECTED rows, not
        scanned rows.  The remaining selection→host case is
        selectivity-driven, not structural: past ~95% observed
        selectivity (per-plan EWMA seeded by the device-side count) the
        shared k-row materialization dominates both paths and the host
        pipeline answers without the dispatch round trip; periodic
        re-probes rediscover workloads whose selectivity drifts back
        down.  The SIZE crossover lives in
        Endpoint.device_row_threshold (rationale there) — and under
        concurrency it is a conservative bound, since the request
        coalescer (server/coalescer.py) amortizes the launch + D2H
        sync this gate exists to avoid paying per-request: the cost
        router in front of the device backend re-decides per request
        with the fixed tax divided by group occupancy.
        force_backend="device" still runs declined shapes for parity
        testing, and a forced/direct call always dispatches the real
        kernels regardless of the EWMA.
        """
        plan = self._analyze(dag)
        if plan is None:
            return False
        if plan.kind == "scan_sel":
            return bool(plan.sel_rpns) and \
                self._sel_allows_device(self._sel_keys(dag, plan))
        return plan.kind in ("simple_agg", "hash_agg", "topn")

    # -- cross-request batching (server/coalescer.py) --

    def batch_class(self, dag: DAGRequest, storage):
        """Coalescing identity for this request, or None if it cannot
        share a dispatch.

        Two requests grouped under the same key are served by ONE
        device launch.  ``("stack", ...)`` keys mark selections whose
        predicate constants are hoisted into traced scalar params
        (selection.split_params): differing thresholds within one
        const-blind ``shape_key`` stack as a leading axis of the params
        and evaluate in one vmapped dispatch.  ``("share", ...)`` keys
        mark byte-identical plans (same exact ``plan_key``, incl.
        output offsets): one dispatch + one fetch serves every member
        (the thundering-herd dashboard-query case) — aggregations and
        param-less selections batch this way.  Either way the members
        must target a CO-RESIDENT feed: same anchor (snapshot /
        lineage identity), same data generation, same ranges.

        The stacked kernel itself is single-device, but a sharded mesh
        is no longer excluded: with placement on, the request routes
        to its anchor's single-device SLICE and coalesces there (the
        slice id joins the key so groups never straddle chips); only
        whole-mesh sharded dispatches — already launch-amortized by
        GSPMD — stay uncoalesced.
        """
        if not hasattr(storage, "scan_columns"):
            return None
        if self._placer is not None:
            target = self._placer.route(storage)
            if target is not self:
                key = target.batch_class(dag, storage)
                return None if key is None \
                    else ("slice", id(target)) + key
        if not self._single:
            return None
        plan = self._analyze(dag)
        if plan is None:
            return None
        anchor = self._feed_anchor(storage)
        lineage = getattr(storage, "feed_lineage", None)
        req_v = getattr(storage, "feed_version", None)
        if lineage is not None and req_v is None:
            req_v = lineage.version
        if plan.kind == "scan_sel" and plan.sel_rpns:
            if plan.sel_params is None:
                from . import selection as selmod
                plan.sel_params = selmod.split_params(
                    plan.sel_rpns, len(plan.used_cols))
            _rpns, _vals, dts = plan.sel_params
            if dts:
                from .selection import shape_key
                return ("stack", id(anchor), req_v, shape_key(plan),
                        dts, dag.ranges, dag.output_offsets)
        if plan.kind in ("simple_agg", "hash_agg", "topn", "scan_sel"):
            return ("share", id(anchor), req_v, dag.plan_key(),
                    dag.ranges)
        return None

    def handle_batched(self, members) -> "_BatchedSelectionGroup":
        """ONE stacked dispatch for ``members`` — a list of
        ``(dag, storage)`` pairs sharing a ``("stack", ...)``
        batch_class.  Returns a :class:`_BatchedSelectionGroup`; raises
        :class:`_BatchUnavailable` when the group cannot be served as
        one launch (the caller retries members solo)."""
        if self._placer is not None and members:
            target = self._placer.route(members[0][1])
            if target is not self:
                return target.handle_batched(members)
        from . import selection as selmod
        stacks = []
        for dag, _s in members:
            plan = self._analyze(dag)
            if plan is None or plan.kind != "scan_sel":
                raise _BatchUnavailable("not a stacked selection plan")
            if plan.sel_params is None:
                plan.sel_params = selmod.split_params(
                    plan.sel_rpns, len(plan.used_cols))
            stacks.append(plan.sel_params[1])
        lead_dag, lead_storage = members[0]
        got = self.handle_request(lead_dag, lead_storage, deferred=True,
                                  _stack=tuple(stacks))
        if not isinstance(got, _GroupPending):
            # the run settled synchronously (zero rows, quarantine,
            # sticky force-host) — those edges carry per-request
            # semantics the solo path owns
            raise _BatchUnavailable("batched dispatch unavailable")
        return _BatchedSelectionGroup(self, got, list(members))

    # -- selectivity-adaptive selection routing (selection.py) --

    _SEL_EWMA_ALPHA = 0.3
    _SEL_REPROBE = 16       # host-routed plans re-try the device every N

    def _sel_keys(self, dag: DAGRequest, plan: _Plan) -> tuple:
        """(exact, shape) stat keys.  Exact = the const-inclusive plan
        key: repeated identical queries get a precise per-threshold
        EWMA.  Shape = the const-blind predicate structure + table: a
        parameterized workload rotating constants (`v > ?`) still warms
        at this level instead of minting a cold stat per value."""
        if plan.sel_stat_key is None:
            from .selection import shape_key
            plan.sel_stat_key = ("shape",
                                 getattr(plan.scan, "table_id", 0),
                                 shape_key(plan))
        return dag.plan_key(), plan.sel_stat_key

    def _sel_stat(self, key, create: bool = True):
        with self._sel_mu:
            st = self._sel_stats.get(key)
            if st is None and create:
                st = self._sel_stats[key] = \
                    {"ewma": None, "n_obs": 0, "probe_tick": 0}
                while len(self._sel_stats) > 256:
                    self._sel_stats.popitem(last=False)
            elif st is not None:
                self._sel_stats.move_to_end(key)
            return st

    def _sel_observe(self, keys, sel: float) -> None:
        from ..utils import metrics as m
        for key in keys:
            st = self._sel_stat(key)
            with self._sel_mu:
                st["ewma"] = sel if st["ewma"] is None else \
                    (self._SEL_EWMA_ALPHA * sel +
                     (1 - self._SEL_EWMA_ALPHA) * st["ewma"])
                st["n_obs"] += 1
        m.DEVICE_SEL_SELECTIVITY.set(sel)

    def _sel_allows_device(self, keys) -> bool:
        from .selection import HOST_SELECTIVITY_CUTOFF
        exact, shape = keys
        st = self._sel_stat(exact, create=False)
        if st is None or st["n_obs"] < 2:
            # no exact history: the shape-level aggregate decides, at a
            # higher confidence bar (it blends thresholds)
            st = self._sel_stat(shape, create=False)
            if st is None or st["n_obs"] < 4:
                return True
        if st["ewma"] < HOST_SELECTIVITY_CUTOFF:
            return True
        with self._sel_mu:
            st["probe_tick"] += 1
            if st["probe_tick"] >= self._SEL_REPROBE:
                st["probe_tick"] = 0
                return True
        return False

    def _sel_predict(self, keys) -> Optional[float]:
        """EWMA selectivity once warm (≥3 observations; exact plan key
        preferred, const-blind shape key as fallback), else None — a
        None sends the request down the cold mask route."""
        for key in keys:
            st = self._sel_stat(key, create=False)
            if st is not None and st["n_obs"] >= 3:
                return st["ewma"]
        return None

    def selection_stats(self) -> dict:
        """Routing-decision + observed-selectivity rollup (/health).
        With placement on, slice runners' route counts fold in (the
        requests execute there)."""
        with self._sel_mu:
            plans = [{"ewma": round(st["ewma"], 4)
                      if st["ewma"] is not None else None,
                      "n_obs": st["n_obs"]}
                     for st in list(self._sel_stats.values())[-8:]]
            routes = dict(self._sel_route_counts)
        if self._placer is not None:
            for r in self._placer.slices:
                for k, v in r.selection_stats()["routes"].items():
                    routes[k] = routes.get(k, 0) + v
        return {"routes": routes, "plans": plans}

    def _analyze(self, dag: DAGRequest) -> Optional[_Plan]:
        key = dag.plan_key()
        if key in self._plan_cache:
            return self._plan_cache[key]
        plan = self._analyze_uncached(dag)
        if len(self._plan_cache) >= self._plan_cache_max:
            # unlocked callers race this FIFO evict (read-pool threads,
            # dispatcher, completion workers): pop defensively — a lost
            # race transiently overshoots the bound by a thread or two,
            # which is fine; raising on the dispatch path is not
            try:
                self._plan_cache.pop(next(iter(self._plan_cache)), None)
            except (StopIteration, KeyError, RuntimeError):
                pass
        self._plan_cache[key] = plan
        return plan

    def _analyze_uncached(self, dag: DAGRequest) -> Optional[_Plan]:
        execs = dag.executors
        # IndexScan heads are device-eligible too: a covering index scan
        # produces columnar (indexed cols, handle) tiles exactly like a
        # table scan (BASELINE config 5 — TopN via IndexScan; reference:
        # index_scan_executor.rs feeds the same BatchExecutor pipeline)
        if not execs or not isinstance(execs[0],
                                       (TableScanDesc, IndexScanDesc)):
            return None
        scan = execs[0]
        if isinstance(scan, IndexScanDesc):
            n_idx = len(scan.columns) - (
                1 if scan.columns and scan.columns[-1].is_pk_handle else 0)
            if n_idx != 1:
                return None     # multi-column index → host row path
        scan_ets = [c.field_type.eval_type for c in scan.columns]

        sel_rpns: list[RpnExpression] = []
        terminal = None
        for d in execs[1:]:
            if isinstance(d, SelectionDesc):
                if terminal is not None:
                    return None
                for cond in d.conditions:
                    sel_rpns.append(build_rpn(cond))
            elif isinstance(d, (AggregationDesc, TopNDesc)):
                if terminal is not None:
                    return None
                terminal = d
            else:
                return None     # projection/limit → host path

        rpns_to_check = list(sel_rpns)
        plan = _Plan(scan=scan, kind="scan", used_cols=[])

        if isinstance(terminal, AggregationDesc):
            if len(terminal.group_by) > 1:
                return None
            agg_rpns, specs = [], []
            for i, a in enumerate(terminal.aggs):
                if a.kind not in ("count", "count_star", "sum", "avg",
                                 "min", "max", "first", "var_pop",
                                 "var_samp", "stddev_pop", "stddev_samp"):
                    # bit_and/or/xor: no XLA scatter-bitop lowering on TPU
                    # → host (they're exact int ops; host numpy is fine)
                    return None
                if a.arg is not None:
                    r = build_rpn(a.arg)
                    if r.ret_type in _TIME_ETS and a.kind not in (
                            "count", "min", "max", "first"):
                        return None     # SUM(datetime) etc. → host
                    agg_rpns.append(r)
                    rpns_to_check.append(r)
                    specs.append(AggSpec(a.kind, i, r.ret_type))
                else:
                    agg_rpns.append(None)
                    specs.append(AggSpec(a.kind, i))
            if terminal.group_by:
                if any(s.kind == "first" for s in specs):
                    return None     # FIRST needs source-row gather → host
                key_rpn = build_rpn(terminal.group_by[0])
                if key_rpn.ret_type is not EvalType.INT:
                    return None
                rpns_to_check.append(key_rpn)
                plan.kind = "hash_agg"
                plan.key_rpn = key_rpn
            else:
                plan.kind = "simple_agg"
            plan.specs = specs
            plan.agg_rpns = agg_rpns
        elif isinstance(terminal, TopNDesc):
            if len(terminal.order_by) != 1 or \
                    terminal.limit > self._max_topn_limit:
                return None
            order_expr, desc = terminal.order_by[0]
            order_rpn = build_rpn(order_expr)
            if order_rpn.ret_type not in _DEVICE_ETS:
                return None
            rpns_to_check.append(order_rpn)
            plan.kind = "topn"
            plan.order_rpn = order_rpn
            plan.order_desc = desc
            plan.limit = terminal.limit
        elif sel_rpns:
            plan.kind = "scan_sel"
        else:
            return None     # bare scan: decode-bound, no device win

        for r in rpns_to_check:
            if not _rpn_device_safe(r, scan_ets):
                return None

        used = sorted(set().union(*[_rpn_col_indices(r) for r in rpns_to_check])
                      if rpns_to_check else set())
        if plan.kind == "scan_sel" and self._single and \
                isinstance(scan, TableScanDesc):
            # late-materialized selection: when EVERY scan column
            # round-trips its device dtype losslessly (value-checked int
            # narrowing; REAL's f32 does not, unsigned BIGINT may exceed
            # int64), ship them all so the compact route can materialize
            # the k-row output on device and skip the host gather
            # entirely (selection.py).  Otherwise only the predicate
            # columns go to HBM and the mask/index routes gather on
            # host.  The mask and index routes run sharded (per-shard
            # packbits/compaction, psum'd count); only COMPACT stays
            # single-device — its gather output is committed to one
            # chip by construction, so widening a whole-mesh feed
            # would waste H2D/HBM there.  Placement-routed requests
            # land on a single-device slice and keep the route.
            lossless = (EvalType.INT, EvalType.DATETIME, EvalType.DURATION)
            if all(c.is_pk_handle or
                   (c.field_type.eval_type in lossless and
                    not c.field_type.is_unsigned)
                   for c in scan.columns):
                used = sorted(set(used) | set(range(len(scan.columns))))
                plan.compact_ok = True
        mapping = {old: new for new, old in enumerate(used)}
        plan.used_cols = used
        plan.sel_rpns = [_remap_rpn(r, mapping) for r in sel_rpns]
        plan.agg_rpns = [None if r is None else _remap_rpn(r, mapping)
                         for r in plan.agg_rpns]
        if plan.key_rpn is not None:
            plan.key_rpn = _remap_rpn(plan.key_rpn, mapping)
        if plan.order_rpn is not None:
            plan.order_rpn = _remap_rpn(plan.order_rpn, mapping)
        return plan

    # ------------------------------------------------------------------ scan

    def _scan_batch(self, dag: DAGRequest, plan: _Plan, storage) -> ColumnBatch:
        if hasattr(storage, "scan_columns"):
            return storage.scan_columns(plan.scan, dag.ranges)
        from ..executors.scan import (
            BatchIndexScanExecutor,
            BatchTableScanExecutor,
        )
        cls = BatchIndexScanExecutor if isinstance(plan.scan, IndexScanDesc) \
            else BatchTableScanExecutor
        ex = cls(storage, plan.scan, dag.ranges)
        chunks = []
        while True:
            r = ex.next_batch(1024)
            if r.batch.num_rows:
                chunks.append(r.batch)
            if r.is_drained:
                break
        return ColumnBatch.concat(chunks) if chunks \
            else ColumnBatch.empty(plan.scan.schema)

    # ------------------------------------------------------------- feed (v2)

    def _nshards(self) -> int:
        return 1 if self._single else num_shards(self._mesh)

    def _feed_unit(self) -> int:
        return self._nshards() * self._block_local

    def _pad_rows(self, n: int) -> int:
        unit = self._feed_unit()
        blocks = max(1, -(-n // unit))
        # bucket the block count into a 9/8-geometric grid: every
        # padded shape is a compile class (pallas grid + XLA scan
        # length), and live regions change size on every write — exact
        # padding would recompile the kernels on each data version.
        # Bucketing bounds the number of compile classes
        # logarithmically and taxes ONLY the cache key, never the
        # computed extent: blocks past the live rows skip their MXU /
        # aggregation work (pl.when dead-block guard in pallas_hash,
        # lax.cond guard in _mega's scan step), so the ≤12.5% padding
        # costs DMA + grid steps, not kernel time.
        if not self._chunk_override and blocks > 8:
            # one block of growth headroom BEFORE bucketing: it only
            # moves sizes that land exactly on a bucket edge (ceil
            # absorbs it everywhere else), so a feed whose live rows
            # exactly fill its bucket — e.g. a power-of-two bulk load —
            # no longer changes compile class (≈30s XLA recompile +
            # full re-upload) on the very first appended row
            blocks += 1
            # round up to a 4-significant-bit block count (k·2^s,
            # 8 ≤ k ≤ 15): keeps n_pad rich in powers of two so
            # _pick_chunk's gcd still finds large scan chunks
            s = blocks.bit_length() - 4
            k = -(-blocks // (1 << s))
            if k > 15:
                s += 1
                k = -(-blocks // (1 << s))
            blocks = k << s
        return blocks * unit

    def _pick_chunk(self, n_pad: int, desired: int) -> int:
        """Largest scan-block size ≤ desired that divides the padded feed
        and splits evenly over shards."""
        unit = self._feed_unit()
        if self._chunk_override:
            desired = unit
        desired = max(unit, (desired // unit) * unit)
        return math.gcd(n_pad, desired)

    def _build_flat(self, host_cols, n: int) -> dict:
        """→ {"flat": device arrays, "null_flags": per-col bool, "n_pad"}.

        One flat padded array per column value; a validity array only for
        columns that actually contain NULLs — all-valid columns reuse the
        on-device row mask (synthesized from iota < n), saving the HBM
        footprint and H2D bandwidth of an all-true mask.
        """
        n_pad = self._pad_rows(n)
        flat, flags = [], []

        def put_padded(arr, dtype):
            if self._single:
                if n_pad == n:
                    return jnp.asarray(arr)
                # pad on the HOST: a device-side concatenate would
                # compile per exact n (every data version has a new row
                # count), costing seconds per cache rebuild; a host
                # memcpy is shape-oblivious
                p = np.zeros(n_pad, dtype=arr.dtype)
                p[:n] = arr
                return jnp.asarray(p)
            p = np.zeros(n_pad, dtype=dtype)
            p[:n] = arr
            return jax.device_put(p, self._row_sharding)

        from .supervisor import host_plane_digest
        digests = [] if self.scrub_digests else None
        for v, ok in host_cols:
            flat.append(put_padded(v, v.dtype))
            has_nulls = not bool(ok.all())
            flags.append(has_nulls)
            if digests is not None:
                # recorded from the HOST truth at build time: the scrub
                # later re-hashes the resident device plane and compares
                digests.append(host_plane_digest(v, n))
            if has_nulls:
                flat.append(put_padded(ok, np.bool_))
                if digests is not None:
                    digests.append(host_plane_digest(ok, n))
        feed = {"flat": tuple(flat), "null_flags": tuple(flags),
                "n_pad": n_pad}
        if digests is not None:
            feed["digests"] = tuple(digests)
            feed["n_live"] = n
            # pre-register the digest kernels now (cold path) so the
            # warm patch path's incremental digest update mints no new
            # kernel cache entries — compile classes stay churn-stable
            for a in feed["flat"]:
                self._range_digest_kernel(a.dtype, a.shape[0])
        return feed

    @staticmethod
    def _feed_anchor(storage):
        """Feed/meta cache key object.  Delta-maintained snapshots carry
        a ``feed_lineage`` whose identity is stable across patch
        generations (copr/region_cache.py FeedLineage) — anchoring on it
        keeps the HBM feed warm across writes; plain snapshots anchor on
        themselves (invalidation by identity, as before)."""
        lineage = getattr(storage, "feed_lineage", None)
        return storage if lineage is None else lineage

    def _get_feed(self, storage, feed_key, host_cols, n: int,
                  lineage=None, used_infos=None, dtypes=None,
                  positional: bool = False, req_v=None) -> dict:
        from ..utils import tracker
        cache = None
        anchor = None
        if storage is not None and feed_key is not None and \
                hasattr(storage, "scan_columns"):
            anchor = self._feed_anchor(storage)
            cache = self._arena.bucket(anchor)
        feed = cache.get(feed_key) if cache is not None else None
        if feed is not None:
            fv = feed.get("lineage_v")
            if lineage is None or fv == req_v:
                tracker.label("device_feed", "hit")
                return feed
            if fv is not None and fv > req_v:
                # an older-generation read (history serve): never
                # downgrade the shared feed — build a private one
                cache = None
                feed = None
            elif positional and self._try_patch_feed(
                    feed, lineage, used_infos, dtypes, n, req_v):
                # the snapshot moved forward under the feed: replay only
                # the journal's dirty row spans into HBM instead of a
                # cold re-upload — bucketed padding keeps n_pad (the
                # compile class) stable across small deltas
                tracker.label("device_feed", "patch")
                self._register_digests(lineage, feed_key, feed)
                return feed
        # device-side region split (supervisor.on_region_split): the
        # parent feed was sliced by key range INTO this child lineage's
        # stash — consume it instead of re-uploading from host.  The
        # stash was digest-verified against the child's host truth at
        # split time, so serving it is as safe as serving a scrubbed
        # resident feed.
        if lineage is not None and positional and cache is not None and \
                getattr(lineage, "split_stash", None):
            feed = self._take_split_feed(lineage, feed_key, n)
            if feed is not None:
                cache[feed_key] = feed
                self._arena.admit(anchor)
                if feed.get("lineage_v") == req_v or self._try_patch_feed(
                        feed, lineage, used_infos, dtypes, n, req_v):
                    tracker.label("device_feed", "split")
                    self._register_digests(lineage, feed_key, feed)
                    return feed
                # the child moved past the stash and the journal could
                # not bridge it: fall through to the upload (which
                # replaces the cache entry)
                feed = None
        # cold-path kill (device/mvcc.py): a device build left its
        # resolve artifacts on the lineage — mint the feed BORN
        # RESIDENT (H2D of raw version planes — or nothing, if the
        # streaming ingest pipeline already uploaded them — plus ONE
        # resolve+gather dispatch) instead of the host pad/astype/upload
        # pass.  One-shot and version-pinned; any failure falls through
        # to the plain upload below, which is always correct.
        if lineage is not None and \
                getattr(lineage, "cold_bundle", None) is not None:
            if positional and cache is not None:
                bundle = lineage.take_cold(req_v)
                if bundle is not None:
                    feed = bundle.mint(self, used_infos, dtypes, n,
                                       self._pad_rows(n))
                    if feed is not None:
                        tracker.label("device_feed", "device_resolve")
                        feed["lineage_v"] = req_v
                        self._mark_splittable(feed, used_infos)
                        cache[feed_key] = feed
                        self._arena.admit(anchor)
                        self._register_digests(lineage, feed_key, feed)
                        return feed
            else:
                # first feed build for this line cannot consume the
                # bundle (desc/index scan): release the raw planes
                # now rather than pinning ~100 bytes/version on the
                # lineage until a delta or teardown gets there
                lineage.drop_cold()
        tracker.label("device_feed", "upload")
        _fp_degrade("device::before_feed_upload")
        with tracker.phase("feed_upload"):
            feed = self._build_flat(host_cols(), n)
        if lineage is not None:
            feed["lineage_v"] = req_v
        if positional:
            self._mark_splittable(feed, used_infos)
        if cache is not None:
            cache[feed_key] = feed
            # admission runs under the dispatch lock (this call site):
            # the budget check may evict other, unpinned anchors
            self._arena.admit(anchor)
            self._register_digests(lineage, feed_key, feed)
        return feed

    @staticmethod
    def _register_digests(lineage, feed_key, feed) -> None:
        """Mirror the feed's per-plane digests into the FeedLineage's
        host-visible journal — the line-level audit record the
        supervisor reports (region_cache.py FeedLineage)."""
        if lineage is not None and feed.get("digests") is not None and \
                hasattr(lineage, "feed_digests"):
            lineage.feed_digests[feed_key] = (feed.get("lineage_v"),
                                              feed["digests"])

    def _try_patch_feed(self, feed, lineage, used_infos, dtypes,
                        n: int, req_v=None) -> bool:
        """Apply the lineage's dirty row spans to the device feed in
        place of a cold upload.  Only sound when the patch journal
        covers the gap with pure row patches (no repack/compaction/
        tombstones), positions map 1:1 (full-snapshot ascending feed),
        the padded shape is unchanged, and every patched value fits the
        feed's established device dtypes.  Sharded feeds patch too:
        GSPMD partitions the update and ``_dus`` pins the result back
        to the row sharding."""
        if used_infos is None or dtypes is None:
            return False
        patches = lineage.since(feed.get("lineage_v", -1), until=req_v)
        if patches is None or any(p.get("structural") for p in patches):
            return False
        if patches and patches[-1]["n"] != n:
            return False        # ranged feed: positions do not map 1:1
        if self._pad_rows(max(n, 1)) != feed["n_pad"]:
            return False        # row count crossed a pad bucket
        # flat index of each used column's value plane
        plane = []
        fi = 0
        for has_nulls in feed["null_flags"]:
            plane.append(fi)
            fi += 2 if has_nulls else 1
        from ..utils import tracker
        flat = list(feed["flat"])
        digests = list(feed["digests"]) \
            if self.scrub_digests and feed.get("digests") is not None \
            else None
        with tracker.phase("feed_patch"):
            for p in patches:
                for span in p["spans"]:
                    lo = span["lo"]
                    for ci, info in enumerate(used_infos):
                        dt = np.dtype(dtypes[ci])
                        if info.is_pk_handle:
                            vals = span["handles"]
                            valid = None
                        else:
                            vals, valid = span["cols"][info.col_id]
                        if not _fits_dtype(vals, valid, dt):
                            return False
                        if valid is not None and not valid.all() and \
                                not feed["null_flags"][ci]:
                            # first NULL in an all-valid column would
                            # change the compile class: rebuild
                            return False
                        fi = plane[ci]
                        flat[fi] = self._patch_plane(
                            feed, digests, flat, fi,
                            np.ascontiguousarray(
                                vals.astype(dt, copy=False)), lo)
                        if feed["null_flags"][ci]:
                            mask = valid if valid is not None else \
                                np.ones(len(vals), np.bool_)
                            flat[fi + 1] = self._patch_plane(
                                feed, digests, flat, fi + 1,
                                np.ascontiguousarray(mask), lo)
        feed["flat"] = tuple(flat)
        feed["lineage_v"] = req_v
        if digests is not None:
            feed["digests"] = tuple(digests)
            feed["n_live"] = n
        return True

    def _patch_plane(self, feed, digests, flat, fi: int,
                     update: np.ndarray, lo: int):
        """One plane's span patch + INCREMENTAL digest maintenance:
        ``R' = R - H_span(old device plane) + H_span(new host data)``.
        Never re-hashes the whole plane from device state — doing so
        would launder any HBM corruption that landed since the last
        scrub into the recorded digest (the recorded value must stay
        anchored to the host-truth chain, so a pre-existing corruption
        delta survives arithmetically and the next scrub still catches
        it, wherever it sits relative to the patched span).  All device
        scalars — nothing blocks under the dispatch lock."""
        old = flat[fi]
        new = self._dus(old, update, lo)
        if digests is not None:
            hi = lo + len(update)
            rng = self._range_digest_kernel(old.dtype, old.shape[0])
            lo_arr = jnp.asarray(lo, jnp.int64)
            hi_arr = jnp.asarray(hi, jnp.int64)
            d_old = rng(old, lo_arr, hi_arr)
            d_new = rng(new, lo_arr, hi_arr)
            digests[fi] = jnp.uint64(digests[fi]) - d_old + d_new
        return new

    def _dus(self, arr, update, lo: int):
        """Jitted in-place-style slice update (dynamic_update_slice);
        the start index is traced, so repeated single-row patches at
        different positions share one compile class per update length.
        On a sharded feed GSPMD partitions the update and the jit's
        ``out_shardings`` pins the result to the row sharding in the
        SAME dispatch — no post-hoc device_put re-lay, so delta churn
        on a sharded feed costs one small collective-free launch per
        span, exactly like the single-device path."""
        fn = self._kernel_cache.get("feed_patch_fn")
        if fn is None:
            def _upd(a, u, i):
                return lax.dynamic_update_slice(a, u, (i,))
            fn = self._kernel_cache["feed_patch_fn"] = jax.jit(_upd) \
                if self._single else \
                jax.jit(_upd, out_shardings=self._row_sharding)
        return fn(arr, update, jnp.asarray(lo, jnp.int32))

    # ------------------------------------- device-state supervision
    #
    # The runner side of device/supervisor.py: explicit feed teardown
    # (drop_feed replaces GC-timed reclamation), HBM accounting, the
    # on-device digest leaf the scrubber re-hashes resident planes
    # with, and the quarantine gate a scrub divergence arms.

    def set_hbm_budget(self, nbytes: int) -> None:
        """Set (or clear, 0) the HBM budget and enforce it NOW — an
        online shrink must not wait for the next feed admission to
        sweep resident state under the new cap.  With placement on,
        the slices split the budget evenly (each owns a disjoint
        anchor set); this whole-mesh arena keeps the full figure for
        the feeds that shard over every chip."""
        self._arena.budget_bytes = int(nbytes)
        self._arena.enforce()
        if self._joiner is not None and nbytes > 0:
            # the join build/probe cache (device/join.py) takes a fixed
            # 1/8 slice of the node budget — the operator's HBM cap
            # bounds join state too, not only the feed arena
            self._joiner.set_budget(int(nbytes) // 8)
        if self._placer is not None:
            self._placer.set_hbm_budget(int(nbytes))
        degraded = self._degraded_sub()
        if degraded is not None:
            degraded._arena.budget_bytes = int(nbytes)
            degraded._arena.enforce()

    def pinned_readback_stats(self) -> dict:
        """Pinned D2H staging pool rollup (/health fastpath)."""
        return HOST_STAGER.stats()

    def hbm_stats(self) -> dict:
        out = self._arena.stats()
        # join build/probe planes (device/join.py) are device-resident
        # bytes too: reported beside the arena figure (bounded by their
        # own slice of the budget, enforced in set_hbm_budget)
        out["join_cache_bytes"] = self._joiner.resident_bytes() \
            if self._joiner is not None else 0
        with self._quar_mu:
            out["quarantined"] = len(self._quarantined)
        # per-tenant residency (resource_control enforcement surface):
        # whose bytes sit in HBM right now, by owning resource group
        out["residency_by_tenant"] = self._arena.residency_by_tenant()
        subs = [r for r in self._placer.slices] \
            if self._placer is not None else []
        degraded = self._degraded_sub()
        if degraded is not None:
            subs.append(degraded)
        # node-level rollup: the budget invariant is judged against
        # ALL device-resident bytes, wherever the anchor is pinned —
        # placement slices and any degraded submesh runner included
        for r in subs:
            sub = r.hbm_stats()
            for k in ("resident_bytes", "resident_lines",
                      "pinned_lines", "pinned_bytes", "evictions",
                      "rejections", "drops", "quarantined",
                      "join_cache_bytes"):
                out[k] = out.get(k, 0) + sub.get(k, 0)
            for t, b in sub.get("residency_by_tenant", {}).items():
                out["residency_by_tenant"][t] = \
                    out["residency_by_tenant"].get(t, 0) + b
        return out

    def arena_items(self) -> list:
        """(anchor, bucket) snapshot for the scrubber — placement
        slices and any degraded submesh runner included, so one scrub
        pass audits every resident plane on the node."""
        items = self._arena.items()
        if self._placer is not None:
            for r in self._placer.slices:
                items.extend(r.arena_items())
        degraded = self._degraded_sub()
        if degraded is not None:
            items.extend(degraded.arena_items())
        return items

    def drop_feed(self, anchor, reason: str = "drop") -> int:
        """Explicitly release every device feed and request memo
        anchored on ``anchor`` (a FeedLineage or a snapshot).  Called
        by region-lifecycle teardown; returns the HBM bytes released
        from the accounting.  An armed quarantine dies with the anchor
        too — a torn-down region must not pin the lineage (and its
        digest scalars) in the quarantine map forever."""
        with self._quar_mu:
            self._quarantined.pop(id(anchor), None)
        drop_cold = getattr(anchor, "drop_cold", None)
        if callable(drop_cold):
            # unminted cold-resolve artifacts (device version planes)
            # die with the line too
            drop_cold()
        if getattr(anchor, "split_stash", None) is not None:
            # unconsumed split-child candidates die with the lineage —
            # their device planes must not outlive the line
            anchor.split_stash = None
        freed = self._arena.drop(anchor, reason=reason)
        if self._joiner is not None:
            # join build/probe planes anchored on the same lineage die
            # with the feed — stale-epoch join state must not survive
            freed += self._joiner.drop_anchor(anchor)
        if self._placer is not None:
            freed += self._placer.drop_feed_all(anchor, reason)
        degraded = self._degraded_sub()
        if degraded is not None:
            freed += degraded.drop_feed(anchor, reason=reason)
        return freed

    def quarantine(self, anchor, reason: str = "") -> None:
        """Scrub divergence: drop the anchor's feeds now and route its
        NEXT request to the host backend; the request after that
        rebuilds a fresh feed from host truth (re-admission).  A
        placed anchor quarantines on its OWNING slice — that is the
        runner its next request routes to."""
        if self._placer is not None:
            owner = self._placer.owner(anchor)
            if owner is not None:
                owner.quarantine(anchor, reason=reason)
                return
        from ..utils.metrics import DEVICE_QUARANTINE_COUNTER
        # a scrub divergence is evidence about the CHIP, not just the
        # line: strike the slice's failure-domain score too (repeated
        # corruption on one slice trips it out of placement entirely)
        self._note_slice_fault("scrub")
        self._arena.drop(anchor, reason="quarantine")
        degraded = self._degraded_sub()
        if degraded is not None:
            # while the mesh is degraded the LIVE feed sits on the
            # submesh runner — and the degrade branch routes the next
            # request there BEFORE this runner's quarantine gate can
            # fire.  The corrupt line must drop (and host-serve its
            # next request) on the sub too, or the scrubber's verdict
            # changes nothing about what keeps being served.
            degraded._arena.drop(anchor, reason="quarantine")
            with degraded._quar_mu:
                degraded._quarantined[id(anchor)] = (anchor, reason)
        with self._quar_mu:
            self._quarantined[id(anchor)] = (anchor, reason)
            # bounded: a quarantined region that is never queried again
            # (and never torn down) must not accumulate forever
            while len(self._quarantined) > 128:
                self._quarantined.pop(next(iter(self._quarantined)))
        DEVICE_QUARANTINE_COUNTER.inc()

    def _consume_quarantine(self, anchor) -> bool:
        with self._quar_mu:
            return self._quarantined.pop(id(anchor), None) is not None

    def _range_digest_kernel(self, dtype, n_pad: int):
        """Jitted plane digest over rows [lo, hi) with GLOBAL position
        weights: sum bits(x[i]) * (2i+1) mod 2^64 — the device half of
        the scrub formula (host half: supervisor.host_plane_digest;
        the full-prefix digest is just lo=0).  Cached per (dtype,
        n_pad) like every other kernel; on a sharded feed GSPMD
        partitions the reduction."""
        dt = np.dtype(dtype)
        key = ("scrubr", str(dt), n_pad)
        fn = self._kernel_cache.get(key)
        if fn is None:
            if dt == np.bool_:
                to_bits = lambda x: x.astype(jnp.uint64)    # noqa: E731
            else:
                # floats and ints alike: bitcast to the same-width
                # unsigned view, then widen
                udt = _UINT_BY_ITEMSIZE[dt.itemsize]

                def to_bits(x, _udt=udt):
                    return lax.bitcast_convert_type(x, _udt) \
                        .astype(jnp.uint64)

            def kern(x, lo_arr, hi_arr):
                iota = jnp.arange(n_pad, dtype=jnp.uint64)
                w = 2 * iota + 1
                sel = (iota >= lo_arr.astype(jnp.uint64)) & \
                    (iota < hi_arr.astype(jnp.uint64))
                return jnp.sum(jnp.where(sel, to_bits(x) * w,
                                         jnp.uint64(0)))

            fn = self._kernel_cache[key] = jax.jit(kern)
        return fn

    def device_digest(self, arr, n: int):
        """Digest of one resident plane's live prefix (device scalar —
        the caller decides when to sync).  Deliberately avoids the
        LRU scalar cache: the background scrubber calls this OUTSIDE
        the dispatch lock, and the OrderedDict's move_to_end/popitem
        is not safe against concurrent request threads."""
        return self._range_digest_kernel(arr.dtype, arr.shape[0])(
            arr, jnp.asarray(0, jnp.int64), jnp.asarray(n, jnp.int64))

    def corrupt_resident_plane(self, feed: dict) -> None:
        """Fault injection (device::feed_corrupt): flip one element of
        the first resident plane in place of the HBM bit-flip a real
        device fault would cause.  Test/chaos surface only."""
        arr = feed["flat"][0]
        dt = np.dtype(arr.dtype)
        if dt == np.bool_:
            bad = arr.at[0].set(~arr[0])
        else:
            # a true single-BIT flip, dtype-agnostic: bitcast → xor 1
            u = lax.bitcast_convert_type(
                arr, _UINT_BY_ITEMSIZE[dt.itemsize])
            bad = lax.bitcast_convert_type(u.at[0].set(u[0] ^ 1),
                                           arr.dtype)
        feed["flat"] = (bad,) + feed["flat"][1:]

    # ------------------------------------- ICI feed migration + split
    #
    # Elastic stress without the host link: a placement move, a
    # quarantine drain, or a co-location pull copies the resident
    # feed between slices over the device interconnect (device_put
    # across the mesh) instead of dropping it and re-minting from
    # host truth; a region split slices the parent feed by key range
    # on device into two child feeds.  Both re-verify against the
    # scrub-digest chain before anything serves.

    @staticmethod
    def _mark_splittable(feed: dict, used_infos) -> None:
        """Positional full-snapshot feeds record which planes carry
        the pk-handle column (sourced from state.handles, not
        state.cols) — the metadata a device-side region split needs
        to re-anchor child digests to host truth."""
        if used_infos is not None:
            feed["positional"] = True
            feed["pk_flags"] = tuple(bool(i.is_pk_handle)
                                     for i in used_infos)

    def _take_split_feed(self, lineage, feed_key, n: int):
        """Pop the stashed split-child feed matching this request's
        shape (one-shot, like ``take_cold``): same columns and device
        dtypes, same live row count, and the pad bucket THIS runner
        would mint — a candidate sliced under a different feed unit
        must not serve here.  Mutation races are benign: production
        and consumption both run under the owning slice's dispatch
        lock (children adopt the parent's slice)."""
        stash = getattr(lineage, "split_stash", None)
        if not stash:
            return None
        col_ids, dtypes, _ranges = feed_key
        want_pad = self._pad_rows(max(n, 1))
        for i, cand in enumerate(stash):
            f = cand["feed"]
            if cand["col_ids"] == col_ids and \
                    cand["dtypes"] == tuple(dtypes) and \
                    f.get("n_live") == n and f.get("n_pad") == want_pad:
                del stash[i]
                return dict(f)
        return None

    def extract_feeds(self, anchor):
        """→ (migratable feeds by key, skipped count) for an ICI move
        of ``anchor`` off this slice, or (None, 0) when nothing can
        travel.  Only feeds carrying scrub digests are migratable —
        the destination re-verifies on arrival, and a feed that
        cannot be verified must re-mint from host truth instead of
        serving unaudited (skipped counts those).  Snapshot under the
        dispatch lock: (flat, digests) pairs update non-atomically on
        the patch path."""
        if not self._single:
            return None, 0
        bucket = self._arena.bucket(anchor, create=False)
        if not bucket:
            return None, 0
        out = {}
        skipped = 0
        with self._dispatch_mu:
            for k, v in bucket.items():
                if not (isinstance(v, dict) and "flat" in v):
                    continue
                if v.get("digests") is None:
                    skipped += 1
                    continue
                out[k] = dict(v)
        return (out or None), skipped

    def install_feeds(self, anchor, feeds: dict) -> str:
        """Arrival side of an ICI feed migration → ``"moved"`` or
        ``"corrupt"``.  Each plane is device_put onto this slice and
        re-hashed against the digests that traveled with it BEFORE
        anything installs — a plane diverging mid-flight (ICI fault,
        HBM corruption on either end; chaos arms
        ``device::feed_migrate``) quarantines-and-rebuilds, never
        serves silently corrupt.  A feed the destination already
        holds at the same or newer lineage generation is never
        clobbered (a request raced the move and re-minted)."""
        from ..utils.failpoint import fail_point
        dev = self._mesh.devices.flat[0]
        installed = {}
        for fkey, feed in feeds.items():
            flat = [jax.device_put(a, dev) for a in feed["flat"]]
            if fail_point("device::feed_migrate") is not None:
                # the injected mid-transfer fault: one bit flips on a
                # transferred plane; the verify below must catch it
                tmp = dict(feed)
                tmp["flat"] = tuple(flat)
                self.corrupt_resident_plane(tmp)
                flat = list(tmp["flat"])
            n = feed.get("n_live", 0)
            arrived = []
            for arr, want in zip(flat, feed["digests"]):
                got = int(np.asarray(self.device_digest(arr, n)))
                if got != int(np.asarray(want)):
                    return "corrupt"
                arrived.append(got)
            nf = dict(feed)
            nf["flat"] = tuple(flat)
            # the digest chain must live where its planes live: a
            # scalar still committed to the SOURCE slice would turn
            # the next incremental patch into a cross-device subtract
            nf["digests"] = tuple(
                jax.device_put(jnp.asarray(w, jnp.uint64), dev)
                for w in arrived)
            installed[fkey] = nf
            # pre-register the digest kernels so the first patch on
            # the new slice mints no new compile class mid-churn
            for a in nf["flat"]:
                self._range_digest_kernel(a.dtype, a.shape[0])
        with self._dispatch_mu:
            bucket = self._arena.bucket(anchor)
            if bucket is None:
                return "corrupt"    # untrackable anchor: caller re-mints
            for fkey, nf in installed.items():
                cur = bucket.get(fkey)
                if isinstance(cur, dict) and \
                        cur.get("lineage_v") is not None and \
                        nf.get("lineage_v") is not None and \
                        cur["lineage_v"] >= nf["lineage_v"]:
                    continue
                bucket[fkey] = nf
                self._register_digests(
                    anchor if hasattr(anchor, "feed_digests") else None,
                    fkey, nf)
            self._arena.admit(anchor)
        return "moved"

    def _split_plane_kernel(self, dtype, n_pad_parent: int,
                            n_pad_child: int, right: bool):
        """Jitted key-range slice of one resident plane into a split
        child: left takes rows [0, pos), right takes [pos, pos+n) via
        a roll — the split position is traced, so every split of the
        same (side, dtype, pad buckets) shares one compile class.
        Rows past the child's live count zero out (padding invariant,
        matching _build_flat's host zeros)."""
        dt = np.dtype(dtype)
        key = ("splitp", bool(right), str(dt), n_pad_parent, n_pad_child)
        fn = self._kernel_cache.get(key)
        if fn is None:
            if right:
                def kern(x, pos, n_child):
                    y = jnp.roll(x, -pos)[:n_pad_child]
                    iota = jnp.arange(n_pad_child)
                    return jnp.where(iota < n_child, y,
                                     jnp.zeros((), y.dtype))
            else:
                def kern(x, pos, n_child):
                    y = x[:n_pad_child]
                    iota = jnp.arange(n_pad_child)
                    return jnp.where(iota < n_child, y,
                                     jnp.zeros((), y.dtype))
            fn = self._kernel_cache[key] = jax.jit(kern)
        return fn

    def split_resident_feeds(self, spec) -> str:
        """Device-side region split of every resident feed anchored on
        the parent lineage (``spec`` from RegionColumnarCache
        .split_lines) → ``"split"`` when at least one child feed was
        minted on device, else ``"none"``.  Fans out to whichever
        runner holds the parent's bucket (placement slice, degraded
        submesh, or this runner)."""
        anchor = spec["parent_lineage"]
        runners = [self]
        if self._placer is not None:
            runners.extend(self._placer.slices)
        degraded = self._degraded_sub()
        if degraded is not None:
            runners.append(degraded)
        for r in runners:
            bucket = r._arena.bucket(anchor, create=False)
            if bucket:
                return r._split_local_feeds(bucket, spec)
        return "none"

    def _split_local_feeds(self, bucket, spec) -> str:
        """Slice this runner's resident parent feeds into split-child
        candidates, stashed on the child lineages for their first
        request to consume (``_take_split_feed``).  Child digests are
        recomputed from the children's HOST state — never derived
        from device planes, so a corruption that landed on the parent
        since its last scrub fails the verify here instead of
        laundering into the child's recorded chain."""
        if not self._single:
            return "none"       # sharded whole-mesh feeds re-mint
        out = "none"
        with self._dispatch_mu:
            for fkey, feed in list(bucket.items()):
                if not (isinstance(feed, dict) and "flat" in feed):
                    continue
                if not feed.get("positional") or \
                        feed.get("pk_flags") is None or \
                        feed.get("digests") is None:
                    continue
                if feed.get("lineage_v") != spec["parent_version"] or \
                        feed.get("n_live") != spec["n_parent"]:
                    continue    # stale generation: positions lie
                for side in ("left", "right"):
                    child = spec.get(side)
                    if child is None or child["n"] <= 0:
                        continue
                    cf = self._mint_split_child(feed, fkey, spec, child,
                                                right=(side == "right"))
                    if cf is not None:
                        stash = getattr(child["lineage"], "split_stash",
                                        None)
                        if stash is None:
                            stash = child["lineage"].split_stash = []
                        stash.append({"col_ids": fkey[0],
                                      "dtypes": tuple(fkey[1]),
                                      "feed": cf})
                        out = "split"
        return out

    def _mint_split_child(self, feed, fkey, spec, child, right: bool):
        """One child feed: slice every parent plane on device, anchor
        the child's digest chain to its host truth, and verify the
        sliced planes against it (the split's arrival verify) — or
        None when anything diverges (that child re-uploads)."""
        from .supervisor import host_plane_digest
        pos = spec["pos"]
        n_child = child["n"]
        n_pad_child = self._pad_rows(max(n_child, 1))
        parent_pad = feed["n_pad"]
        if n_pad_child > parent_pad:
            return None
        state = child["state"]
        pos_arr = jnp.asarray(pos, jnp.int32)
        n_arr = jnp.asarray(n_child, jnp.int32)
        flat, digests = [], []
        fi = 0
        for ci, has_nulls in enumerate(feed["null_flags"]):
            pk = feed["pk_flags"][ci]
            dt = np.dtype(fkey[1][ci])
            if pk:
                vals = state.handles[:n_child]
                valid = None
            else:
                bufs = state.cols.get(fkey[0][ci])
                if bufs is None:
                    return None
                vals = bufs[0][:n_child]
                valid = bufs[1][:n_child]
            host_v = np.ascontiguousarray(vals.astype(dt, copy=False))
            kern = self._split_plane_kernel(feed["flat"][fi].dtype,
                                            parent_pad, n_pad_child,
                                            right)
            arr = kern(feed["flat"][fi], pos_arr, n_arr)
            want = host_plane_digest(host_v, n_child)
            if int(np.asarray(self.device_digest(arr, n_child))) != \
                    int(want):
                return None
            flat.append(arr)
            digests.append(want)
            fi += 1
            if has_nulls:
                mask = np.ascontiguousarray(
                    valid if valid is not None
                    else np.ones(n_child, np.bool_))
                kern = self._split_plane_kernel(np.bool_, parent_pad,
                                                n_pad_child, right)
                marr = kern(feed["flat"][fi], pos_arr, n_arr)
                mwant = host_plane_digest(mask, n_child)
                if int(np.asarray(self.device_digest(
                        marr, n_child))) != int(mwant):
                    return None
                flat.append(marr)
                digests.append(mwant)
                fi += 1
        cf = {"flat": tuple(flat), "null_flags": feed["null_flags"],
              "n_pad": n_pad_child, "digests": tuple(digests),
              "n_live": n_child, "lineage_v": child["lineage"].version,
              "positional": True, "pk_flags": feed["pk_flags"]}
        for a in cf["flat"]:
            self._range_digest_kernel(a.dtype, a.shape[0])
        return cf

    # --------------------------------------------------------------- kernels

    def _shard_kernel(self, cache_key, build):
        kern = self._kernel_cache.get(cache_key)
        if kern is None:
            kern = build()
            self._kernel_cache[cache_key] = kern
        return kern

    def _scalar_cache_get(self, key, v, dtype):
        cache = self._scalar_cache
        arr = cache.get(key)
        if arr is None:
            arr = jnp.asarray(v, dtype)
            cache[key] = arr
            while len(cache) > 256:
                cache.popitem(last=False)
        else:
            cache.move_to_end(key)
        return arr

    def _cached_scalar(self, v, dtype):
        """Device-resident scalar, uploaded once per value.  A fresh H2D
        per request adds ~30ms to the next fetch through the tunnel.
        LRU-bounded: row counts vary per snapshot, so unbounded caching
        would leak one device buffer per distinct n on a live server."""
        return self._scalar_cache_get((int(v), str(dtype)), v, dtype)

    def _cached_param(self, v, dtype):
        """Device-resident predicate parameter (selection.py hoisted
        constants) — same LRU as _cached_scalar but float-capable, so a
        repeated threshold never re-pays the scalar H2D."""
        key = ("param", float(v) if isinstance(v, float) else int(v),
               str(dtype))
        return self._scalar_cache_get(key, v, dtype)

    def _cached_carry(self, cache_key, build):
        """Device-resident initial carry, uploaded once per kernel key.
        Kernels never donate their inputs, so the same zero/identity
        buffers are safe to reuse across requests."""
        key = ("carry0",) + cache_key
        carry = self._kernel_cache.get(key)
        if carry is None:
            carry = self._put_carry(build())
            self._kernel_cache[key] = carry
        return carry

    def _eval_masked(self, plan: _Plan, pairs, n_local, row_mask):
        mask = row_mask
        for rpn in plan.sel_rpns:
            v, ok = eval_rpn(rpn, pairs, n_local, jnp)
            mask = mask & ok & (v != 0)
        return mask

    def _shard_index(self):
        if self._single:
            return jnp.asarray(0, jnp.int64)
        tile = self._mesh.shape[ROW_AXES[1]]
        return (lax.axis_index(ROW_AXES[0]) * tile
                + lax.axis_index(ROW_AXES[1])).astype(jnp.int64)

    def _psum(self, x):
        return x if self._single else lax.psum(x, ROW_AXES)

    # -- cross-shard merges --
    #
    # The TPU runtime here lowers only Sum all-reduce (observed: the axon
    # AOT compiler rejects pmin/pmax), so the dominant state fields
    # (count/sum/nonnull — every config in BASELINE.md) merge with one
    # post-scan psum on ICI, while order-sensitive fields (min/max/
    # first-pos) come back per-shard — a (n_shards, slots) stack, KBs —
    # and reduce on host.

    @staticmethod
    def _merge_stacked(specs, summed_states, stacked_states) -> list:
        """Host-side: reduce the per-shard stacks into one state per spec."""
        out = []
        for spec, sm, st in zip(specs, summed_states, stacked_states):
            d = {k: np.asarray(v) for k, v in sm.items()}
            if spec.kind == "min":
                d["min"] = np.min(np.asarray(st["min"]), axis=0)
            elif spec.kind == "max":
                d["max"] = np.max(np.asarray(st["max"]), axis=0)
            elif spec.kind == "first":
                pos = np.asarray(st["pos"])
                if "value" in st:       # simple agg: scalar per shard
                    i = int(np.argmin(pos))
                    d["pos"] = pos[i]
                    d["value"] = np.asarray(st["value"])[i]
                else:                   # hash agg: (n_shards, slots)
                    d["pos"] = np.min(pos, axis=0)
            out.append(d)
        return out

    def _canon_state(self, s: dict) -> dict:
        """Cast state leaves to carry dtypes (int64 / float64)."""
        return {k: (v.astype(jnp.float64) if v.dtype.kind == "f"
                    else v.astype(jnp.int64)) for k, v in s.items()}

    @staticmethod
    def _merge_summed(carry: dict, new: dict) -> dict:
        return {k: carry[k] + new[k] for k in carry}

    @staticmethod
    def _merge_stacked_dict(carry: dict, new: dict) -> dict:
        d = {}
        if "pos" in carry and "value" in carry:     # FIRST (simple agg)
            take_new = new["pos"] < carry["pos"]
            d["pos"] = jnp.where(take_new, new["pos"], carry["pos"])
            d["value"] = jnp.where(take_new, new["value"], carry["value"])
            return d
        for k in carry:
            if k == "min" or k == "pos":
                d[k] = jnp.minimum(carry[k], new[k])
            elif k == "max":
                d[k] = jnp.maximum(carry[k], new[k])
            else:   # pragma: no cover
                raise ValueError(k)
        return d

    def _split_new_state(self, s: dict):
        """→ (summed fields, per-shard stacked fields shaped [1, ...])."""
        summed, stacked = {}, {}
        for k, v in s.items():
            if k in ("count", "sum", "nonnull", "sumsq"):
                summed[k] = v
            else:
                stacked[k] = v[None] if getattr(v, "ndim", 0) else \
                    jnp.reshape(v, (1,))
        return summed, stacked

    def _carry_specs(self, carry):
        """shard_map in/out specs matching a carry pytree: stacked leaves
        (leading shard axis) are P(ROW_AXES); everything else replicated."""
        summedlike, stackedlike = carry
        return (jax.tree.map(lambda _: P(), summedlike),
                jax.tree.map(lambda _: P(ROW_AXES), stackedlike))

    # -- the single-dispatch scan wrapper --
    #
    # Every request is ONE jit call: body(carry, aux, base, *cols, row_mask)
    # folds one scan block; lax.scan drives it across the whole feed; the
    # finalize hook (cross-shard psum of the summed subtree) runs once
    # after the scan.  r2 dispatched one jit per 2^23-row chunk — enqueues
    # are cheap but the per-chunk carries defeated XLA's scheduling and
    # every extra sync through the tunnel costs ~0.1s.

    def _mega(self, body, finalize, null_flags, n_pad: int, chunk: int,
              emits: bool = False):
        S = self._nshards()
        n_local_total = n_pad // S
        chunk_local = chunk // S
        nblk = n_pad // chunk

        def local_fn(carry, n_scalar, aux, *flat):
            if not self._single:
                # the replicated summed subtree becomes device-varying as
                # soon as local rows fold in; the scan carry type must be
                # varying from step 0
                summed0, stacked0 = carry
                carry = (jax.tree.map(lambda x: _pvary(x, ROW_AXES),
                                      summed0), stacked0)
            base0 = self._shard_index() * n_local_total
            xs = tuple(a.reshape(nblk, chunk_local) for a in flat)
            steps = jnp.arange(nblk, dtype=jnp.int64)
            # the ragged-tail mask comes from an iota compare (int32 when
            # rows fit — int64 is pair-emulated on TPU), so it costs no
            # HBM reads
            idt = jnp.int32 if n_pad <= np.iinfo(np.int32).max else jnp.int64
            iota = jnp.arange(chunk_local, dtype=idt)

            def step(c, x):
                s_i = x[0]
                cols = x[1:]
                base = base0 + s_i * chunk_local

                def live(c):
                    row_mask = (base.astype(idt) + iota) < \
                        n_scalar.astype(idt)
                    args = []
                    fi = 0
                    for has_nulls in null_flags:
                        v = cols[fi]
                        fi += 1
                        if has_nulls:
                            m = cols[fi]
                            fi += 1
                        else:
                            m = row_mask
                        args.append(v)
                        args.append(m)
                    out = body(c, aux, base, *args, row_mask)
                    if emits:
                        return out
                    return out, None

                def dead(c):
                    # block entirely past the live rows (bucketed feed
                    # padding): an all-masked body invocation is a
                    # carry no-op by construction, so skip its HBM pass
                    ys = jnp.zeros((chunk_local,), jnp.bool_) \
                        if emits else None
                    return c, ys

                return lax.cond(base < n_scalar, live, dead, c)

            carry, ys = lax.scan(step, carry, (steps,) + xs)
            carry = finalize(carry)
            return (carry, ys) if emits else carry

        return local_fn

    def _wrap_mega(self, local_fn, carry_example, n_flat: int,
                   ys_specs=None):
        if self._single:
            return jax.jit(local_fn)
        cs = self._carry_specs(carry_example)
        out_specs = (cs, ys_specs) if ys_specs is not None else cs
        return jax.jit(_shard_map(
            local_fn, mesh=self._mesh,
            in_specs=(cs, P(), P()) + (P(ROW_AXES),) * n_flat,
            out_specs=out_specs))

    # -- carry initialization (host → device once per request) --

    def _put_carry(self, carry):
        """Place a (summed, stacked) carry pytree built from numpy."""
        if self._single:
            return jax.tree.map(jnp.asarray, carry)
        summed, stacked = carry
        repl = self._repl
        rows = self._row_sharding
        return (jax.tree.map(lambda x: jax.device_put(x, repl), summed),
                jax.tree.map(lambda x: jax.device_put(x, rows), stacked))

    def _init_agg_carry(self, plan: _Plan, slots: Optional[int],
                        stacked_slots: Optional[int] = None):
        """Zero/identity states for the scatter-path carries.

        ``slots=None`` → simple agg (scalar states); else hash agg
        arrays.  ``stacked_slots`` widens only the per-shard stacked
        leaves (min/max/first) — the sharded tree-reduce pads their
        slot axis to a multiple of the shard count so the all-to-all
        bucket exchange splits it evenly.
        """
        S = self._nshards()
        shape = () if slots is None else (slots,)
        sshape = (S,) if slots is None else \
            (S, slots if stacked_slots is None else stacked_slots)
        summed, stacked = [], []
        for spec, rpn in zip(plan.specs, plan.agg_rpns):
            is_real = rpn is not None and rpn.ret_type is EvalType.REAL
            sm, st = {}, {}
            if spec.kind in ("count", "count_star"):
                sm["count"] = np.zeros(shape, np.int64)
            elif spec.kind == "sum":
                sm["sum"] = np.zeros(shape, np.float64 if is_real else np.int64)
                sm["nonnull"] = np.zeros(shape, np.int64)
            elif spec.kind == "avg":
                sm["sum"] = np.zeros(shape, np.float64 if is_real else np.int64)
                sm["count"] = np.zeros(shape, np.int64)
            elif spec.kind in ("min", "max"):
                ident = (np.inf if spec.kind == "min" else -np.inf) \
                    if is_real else \
                    (np.iinfo(np.int64).max if spec.kind == "min"
                     else np.iinfo(np.int64).min)
                st[spec.kind] = np.full(
                    sshape, ident, np.float64 if is_real else np.int64)
                sm["nonnull"] = np.zeros(shape, np.int64)
            elif spec.kind == "first":
                st["pos"] = np.full(sshape, _BIG, np.int64)
                st["value"] = np.zeros(
                    sshape, np.float64 if is_real else np.int64)
            elif spec.kind in ("var_pop", "var_samp", "stddev_pop",
                               "stddev_samp"):
                sm["sum"] = np.zeros(shape, np.float64)
                sm["sumsq"] = np.zeros(shape, np.float64)
                sm["count"] = np.zeros(shape, np.int64)
            summed.append(sm)
            stacked.append(st)
        return summed, stacked

    def _finalize_psum_summed(self):
        """Post-scan cross-shard merge: psum every summed leaf."""
        def fin(carry):
            summed, stacked = carry
            return jax.tree.map(self._psum, summed), stacked
        return fin

    def _finalize_hash_bucket_merge(self):
        """Sharded hash-agg tree-reduce, entirely on the interconnect:
        psum the mergeable (count/sum/nonnull/present) fields, and
        merge the order-sensitive stacked fields (min/max) with an
        ALL-TO-ALL BY KEY BUCKET — each shard sends bucket ``j`` of
        its local (1, slots_m) partial to shard ``j``, reduces the
        (S, slots_m/S) pile it receives, and returns its merged bucket.
        This is the TiDB partial-at-TiKV / final-at-TiDB split mapped
        onto mesh axes: the runtime here lowers only Sum all-reduce
        (no pmin/pmax), but an all-to-all is a pure permutation, so
        the min/max merge that used to ship a (S, slots) stack over
        D2H for a host reduce now crosses ICI once and ships (slots,)."""
        def fin(carry):
            summed, stacked = carry
            summed = jax.tree.map(self._psum, summed)
            out_st = []
            for st in stacked:
                d = {}
                for k, v in st.items():
                    b = lax.all_to_all(v, ROW_AXES, split_axis=1,
                                       concat_axis=0, tiled=True)
                    red = jnp.max if k == "max" else jnp.min
                    d[k] = red(b, axis=0, keepdims=True)
                out_st.append(d)
            return summed, out_st
        return fin

    @staticmethod
    def _pad_stacked(st: dict, pad: int) -> dict:
        """Pad a new stacked state's slot axis with the merge identity
        (min/pos → +big, max → -big) so it folds into the widened
        sharded carry without perturbing any real slot."""
        if not pad:
            return st
        out = {}
        for k, v in st.items():
            if v.dtype.kind == "f":
                fill = -jnp.inf if k == "max" else jnp.inf
            else:
                fill = np.iinfo(np.int64).min if k == "max" \
                    else np.iinfo(np.int64).max
            out[k] = jnp.pad(v, ((0, 0), (0, pad)),
                             constant_values=fill)
        return out

    @staticmethod
    def _merge_bucketed(specs, summed_states, stacked_states,
                        slots: int) -> list:
        """Host-side unpack after the device bucket merge: the fetched
        stacked leaves are (S, slots_m/S) — shard j's row IS bucket j,
        already cross-shard reduced — so the merged per-slot vector is
        just the row-major flatten, trimmed of the all-to-all pad."""
        out = []
        for spec, sm, st in zip(specs, summed_states, stacked_states):
            d = {k: np.asarray(v) for k, v in sm.items()}
            for k, v in st.items():
                d[k] = np.asarray(v).reshape(-1)[:slots]
            out.append(d)
        return out

    # -- kernel bodies --

    def _build_simple_body(self, plan: _Plan, n_cols: int):
        specs = plan.specs

        def body(carry, aux, base, *flat):
            summed_c, stacked_c = carry
            row_mask = flat[-1]
            pairs = [(flat[2 * i], flat[2 * i + 1]) for i in range(n_cols)]
            n_local = row_mask.shape[0]
            mask = self._eval_masked(plan, pairs, n_local, row_mask)
            cols = []
            for r in plan.agg_rpns:
                if r is None:
                    cols.append((jnp.zeros((n_local,), jnp.int32), mask))
                else:
                    v, ok = eval_rpn(r, pairs, n_local, jnp)
                    cols.append((v, ok & mask))
            n_valid = jnp.sum(mask, dtype="int64")
            states = simple_agg_tile(jnp, specs, cols, n_valid_rows=n_valid)
            out_sm, out_st = [], []
            for spec, s, cs, cst in zip(specs, states, summed_c, stacked_c):
                s = self._canon_state(s)
                if spec.kind == "first":
                    # globalize positions; host picks the cross-shard argmin
                    s["pos"] = jnp.where(s["pos"] == _BIG, _BIG,
                                         s["pos"] + base)
                sm, st = self._split_new_state(s)
                out_sm.append(self._merge_summed(cs, sm))
                out_st.append(self._merge_stacked_dict(cst, st)
                              if st else cst)
            return out_sm, out_st

        return body

    def _build_hash_scatter_body(self, plan: _Plan, n_cols: int,
                                 capacity: int, sparse: bool = False,
                                 stack_pad: int = 0):
        specs = plan.specs
        n_pairs = n_cols + (1 if sparse else 0)

        def body(carry, aux, base, *flat):
            (summed_c, present_c, overflow_c), stacked_c = carry
            row_mask = flat[-1]
            pairs = [(flat[2 * i], flat[2 * i + 1])
                     for i in range(n_pairs)]
            n_local = row_mask.shape[0]
            mask = self._eval_masked(plan, pairs, n_local, row_mask)
            cols = []
            for r in plan.agg_rpns:
                if r is None:
                    cols.append((jnp.zeros((n_local,), jnp.int32), mask))
                else:
                    cols.append(eval_rpn(r, pairs, n_local, jnp))
            if sparse:
                # precomputed slot ids ride as the trailing column
                key_pair = (jnp.zeros((n_local,), jnp.int32), mask)
                tile_base = ("precomp", pairs[n_cols][0])
            else:
                key_pair = eval_rpn(plan.key_rpn, pairs, n_local, jnp)
                tile_base = aux
            st = hash_agg_tile(jnp, specs, key_pair, cols, capacity,
                               tile_base, row_mask=mask)
            present = present_c + st["present"].astype(jnp.int64)
            overflow = overflow_c + st["overflow"].astype(jnp.int64)
            out_sm, out_st = [], []
            for spec, s, cs, cst in zip(specs, st["states"], summed_c,
                                        stacked_c):
                sm, stk = self._split_new_state(self._canon_state(s))
                stk = self._pad_stacked(stk, stack_pad)
                out_sm.append(self._merge_summed(cs, sm))
                out_st.append(self._merge_stacked_dict(cst, stk)
                              if stk else cst)
            return (out_sm, present, overflow), out_st

        return body

    def _build_hash_twolevel_body(self, plan: _Plan, n_cols: int,
                                  capacity: int, layouts, LO: int, HI: int,
                                  pf: int, sparse: bool = False):
        from .kernels import make_planes, slot_index, twolevel_partial
        specs = plan.specs
        n_pairs = n_cols + (1 if sparse else 0)

        def body(carry, aux, base, *flat):
            (S8_c, Sf_c, ovf_c), _unused = carry
            row_mask = flat[-1]
            pairs = [(flat[2 * i], flat[2 * i + 1])
                     for i in range(n_pairs)]
            n_local = row_mask.shape[0]
            mask = self._eval_masked(plan, pairs, n_local, row_mask)
            cols = []
            for r in plan.agg_rpns:
                if r is None:
                    cols.append((jnp.zeros((n_local,), jnp.int32), mask))
                else:
                    cols.append(eval_rpn(r, pairs, n_local, jnp))
            if sparse:
                # precomputed slot ids (trailing column); only the
                # request's selection/row mask is applied here
                scrap = capacity + 1
                idx = jnp.where(mask, pairs[n_cols][0].astype(jnp.int32),
                                scrap)
                overflow = jnp.zeros((), jnp.bool_)
            else:
                key_pair = eval_rpn(plan.key_rpn, pairs, n_local, jnp)
                idx, overflow = slot_index(key_pair, capacity, aux, mask)
            L8, Lf = make_planes(layouts, specs, cols, mask)
            S2_8, S2_f = twolevel_partial(idx, L8, Lf, LO, HI)
            S8_c = S8_c + S2_8.astype(jnp.int64)
            if S2_f is not None:
                Sf_c = Sf_c + S2_f.astype(jnp.float64)
            ovf_c = ovf_c + overflow.astype(jnp.int64)
            return (S8_c, Sf_c, ovf_c), _unused

        return body

    def _topn_sort_key(self, plan: _Plan, v, ok, mask):
        """Map the order expression to one descending-top_k sort key.

        ``top_k(key2)`` must rank: real rows in requested order, then
        NULL rows per MySQL (first for ASC, last for DESC), then
        masked-out rows never. Keys stay in the narrowest exact dtype —
        f32 for REAL (the device column resolution), int32 for int32 INT
        (top_k on pair-emulated int64/f64 measures 1.5-4× slower) — and
        any boundary ambiguity is repaired by the exact host refine over
        the candidate set.
        """
        desc = plan.order_desc
        if v.dtype == jnp.float32:
            key2 = v if desc else -v
            null_key = jnp.float32(-3e38) if desc else jnp.float32(np.inf)
            excl = jnp.float32(-np.inf)
        elif v.dtype == jnp.int32:
            lo = np.iinfo(np.int32)
            vv = jnp.maximum(v, lo.min + 2)
            key2 = vv if desc else -vv
            null_key = jnp.int32(lo.min + 1) if desc else jnp.int32(lo.max)
            excl = jnp.int32(lo.min)
        elif v.dtype in (jnp.int64, jnp.uint64):
            # exact 64-bit candidate keys: an f64 key collapses values
            # within 512 of each other at DATETIME magnitudes (~2^61),
            # and top_k over collapsed ties can DROP the true top rows
            # before the host refine ever sees them.  u64 cores are
            # < 2^63 (feed guard) so the int64 view preserves order.
            lo = np.iinfo(np.int64)
            vv = jnp.maximum(v.astype(jnp.int64), lo.min + 2)
            key2 = vv if desc else -vv
            null_key = jnp.int64(lo.min + 1) if desc else jnp.int64(lo.max)
            excl = jnp.int64(lo.min)
        else:
            keyf = jnp.asarray(v, jnp.float64)
            key2 = keyf if desc else -keyf
            null_key = jnp.float64(_NULL_KEY) if desc \
                else jnp.float64(-_NULL_KEY)
            excl = jnp.float64(_EXCLUDED_DESC)
        key2 = jnp.where(ok, key2, null_key)
        return jnp.where(mask, key2, excl)

    def _build_topn_kernel(self, plan: _Plan, n_cols: int, k: int,
                           null_flags, n_pad: int, n_flat: int,
                           n_used: Optional[int] = None):
        """Whole-feed two-stage top-k — ONE dispatch, no scan.

        ``lax.top_k`` over one flat 100M-row array costs 340-530ms on v5e
        and degrades further inside lax.scan; batched over segment rows it
        runs ~3× faster. Stage 1 takes the per-segment top k over a
        (nseg, seglen) view (any global top-k row is in its segment's
        top k), stage 2 reduces the nseg·k candidates to k.

        ``n_used`` (single-device): the live seglen-rounded row prefix —
        the kernel slices the feed to it so the bucketed padding
        (_pad_rows) taxes only the cache key, never the top_k extent
        (an XLA prefix slice streams at HBM speed; top_k over the same
        rows costs an order of magnitude more).
        """
        S = self._nshards()
        n_local = n_pad // S
        trim = self._single and n_used is not None and n_used < n_local
        if trim:
            n_local = n_used
        seglen = math.gcd(n_local, 1 << 17)
        nseg = n_local // seglen
        kk = min(k, seglen)

        idt = jnp.int32 if n_pad <= np.iinfo(np.int32).max else jnp.int64

        def local_fn(n_scalar, *flat):
            if trim:
                flat = tuple(a[:n_local] for a in flat)
            if self._single:
                base0 = idt(0)
            else:
                base0 = (self._shard_index() * n_local).astype(idt)
            iota = jnp.arange(n_local, dtype=idt)
            row_mask = (base0 + iota) < n_scalar.astype(idt)
            args = []
            fi = 0
            for has_nulls in null_flags:
                vv = flat[fi]
                fi += 1
                if has_nulls:
                    m = flat[fi]
                    fi += 1
                else:
                    m = row_mask
                args.append((vv, m))
            mask = self._eval_masked(plan, args, n_local, row_mask)
            v, ok = eval_rpn(plan.order_rpn, args, n_local, jnp)
            v = jnp.broadcast_to(v, (n_local,))
            ok = jnp.broadcast_to(ok & mask, (n_local,))
            key2 = self._topn_sort_key(plan, v, ok, mask)
            kv1, ki1 = lax.top_k(key2.reshape(nseg, seglen), kk)
            seg_base = (jnp.arange(nseg, dtype=idt) * seglen)[:, None]
            gidx1 = (base0 + seg_base + ki1.astype(idt)).astype(jnp.int64)
            _, sel = lax.top_k(kv1.reshape(-1), min(k, nseg * kk))
            gidx = gidx1.reshape(-1)[sel]
            m1 = jnp.take_along_axis(mask.reshape(nseg, seglen), ki1, axis=1)
            o1 = jnp.take_along_axis(ok.reshape(nseg, seglen), ki1, axis=1)
            return gidx, m1.reshape(-1)[sel], o1.reshape(-1)[sel]

        if self._single:
            return jax.jit(local_fn)
        return jax.jit(_shard_map(
            local_fn, mesh=self._mesh,
            in_specs=(P(),) + (P(ROW_AXES),) * n_flat,
            out_specs=(P(ROW_AXES),) * 3))

    # -- dispatch span + flight-recorder feed --

    @contextmanager
    def _dispatch_phase(self, klass: str, key=None):
        """Every kernel launch site runs under this: the
        ``device_dispatch`` tracker span, plus one flight-recorder
        entry (launch wall, compile class, first-launch flag, mesh
        shape, slice id, arena-pinned bytes) annotated onto the span —
        the trace carries the launch's black-box record inline.

        ``key`` refines the compile class (n_pad bucket / kernel cache
        key) so the ``first_launch`` flag distinguishes a real
        cold-compile launch from a warm cache hit within the same plan
        kind."""
        from .. import resource_metering as rm
        from ..utils import tracker
        rec = self.flight_recorder
        with tracker.phase("device_dispatch"):
            t0 = time.perf_counter()
            ok = True
            try:
                yield
            except BaseException:
                ok = False
                raise
            finally:
                wall_s = time.perf_counter() - t0
                # RU metering: every launch wall is charged to the
                # ambient (tag, region) — a coalesced group's shared
                # launch splits by occupancy share across member tags
                # (resource_metering.charge_launch site resolution)
                rm.charge_launch(wall_s)
                if rec is not None:
                    entry = rec.note(
                        klass=klass, key=key,
                        wall_s=wall_s,
                        mesh=self._mesh_desc,
                        slice_id=self._slice_indices[0]
                        if len(self._slice_indices) == 1 else None,
                        pinned_bytes=self._arena.pinned_bytes(),
                        ok=ok)
                    tracker.annotate(**entry)

    # -- packed device→host readback (one transfer, one sync) --

    def _readback(self, tree):
        """Fetch a device pytree with every D2H transfer in flight at once.

        ``copy_to_host_async`` is issued for every leaf before the first
        blocking fetch, so the whole tree lands in ~one sync round-trip
        (through a tunneled TPU a cold blocking fetch costs ~0.1s;
        r2's sequential per-array fetches paid that 3+ times per
        request). Returns the same pytree as numpy.
        """
        from ..utils import tracker
        _fp_degrade("device::before_fetch")
        # a transfer-level corruption is DETECTED (link CRC) and surfaces
        # as a failed fetch: the request degrades to the host pipeline —
        # corrupted bytes never become an answer
        _fp_degrade("device::d2h_corrupt")
        # a chip that died BETWEEN dispatch and fetch fails the D2H: the
        # in-flight request rescues onto a healthy slice/submesh
        # (DeferredResult/_GroupPending catch this) or degrades to host
        hit = self._slice_dead_targets()
        if hit:
            if self._health is None:
                board = self._strike_board()
                if board is not None:
                    for i in hit:
                        board.note_fault(i, "fetch")
            raise _FallbackToHost("device::slice_dead")
        # the old monolithic "device_fetch" phase is split so a warm
        # p50 can be attributed from the artifact alone: "d2h_wait" is
        # the transfer + sync (here), "host_materialize" is the host
        # finalize that follows (_finish)
        with tracker.phase("d2h_wait"):
            leaves, treedef = jax.tree.flatten(tree)
            for x in leaves:
                try:
                    x.copy_to_host_async()
                except Exception:   # pragma: no cover - CPU arrays
                    pass
            fetched = [np.asarray(x) for x in leaves]
            # RU metering: the MEASURED transfer payload, charged once
            # per physical D2H (a group's shared fetch splits across
            # its members through the captured group context)
            from .. import resource_metering as rm
            rm.charge_d2h(sum(int(a.nbytes) for a in fetched))
            return jax.tree.unflatten(treedef, fetched)

    # ------------------------------------------------------------ dispatch

    def handle_request(self, dag: DAGRequest, storage,
                       deferred: bool = False, _stack=None):
        """Execute a supported plan on the device.

        ``_stack`` (handle_batched only): a tuple of per-member hoisted
        predicate parameter value tuples.  The scan_sel run then builds
        the STACKED mask kernel, dispatches the whole group once, and
        the call returns a :class:`_GroupPending` (raw group arrays,
        shared fetch) instead of a per-request result; any path that
        cannot produce a group dispatch raises
        :class:`_BatchUnavailable` or returns a settled result the
        caller must treat as such.

        ``deferred=True``: return as soon as the kernel is dispatched —
        the result is a :class:`DeferredResult` whose ``result()`` runs
        the D2H fetch + host finalize (on whatever thread calls it), so
        N in-flight requests overlap dispatch/compute/fetch instead of
        serializing on the transport round trip.  Paths that never
        reach a device dispatch (host fallback, zero rows, cold kernel
        builds that validate synchronously) still return a finished
        SelectResult; callers must accept either.
        """
        if self._placer is not None and _stack is None and \
                hasattr(storage, "scan_columns"):
            # hot-region placement (device/placement.py): small feeds
            # pin to a single-device slice picked by load; large feeds
            # come back to this whole-mesh runner (scale-up)
            target = self._placer.route(storage)
            if target is not self:
                return target.handle_request(dag, storage,
                                             deferred=deferred)
        if self._board is not None:
            # elastic mesh degrade: a quarantined chip routes whole-
            # mesh plans to the largest healthy submesh (8→4→2→1; the
            # sharded feeds re-mint from host truth onto survivors)
            # instead of collapsing to host — host stays the FINAL
            # rung, taken only when the rebuild itself fails
            try:
                degraded = self._degraded_target()
            except _FallbackToHost:
                from ..executors.runner import BatchExecutorsRunner
                return BatchExecutorsRunner(dag, storage).handle_request()
            if degraded is not None:
                return degraded.handle_request(dag, storage,
                                               deferred=deferred,
                                               _stack=_stack)
        plan = self._analyze(dag)
        if plan is None:
            raise RuntimeError("plan not supported by device backend")

        if self._refuse_if_quarantined():
            if _stack is not None:
                # a group must not burn the leader's deadline on a
                # throwaway synchronous host run — the coalescer's
                # solo retries re-route each member via the placer,
                # which now excludes this slice
                raise _BatchUnavailable("slice quarantined")
            # this slice is a condemned chip: serve from the host
            # pipeline without touching any per-slice state (a racing
            # caller that bypassed the placer's exclusion lands here)
            from ..utils import tracker
            tracker.label("device_feed", "slice_quarantined")
            from ..executors.runner import BatchExecutorsRunner
            return BatchExecutorsRunner(dag, storage).handle_request()

        if self._quarantined and hasattr(storage, "scan_columns") and \
                self._consume_quarantine(self._feed_anchor(storage)):
            # scrub divergence on this line: its feeds were dropped at
            # quarantine time; serve THIS request from the host
            # pipeline, then let the next one rebuild from host truth
            from ..utils import tracker
            tracker.label("device_feed", "quarantined")
            from ..executors.runner import BatchExecutorsRunner
            return BatchExecutorsRunner(dag, storage).handle_request()

        # bucket tiling (SURVEY §5.7 "region → chip, bucket → tile";
        # pd_client buckets): a hash-agg request covering a strict
        # subset of the region's rows reuses the WHOLE-region HBM feed
        # and dispatches the kernel only over the covering block spans;
        # disjoint spans' packed partials add like psum partials.
        tile_spans = None
        orig_dag = dag
        if self._single and plan.kind == "hash_agg" and dag.ranges \
                and hasattr(storage, "row_slices"):
            try:
                spans = storage.row_slices(dag.ranges)
                n_all = storage.estimated_rows()
            except Exception:   # noqa: BLE001 — storage without spans
                spans, n_all = None, 0
            covered = sum(j - i for i, j in spans) if spans else 0
            if spans and 0 < covered < n_all:
                tile_spans = tuple(spans)
                # feed/meta keyed WITHOUT ranges: every tiled request
                # over this snapshot shares one region feed
                dag = DAGRequest(dag.executors, (), dag.start_ts,
                                 dag.output_offsets, dag.encode_type)

        # keyed on the full plan: hash_bounds/arg_nbytes depend on the
        # key/arg expressions, not just on which columns are shipped
        meta_key = (dag.plan_key(), dag.ranges)
        meta = self._request_meta(storage, meta_key)
        lineage = getattr(storage, "feed_lineage", None)
        # the generation THIS snapshot reflects — the line may already
        # be further ahead (or this may be a history-served older
        # generation); every shared-memo interaction pins to it
        req_v = getattr(storage, "feed_version", None)
        if lineage is not None and req_v is None:
            req_v = lineage.version
        if lineage is not None:
            mv = meta.get("lineage_v", req_v)
            if mv < req_v:
                # the memo lags this snapshot: carry what provably
                # survives the gap, drop the rest
                self._refresh_meta(meta, lineage, plan, mv, req_v)
            elif mv > req_v:
                # an older-generation read (history serve) must not
                # consume or mutate the newer shared memo: go local
                meta = {"lineage_v": req_v,
                        "force_host": meta.get("force_host", False)}
            meta.setdefault("lineage_v", req_v)
        if meta.get("force_host"):
            from ..executors.runner import BatchExecutorsRunner
            return BatchExecutorsRunner(orig_dag, storage).handle_request()

        # shared-memo writes are only allowed while the memo still
        # reflects req_v — a request (or deferred finalize) racing a
        # newer generation's refresh must not repopulate the shared
        # memo with stale data; stale results stay request-local
        memo: dict = {}

        def memo_fresh() -> bool:
            return req_v is None or meta.get("lineage_v") == req_v

        def get_batch():
            """Host ColumnBatch for this scan (built at most once; the
            warm agg path never needs it — the feed is HBM-resident and
            the row count is memoized)."""
            if "batch" not in memo:
                memo["batch"] = self._scan_batch(dag, plan, storage)
            return memo["batch"]

        if "n_rows" in meta and memo_fresh():
            n = meta["n_rows"]
        else:
            if isinstance(plan.scan, TableScanDesc) and \
                    hasattr(storage, "count_rows") and \
                    hasattr(storage, "scan_columns"):
                # row count without materializing the batch — the warm
                # delta path must not pay a full columnar gather just
                # to re-learn n
                n = storage.count_rows(dag.ranges)
            else:
                n = get_batch().num_rows
            if memo_fresh():
                meta["n_rows"] = n
        if n == 0:
            from ..executors.runner import BatchExecutorsRunner
            return BatchExecutorsRunner(orig_dag, storage).handle_request()

        def get_dtypes() -> tuple:
            if "dtypes" in memo:
                return memo["dtypes"]
            if "dtypes" in meta and memo_fresh():
                return meta["dtypes"]
            batch = get_batch()
            dts = []
            for ci in plan.used_cols:
                col = batch.columns[ci]
                dt = _device_dtype(col.eval_type, col.values)
                if dt == np.dtype(np.uint64) and col.values.size \
                        and int(col.values.max()) >= (1 << 63):
                    # packed cores above 2^63 (year >= 8192) would
                    # wrap in the int64 state carries.  Remember the
                    # verdict: repeat requests must not rebuild the
                    # preceding columns just to re-discover it.
                    # (Conservative-sticky: safe to set cross-version.)
                    meta["force_host"] = True
                    raise _FallbackToHost("u64 column beyond int64")
                dts.append(str(dt))
            memo["dtypes"] = tuple(dts)
            if memo_fresh():
                meta["dtypes"] = memo["dtypes"]
            return memo["dtypes"]

        def host_cols():
            """Device-dtype numpy column pairs.

            Cached for the snapshot's lifetime (in ``meta``, same policy
            as the device feed): the astype alone costs ~2s per 100M-row
            REAL column, and the TopN candidate refine reads these on
            every request.  Version-guarded: if the line moved on, the
            rebuild stays request-local (``memo``)."""
            if "host_cols" in memo:
                return memo["host_cols"]
            if "host_cols" in meta and memo_fresh():
                return meta["host_cols"]
            dts = get_dtypes()
            batch = get_batch()
            cols = []
            for ci, ds in zip(plan.used_cols, dts):
                col = batch.columns[ci]
                cols.append((np.ascontiguousarray(
                    col.values.astype(np.dtype(ds), copy=False)),
                    np.ascontiguousarray(col.validity)))
            memo["host_cols"] = cols
            if memo_fresh():
                meta["host_cols"] = cols
            return cols

        def host_cols_stream():
            """Yield device-dtype pairs one column at a time, building
            the host_cols memo incrementally: the cold feed upload
            issues each column's (async) device_put as soon as that
            column is converted, so the H2D transfer of column i
            overlaps the astype of column i+1 — double-buffering the
            tail of a columnar build instead of serializing convert-all
            then upload-all."""
            if "host_cols" in memo:
                yield from memo["host_cols"]
                return
            if "host_cols" in meta and memo_fresh():
                yield from meta["host_cols"]
                return
            dts = get_dtypes()
            batch = get_batch()
            built = []
            for ci, ds in zip(plan.used_cols, dts):
                col = batch.columns[ci]
                pair = (np.ascontiguousarray(
                    col.values.astype(np.dtype(ds), copy=False)),
                    np.ascontiguousarray(col.validity))
                built.append(pair)
                yield pair
            memo["host_cols"] = built
            if memo_fresh():
                meta["host_cols"] = built

        pin_anchor = None
        try:
            _fp_degrade("device::before_dispatch")
            # chip failure domains: refuse to launch on a quarantined
            # slice, and fail the way the chip would when
            # device::slice_dead names one of mine
            self._preflight_slice()
            dtypes = get_dtypes()

            feed_key = (tuple(plan.scan.columns[ci].col_id
                              for ci in plan.used_cols),
                        tuple(dtypes), dag.ranges)
            used_infos = [plan.scan.columns[ci] for ci in plan.used_cols]
            # patching maps journal row positions straight onto feed
            # rows — only sound for an ascending table scan (index
            # scans re-sort, desc scans reverse)
            positional = isinstance(plan.scan, TableScanDesc) and \
                not getattr(plan.scan, "desc", False)
            with self._dispatch_mu:
                if not self._single:
                    # one shard's enqueue failing (device loss, ICI
                    # fault) surfaces as a whole-launch failure mid-
                    # dispatch, with the lock HELD.  The plan degrades
                    # to host WHOLE — never a partial per-shard answer
                    # — and the raise unwinds this ``with``, releasing
                    # the lock on the way out: a sharded launch fault
                    # must not wedge the serialized dispatch stream
                    # (the launch-order-inversion hazard the lock
                    # exists for — see its comment at the definition)
                    _fp_degrade("device::shard_launch")
                feed = self._get_feed(storage, feed_key,
                                      host_cols_stream, n,
                                      lineage=lineage,
                                      used_infos=used_infos,
                                      dtypes=dtypes,
                                      positional=positional,
                                      req_v=req_v)
                # derived kernel constants written inside the run
                # bodies ride the guarded view: a stale-generation
                # request keeps them request-local
                gmeta = _GuardedMeta(meta, memo_fresh)
                if plan.kind == "simple_agg":
                    result = self._run_simple(dag, plan, host_cols, dtypes,
                                              n, feed, gmeta)
                elif plan.kind == "hash_agg":
                    result = self._run_hash(dag, plan, host_cols, dtypes,
                                            n, feed, gmeta,
                                            tile_spans=tile_spans)
                elif plan.kind == "topn":
                    result = self._run_topn(dag, plan, host_cols, dtypes,
                                            n, get_batch, feed)
                else:   # scan_sel
                    result = self._run_scan_sel(dag, plan, dtypes, n,
                                                get_batch, feed, storage,
                                                stack=_stack)
                if isinstance(result, _Pending) and \
                        self._health is not None and \
                        self._health.quarantined():
                    # the invariant counter chaos audits: a quarantine
                    # landing between the preflight gate and the launch
                    # means a kernel ran on a condemned chip
                    self._health.launched_quarantined += 1
                if isinstance(result, _Pending) and \
                        hasattr(storage, "scan_columns"):
                    # pin the line for the in-flight dispatch: budget
                    # eviction (arena.admit, also under this lock) must
                    # never reclaim HBM a launched kernel still reads
                    anc = self._feed_anchor(storage)
                    pin_anchor = self._arena.pin(anc)
                    # re-account: the run may have cached new device
                    # state (sparse slot planes) in the request memo
                    self._arena.admit(anc)
            if isinstance(result, _Pending) and not deferred:
                # synchronous callers block here; the before_fetch
                # failpoint inside _readback still degrades to host
                try:
                    result = self._finish(result)
                finally:
                    if pin_anchor is not None:
                        self._arena.unpin(pin_anchor)
                        pin_anchor = None
        except _FallbackToHost:
            if pin_anchor is not None:
                self._arena.unpin(pin_anchor)
            # a dispatch-side fault on a placement slice strikes its
            # health score exactly once (the failure-domain feed; the
            # whole-mesh runner's slice-attributable strikes happen at
            # the _preflight_slice / _readback sites instead)
            self._note_slice_fault("dispatch")
            if _stack is not None:
                # a degrade mid-group must not serve the LEADER's host
                # answer to every member — the coalescer retries each
                # member as a solo dispatch (per-member degrade intact)
                raise _BatchUnavailable("degraded during batched "
                                        "dispatch")
            from ..executors.runner import BatchExecutorsRunner
            return BatchExecutorsRunner(orig_dag, storage).handle_request()
        except BaseException:
            if pin_anchor is not None:
                self._arena.unpin(pin_anchor)
            raise

        if _stack is not None:
            if isinstance(result, _Pending):
                return _GroupPending(self, result, pin_anchor)
            return result       # settled synchronously: caller bails
        if isinstance(result, _Pending):
            return DeferredResult(self, result, orig_dag, storage,
                                  pin_anchor=pin_anchor)
        return self._apply_output_offsets(orig_dag, result)

    def _finish(self, pending: _Pending):
        """Blocking fetch + host finalize for a dispatched request."""
        import time as _time

        from ..utils import tracker
        t0 = _time.perf_counter()
        fetched = self._readback(pending.tree)
        with tracker.phase("host_materialize"):
            out = pending.finalize(fetched)
        # a served request decays the slice's strike score (and feeds
        # the launch-latency outlier detector when configured)
        self._note_slice_ok(_time.perf_counter() - t0)
        return out

    @staticmethod
    def _apply_output_offsets(dag, result):
        if dag.output_offsets is not None:
            b = result.batch
            result.batch = ColumnBatch(
                [b.schema[i] for i in dag.output_offsets],
                [b.columns[i] for i in dag.output_offsets])
        return result

    def probe_kernel(self, dag, storage, launches: int = 32):
        """Diagnostic: amortized kernel-only ms/pass for a cached Pallas
        plan.  Dispatches ``launches`` back-to-back kernels and blocks
        once on the last (in-order stream), so the transport round-trip
        is paid once: per-launch ≈ true device time when kernel >>
        dispatch.  → {"kernel_ms", "launches"} or None when the plan has
        no cached Pallas kernel (XLA path / host fallback).

        Exists for bench.py's phase decomposition (VERDICT r4 #2: a
        perf artifact must attribute kernel vs transport); not a serving
        path."""
        import time as _time
        self.handle_request(dag, storage)       # warm: feed + kernel
        entry = None
        for key, val in self._kernel_cache.items():
            if isinstance(key, tuple) and key and key[0] == "hashpl" \
                    and isinstance(val, dict) and "runs" in val:
                # sharded entries wrap their grid in shard_map; the
                # launch-train probe times the raw single-device runs
                if key[1] == dag.plan_key():
                    entry = val
        if entry is None:
            return None
        runs_by_nb = entry["runs"]
        run = runs_by_nb[max(runs_by_nb)]      # the full-feed span
        meta = self._request_meta(storage, (dag.plan_key(), dag.ranges))
        if "n_rows" not in meta:
            return None
        # simple-agg plans have no key bounds; their kernels ignore base
        base = meta["hash_bounds"][0] if "hash_bounds" in meta else 0
        n = meta["n_rows"]
        feed = None
        cache = self._arena.bucket(self._feed_anchor(storage),
                                   create=False)
        for k, v in (cache or {}).items():
            if isinstance(v, dict) and "flat" in v:
                feed = v
        if feed is None:
            return None
        cols = tuple(feed["flat"][j] for j in entry["col_sel"])
        if entry["mode"] == "sparse":
            got = meta.get("sparse_slots")
            if got is None:
                return None
            cols += (got[3],)
        out = run(0, n, base, 0, cols)
        np.asarray(out)                         # sync
        t0 = _time.perf_counter()
        outs = [run(0, n, base, 0, cols)
                for _ in range(launches)]
        outs[-1].block_until_ready()
        per = (_time.perf_counter() - t0) / launches
        return {"kernel_ms": round(per * 1e3, 3), "launches": launches}

    def probe_scan_kernel(self, dag, storage, launches: int = 32):
        """Diagnostic twin of :meth:`probe_kernel` for the selection /
        scan mask kernel: amortized kernel-only ms per full-feed
        predicate pass via an RTT-amortized launch train, plus the feed
        bytes the pass streams (→ bench's kernel_feed_gbps for configs
        1-2).  → {"kernel_ms", "launches", "feed_bytes"} or None when
        the plan has no cached selection kernel."""
        import time as _time
        self.handle_request(dag, storage)       # warm: feed + kernel
        entry = getattr(self, "_selmask_last", None)
        if entry is None or entry[0] != dag.plan_key():
            return None
        _pkey, skey, params, n = entry
        kern = self._kernel_cache.get(skey)
        plan = self._analyze(dag)
        meta = self._request_meta(storage, (dag.plan_key(), dag.ranges))
        dts = meta.get("dtypes")
        if kern is None or plan is None or dts is None:
            return None
        # THIS plan's feed, by its exact cache key — another plan over
        # the same snapshot may have a different column set, and timing
        # the wrong planes would silently corrupt the attribution
        feed_key = (tuple(plan.scan.columns[ci].col_id
                          for ci in plan.used_cols), tuple(dts),
                    dag.ranges)
        cache = self._arena.bucket(self._feed_anchor(storage),
                                   create=False)
        feed = (cache or {}).get(feed_key)
        if feed is None:
            return None
        pvals = tuple(self._cached_param(v, dt) for v, dt in params)
        n_arr = self._cached_scalar(n, jnp.int64)
        out = kern(n_arr, *pvals, *feed["flat"])
        jax.block_until_ready(out)              # compile + sync
        t0 = _time.perf_counter()
        outs = [kern(n_arr, *pvals, *feed["flat"])
                for _ in range(launches)]
        jax.block_until_ready(outs[-1])
        per = (_time.perf_counter() - t0) / launches
        feed_bytes = int(sum(a.nbytes for a in feed["flat"]))
        return {"kernel_ms": round(per * 1e3, 3), "launches": launches,
                "feed_bytes": feed_bytes}

    def _request_meta(self, storage, meta_key) -> dict:
        """Snapshot-lifetime memo for host-derived request constants
        (device dtypes, hash key bounds, byte-plane widths).  Anchored
        on the feed lineage when the snapshot is delta-maintained, so
        the memo survives patch generations (version-checked by
        ``_refresh_meta``)."""
        if not hasattr(storage, "scan_columns"):
            return {}
        per_storage = self._arena.bucket(self._feed_anchor(storage))
        if per_storage is None:         # anchor not trackable
            return {}
        return per_storage.setdefault(("meta", meta_key), {})

    def _refresh_meta(self, meta: dict, lineage, plan, from_v: int,
                      to_v: int) -> None:
        """Roll a request memo forward across a feed-lineage gap.

        Volatile fields (row count, host column copies) always drop.
        Derived kernel constants — device dtypes, hash key bounds,
        byte-plane widths — survive only when every dirty row provably
        stays inside them, because each is baked into a compiled kernel
        (capacity, plane count) or a value transform (dtype narrowing);
        keeping a violated constant would corrupt results, dropping a
        valid one only costs a re-derivation (still no MVCC rebuild).
        Sparse key recodes always drop: new rows have no slot ids.
        """
        patches = lineage.since(from_v, until=to_v)
        meta.pop("n_rows", None)
        meta.pop("host_cols", None)
        meta.pop("sparse_slots", None)
        keep = patches is not None and \
            not any(p.get("structural") for p in patches)
        if keep:
            used_infos = [plan.scan.columns[ci] for ci in plan.used_cols]
            spans = [s for p in patches for s in p["spans"]]
            keep = self._verify_meta_consts(meta, plan, used_infos,
                                            spans)
        if not keep:
            meta.pop("dtypes", None)
            meta.pop("hash_bounds", None)
            meta.pop("simple_arg_nbytes", None)
        meta["lineage_v"] = to_v

    def _verify_meta_consts(self, meta, plan, used_infos, spans) -> bool:
        from .kernels import int_planes_needed
        dtypes = meta.get("dtypes")
        if dtypes is not None:
            for ci, info in enumerate(used_infos):
                dt = np.dtype(dtypes[ci])
                for span in spans:
                    vals, valid = (span["handles"], None) \
                        if info.is_pk_handle \
                        else span["cols"][info.col_id]
                    if not _fits_dtype(vals, valid, dt):
                        return False

        def span_pairs(span):
            pairs = []
            for info in used_infos:
                if info.is_pk_handle:
                    h = span["handles"]
                    pairs.append((h, np.ones(len(h), np.bool_)))
                else:
                    pairs.append(span["cols"][info.col_id])
            return pairs

        def arg_planes_ok(arg_nbytes) -> bool:
            for r, planes in zip(plan.agg_rpns, arg_nbytes):
                if r is None or r.ret_type is EvalType.REAL or \
                        len(r.nodes) != 1 or \
                        not isinstance(r.nodes[0], RpnColumnRef):
                    continue    # computed exprs use dtype widths: stable
                ci = r.nodes[0].col_idx
                for span in spans:
                    vals, valid = span_pairs(span)[ci]
                    live = vals if valid is None or valid.all() \
                        else vals[valid]
                    if live.size and int_planes_needed(
                            int(live.min()), int(live.max())) > planes:
                        return False
            return True

        if "hash_bounds" in meta:
            base, width, arg_nbytes = meta["hash_bounds"]
            for span in spans:
                pairs = span_pairs(span)
                m = len(span["handles"])
                kv, km = eval_rpn(plan.key_rpn, pairs, m, np)
                kv = np.broadcast_to(kv, (m,))
                km = np.broadcast_to(km, (m,))
                live = kv[km]
                if live.size and (int(live.min()) < base or
                                  int(live.max()) >= base + width):
                    return False
            if not arg_planes_ok(arg_nbytes):
                return False
        if "simple_arg_nbytes" in meta and \
                not arg_planes_ok(meta["simple_arg_nbytes"]):
            return False
        return True

    def _result(self, dag, schema, columns) -> "SelectResult":
        from ..executors.runner import SelectResult
        return SelectResult(ColumnBatch(schema, columns), [])

    def _kern_key(self, kind, dag, feed, chunk, *extra):
        return (kind, dag.plan_key(), feed["null_flags"], feed["n_pad"],
                chunk) + extra

    # -- analyze (tp=104) --

    # -- simple agg --

    def _arg_ok_is_mask(self, plan, feed) -> list:
        """Per-agg flag: the arg's validity provably equals the row mask
        (bare NOT NULL column ref), so its plane aliases the mask plane."""
        out = []
        for r in plan.agg_rpns:
            flag = False
            if r is not None and len(r.nodes) == 1 and \
                    isinstance(r.nodes[0], RpnColumnRef):
                ci = r.nodes[0].col_idx
                flag = not feed["null_flags"][ci]
            out.append(flag)
        return out

    def _simple_result(self, dag, plan, merged):
        finals = finalize_simple(plan.specs, merged)
        from ..executors.aggregation import _agg_ret_ft
        schema, cols = [], []
        for spec, val in zip(plan.specs, finals):
            ft = _agg_ret_ft(spec.kind, spec.eval_type if spec.kind not in
                             ("count", "count_star") else None)
            schema.append(ft)
            cols.append(Column.from_list(ft.eval_type, [val]))
        return self._result(dag, schema, cols)

    def _run_simple(self, dag, plan, host_cols, dtypes, n, feed, meta):
        from ..utils import tracker as _tracker
        # the fused Pallas kernel serves simple aggregations too (r6):
        # a single-slot grid turns SUM/COUNT/AVG into one direct-index
        # pass — the XLA scan's per-step and fusion-boundary costs
        # (pallas_hash.py module doc) taxed config 3 the same way they
        # taxed config 4
        from .kernels import build_layouts, matmul_supported
        if matmul_supported(plan.specs):
            arg_nbytes = meta.get("simple_arg_nbytes") \
                if meta is not None else None
            if arg_nbytes is None:
                arg_nbytes = self._arg_nbytes(plan, host_cols(), n)
                if meta is not None:
                    meta["simple_arg_nbytes"] = arg_nbytes
            arg_is_real = [r is not None and r.ret_type is EvalType.REAL
                           for r in plan.agg_rpns]
            arg_ok_is_mask = self._arg_ok_is_mask(plan, feed)
            layouts, p8, pf = build_layouts(plan.specs, arg_is_real,
                                            arg_nbytes, arg_ok_is_mask)
            got = self._try_pallas(dag, plan, feed, dtypes, n, 0, 1,
                                   layouts, p8, pf, arg_nbytes,
                                   arg_ok_is_mask, mode="simple")
            if got is not None:
                kind, payload, LO = got

                def from_packed(packed):
                    _present, states = self._pallas_states(
                        packed, LO, p8, layouts, plan.specs, 1)
                    merged = [{k: np.asarray(v).reshape(-1)[0]
                               for k, v in s.items()} for s in states]
                    return self._simple_result(dag, plan, merged)

                if kind == "sync":
                    return from_packed(payload)
                return _Pending(payload,
                                lambda parts: from_packed(_sum_parts(parts)))

        chunk = self._pick_chunk(feed["n_pad"], _CHUNK_AGG)
        n_cols = len(plan.used_cols)
        key = self._kern_key("simple", dag, feed, chunk, tuple(dtypes))
        carry = self._cached_carry(key,
                                   lambda: self._init_agg_carry(plan, None))
        kern = self._shard_kernel(
            key, lambda: self._wrap_mega(
                self._mega(self._build_simple_body(plan, n_cols),
                           self._finalize_psum_summed(),
                           feed["null_flags"], feed["n_pad"], chunk),
                carry, len(feed["flat"])))
        with self._dispatch_phase("simple", key):
            carry = kern(carry, self._cached_scalar(n, jnp.int64),
                         self._cached_scalar(0, jnp.int64),
                         *feed["flat"])

        def fin(fetched):
            summed, stacked = fetched
            if not self._single:
                # summed fields already psum-merged on ICI; only the
                # per-shard (S,) min/max/first scalars reduce here
                with _tracker.phase("shard_merge"):
                    merged = self._merge_stacked(plan.specs, summed,
                                                 stacked)
            else:
                merged = self._merge_stacked(plan.specs, summed,
                                             stacked)
            return self._simple_result(dag, plan, merged)

        return _Pending(carry, fin)

    # -- hash agg --

    def _sparse_slots(self, plan, host_cols, n, feed, meta):
        """Host recode of a sparse GROUP BY key into dense slot ids.

        A sparse int64 key domain (user ids, hashes) cannot
        direct-index into [0, capacity).  Ranking on device was tried
        and measured: ``searchsorted``/gather per row lowers to
        scalar-gather loops on TPU (~120× slower than the dense MXU
        path).  The TPU-shaped answer is dictionary encoding OUTSIDE
        the kernel — exactly how BYTES columns reach devices — so the
        recode runs once per snapshot on host (np.unique's sort is the
        C path) and the slot column is cached in HBM next to the feed;
        warm requests then run the identical one-hot MXU kernel as the
        dense case.  Reference analog: fast_hash_aggr_executor.rs keys
        its specialised hashmap once per scan, not per batch.

        Returns (uniq_np, nd, capacity, slot device array) or None when
        the distinct count exceeds the sparse budget.
        """
        if "sparse_slots" in meta:
            return meta["sparse_slots"]
        kv, km = eval_rpn(plan.key_rpn, host_cols(), n, np)
        kv = np.broadcast_to(kv, (n,))
        km = np.broadcast_to(km, (n,))
        valid = kv[km] if not km.all() else kv
        got = None
        if valid.size:
            # keep the key dtype: casting a uint64 domain to int64 would
            # wrap keys >= 2^63 and emit wrong group values
            uniq, inv = np.unique(valid, return_inverse=True)
            nd = len(uniq)
            if nd <= self._max_hash_capacity:
                capacity = max(1024, _next_pow2(nd))
                idx = np.full(n, capacity, np.int32)       # NULL slot
                if km.all():
                    idx[:] = inv.astype(np.int32)
                else:
                    idx[km] = inv.astype(np.int32)
                n_pad = feed["n_pad"]
                padded = np.full(n_pad, capacity + 1, np.int32)  # scrap
                padded[:n] = idx
                dev = jnp.asarray(padded) if self._single else \
                    jax.device_put(padded, self._row_sharding)
                got = (uniq, nd, capacity, dev)
        meta["sparse_slots"] = got
        return got

    def _run_hash(self, dag, plan, host_cols, dtypes, n, feed, meta,
                  tile_spans=None):
        from ..utils import tracker as _tracker
        from .kernels import (
            build_layouts,
            matmul_supported,
            states_from_matmul,
            twolevel_dims,
            twolevel_lo,
            twolevel_unpack,
        )
        if "hash_bounds" in meta:
            base, span, arg_nbytes = meta["hash_bounds"]
        else:
            kv, km = eval_rpn(plan.key_rpn, host_cols(), n, np)
            kv = np.broadcast_to(kv, (n,))
            km = np.broadcast_to(km, (n,))
            valid_keys = kv[km]
            if valid_keys.size:
                base = int(valid_keys.min())
                span = int(valid_keys.max()) - base + 1
            else:
                base, span = 0, 1
            arg_nbytes = self._arg_nbytes(plan, host_cols(), n)
            meta["hash_bounds"] = (base, span, arg_nbytes)
            meta.setdefault("n_rows", n)
        sparse_keys = None          # (uniq_np, slot device array)
        if span > self._max_hash_capacity:
            # sparse key domain: direct indexing can't span it, but the
            # DISTINCT count may still be small — dictionary-encode the
            # key once per snapshot and feed dense slot ids (the
            # reference's fast_hash_aggr_executor.rs handles arbitrary
            # int keys with a hashmap, runner.rs:293-318)
            got = self._sparse_slots(plan, host_cols, n, feed, meta)
            if got is None:
                raise _FallbackToHost(f"hash key span {span}")
            uniq_np, nd, capacity, slots_dev = got
            sparse_keys = (uniq_np, slots_dev)
        else:
            capacity = max(1024, _next_pow2(span))
        slots = capacity + 2
        arg_is_real = [r is not None and r.ret_type is EvalType.REAL
                       for r in plan.agg_rpns]
        # a bare reference to a NOT NULL column has validity ≡ row mask —
        # alias its plane to the mask plane instead of duplicating it
        # through the matmul (cuts config-4's W operand 4→3 planes)
        arg_ok_is_mask = self._arg_ok_is_mask(plan, feed)
        layouts = p8 = pf = None
        if matmul_supported(plan.specs):
            layouts, p8, pf = build_layouts(plan.specs, arg_is_real,
                                            arg_nbytes, arg_ok_is_mask)
        sparse = sparse_keys is not None
        # the sparse slot column rides the sharded flat inputs like any
        # other column (one extra all-valid pair after the scan columns)
        kern_flat = feed["flat"] + (sparse_keys[1],) if sparse \
            else feed["flat"]
        kern_null_flags = feed["null_flags"] + (False,) if sparse \
            else feed["null_flags"]
        aux_arr = self._cached_scalar(base, jnp.int64)
        n_arr = self._cached_scalar(n, jnp.int64)
        n_cols = len(plan.used_cols)

        slot_keys = sparse_keys[0] if sparse else None

        def hash_result(merged):
            keys, results = finalize_hash(plan.specs, merged, base,
                                          capacity, slot_keys=slot_keys)
            from ..executors.aggregation import _agg_ret_ft
            schema, cols = [], []
            for spec, vals in zip(plan.specs, results):
                ft = _agg_ret_ft(spec.kind,
                                 spec.eval_type if spec.kind not in
                                 ("count", "count_star") else None)
                schema.append(ft)
                cols.append(Column.from_list(ft.eval_type, vals))
            schema.append(FieldType.long())
            cols.append(Column.from_list(EvalType.INT, keys))
            return self._result(dag, schema, cols)

        got = None
        if layouts is not None:
            # the fused direct-index kernel is the default body for
            # both dense and (dictionary-encoded) sparse key domains —
            # the slot column rides as one extra int32 kernel input
            got = self._try_pallas(dag, plan, feed, dtypes, n, base,
                                   capacity, layouts, p8, pf,
                                   arg_nbytes, arg_ok_is_mask,
                                   mode="sparse" if sparse else "dense",
                                   spans=tile_spans,
                                   slots_dev=sparse_keys[1] if sparse
                                   else None)
        if got is None and tile_spans is not None:
            # bucket tiles exist only on the fused-kernel path; the
            # host pipeline serves the original ranged request instead
            raise _FallbackToHost("bucket tiles need the pallas kernel")
        if got is not None:
            kind, payload, pl_LO = got

            def from_packed(packed):
                present, states = self._pallas_states(
                    packed, pl_LO, p8, layouts, plan.specs, slots)
                return hash_result({"present": present,
                                    "overflow": False, "states": states})

            if kind == "sync":
                return from_packed(payload)
            return _Pending(payload,
                            lambda parts: from_packed(_sum_parts(parts)))
        elif layouts is not None and twolevel_lo(p8, pf) is not None:
            LO, HI = twolevel_dims(slots, p8, pf)
            chunk = self._pick_chunk(feed["n_pad"], self._feed_unit())
            key = self._kern_key("hash2l", dag, feed, chunk, tuple(dtypes),
                                 capacity, arg_nbytes,
                                 tuple(arg_ok_is_mask), sparse)
            carry = self._cached_carry(key, lambda: (
                (np.zeros((HI, p8 * LO), np.int64),
                 np.zeros((HI, max(pf, 1) * LO), np.float64),
                 np.zeros((), np.int64)),
                []))
            kern = self._shard_kernel(
                key, lambda: self._wrap_mega(
                    self._mega(self._build_hash_twolevel_body(
                        plan, n_cols, capacity, layouts, LO, HI, pf,
                        sparse=sparse),
                        self._finalize_psum_summed(),
                        kern_null_flags, feed["n_pad"], chunk),
                    carry, len(kern_flat)))
            with self._dispatch_phase("hash_twolevel", key):
                carry = kern(carry, n_arr, aux_arr, *kern_flat)

            def fin_twolevel(fetched):
                (S8p, Sfp, ovf), _ = fetched
                assert int(ovf) == 0, "hash agg key range overflow"
                S8 = twolevel_unpack(S8p, p8, LO, slots, xp=np)
                Sf = twolevel_unpack(Sfp, pf, LO, slots, xp=np) \
                    if pf else None
                present, states = states_from_matmul(layouts, plan.specs,
                                                     S8, Sf, xp=np)
                return hash_result({"present": present, "overflow": False,
                                    "states": states})

            return _Pending(carry, fin_twolevel)
        else:
            chunk = self._pick_chunk(feed["n_pad"], _CHUNK_AGG)
            key = self._kern_key("hashsc", dag, feed, chunk, tuple(dtypes),
                                 capacity, sparse)
            # sharded: the order-sensitive stacked states (min/max)
            # tree-reduce on device via the all-to-all bucket merge —
            # the slot axis pads to a shard multiple so buckets split
            # evenly, and D2H shrinks from (S, slots) to (slots,)
            S = self._nshards()
            bucket_merge = not self._single
            slots_m = -(-slots // S) * S if bucket_merge else slots

            def build_scatter_carry():
                sm_init, st_init = self._init_agg_carry(
                    plan, slots, stacked_slots=slots_m)
                return ((sm_init, np.zeros(slots, np.int64),
                         np.zeros((), np.int64)), st_init)

            carry = self._cached_carry(key, build_scatter_carry)
            kern = self._shard_kernel(
                key, lambda: self._wrap_mega(
                    self._mega(self._build_hash_scatter_body(
                        plan, n_cols, capacity, sparse=sparse,
                        stack_pad=slots_m - slots),
                        self._finalize_hash_bucket_merge()
                        if bucket_merge else
                        self._finalize_psum_summed(),
                        kern_null_flags, feed["n_pad"], chunk),
                    carry, len(kern_flat)))
            with self._dispatch_phase("hash_scatter", key):
                carry = kern(carry, n_arr, aux_arr, *kern_flat)

            def fin_scatter(fetched):
                (summed, present_counts, ovf), stacked = fetched
                assert int(ovf) == 0, "hash agg key range overflow"
                if bucket_merge:
                    with _tracker.phase("shard_merge"):
                        states = self._merge_bucketed(
                            plan.specs, summed, stacked, slots)
                else:
                    states = self._merge_stacked(plan.specs, summed,
                                                 stacked)
                return hash_result({
                    "present": present_counts > 0,
                    "overflow": False,
                    "states": states,
                })

            return _Pending(carry, fin_scatter)

    def _bucket_blocks(self, blocks: int) -> int:
        """Round a grid span up to a 4-significant-bit block count —
        the compile-class grid shared with _pad_rows."""
        if blocks > 8:
            s = blocks.bit_length() - 4
            k = -(-blocks // (1 << s))
            if k > 15:
                s += 1
                k = -(-blocks // (1 << s))
            blocks = k << s
        return max(1, blocks)

    @staticmethod
    def _pallas_states(packed, LO, p8, layouts, specs, slots):
        """Packed (2, HI, p8*LO) accumulator pair → (present, states).

        The tight slot grid (no scrap slot; NULL slot only when the key
        may be NULL) may hold fewer than ``slots`` rows: the dropped
        slots are zero by construction (nothing ever scatters there),
        so zero-pad back to the shared layout.
        """
        from . import pallas_hash
        from .kernels import states_from_matmul, twolevel_unpack
        S = pallas_hash.unpack_to_int64(packed)
        have = min(slots, S.shape[0] * LO)
        S8 = twolevel_unpack(S, p8, LO, have, xp=np)
        if have < slots:
            S8 = np.pad(S8, ((0, 0), (0, slots - have)))
        return states_from_matmul(layouts, specs, S8, None, xp=np)

    def _pallas_sharded_wrap(self, run, n_in: int, n_local_pad: int):
        """shard_map wrapper for the fused kernel: each shard runs one
        grid over its LOCAL feed slice (row bounds traced from the
        shard index — the kernel's dead-block guard masks the ragged
        tail shard exactly as it masks bucket padding), then the packed
        int32 partial pairs psum over both mesh axes.  check_rep is
        disabled where the API still has it: pallas_call carries no
        replication rule, and the psum makes the output replicated by
        construction."""
        def local_fn(n_arr, base_arr, *cols_local):
            start = self._shard_index() * n_local_pad
            row_hi = jnp.clip(n_arr - start, 0, n_local_pad)
            packed = run(jnp.asarray(0, jnp.int32), row_hi, base_arr,
                         jnp.asarray(0, jnp.int32), cols_local)
            return lax.psum(packed, ROW_AXES)

        kwargs = dict(mesh=self._mesh,
                      in_specs=(P(), P()) + (P(ROW_AXES),) * n_in,
                      out_specs=P())
        try:
            sm = _shard_map(local_fn, check_rep=False, **kwargs)
        except TypeError:       # newer jax: check_rep retired
            sm = _shard_map(local_fn, **kwargs)
        return jax.jit(sm)

    def _try_pallas(self, dag, plan, feed, dtypes, n, base, capacity,
                    layouts, p8, pf, arg_nbytes, arg_ok_is_mask,
                    mode="dense", spans=None, slots_dev=None):
        """Fused Pallas fast path for the direct-index aggregation
        (dense / sparse-slot / simple modes — pallas_hash module doc).

        ``spans``: row intervals to aggregate (bucket tiles); None =
        the whole feed, dispatched over the ENTIRE padded grid so the
        compile class is exactly the feed-shape cache key — the
        dead-block guard makes the bucketed padding cost DMA only.
        Span tiles keep bucketed block counts for compile-class reuse
        (block offset via prefetch scalar); the packed partials ADD —
        psum-partial merge semantics.

        Returns None when the plan/feed/platform is outside the
        kernel's envelope (the caller then runs an XLA path), else
        ``(kind, payload, LO)``:

        - ``("sync",  packed np.ndarray, LO)`` — first build: compile +
          validate ran synchronously so Mosaic rejections fall back.
        - ``("parts", [device arrays], LO)`` — warm dispatch; the
          caller fetches and ``_sum_parts``-merges them (possibly on a
          completion thread — the async serving path).

        A build or compile failure is cached so the fallback is taken
        once per plan, not per request.

        SHARDED meshes ride the same kernel as per-shard partials
        (partial-at-shard / final-on-ICI — the TiDB split): shard_map
        runs one grid over each shard's local feed slice with traced
        row bounds from the shard index, and the packed int32 partial
        pairs — exact sums by construction — psum across both mesh
        axes before ONE replicated (2, HI, W) result crosses D2H.  Any
        build/lowering failure falls back to the sharded XLA paths
        exactly like the single-device case.
        """
        from . import pallas_hash
        dev0 = self._mesh.devices.flat[0]
        if dev0.platform == "cpu":
            return None     # Mosaic kernels need real TPU lowering
        if not pallas_hash.supported(plan, feed, dtypes, pf, capacity,
                                     self._nshards(), mode):
            return None
        if not self._single and spans is not None:
            return None     # bucket tiles are a single-device shape
        sparse = mode == pallas_hash.MODE_SPARSE
        B = pallas_hash.BLOCK
        total_blocks = feed["n_pad"] // B
        tiles = []          # (row_lo, row_hi, blk0, span_blocks)
        if spans is None:
            tiles.append((0, n, 0, total_blocks))
        else:
            for lo, hi in spans:
                hi = min(hi, n)
                if hi <= lo:
                    continue
                blk0 = lo // B
                nb = self._bucket_blocks(-(-hi // B) - blk0)
                nb = min(nb, total_blocks)
                if blk0 + nb > total_blocks:
                    blk0 = total_blocks - nb  # shift left; rows mask exact
                tiles.append((lo, hi, blk0, nb))
            if not tiles:
                return None

        # kernel input selection: only columns the kernel evaluates
        # (int32, non-null ⇒ one flat entry each) plus the sparse slot
        # column; everything else (e.g. the raw int64 sparse key) stays
        # host/XLA-side
        kset = set(pallas_hash.kernel_col_ids(plan, mode))
        col_sel, col_map, fi = [], [], 0
        for i, has_nulls in enumerate(feed["null_flags"]):
            if i in kset:
                col_map.append(len(col_sel))
                col_sel.append(fi)
            else:
                col_map.append(-1)
            fi += 2 if has_nulls else 1
        col_sel, col_map = tuple(col_sel), tuple(col_map)
        cols = tuple(feed["flat"][j] for j in col_sel)
        if sparse:
            cols += (slots_dev,)

        def dispatch(runs_by_nb):
            packed = None
            for lo, hi, blk0, nb in tiles:
                part = np.asarray(
                    runs_by_nb[nb](lo, hi, base, blk0, cols))
                packed = part if packed is None else packed + part
            return packed

        key = ("hashpl", dag.plan_key(), mode,
               tuple(sorted({t[3] for t in tiles})), tuple(dtypes),
               capacity, arg_nbytes, tuple(arg_ok_is_mask),
               self._nshards())
        entry = self._kernel_cache.get(key)
        if entry is False:
            return None
        if entry is None:
            try:
                if not self._single:
                    # per-shard partial grids + psum tree-reduce: one
                    # shard_map launch, one replicated packed result
                    S = self._nshards()
                    run, LO, HI = pallas_hash.build(
                        plan, layouts, p8, capacity,
                        feed["n_pad"] // (S * B), col_map, mode=mode)
                    wrapped = self._pallas_sharded_wrap(
                        run, len(cols), feed["n_pad"] // S)
                    # compile + validate now so Mosaic/shard_map
                    # rejections fall back to the sharded XLA paths
                    packed = np.asarray(wrapped(
                        self._cached_scalar(n, jnp.int64),
                        self._cached_scalar(base, jnp.int64), *cols))
                    entry = {"sharded": wrapped, "LO": LO,
                             "col_sel": col_sel, "mode": mode}
                else:
                    runs_by_nb = {}
                    LO = None
                    for nb in sorted({t[3] for t in tiles}):
                        run, LO, HI = pallas_hash.build(
                            plan, layouts, p8, capacity, nb, col_map,
                            mode=mode)
                        runs_by_nb[nb] = run
                    # compile + validate now so Mosaic rejections fall
                    # back
                    packed = dispatch(runs_by_nb)
                    entry = {"runs": runs_by_nb, "LO": LO,
                             "col_sel": col_sel, "mode": mode}
            except Exception as e:
                # never silently: a swallowed genuine bug here would
                # disguise itself as the slower XLA path
                import logging
                # cache-disable deterministic build/lowering rejections
                # (Mosaic/compile errors) immediately; a transient runtime
                # failure (device OOM, tunnel hiccup) falls back without
                # poisoning the cache — but only a few times, so a
                # deterministic failure dressed as transient can't re-pay
                # the build+compile cost on every request forever
                name = type(e).__name__
                transient = isinstance(e, (OSError, TimeoutError)) or \
                    "RESOURCE_EXHAUSTED" in str(e) or \
                    name in ("XlaRuntimeError", "InternalError") and \
                    "Mosaic" not in str(e)
                tries = self._kernel_cache.get(("hashpl_tries", key), 0) + 1
                self._kernel_cache[("hashpl_tries", key)] = tries
                if transient and tries < 3:
                    logging.getLogger(__name__).warning(
                        "pallas hash kernel transient failure for plan %r "
                        "(attempt %d/3, falling back once): %s: %s",
                        key[1], tries, name, e)
                else:
                    logging.getLogger(__name__).warning(
                        "pallas hash kernel disabled (cached) for plan "
                        "%r: %s: %s", key[1], name, e)
                    self._kernel_cache[key] = False
                return None
            self._kernel_cache[key] = entry
            # success clears the transient strike count — three isolated
            # hiccups over a process lifetime must not kill the fast path
            self._kernel_cache.pop(("hashpl_tries", key), None)
            return ("sync", packed, entry["LO"])
        LO = entry["LO"]
        try:
            with self._dispatch_phase("pallas_hash", key):
                if "sharded" in entry:
                    parts = [entry["sharded"](
                        self._cached_scalar(n, jnp.int64),
                        self._cached_scalar(base, jnp.int64), *cols)]
                else:
                    runs_by_nb = entry["runs"]
                    parts = [runs_by_nb[nb](lo, hi, base, blk0, cols)
                             for lo, hi, blk0, nb in tiles]
            self._kernel_cache.pop(("hashpl_tries", key), None)
        except Exception as e:
            # a transient DISPATCH failure on a cached kernel must fall
            # back to the XLA path for THIS request, same as the
            # build-time path — not fail the coprocessor request.  (A
            # failure surfacing later, at the possibly-deferred fetch,
            # degrades to the host pipeline via the DeferredResult /
            # endpoint contract instead.)
            import logging
            logging.getLogger(__name__).warning(
                "pallas hash kernel runtime failure for cached plan "
                "%r (falling back once): %s: %s",
                key[1], type(e).__name__, e)
            tries = self._kernel_cache.get(("hashpl_tries", key), 0) + 1
            self._kernel_cache[("hashpl_tries", key)] = tries
            if tries >= 3:
                self._kernel_cache[key] = False
            return None
        return ("parts", parts, LO)

    def _arg_nbytes(self, plan: _Plan, host_cols, n: int) -> tuple:
        """Byte-plane count per aggregate arg for the MXU int path.

        Plain column refs use the column's actual value range (host
        min/max, vectorized); computed expressions use the device dtype
        width (int arithmetic wraps in-dtype on device — documented
        deviation, expr/functions.py)."""
        from .kernels import int_planes_needed
        out = []
        for r in plan.agg_rpns:
            if r is None or r.ret_type is EvalType.REAL:
                out.append(0)
                continue
            nodes = r.nodes
            if len(nodes) == 1 and isinstance(nodes[0], RpnColumnRef):
                v, ok = host_cols[nodes[0].col_idx]
                if v.size:
                    out.append(int_planes_needed(int(v.min()), int(v.max())))
                else:
                    out.append(1)
            else:
                widths = [host_cols[i][0].dtype.itemsize
                          for i in _rpn_col_indices(r)] or [4]
                out.append(max(widths))
        return tuple(out)

    # -- selection (late materialization: predicate on device, COMPACT
    #    selection vector over D2H, alive-mask-aware host gather) --

    def _sel_route_note(self, route: str) -> None:
        from ..utils import metrics as m
        from ..utils import tracker
        tracker.label("routing", route)
        m.DEVICE_SEL_ROUTE_COUNTER.labels(route).inc()
        with self._sel_mu:
            self._sel_route_counts[route] = \
                self._sel_route_counts.get(route, 0) + 1

    def _run_scan_sel(self, dag, plan, dtypes, n, get_batch, feed,
                      storage, stack=None):
        """Device selection whose D2H volume scales with SELECTED rows.

        One fused dispatch evaluates the predicates over the resident
        feed and leaves (count, packed bitmask, bool mask) on device.
        The router (selection.choose_route) then moves the cheapest
        selection vector: the packed mask (n/8 bytes), compacted row
        indices (4·K bytes, second tiny dispatch consuming the resident
        mask), or — small k on a single device — the projected columns
        themselves, compacted on device so the host gather is skipped.
        NOTHING blocks under the dispatch lock: cold requests take the
        always-correct mask route while the device-side count — a
        scalar leaf of every route's readback — rides home with the
        result and seeds the per-plan selectivity EWMA; warm requests
        route by the EWMA with capacity headroom (an undersized
        capacity surfaces as an overflow flag at fetch time and falls
        back to the still-resident packed mask — never a truncated
        result).
        """
        from . import selection as selmod
        from ..utils import tracker as _tracker
        n_pad = feed["n_pad"]
        n_local = n_pad // self._nshards()
        pkey = dag.plan_key()
        stat_keys = self._sel_keys(dag, plan)

        if plan.sel_params is None:
            plan.sel_params = selmod.split_params(plan.sel_rpns,
                                                  len(plan.used_cols))
        param_rpns, param_vals, param_dts = plan.sel_params
        if stack is not None:
            # cross-request STACKED dispatch (server/coalescer.py):
            # every member's hoisted constants ride a leading group
            # axis of the traced scalar params and the whole group is
            # ONE launch + ONE shared D2H.  Pow2 lane buckets keep the
            # compile classes logarithmic; dead lanes repeat lane 0's
            # params and are sliced away by the per-member resolve.
            # Always the packed-mask payload — the always-correct
            # route, since per-member counts are unknown at dispatch.
            G = len(stack)
            gb = 1 << max(0, (G - 1).bit_length())
            bkey = ("selmaskb", selmod.shape_key(plan),
                    feed["null_flags"], n_pad, tuple(dtypes),
                    param_dts, gb)
            bkern = self._shard_kernel(
                bkey, lambda: selmod.build_batched_mask_kernel(
                    param_rpns, feed["null_flags"], n_pad,
                    len(feed["flat"]), len(param_dts), gb))
            lanes = []
            for pi, dt in enumerate(param_dts):
                vals = [stack[g][pi] for g in range(G)]
                vals += [vals[0]] * (gb - G)
                lanes.append(jnp.asarray(
                    np.asarray(vals, dtype=np.dtype(dt))))
            with self._dispatch_phase("scan_sel_batched", bkey):
                counts_dev, packed_dev = bkern(
                    self._cached_scalar(n, jnp.int64), *lanes,
                    *feed["flat"])
            self._sel_route_note("batched")
            return _Pending(
                (counts_dev, packed_dev),
                lambda fetched: (np.asarray(fetched[0]),
                                 np.asarray(fetched[1]), n),
                small=False)
        # const-blind kernel key: repeated selections at differing
        # thresholds within one n_pad bucket share ONE compile class
        skey = ("selmask", selmod.shape_key(plan), feed["null_flags"],
                n_pad, tuple(dtypes), param_dts)
        kern = self._shard_kernel(skey, lambda: selmod.build_mask_kernel(
            param_rpns, feed["null_flags"], n_pad, len(feed["flat"]),
            len(param_dts), None if self._single else self._mesh))
        params = tuple(self._cached_param(v, dt)
                       for v, dt in zip(param_vals, param_dts))
        with self._dispatch_phase("scan_sel_mask", skey):
            count_dev, packed_dev, mask_dev = kern(
                self._cached_scalar(n, jnp.int64), *params, *feed["flat"])
        # bench attribution seam (probe_scan_kernel launch train): ONE
        # slot, not a per-plan-key cache entry — const-inclusive keys
        # would grow the kernel cache per distinct threshold forever
        self._selmask_last = (pkey, skey,
                              tuple(zip(param_vals, param_dts)), n)

        pred = self._sel_predict(stat_keys)
        if pred is None:
            # cold: take the always-correct mask route rather than sync
            # the count here — this runs under _dispatch_mu, and a
            # blocking D2H would serialize every in-flight dispatch
            # behind this kernel (the lock's contract: fetches block
            # OUTSIDE it).  The count leaf seeds the EWMA at finalize.
            route = selmod.ROUTE_MASK
            cap = 0
        else:
            k_est = pred * n
            cap = selmod.index_capacity(k_est * 1.5 + 64, n_local)
            # the index comparison uses the REAL transfer — per-shard
            # pow2 capacity × shard count — not 4·k, which understates
            # it several-fold near the crossover
            route = selmod.choose_route(
                n, k_est, plan.compact_ok and self._single,
                idx_bytes=4 * cap * self._nshards())
        gather_ok = isinstance(plan.scan, TableScanDesc) and \
            hasattr(storage, "gather_rows")

        def gather(sel):
            """sel: bool mask over the scan output, or ascending feed
            positions.  The columnar snapshot's alive-mask-aware
            vectorized take (ColumnarTable.gather_rows) serves both;
            storages without it (row-codec fixtures) pay the batch."""
            if gather_ok:
                out = storage.gather_rows(plan.scan, dag.ranges, sel)
            else:
                b = get_batch()
                out = b.filter(sel) if sel.dtype == np.bool_ else b.take(sel)
            return self._result(dag, out.schema, out.columns)

        def mask_from_packed(packed_np):
            return np.unpackbits(packed_np)[:n].astype(np.bool_)

        def observe(cnt) -> int:
            k = int(cnt)
            self._sel_observe(stat_keys, k / n if n else 0.0)
            return k

        def fallback_to_mask():
            # predicted capacity undersized: the packed bitmask is
            # still device-resident — fetch it instead (plain D2H, no
            # dispatch lock needed)
            self._sel_route_note("mask_fallback")
            _tracker.label("routing", selmod.ROUTE_MASK)
            return gather(mask_from_packed(np.asarray(packed_dev)))

        if route == selmod.ROUTE_COMPACT:
            ckey = ("selcompact", n_pad, cap, feed["null_flags"],
                    tuple(dtypes))
            ckern = self._shard_kernel(
                ckey, lambda: selmod.build_compact_kernel(
                    n_pad, cap, feed["null_flags"]))
            with self._dispatch_phase("scan_sel_compact", ckey):
                outs_dev, ovf_dev = ckern(mask_dev, *feed["flat"])
            self._sel_route_note(route)
            scan_cols = plan.scan.columns

            def fin_compact(fetched):
                cnt, outs, ovf = fetched
                k = observe(cnt)
                if int(ovf):
                    return fallback_to_mask()
                schema, cols = [], []
                oi = 0
                for ci, info in enumerate(scan_cols):
                    et = EvalType.INT if info.is_pk_handle \
                        else info.field_type.eval_type
                    vals = outs[oi][:k]
                    oi += 1
                    if feed["null_flags"][ci]:
                        valid = outs[oi][:k].astype(np.bool_)
                        oi += 1
                    else:
                        valid = np.ones(k, np.bool_)
                    hdt = np.uint64 if et is EvalType.DATETIME else np.int64
                    schema.append(info.field_type)
                    cols.append(Column(et, vals.astype(hdt, copy=False),
                                       valid))
                return self._result(dag, schema, cols)

            payload = cap * (sum(np.dtype(ds).itemsize for ds in dtypes)
                             + sum(feed["null_flags"]))
            return _Pending((count_dev, outs_dev, ovf_dev), fin_compact,
                            small=payload <= (1 << 16))

        if route == selmod.ROUTE_INDEX:
            # plan-independent kernels: every selection shares them
            ikey = ("selidx", n_pad, cap)
            ikern = self._shard_kernel(
                ikey, lambda: selmod.build_index_kernel(
                    n_pad, cap, None if self._single else self._mesh))
            with self._dispatch_phase("scan_sel_index", ikey):
                idx_dev, ovf_dev = ikern(mask_dev)
            self._sel_route_note(route)

            def fin_index(fetched):
                cnt, idx, ovf = fetched
                observe(cnt)
                if int(ovf):
                    return fallback_to_mask()
                ids = np.asarray(idx, dtype=np.int64)
                return gather(ids[ids >= 0])

            # "small" is a completion-pool priority hint for KB-class
            # fetches; a capacity near the 3.1% crossover can be MBs
            return _Pending((count_dev, idx_dev, ovf_dev), fin_index,
                            small=4 * cap * self._nshards() <= (1 << 16))

        self._sel_route_note(selmod.ROUTE_MASK)

        def fin_mask(fetched):
            cnt, packed = fetched
            observe(cnt)
            return gather(mask_from_packed(packed))

        return _Pending((count_dev, packed_dev), fin_mask, small=False)

    # -- top-n --

    def _run_topn(self, dag, plan, host_cols, dtypes, n, get_batch, feed):
        k = plan.limit
        n_used = None
        if self._single:
            seg = math.gcd(feed["n_pad"], 1 << 17)
            n_used = min(feed["n_pad"], -(-n // seg) * seg)
        key = self._kern_key("topn", dag, feed, 0, tuple(dtypes), k,
                             n_used)
        kern = self._shard_kernel(
            key, lambda: self._build_topn_kernel(
                plan, len(plan.used_cols), k, feed["null_flags"],
                feed["n_pad"], len(feed["flat"]), n_used=n_used))
        with self._dispatch_phase("topn", key):
            ys = kern(self._cached_scalar(n, jnp.int64), *feed["flat"])

        def fin(fetched):
            gidx_s, mask_s, ok_s = fetched
            gidx = gidx_s.reshape(-1)
            mask = mask_s.reshape(-1)
            ok = ok_s.reshape(-1)
            sel = mask & (gidx < n)
            gidx, okk = gidx[sel], ok[sel]
            # exact host ordering over <= k * n_chunks * n_shards
            # candidates: evaluate the order expression only on the
            # gathered candidate rows (plan rpns are remapped onto
            # host_cols positions)
            cand_cols = [(v[gidx], m[gidx]) for v, m in host_cols()]
            ov, _om = eval_rpn(plan.order_rpn, cand_cols, len(gidx), np)
            ov = np.broadcast_to(ov, (len(gidx),))
            if plan.order_rpn.ret_type in (EvalType.INT, EvalType.DATETIME,
                                           EvalType.DURATION):
                # exact int ordering (no f64 collapse above 2^53 — a
                # packed DATETIME core at ~2^61 loses sub-millisecond
                # bits in f64); NULL is the smallest value, so asc →
                # NULL first, desc → NULL last.  Clamp to min+2 so
                # negation cannot overflow.  DATETIME u64 cores are
                # < 2^63 (feed guard) so the int64 view is
                # order-preserving.
                lo, hi = np.iinfo(np.int64).min, np.iinfo(np.int64).max
                vals = np.maximum(np.asarray(ov).astype(np.int64), lo + 2)
                if plan.order_desc:
                    skey = np.where(okk, -vals, hi)
                else:
                    skey = np.where(okk, vals, lo)
                order = np.lexsort((gidx, skey))
            else:
                vals = np.asarray(ov, dtype=np.float64)
                keyf = np.where(okk, vals, -np.inf)     # NULL smallest
                order = np.lexsort((gidx,
                                    -keyf if plan.order_desc else keyf))
            take = gidx[order[:plan.limit]]
            out = get_batch().take(take)
            return self._result(dag, out.schema, out.columns)

        return _Pending(ys, fin, small=False)


class _AnalyzeKernels:
    """Per-(dtype, n_pad, buckets) jitted ANALYZE kernels.

    One ``jnp.sort`` per column is the whole cost — XLA's on-device sort
    runs at HBM speed, which is exactly why ANALYZE belongs on the TPU
    (SURVEY §2.4: statistics; the reference's sample collectors are a
    CPU workaround for not having a fast sort).  NULL/padding rows key
    past every real value; null count, distinct count (boundary diffs)
    and the equi-depth bucket bounds all fall out of the same sorted
    array, gathered at rank positions ON DEVICE so one packed (2B+2,)
    int64 vector comes back per column.

    Measured (v5e, 20M int32 rows): on-device sort ~4ms vs numpy 660ms
    (~160x).  Through the tunneled session the request is
    transfer-bound (~0.4s H2D + ~0.65s fetch sync per column,
    overlapped across columns); co-located chips don't pay that RTT.
    """

    def __init__(self):
        self._cache: dict = {}

    def get(self, dtype, n_pad: int, n_buckets: int):
        key = (str(dtype), n_pad, n_buckets)
        fn = self._cache.get(key)
        if fn is None:
            fn = self._cache[key] = self._build(np.dtype(dtype),
                                                n_buckets)
        return fn

    @staticmethod
    def _build(dt, n_buckets: int):
        is_f = dt.kind == "f"
        # sort in the column's NATIVE dtype — an int64 up-cast would put
        # the whole sort on the pair-emulated path (measured 4x slower
        # than host numpy at 20M rows; native int32 sort beats it).
        # Int sentinel for NULL/padding = dtype max: a real value EQUAL
        # to the sentinel interleaves with the padding block, but rank
        # gathers read the same numeric value and equal values stay
        # adjacent for the distinct count — results unchanged.  Float
        # sentinel must be NaN, NOT +inf: jnp.sort puts NaNs last, so
        # an inf sentinel would sort BEFORE a column's real NaNs and
        # leak padding into the valid prefix; with NaN fills, valid
        # NaNs and padding share one tail block whose prefix slice is
        # value-identical to the host's np.sort(valid) ordering (each
        # NaN counts distinct on both paths — NaN != NaN).
        if is_f:
            sent = dt.type(np.nan)
        else:
            sent = np.iinfo(dt).max

        def kern(values, validity, n_arr):
            n_pad = values.shape[0]
            iota = jnp.arange(n_pad, dtype=jnp.int64)
            mask = (iota < n_arr) & validity
            key = jnp.where(mask, values, jnp.asarray(sent, values.dtype))
            s = jnp.sort(key)
            n_valid = jnp.sum(mask, dtype=jnp.int64)
            in_prefix = iota[1:] < n_valid
            distinct = jnp.sum((s[1:] != s[:-1]) & in_prefix,
                               dtype=jnp.int64) + \
                jnp.where(n_valid > 0, 1, 0)
            # equi-depth rank positions over the VALID prefix
            b = jnp.arange(1, n_buckets + 1, dtype=jnp.int64)
            ranks = jnp.maximum((b * n_valid) // n_buckets - 1, 0)
            bounds = jnp.take(s, ranks)
            # ONE packed int64 output → ONE D2H fetch: through the
            # tunnel every blocking fetch is a ~0.65s sync round trip,
            # and four outputs per column dominated the request.
            # Floats ride bit-cast; ints widen losslessly.
            if is_f:
                bits = lax.bitcast_convert_type(
                    bounds.astype(jnp.float64), jnp.int64)
            else:
                bits = bounds.astype(jnp.int64)
            return jnp.concatenate([
                bits, ranks + 1,
                jnp.stack([n_valid, distinct])])

        return jax.jit(kern)


def _analyze_on_device(runner, dag, storage, n_buckets: int):
    """DeviceRunner.handle_analyze body (module-level to keep the class
    focused on DAG execution).  Returning None routes the request to
    the host analyze path — including when a device::* failpoint fires
    inside the dispatch/fetch (the degrade contract)."""
    if runner._placer is not None and hasattr(storage, "scan_columns"):
        # placement: ANALYZE sorts are single-device kernels — run them
        # on the region's placed slice instead of declining shard-wide
        target = runner._placer.route(storage)
        if target is not runner:
            return _analyze_on_device(target, dag, storage, n_buckets)
    try:
        return _analyze_on_device_impl(runner, dag, storage, n_buckets)
    except _FallbackToHost:
        return None


def _analyze_on_device_impl(runner, dag, storage, n_buckets: int):
    from ..copr.analyze import ColumnStats, analyze_columns
    if not runner._single:
        # a global sort across shards needs an all-to-all; stats merge
        # across hosts happens at the PD/stats layer instead
        return None
    scan = dag.executors[0]
    plan = _Plan(scan=scan, kind="scan", used_cols=[])
    batch = runner._scan_batch(dag, plan, storage)
    n = batch.num_rows
    if n == 0:
        return analyze_columns(batch, scan.columns, n_buckets)
    if not hasattr(runner, "_analyze_kernels"):
        runner._analyze_kernels = _AnalyzeKernels()
    # phase 1 — dispatch EVERY device column before any blocking fetch:
    # through the tunnel each fetch is a ~0.65s sync round trip, so the
    # per-column work must overlap
    pending: dict = {}
    out_by_idx: dict = {}
    host_cols_idx: list = []
    for i, info in enumerate(scan.columns):
        col = batch.columns[i]
        et = col.eval_type
        if et not in _DEVICE_ETS or (
                col.values.dtype == np.uint64 and col.values.size
                and int(col.values.max()) >= (1 << 63)):
            # BYTES/JSON/etc or beyond-int64 cores: host numpy path —
            # DEFERRED until every device column has been dispatched
            # (a python-object sort here would serialize in front of
            # the device work this split exists to overlap)
            host_cols_idx.append(i)
            continue
        # stats must be EXACT: REAL keeps float64 (the f32 device column
        # resolution would collapse near-equal doubles, changing
        # distinct counts and bucket bounds)
        dt = np.dtype(np.float64) if et is EvalType.REAL \
            else _device_dtype(et, col.values)
        n_pad = runner._pad_rows(n)
        vals = np.zeros(n_pad, dtype=dt)
        vals[:n] = col.values.astype(dt, copy=False)
        valid = np.zeros(n_pad, dtype=np.bool_)
        valid[:n] = col.validity
        kern = runner._analyze_kernels.get(dt, n_pad, n_buckets)
        pending[i] = (info, et, kern(
            jnp.asarray(vals), jnp.asarray(valid),
            jnp.asarray(n, jnp.int64)))
    # host-fallback columns run while the device crunches the rest
    for i in host_cols_idx:
        out_by_idx[i] = analyze_columns(
            ColumnBatch([batch.schema[i]], [batch.columns[i]]),
            [scan.columns[i]], n_buckets)[0]
    # phase 2 — ONE batched readback for every column (copy_to_host
    # issued for all before the first blocking fetch), then unpack
    fetched = runner._readback({i: dev for i, (_info, _et, dev)
                                in pending.items()})
    for i, (info, et, _dev) in pending.items():
        packed = fetched[i]
        bits = packed[:n_buckets]
        counts = packed[n_buckets:2 * n_buckets]
        n_valid = int(packed[-2])
        distinct = int(packed[-1])
        bounds = bits.view(np.float64) if et is EvalType.REAL else bits
        buckets = []
        prev = 0
        for bnd, cnt in zip(bounds.tolist(), counts.tolist()):
            cnt = min(int(cnt), n_valid)
            if cnt <= prev:     # degenerate bucket (n_valid < buckets)
                continue
            buckets.append((float(bnd) if et is EvalType.REAL
                            else int(bnd), cnt))
            prev = cnt
        out_by_idx[i] = ColumnStats(info.col_id, n, n - n_valid,
                                    distinct, buckets)
    return [out_by_idx[i] for i in range(len(scan.columns))]


# bound as a method so the endpoint's hasattr(runner, "handle_analyze")
# routing sees it (endpoint.handle_analyze)
DeviceRunner.handle_analyze = _analyze_on_device
