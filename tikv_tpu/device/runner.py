"""Device (TPU) coprocessor backend — fused jit/shard_map pipelines.

This is the north-star slice (SURVEY.md §7, BASELINE.md): the CPU
``BatchExecutor`` hot loop (tidb_query_executors/src/runner.rs:641 —
scan → selection → aggregation per 1024-row batch) becomes ONE fused XLA
computation per plan over million-row chunks:

- rows are sharded over the ("range", "tile") mesh (parallel/mesh.py) —
  TiKV's region/bucket sharding mapped to mesh axes;
- RpnExpression evaluation (expr/eval.py) traces into the same jit as the
  filter mask and the aggregate kernels, so XLA fuses selection into the
  aggregation's HBM pass;
- group-by COUNT/SUM runs on the MXU as one-hot matmuls with exact int8
  byte-split arithmetic (device/kernels.py) — XLA's scatter lowering on
  TPU is ~10× slower on the same shapes;
- aggregation state is a device-resident *carry* folded across row chunks;
  psum-mergeable fields (count/sum/nonnull — TiKV's partial aggregate
  states, tidb_query_aggr) merge with ``lax.psum`` over both mesh axes,
  order-fields (min/max/first-pos) stay per-shard and reduce on host;
- ONE packed device→host transfer ends the request (through a tunneled
  TPU every D2H sync costs ~0.1s; per-chunk readbacks are ruinous).

On a 1-device mesh kernels compile as plain jit (no shard_map, no
NamedSharding transfers — both measurably degrade the tunneled session's
dispatch path).  Host decode never appears on this path: the scan feed is
a columnar snapshot (executors/columnar.py), cached in HBM across requests
(the region-cache-engine analog).  Small requests stay on the host numpy
path (copr/endpoint.py routing) so p99 latency never pays device dispatch.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass, field
from typing import Optional, Sequence

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from ..copr.dag import (
    AggregationDesc,
    DAGRequest,
    IndexScanDesc,
    LimitDesc,
    SelectionDesc,
    TableScanDesc,
    TopNDesc,
)
from ..datatype import Column, ColumnBatch, EvalType, FieldType
from ..datatype.tile import _device_dtype
from ..expr import build_rpn
from ..expr.eval import eval_rpn
from ..expr.rpn import RpnColumnRef, RpnConst, RpnExpression, RpnFnCall
from ..ops.agg import (
    AggSpec,
    finalize_hash,
    finalize_simple,
    hash_agg_tile,
    merge_hash_states,
    merge_simple_states,
    simple_agg_tile,
)
from ..parallel import ROW_AXES, make_mesh, num_shards, row_sharding

_BIG = np.iinfo(np.int64).max


class _FallbackToHost(Exception):
    """Raised when a runtime property (not the plan) forces the host path."""
_DEVICE_ETS = (EvalType.INT, EvalType.REAL)

# TopN sort-key sentinels (float64 keys; any real data is far inside these)
_EXCLUDED_ASC = 1e308
_EXCLUDED_DESC = -1e308
_NULL_KEY = -1e307          # MySQL: NULL sorts below every value


def _next_pow2(n: int) -> int:
    return 1 << max(0, (n - 1).bit_length())


def _rpn_col_indices(rpn: RpnExpression) -> set:
    return {n.col_idx for n in rpn.nodes if isinstance(n, RpnColumnRef)}


def _remap_rpn(rpn: RpnExpression, mapping: dict) -> RpnExpression:
    nodes = []
    for n in rpn.nodes:
        if isinstance(n, RpnColumnRef):
            nodes.append(RpnColumnRef(mapping[n.col_idx], n.eval_type))
        else:
            nodes.append(n)
    return RpnExpression(tuple(nodes))


def _rpn_device_safe(rpn: RpnExpression, scan_ets: Sequence[EvalType]) -> bool:
    for n in rpn.nodes:
        if isinstance(n, RpnConst):
            if n.value is not None and not isinstance(n.value, (int, float, bool)):
                return False
        elif isinstance(n, RpnColumnRef):
            if n.col_idx >= len(scan_ets) or scan_ets[n.col_idx] not in _DEVICE_ETS:
                return False
        elif isinstance(n, RpnFnCall):
            if n.meta.ret not in _DEVICE_ETS:
                return False
    return True


@dataclass
class _Plan:
    """Analyzed device plan (rpns remapped onto ``used_cols`` positions)."""

    scan: TableScanDesc
    kind: str                        # scan | simple_agg | hash_agg | topn
    used_cols: list                  # original scan column offsets shipped to device
    sel_rpns: list = field(default_factory=list)
    specs: list = field(default_factory=list)        # AggSpec per agg
    agg_rpns: list = field(default_factory=list)     # RpnExpression | None
    key_rpn: Optional[RpnExpression] = None
    order_rpn: Optional[RpnExpression] = None
    order_desc: bool = False
    limit: int = 0


class DeviceRunner:
    """Executes supported DAG plans on the device mesh.

    Registered with copr.Endpoint the way coprocessor_v2 plugins register an
    alternate execution backend (coprocessor_plugin_api/src/lib.rs:5-43).
    """

    def __init__(self, mesh=None, chunk_rows: int = 1 << 23,
                 max_hash_capacity: int = 1 << 20,
                 max_topn_limit: int = 1 << 14):
        # int64 accumulators are required for exact SUM/COUNT over 1e8
        # rows; jax defaults to 32-bit.  Values stay int32/float32 on
        # device, only accumulators widen.  (Set here, not at import, so
        # importing the package has no process-global side effect.)
        jax.config.update("jax_enable_x64", True)
        self._mesh = mesh if mesh is not None else make_mesh()
        self._chunk_rows = chunk_rows
        self._max_hash_capacity = max_hash_capacity
        self._max_topn_limit = max_topn_limit
        self._row_sharding = row_sharding(self._mesh)
        self._repl = NamedSharding(self._mesh, P())
        # Single-device (the real-chip bench): plain jit + uncommitted
        # arrays.  Explicit NamedSharding transfers and shard_map wrappers
        # measurably degrade the tunneled-TPU session's dispatch path, and
        # a 1-device mesh gains nothing from them.
        self._single = num_shards(self._mesh) == 1
        self._plan_cache: dict = {}
        self._kernel_cache: dict = {}
        # HBM-resident feed cache — the TPU-native analog of TiKV's
        # in-memory region cache engine (components/
        # region_cache_memory_engine: RangeCacheMemoryEngine layered over
        # RocksDB).  Columnar snapshots are immutable, so cache entries are
        # valid for the snapshot's lifetime; keyed weakly on the snapshot.
        import weakref
        self._feed_cache: "weakref.WeakKeyDictionary" = \
            weakref.WeakKeyDictionary()

    # ------------------------------------------------------------------ plan

    def supports(self, dag: DAGRequest) -> bool:
        return self._analyze(dag) is not None

    def profitable(self, dag: DAGRequest) -> bool:
        """Should auto-routing pick the device for this plan?

        Aggregations and TopN reduce on device (tiny D2H readback) and
        measure far above the host path; selection-only plans materialize
        their full output through the host anyway, so the device pass
        only adds transfer cost — measured slower than the vectorized
        host path on 10M rows (bench config 2).  force_backend="device"
        still runs them for parity testing.
        """
        plan = self._analyze(dag)
        return plan is not None and plan.kind in ("simple_agg", "hash_agg",
                                                  "topn")

    def _analyze(self, dag: DAGRequest) -> Optional[_Plan]:
        key = dag.plan_key()
        if key in self._plan_cache:
            return self._plan_cache[key]
        plan = self._analyze_uncached(dag)
        self._plan_cache[key] = plan
        return plan

    def _analyze_uncached(self, dag: DAGRequest) -> Optional[_Plan]:
        execs = dag.executors
        # IndexScan heads are device-eligible too: a covering index scan
        # produces columnar (indexed cols, handle) tiles exactly like a
        # table scan (BASELINE config 5 — TopN via IndexScan; reference:
        # index_scan_executor.rs feeds the same BatchExecutor pipeline)
        if not execs or not isinstance(execs[0],
                                       (TableScanDesc, IndexScanDesc)):
            return None
        scan = execs[0]
        if isinstance(scan, IndexScanDesc):
            n_idx = len(scan.columns) - (
                1 if scan.columns and scan.columns[-1].is_pk_handle else 0)
            if n_idx != 1:
                return None     # multi-column index → host row path
        scan_ets = [c.field_type.eval_type for c in scan.columns]

        sel_rpns: list[RpnExpression] = []
        terminal = None
        for d in execs[1:]:
            if isinstance(d, SelectionDesc):
                if terminal is not None:
                    return None
                for cond in d.conditions:
                    sel_rpns.append(build_rpn(cond))
            elif isinstance(d, (AggregationDesc, TopNDesc)):
                if terminal is not None:
                    return None
                terminal = d
            else:
                return None     # projection/limit → host path

        rpns_to_check = list(sel_rpns)
        plan = _Plan(scan=scan, kind="scan", used_cols=[])

        if isinstance(terminal, AggregationDesc):
            if len(terminal.group_by) > 1:
                return None
            agg_rpns, specs = [], []
            for i, a in enumerate(terminal.aggs):
                if a.kind not in ("count", "count_star", "sum", "avg",
                                 "min", "max", "first"):
                    return None
                if a.arg is not None:
                    r = build_rpn(a.arg)
                    agg_rpns.append(r)
                    rpns_to_check.append(r)
                    specs.append(AggSpec(a.kind, i, r.ret_type))
                else:
                    agg_rpns.append(None)
                    specs.append(AggSpec(a.kind, i))
            if terminal.group_by:
                if any(s.kind == "first" for s in specs):
                    return None     # FIRST needs source-row gather → host
                key_rpn = build_rpn(terminal.group_by[0])
                if key_rpn.ret_type is not EvalType.INT:
                    return None
                rpns_to_check.append(key_rpn)
                plan.kind = "hash_agg"
                plan.key_rpn = key_rpn
            else:
                plan.kind = "simple_agg"
            plan.specs = specs
            plan.agg_rpns = agg_rpns
        elif isinstance(terminal, TopNDesc):
            if len(terminal.order_by) != 1 or \
                    terminal.limit > self._max_topn_limit:
                return None
            order_expr, desc = terminal.order_by[0]
            order_rpn = build_rpn(order_expr)
            if order_rpn.ret_type not in _DEVICE_ETS:
                return None
            rpns_to_check.append(order_rpn)
            plan.kind = "topn"
            plan.order_rpn = order_rpn
            plan.order_desc = desc
            plan.limit = terminal.limit
        elif sel_rpns:
            plan.kind = "scan_sel"
        else:
            return None     # bare scan: decode-bound, no device win

        for r in rpns_to_check:
            if not _rpn_device_safe(r, scan_ets):
                return None

        used = sorted(set().union(*[_rpn_col_indices(r) for r in rpns_to_check])
                      if rpns_to_check else set())
        mapping = {old: new for new, old in enumerate(used)}
        plan.used_cols = used
        plan.sel_rpns = [_remap_rpn(r, mapping) for r in sel_rpns]
        plan.agg_rpns = [None if r is None else _remap_rpn(r, mapping)
                         for r in plan.agg_rpns]
        if plan.key_rpn is not None:
            plan.key_rpn = _remap_rpn(plan.key_rpn, mapping)
        if plan.order_rpn is not None:
            plan.order_rpn = _remap_rpn(plan.order_rpn, mapping)
        return plan

    # ------------------------------------------------------------------ scan

    def _scan_batch(self, dag: DAGRequest, plan: _Plan, storage) -> ColumnBatch:
        if hasattr(storage, "scan_columns"):
            return storage.scan_columns(plan.scan, dag.ranges)
        from ..executors.scan import (
            BatchIndexScanExecutor,
            BatchTableScanExecutor,
        )
        cls = BatchIndexScanExecutor if isinstance(plan.scan, IndexScanDesc) \
            else BatchTableScanExecutor
        ex = cls(storage, plan.scan, dag.ranges)
        chunks = []
        while True:
            r = ex.next_batch(1024)
            if r.batch.num_rows:
                chunks.append(r.batch)
            if r.is_drained:
                break
        return ColumnBatch.concat(chunks) if chunks \
            else ColumnBatch.empty(plan.scan.schema)

    # --------------------------------------------------------------- kernels

    def _chunk_size_for(self, n: int) -> int:
        from .kernels import BLOCK_ROWS
        S = num_shards(self._mesh)
        unit = S * 8
        if n >= self._chunk_rows:
            # a chunk must split evenly across shards (device_put over the
            # row axis) and each shard's slice must divide into full scan
            # blocks, or matmul_groupby degrades to tiny gcd-sized blocks
            if self._chunk_rows >= S * BLOCK_ROWS:
                unit = S * BLOCK_ROWS
            return ((self._chunk_rows + unit - 1) // unit) * unit
        target = max(unit, _next_pow2(max(n, 1)))
        return ((target + unit - 1) // unit) * unit

    def _shard_kernel(self, cache_key, build):
        kern = self._kernel_cache.get(cache_key)
        if kern is None:
            kern = build()
            self._kernel_cache[cache_key] = kern
        return kern

    def _eval_masked(self, plan: _Plan, pairs, n_local, row_mask):
        mask = row_mask
        for rpn in plan.sel_rpns:
            v, ok = eval_rpn(rpn, pairs, n_local, jnp)
            mask = mask & ok & (v != 0)
        return mask

    def _shard_index(self):
        if self._single:
            return jnp.asarray(0, jnp.int64)
        tile = self._mesh.shape[ROW_AXES[1]]
        return (lax.axis_index(ROW_AXES[0]) * tile
                + lax.axis_index(ROW_AXES[1])).astype(jnp.int64)

    def _psum(self, x):
        return x if self._single else lax.psum(x, ROW_AXES)

    def _put(self, arr):
        return jnp.asarray(arr) if self._single \
            else jax.device_put(arr, self._row_sharding)

    def _wrap(self, body, n_row_args, out_specs):
        """jit the kernel body; on a multi-device mesh, as shard_map with
        rows split over both axes and one replicated scalar arg."""
        if self._single:
            return jax.jit(body)
        return jax.jit(jax.shard_map(
            body, mesh=self._mesh,
            in_specs=(P(),) + (P(ROW_AXES),) * n_row_args,
            out_specs=out_specs))

    # -- cross-shard merges --
    #
    # The TPU runtime here lowers only Sum all-reduce (observed: the axon
    # AOT compiler rejects pmin/pmax), so the dominant state fields
    # (count/sum/nonnull — every config in BASELINE.md) merge with psum on
    # ICI, while order-sensitive fields (min/max/first-pos) come back
    # per-shard — a (n_shards, slots) stack, KBs — and reduce on host.

    @staticmethod
    def _merge_stacked(specs, summed_states, stacked_states) -> list:
        """Host-side: reduce the per-shard stacks into one state per spec."""
        out = []
        for spec, sm, st in zip(specs, summed_states, stacked_states):
            d = {k: np.asarray(v) for k, v in sm.items()}
            if spec.kind == "min":
                d["min"] = np.min(np.asarray(st["min"]), axis=0)
            elif spec.kind == "max":
                d["max"] = np.max(np.asarray(st["max"]), axis=0)
            elif spec.kind == "first":
                pos = np.asarray(st["pos"])
                if "value" in st:       # simple agg: scalar per shard
                    i = int(np.argmin(pos))
                    d["pos"] = pos[i]
                    d["value"] = np.asarray(st["value"])[i]
                else:                   # hash agg: (n_shards, slots)
                    d["pos"] = np.min(pos, axis=0)
            out.append(d)
        return out

    # Kernels are *carry-style*: the aggregation state lives on device and
    # each chunk call folds new rows in; a single packed device→host
    # transfer at the end returns the final state.  (Per-chunk readbacks
    # are ruinous through a tunneled TPU: each D2H sync costs ~0.1s.)

    def _canon_state(self, s: dict) -> dict:
        """Cast state leaves to carry dtypes (int64 / float64)."""
        return {k: (v.astype(jnp.float64) if v.dtype.kind == "f"
                    else v.astype(jnp.int64)) for k, v in s.items()}

    @staticmethod
    def _merge_summed(carry: dict, new: dict) -> dict:
        return {k: carry[k] + new[k] for k in carry}

    @staticmethod
    def _merge_stacked_dict(carry: dict, new: dict) -> dict:
        d = {}
        if "pos" in carry and "value" in carry:     # FIRST (simple agg)
            take_new = new["pos"] < carry["pos"]
            d["pos"] = jnp.where(take_new, new["pos"], carry["pos"])
            d["value"] = jnp.where(take_new, new["value"], carry["value"])
            return d
        for k in carry:
            if k == "min" or k == "pos":
                d[k] = jnp.minimum(carry[k], new[k])
            elif k == "max":
                d[k] = jnp.maximum(carry[k], new[k])
            else:   # pragma: no cover
                raise ValueError(k)
        return d

    def _split_new_state(self, s: dict):
        """→ (summed fields psum-merged, per-shard stacked fields [1, ...])."""
        summed, stacked = {}, {}
        for k, v in s.items():
            if k in ("count", "sum", "nonnull"):
                summed[k] = self._psum(v)
            else:
                stacked[k] = v[None] if getattr(v, "ndim", 0) else \
                    jnp.reshape(v, (1,))
        return summed, stacked

    def _carry_specs(self, carry):
        """shard_map in/out specs matching a carry pytree: stacked leaves
        (leading shard axis) are P(ROW_AXES); everything else replicated."""
        summedlike, stackedlike = carry
        return (jax.tree.map(lambda _: P(), summedlike),
                jax.tree.map(lambda _: P(ROW_AXES), stackedlike))

    def _wrap_carry(self, body, carry_example, n_row_args):
        """jit a carry-style kernel body(carry, scalar, *rows) -> carry."""
        if self._single:
            return jax.jit(body)
        cs = self._carry_specs(carry_example)
        return jax.jit(jax.shard_map(
            body, mesh=self._mesh,
            in_specs=(cs, P()) + (P(ROW_AXES),) * n_row_args,
            out_specs=cs))

    # -- carry initialization (host → device once per request) --

    def _nshards(self) -> int:
        return 1 if self._single else num_shards(self._mesh)

    def _put_carry(self, carry):
        """Place an (summed, stacked) carry pytree built from numpy."""
        if self._single:
            return jax.tree.map(jnp.asarray, carry)
        summed, stacked = carry
        repl = self._repl
        rows = self._row_sharding
        return (jax.tree.map(lambda x: jax.device_put(x, repl), summed),
                jax.tree.map(lambda x: jax.device_put(x, rows), stacked))

    def _init_agg_carry(self, plan: _Plan, slots: Optional[int]):
        """Zero/identity states for the scatter-path carries.

        ``slots=None`` → simple agg (scalar states); else hash agg arrays.
        """
        S = self._nshards()
        shape = () if slots is None else (slots,)
        sshape = (S,) if slots is None else (S, slots)
        summed, stacked = [], []
        for spec, rpn in zip(plan.specs, plan.agg_rpns):
            is_real = rpn is not None and rpn.ret_type is EvalType.REAL
            sm, st = {}, {}
            if spec.kind in ("count", "count_star"):
                sm["count"] = np.zeros(shape, np.int64)
            elif spec.kind == "sum":
                sm["sum"] = np.zeros(shape, np.float64 if is_real else np.int64)
                sm["nonnull"] = np.zeros(shape, np.int64)
            elif spec.kind == "avg":
                sm["sum"] = np.zeros(shape, np.float64 if is_real else np.int64)
                sm["count"] = np.zeros(shape, np.int64)
            elif spec.kind in ("min", "max"):
                ident = (np.inf if spec.kind == "min" else -np.inf) \
                    if is_real else \
                    (np.iinfo(np.int64).max if spec.kind == "min"
                     else np.iinfo(np.int64).min)
                st[spec.kind] = np.full(
                    sshape, ident, np.float64 if is_real else np.int64)
                sm["nonnull"] = np.zeros(shape, np.int64)
            elif spec.kind == "first":
                st["pos"] = np.full(sshape, _BIG, np.int64)
                st["value"] = np.zeros(
                    sshape, np.float64 if is_real else np.int64)
            summed.append(sm)
            stacked.append(st)
        return summed, stacked

    # -- kernel builders --

    def _build_simple_kernel(self, plan: _Plan, n_cols: int):
        specs = plan.specs

        def body(carry, chunk_base, *flat):
            summed_c, stacked_c = carry
            row_mask = flat[-1]
            pairs = [(flat[2 * i], flat[2 * i + 1]) for i in range(n_cols)]
            n_local = row_mask.shape[0]
            mask = self._eval_masked(plan, pairs, n_local, row_mask)
            cols = []
            for r in plan.agg_rpns:
                if r is None:
                    cols.append((jnp.zeros((n_local,), jnp.int32), mask))
                else:
                    v, ok = eval_rpn(r, pairs, n_local, jnp)
                    cols.append((v, ok & mask))
            n_valid = jnp.sum(mask, dtype="int64")
            states = simple_agg_tile(jnp, specs, cols, n_valid_rows=n_valid)
            offset = chunk_base + self._shard_index() * n_local
            out_sm, out_st = [], []
            for spec, s, cs, cst in zip(specs, states, summed_c, stacked_c):
                s = self._canon_state(s)
                if spec.kind == "first":
                    # globalize positions; host picks the cross-shard argmin
                    s["pos"] = jnp.where(s["pos"] == _BIG, _BIG,
                                         s["pos"] + offset)
                sm, st = self._split_new_state(s)
                out_sm.append(self._merge_summed(cs, sm))
                out_st.append(self._merge_stacked_dict(cst, st)
                              if st else cst)
            return out_sm, out_st

        return body

    def _build_hash_scatter_kernel(self, plan: _Plan, n_cols: int,
                                   capacity: int):
        specs = plan.specs

        def body(carry, base, *flat):
            (summed_c, present_c, overflow_c), stacked_c = carry
            row_mask = flat[-1]
            pairs = [(flat[2 * i], flat[2 * i + 1]) for i in range(n_cols)]
            n_local = row_mask.shape[0]
            mask = self._eval_masked(plan, pairs, n_local, row_mask)
            key_pair = eval_rpn(plan.key_rpn, pairs, n_local, jnp)
            cols = []
            for r in plan.agg_rpns:
                if r is None:
                    cols.append((jnp.zeros((n_local,), jnp.int32), mask))
                else:
                    cols.append(eval_rpn(r, pairs, n_local, jnp))
            st = hash_agg_tile(jnp, specs, key_pair, cols, capacity, base,
                               row_mask=mask)
            present = present_c + self._psum(st["present"].astype(jnp.int64))
            overflow = overflow_c + \
                self._psum(st["overflow"].astype(jnp.int64))
            out_sm, out_st = [], []
            for spec, s, cs, cst in zip(specs, st["states"], summed_c,
                                        stacked_c):
                sm, stk = self._split_new_state(self._canon_state(s))
                out_sm.append(self._merge_summed(cs, sm))
                out_st.append(self._merge_stacked_dict(cst, stk)
                              if stk else cst)
            return (out_sm, present, overflow), out_st

        return body

    def _build_hash_matmul_kernel(self, plan: _Plan, n_cols: int,
                                  capacity: int, layouts):
        from .kernels import make_planes, matmul_groupby, slot_index
        specs = plan.specs

        def body(carry, base, *flat):
            (S8_c, Sf_c, ovf_c), _unused = carry
            row_mask = flat[-1]
            pairs = [(flat[2 * i], flat[2 * i + 1]) for i in range(n_cols)]
            n_local = row_mask.shape[0]
            mask = self._eval_masked(plan, pairs, n_local, row_mask)
            key_pair = eval_rpn(plan.key_rpn, pairs, n_local, jnp)
            cols = []
            for r in plan.agg_rpns:
                if r is None:
                    cols.append((jnp.zeros((n_local,), jnp.int32), mask))
                else:
                    cols.append(eval_rpn(r, pairs, n_local, jnp))
            idx, overflow = slot_index(key_pair, capacity, base, mask)
            L8, Lf = make_planes(layouts, specs, cols, mask)
            S8, Sf = matmul_groupby(
                idx, L8, Lf, capacity + 2,
                vary_axes=() if self._single else ROW_AXES)
            S8_c = S8_c + self._psum(S8)
            if Sf is not None:
                Sf_c = Sf_c + self._psum(Sf)
            ovf_c = ovf_c + self._psum(overflow.astype(jnp.int64))
            return (S8_c, Sf_c, ovf_c), _unused

        return body

    def _build_mask_kernel(self, plan: _Plan, n_cols: int):
        def fn(*flat):
            row_mask = flat[-1]
            pairs = [(flat[2 * i], flat[2 * i + 1]) for i in range(n_cols)]
            return self._eval_masked(plan, pairs, row_mask.shape[0], row_mask)
        return jax.jit(fn)

    def _build_topn_kernel(self, plan: _Plan, n_cols: int, k: int):
        desc = plan.order_desc

        def shard_fn(chunk_base, *flat):
            row_mask = flat[-1]
            pairs = [(flat[2 * i], flat[2 * i + 1]) for i in range(n_cols)]
            n_local = row_mask.shape[0]
            mask = self._eval_masked(plan, pairs, n_local, row_mask)
            v, ok = eval_rpn(plan.order_rpn, pairs, n_local, jnp)
            keyf = jnp.asarray(v, jnp.float64)
            keyf = jnp.where(ok, keyf, _NULL_KEY)           # NULL below all
            excluded = _EXCLUDED_DESC if desc else _EXCLUDED_ASC
            keyf = jnp.where(mask, keyf, excluded)
            kk = min(k, n_local)
            if desc:
                topv, idx = lax.top_k(keyf, kk)
            else:
                topv, idx = lax.top_k(-keyf, kk)
            offset = chunk_base + self._shard_index() * n_local
            gidx = idx.astype(jnp.int64) + offset
            return gidx, mask[idx], ok[idx]

        return self._wrap(shard_fn, 2 * n_cols + 1, P(ROW_AXES))

    # -- packed device→host readback (one sync for the whole request) --

    @staticmethod
    @jax.jit
    def _pack_jit(ints, flts, bools):
        i = jnp.concatenate([x.ravel() for x in ints]) if ints \
            else jnp.zeros(0, jnp.int64)
        f = jnp.concatenate([x.ravel() for x in flts]) if flts \
            else jnp.zeros(0, jnp.float64)
        b = jnp.concatenate([x.ravel().astype(jnp.uint8) for x in bools]) \
            if bools else jnp.zeros(0, jnp.uint8)
        return i, f, b

    def _readback(self, tree):
        """Transfer an arbitrary device pytree in (at most) three packed
        arrays; returns the same pytree as numpy."""
        leaves, treedef = jax.tree.flatten(tree)
        ints = tuple(x for x in leaves
                     if x.dtype.kind in "iu" and x.dtype != jnp.uint8)
        flts = tuple(x for x in leaves if x.dtype.kind == "f")
        bools = tuple(x for x in leaves
                      if x.dtype.kind == "b" or x.dtype == jnp.uint8)
        i, f, b = DeviceRunner._pack_jit(ints, flts, bools)
        i_np, f_np, b_np = np.asarray(i), np.asarray(f), np.asarray(b)
        io = fo = bo = 0
        out = []
        for x in leaves:
            size = int(np.prod(x.shape, dtype=np.int64))
            if x.dtype.kind == "f":
                out.append(f_np[fo:fo + size].reshape(x.shape)
                           .astype(np.dtype(str(x.dtype)), copy=False))
                fo += size
            elif x.dtype.kind == "b" or x.dtype == jnp.uint8:
                arr = b_np[bo:bo + size].reshape(x.shape)
                out.append(arr.astype(np.bool_) if x.dtype.kind == "b"
                           else arr)
                bo += size
            else:
                out.append(i_np[io:io + size].reshape(x.shape)
                           .astype(np.dtype(str(x.dtype)), copy=False))
                io += size
        return jax.tree.unflatten(treedef, out)

    # ------------------------------------------------------------ dispatch

    def handle_request(self, dag: DAGRequest, storage):
        plan = self._analyze(dag)
        if plan is None:
            raise RuntimeError("plan not supported by device backend")
        batch = self._scan_batch(dag, plan, storage)
        n = batch.num_rows
        if n == 0:
            from ..executors.runner import BatchExecutorsRunner
            return BatchExecutorsRunner(dag, storage).handle_request()

        # keyed on the full plan: hash_bounds/arg_nbytes depend on the
        # key/arg expressions, not just on which columns are shipped
        meta_key = (dag.plan_key(), dag.ranges)
        meta = self._request_meta(storage, meta_key)

        memo: dict = {}

        def host_cols():
            """Device-dtype numpy column pairs (built at most once)."""
            if "cols" not in memo:
                cols, dts = [], []
                for ci in plan.used_cols:
                    col = batch.columns[ci]
                    dt = _device_dtype(col.eval_type, col.values)
                    cols.append((np.ascontiguousarray(
                        col.values.astype(dt, copy=False)),
                        np.ascontiguousarray(col.validity)))
                    dts.append(str(dt))
                memo["cols"] = cols
                meta.setdefault("dtypes", tuple(dts))
            return memo["cols"]

        if "dtypes" not in meta:
            host_cols()
        dtypes = meta["dtypes"]

        feed_key = (tuple(plan.scan.columns[ci].col_id
                          for ci in plan.used_cols),
                    tuple(dtypes), dag.ranges, self._chunk_size_for(n))
        feed = (storage, feed_key)
        try:
            if plan.kind == "simple_agg":
                result = self._run_simple(dag, plan, host_cols, dtypes, n, feed)
            elif plan.kind == "hash_agg":
                result = self._run_hash(dag, plan, host_cols, dtypes, n, feed,
                                        meta)
            elif plan.kind == "topn":
                result = self._run_topn(dag, plan, host_cols, dtypes, n, batch,
                                        feed)
            else:   # scan_sel
                result = self._run_scan_sel(dag, plan, host_cols, dtypes, n,
                                            batch, feed)
        except _FallbackToHost:
            from ..executors.runner import BatchExecutorsRunner
            return BatchExecutorsRunner(dag, storage).handle_request()

        if dag.output_offsets is not None:
            b = result.batch
            result.batch = ColumnBatch(
                [b.schema[i] for i in dag.output_offsets],
                [b.columns[i] for i in dag.output_offsets])
        return result

    def _request_meta(self, storage, meta_key) -> dict:
        """Snapshot-lifetime memo for host-derived request constants
        (device dtypes, hash key bounds, byte-plane widths)."""
        if not hasattr(storage, "scan_columns"):
            return {}
        try:
            per_storage = self._feed_cache.setdefault(storage, {})
        except TypeError:
            return {}
        return per_storage.setdefault(("meta", meta_key), {})

    # -- chunk feed --

    def _chunks(self, host_cols, n: int, storage=None, feed_key=None):
        """Yield (chunk_base, padded device arrays flat list) per chunk.

        When ``storage`` is an immutable columnar snapshot, the device
        arrays are cached in HBM across requests (region-cache analog).
        """
        cache = None
        if storage is not None and feed_key is not None and \
                hasattr(storage, "scan_columns"):
            try:
                cache = self._feed_cache.setdefault(storage, {})
            except TypeError:       # not weak-referenceable
                cache = None
        if cache is not None and feed_key in cache:
            yield from cache[feed_key]
            return
        built = []
        for item in self._chunks_uncached(host_cols(), n):
            built.append(item)
            yield item
        if cache is not None:
            cache[feed_key] = built

    def _chunks_uncached(self, host_cols, n: int):
        chunk = self._chunk_size_for(n)
        for start in range(0, n, chunk):
            stop = min(start + chunk, n)
            m = stop - start
            flat = []
            for v, ok in host_cols:
                if m == chunk:
                    vv, mm = v[start:stop], ok[start:stop]
                else:
                    vv = np.zeros(chunk, dtype=v.dtype)
                    vv[:m] = v[start:stop]
                    mm = np.zeros(chunk, dtype=np.bool_)
                    mm[:m] = ok[start:stop]
                flat.append(self._put(vv))
                flat.append(self._put(mm))
            if m == chunk:
                row_mask = np.ones(chunk, dtype=np.bool_)
            else:
                row_mask = np.zeros(chunk, dtype=np.bool_)
                row_mask[:m] = True
            flat.append(self._put(row_mask))
            yield start, flat

    def _result(self, dag, schema, columns) -> "SelectResult":
        from ..executors.runner import SelectResult
        return SelectResult(ColumnBatch(schema, columns), [])

    # -- simple agg --

    def _run_simple(self, dag, plan, host_cols, dtypes, n, feed):
        carry = self._put_carry(self._init_agg_carry(plan, None))
        key = ("simple", dag.plan_key(), tuple(dtypes),
               self._chunk_size_for(n))
        n_cols = len(plan.used_cols)
        kern = self._shard_kernel(
            key, lambda: self._wrap_carry(
                self._build_simple_kernel(plan, n_cols),
                carry, 2 * n_cols + 1))
        for base, flat in self._chunks(host_cols, n, *feed):
            carry = kern(carry, jnp.asarray(base, jnp.int64), *flat)
        summed, stacked = self._readback(carry)
        merged = self._merge_stacked(plan.specs, summed, stacked)
        finals = finalize_simple(plan.specs, merged)
        from ..executors.aggregation import _agg_ret_ft
        schema, cols = [], []
        for spec, val in zip(plan.specs, finals):
            ft = _agg_ret_ft(spec.kind, spec.eval_type if spec.kind not in
                             ("count", "count_star") else None)
            schema.append(ft)
            cols.append(Column.from_list(ft.eval_type, [val]))
        return self._result(dag, schema, cols)

    # -- hash agg --

    def _run_hash(self, dag, plan, host_cols, dtypes, n, feed, meta):
        from .kernels import build_layouts, matmul_supported, \
            states_from_matmul
        if "hash_bounds" in meta:
            base, span, arg_nbytes = meta["hash_bounds"]
        else:
            kv, km = eval_rpn(plan.key_rpn, host_cols(), n, np)
            kv = np.broadcast_to(kv, (n,))
            km = np.broadcast_to(km, (n,))
            valid_keys = kv[km]
            if valid_keys.size:
                base = int(valid_keys.min())
                span = int(valid_keys.max()) - base + 1
            else:
                base, span = 0, 1
            arg_nbytes = self._arg_nbytes(plan, host_cols(), n)
            meta["hash_bounds"] = (base, span, arg_nbytes)
        if span > self._max_hash_capacity:
            # group cardinality exceeds the device direct-index capacity —
            # reference splits fast vs slow hash agg the same way
            # (runner.rs:293-318); the general path stays on host.
            raise _FallbackToHost(f"hash key span {span}")
        capacity = max(1024, _next_pow2(span))
        slots = capacity + 2
        use_matmul = matmul_supported(plan.specs)
        base_arr = jnp.asarray(base, jnp.int64)

        if use_matmul:
            arg_is_real = [r is not None and r.ret_type is EvalType.REAL
                           for r in plan.agg_rpns]
            layouts, p8, pf = build_layouts(plan.specs, arg_is_real,
                                            arg_nbytes)
            carry = self._put_carry((
                (np.zeros((p8, slots), np.int64),
                 np.zeros((max(pf, 1), slots), np.float64),
                 np.zeros((), np.int64)),
                []))
            key = ("hashmm", dag.plan_key(), tuple(dtypes), capacity,
                   arg_nbytes, self._chunk_size_for(n))
            n_cols = len(plan.used_cols)
            kern = self._shard_kernel(
                key, lambda: self._wrap_carry(
                    self._build_hash_matmul_kernel(
                        plan, n_cols, capacity, layouts),
                    carry, 2 * n_cols + 1))
            for _, flat in self._chunks(host_cols, n, *feed):
                carry = kern(carry, base_arr, *flat)
            (S8, Sf, ovf), _ = self._readback(carry)
            assert int(ovf) == 0, "hash agg key range overflow"
            present, states = states_from_matmul(layouts, plan.specs, S8,
                                                 Sf if pf else None, xp=np)
            merged = {"present": present, "overflow": False,
                      "states": states}
        else:
            sm_init, st_init = self._init_agg_carry(plan, slots)
            carry = self._put_carry((
                (sm_init, np.zeros(slots, np.int64), np.zeros((), np.int64)),
                st_init))
            key = ("hash", dag.plan_key(), tuple(dtypes), capacity,
                   self._chunk_size_for(n))
            n_cols = len(plan.used_cols)
            kern = self._shard_kernel(
                key, lambda: self._wrap_carry(
                    self._build_hash_scatter_kernel(
                        plan, n_cols, capacity),
                    carry, 2 * n_cols + 1))
            for _, flat in self._chunks(host_cols, n, *feed):
                carry = kern(carry, base_arr, *flat)
            (summed, present_counts, ovf), stacked = self._readback(carry)
            assert int(ovf) == 0, "hash agg key range overflow"
            merged = {
                "present": present_counts > 0,
                "overflow": False,
                "states": self._merge_stacked(plan.specs, summed, stacked),
            }
        keys, results = finalize_hash(plan.specs, merged, base, capacity)

        from ..executors.aggregation import _agg_ret_ft
        schema, cols = [], []
        for spec, vals in zip(plan.specs, results):
            ft = _agg_ret_ft(spec.kind, spec.eval_type if spec.kind not in
                             ("count", "count_star") else None)
            schema.append(ft)
            cols.append(Column.from_list(ft.eval_type, vals))
        schema.append(FieldType.long())
        cols.append(Column.from_list(EvalType.INT, keys))
        return self._result(dag, schema, cols)

    def _arg_nbytes(self, plan: _Plan, host_cols, n: int) -> tuple:
        """Byte-plane count per aggregate arg for the MXU int path.

        Plain column refs use the column's actual value range (host
        min/max, vectorized); computed expressions use the device dtype
        width (int arithmetic wraps in-dtype on device — documented
        deviation, expr/functions.py)."""
        from .kernels import int_planes_needed
        out = []
        for r in plan.agg_rpns:
            if r is None or r.ret_type is EvalType.REAL:
                out.append(0)
                continue
            nodes = r.nodes
            if len(nodes) == 1 and isinstance(nodes[0], RpnColumnRef):
                v, ok = host_cols[nodes[0].col_idx]
                if v.size:
                    out.append(int_planes_needed(int(v.min()), int(v.max())))
                else:
                    out.append(1)
            else:
                widths = [host_cols[i][0].dtype.itemsize
                          for i in _rpn_col_indices(r)] or [4]
                out.append(max(widths))
        return tuple(out)

    # -- selection (mask on device, compact on host) --

    def _run_scan_sel(self, dag, plan, host_cols, dtypes, n, batch, feed):
        key = ("mask", dag.plan_key(), tuple(dtypes), self._chunk_size_for(n))
        kern = self._shard_kernel(
            key, lambda: self._build_mask_kernel(plan, len(plan.used_cols)))
        masks = []
        for base, flat in self._chunks(host_cols, n, *feed):
            masks.append((base, kern(*flat)))
        parts = self._readback(tuple(m for _, m in masks))
        full = np.zeros(n, dtype=np.bool_)
        for (base, _), m in zip(masks, parts):
            stop = min(base + len(m), n)
            full[base:stop] = m[:stop - base]
        out = batch.filter(full)
        return self._result(dag, out.schema, out.columns)

    # -- top-n --

    def _run_topn(self, dag, plan, host_cols, dtypes, n, batch, feed):
        k = min(plan.limit, max(1, n))
        key = ("topn", dag.plan_key(), tuple(dtypes), k,
               self._chunk_size_for(n))
        kern = self._shard_kernel(
            key, lambda: self._build_topn_kernel(plan, len(plan.used_cols), k))
        outs = []
        for base, flat in self._chunks(host_cols, n, *feed):
            outs.append(kern(jnp.asarray(base, jnp.int64), *flat))
        parts = self._readback(tuple(outs))
        gidx = np.concatenate([p[0] for p in parts])
        mask = np.concatenate([p[1] for p in parts])
        ok = np.concatenate([p[2] for p in parts])
        sel = mask & (gidx < n)
        gidx, ok = gidx[sel], ok[sel]
        # exact host ordering over <= k * n_chunks * n_shards candidates:
        # evaluate the order expression only on the gathered candidate rows
        # (plan rpns are remapped onto host_cols positions)
        cand_cols = [(v[gidx], m[gidx]) for v, m in host_cols()]
        ov, _om = eval_rpn(plan.order_rpn, cand_cols, len(gidx), np)
        ov = np.broadcast_to(ov, (len(gidx),))
        if plan.order_rpn.ret_type is EvalType.INT:
            # exact int ordering (no f64 collapse above 2^53); NULL is the
            # smallest value, so asc → NULL first, desc → NULL last.
            # Clamp to min+2 so negation cannot overflow int64.min.
            lo, hi = np.iinfo(np.int64).min, np.iinfo(np.int64).max
            vals = np.maximum(np.asarray(ov, dtype=np.int64), lo + 2)
            if plan.order_desc:
                key = np.where(ok, -vals, hi)
            else:
                key = np.where(ok, vals, lo)
            order = np.lexsort((gidx, key))
        else:
            vals = np.asarray(ov, dtype=np.float64)
            keyf = np.where(ok, vals, -np.inf)      # NULL smallest
            order = np.lexsort((gidx, -keyf if plan.order_desc else keyf))
        take = gidx[order[:plan.limit]]
        out = batch.take(take)
        return self._result(dag, out.schema, out.columns)
