"""Device-side MVCC version resolution — the cold-path kill.

The cold build used to be a host affair: one native pass over the
region's CF_WRITE range resolving Percolator versions AND decoding rows
(``native/fastbuild.cpp mvcc_build_columnar``, ~4s per 10M rows), then a
separate padded feed upload.  Late materialization (Abadi et al., ICDE
2007 — PAPERS.md) applies to the TIME axis too: never materialize on
the host what the device can resolve in place.  Newest-committed-version
selection is a **segmented arg-max over commit_ts** — the exact
vectorized shape the MonetDB/X100-style kernels in ``pallas_hash.py``
already handle — so the split here is:

- **host (C++, GIL released)**: a flat-plane PARSE only
  (``native.mvcc_parse_planes``) — key-ordinal segments, commit_ts /
  start_ts / write-type planes, per-column datum planes, short-value
  spill markers.  No per-key branching, no resolution.
- **device (one dispatch)**: eligibility mask
  (``commit_ts <= read_ts ∧ type ∈ {PUT, DELETE}`` — LOCK/ROLLBACK
  records are skipped exactly as the row reader skips them), segmented
  arg-max over commit_ts, DELETE suppression, then an on-device gather
  of the winning versions straight into the **columnar feed layout**
  (value plane per used column, validity plane only where NULLs exist,
  padded to the runner's bucketed ``n_pad``).  The resolved feed is
  *born resident* — there is no separate ``feed_upload`` phase.

The host keeps a cheap numpy mirror of the same resolution
(:func:`resolve_host` — ``np.maximum.reduceat`` over the segment
offsets) because the columnar cache line itself must hold host-truth
buffers (delta patching, ``gather_rows``, checksum, and the scrub
digest contract all read them); the recorded per-plane digests come
from that host truth, so a divergent device resolve is caught by the
scrubber like any other HBM corruption (device/supervisor.py).

Chunked H2D (the streaming cold pipeline, copr/stream_build.py) rides
:class:`DeviceVersionPlanes`: version planes accumulate on device in
capacity-bucketed buffers via the same jitted ``dynamic_update_slice``
span machinery the delta feed patches use, so chunk *k*'s parse/H2D
overlaps chunk *k+1*'s SST ingest and the final resolve dispatch reads
already-resident planes.

Envelope: numeric columns only (INT/DURATION → int64 planes, REAL →
float64, DATETIME/ENUM/SET and unsigned BIGINT → uint64), NULL-able
defaults only; DECIMAL/JSON/BYTES schemas and non-NULL column defaults
fall back to the native host builder (copr/region_cache.py keeps the
build ladder: device → native → interpreted).  CF_DEFAULT spill rows
(values > SHORT_VALUE_MAX_LEN) resolve on device like any other PUT and
their cells are host-patched after the kernel.
"""

from __future__ import annotations

import threading
from typing import Optional, Sequence

import numpy as np

from ..datatype import Column, EvalType

# plane kind codes (shared with fastbuild.cpp): 0=int64 1=float64 3=uint64
_PLANE_KINDS = {
    EvalType.INT: 0, EvalType.DURATION: 0,
    EvalType.REAL: 1,
    EvalType.DATETIME: 3, EvalType.ENUM: 3, EvalType.SET: 3,
}

_NP_BY_KIND = {0: np.int64, 1: np.float64, 3: np.uint64}

# write-type codes in the wtype plane
WT_PUT, WT_DELETE, WT_LOCK, WT_ROLLBACK = 0, 1, 2, 3


def plane_schema(col_infos: Sequence):
    """→ (col_ids, kinds) for the flat-plane parse, or None when the
    schema leaves the device envelope (BYTES/DECIMAL/JSON payloads or
    non-NULL defaults — the native/interpreted ladder serves those)."""
    ids, kinds = [], []
    for info in col_infos:
        if info.is_pk_handle:
            continue
        ft = info.field_type
        kind = _PLANE_KINDS.get(ft.eval_type)
        if kind is None or info.default_value is not None:
            return None
        if kind == 0 and ft.is_unsigned:
            kind = 3            # unsigned BIGINT: values live above 2^63
        ids.append(info.col_id)
        kinds.append(kind)
    return tuple(ids), tuple(kinds)


class WritePlanes:
    """Flat planes of one CF_WRITE range (or a concatenation of
    streamed chunks): one row per stored VERSION, one segment per user
    key, plus per-column datum planes decoded from short values."""

    __slots__ = ("n_ver", "n_keys", "table_id", "safe_ts", "commit_ts",
                 "start_ts", "wtype", "has_payload", "seg_id", "handles",
                 "seg_start", "cols", "need_default", "col_ids")

    def __init__(self, n_ver: int, n_keys: int, table_id: int,
                 safe_ts: int, commit_ts, start_ts, wtype, has_payload,
                 seg_id, handles, seg_start, cols: dict, need_default,
                 col_ids: tuple):
        self.n_ver = n_ver
        self.n_keys = n_keys
        self.table_id = table_id
        self.safe_ts = safe_ts
        self.commit_ts = commit_ts          # uint64[n_ver]
        self.start_ts = start_ts            # uint64[n_ver]
        self.wtype = wtype                  # uint8[n_ver]
        self.has_payload = has_payload      # uint8[n_ver]
        self.seg_id = seg_id                # int32[n_ver]
        self.handles = handles              # int64[n_keys]
        self.seg_start = seg_start          # int64[n_keys + 1]
        # col_id -> (kind, values ndarray[n_ver], valid bool[n_ver])
        self.cols = cols
        self.need_default = need_default    # [(ver_row, start_ts, ukey)]
        self.col_ids = col_ids

    def nbytes(self) -> int:
        per_ver = 8 + 8 + 1 + 1 + 4 + sum(
            9 for _ in self.cols)           # 8B value + 1B valid per col
        return self.n_ver * per_ver + self.n_keys * 8


def _parse_may_yield() -> bool:
    """Whether the build-path parse should release the GIL: only worth
    it with a spare core — on a single-CPU box yielding just hands the
    core to the node's background tick threads and the parse's wall
    time balloons (measured 3.8s → 18s at 10M versions); the host
    builder this rung replaces holds the GIL for its whole pass too."""
    from ..utils import spare_cores
    return spare_cores() > 1


def parse_write_planes(keys, vals, prefix_skip: int,
                       col_infos: Optional[Sequence],
                       release_gil: Optional[bool] = None) -> \
        Optional[WritePlanes]:
    """Native flat-plane parse of one contiguous CF_WRITE slice, or
    None when the native module is unavailable / the data is outside
    the envelope (index keys, mixed tables, exotic datums).

    ``col_infos=None`` selects DISCOVERY mode (the streaming ingest
    path, which has no query schema yet): every column id seen in a row
    payload mints a plane with its stored kind; :func:`align_planes`
    reconciles the result against a schema at build time.

    ``release_gil``: None = auto (yield only with a spare core); the
    streaming worker passes True — its entire point is letting the
    apply loop make progress while it parses."""
    from ..native import mvcc_parse_planes
    if mvcc_parse_planes is None or not keys:
        return None
    if col_infos is None:
        ids, kinds = (), ()
    else:
        schema = plane_schema(col_infos)
        if schema is None:
            return None
        ids, kinds = schema
    if release_gil is None:
        release_gil = _parse_may_yield()
    try:
        out = mvcc_parse_planes(keys, vals, prefix_skip, ids, kinds,
                                bool(release_gil))
    except ValueError:
        return None
    if out["safe_ts"] >= (1 << 63):
        return None     # commit_ts beyond int64: device compares in i64
    n = out["n_ver"]
    cols = {}
    out_ids = []
    for col_id, kind, payload, valid in out["cols"]:
        out_ids.append(col_id)
        cols[col_id] = (kind,
                        np.frombuffer(payload, _NP_BY_KIND[kind]),
                        np.frombuffer(valid, np.uint8).astype(np.bool_))
    return WritePlanes(
        n, out["n_keys"], out["table_id"], out["safe_ts"],
        np.frombuffer(out["commit_ts"], np.uint64),
        np.frombuffer(out["start_ts"], np.uint64),
        np.frombuffer(out["wtype"], np.uint8),
        np.frombuffer(out["has_payload"], np.uint8),
        np.frombuffer(out["seg_id"], np.int32),
        np.frombuffer(out["handles"], np.int64),
        np.frombuffer(out["seg_start"], np.int64),
        cols, out["need_default"], tuple(out_ids) if col_infos is None
        else ids)


def align_planes(planes: WritePlanes,
                 col_infos: Sequence) -> Optional[WritePlanes]:
    """Reconcile DISCOVERED planes (streamed chunks) with a query
    schema, or None when they cannot serve it.

    Stored int64 planes serve unsigned/time kinds by uint64 bit-view
    (msgpack encodes both through the same 8-byte integer) and REAL
    requests by numeric astype (matching the explicit parse's
    coercion); a column never seen in any payload is all-NULL and
    synthesizes an invalid zero plane.  A float-stored plane can only
    serve a REAL request."""
    schema = plane_schema(col_infos)
    if schema is None:
        return None
    ids, kinds = schema
    cols: dict = {}
    for cid, want in zip(ids, kinds):
        got = planes.cols.get(cid)
        if got is None:
            cols[cid] = (want,
                         np.zeros(planes.n_ver, _NP_BY_KIND[want]),
                         np.zeros(planes.n_ver, np.bool_))
            continue
        kind, vals, valid = got
        if kind == want:
            cols[cid] = got
        elif kind == 0 and want == 3:
            cols[cid] = (3, vals.view(np.uint64), valid)
        elif kind == 0 and want == 1:
            cols[cid] = (1, vals.astype(np.float64), valid)
        else:
            return None
    return WritePlanes(
        planes.n_ver, planes.n_keys, planes.table_id, planes.safe_ts,
        planes.commit_ts, planes.start_ts, planes.wtype,
        planes.has_payload, planes.seg_id, planes.handles,
        planes.seg_start, cols, planes.need_default, ids)


def concat_planes(chunks: Sequence[WritePlanes]) -> WritePlanes:
    """Streamed per-chunk planes → one WritePlanes.  Chunks must hold
    strictly ascending, non-overlapping user keys (the streamer's
    coverage contract), so segment ids offset by the running key count
    and version rows offset by the running version count.  Discovered
    column sets may differ per chunk (a column can first appear
    mid-stream); a chunk without a column contributes an invalid zero
    slice — exactly what its payloads said."""
    if len(chunks) == 1:
        return chunks[0]
    n_ver = sum(c.n_ver for c in chunks)
    n_keys = sum(c.n_keys for c in chunks)
    first = chunks[0]
    seg_id = np.empty(n_ver, np.int32)
    seg_start = np.empty(n_keys + 1, np.int64)
    need = []
    vb = kb = 0
    for c in chunks:
        seg_id[vb:vb + c.n_ver] = c.seg_id + kb
        seg_start[kb:kb + c.n_keys] = c.seg_start[:-1] + vb
        need.extend((row + vb, sts, uk) for row, sts, uk in
                    c.need_default)
        vb += c.n_ver
        kb += c.n_keys
    seg_start[n_keys] = n_ver
    all_ids, kinds = [], {}
    for c in chunks:
        for cid in c.col_ids:
            if cid not in kinds:
                all_ids.append(cid)
                kinds[cid] = c.cols[cid][0]
            elif kinds[cid] != c.cols[cid][0]:
                # int-stored then float-stored (or vice versa): promote
                # to float64 like the explicit parse's coercion would
                kinds[cid] = 1
    cols = {}
    for cid in all_ids:
        kind = kinds[cid]
        dt = _NP_BY_KIND[kind]
        vparts, mparts = [], []
        for c in chunks:
            got = c.cols.get(cid)
            if got is None:
                vparts.append(np.zeros(c.n_ver, dt))
                mparts.append(np.zeros(c.n_ver, np.bool_))
            else:
                vparts.append(got[1].astype(dt, copy=False))
                mparts.append(got[2])
        cols[cid] = (kind, np.concatenate(vparts),
                     np.concatenate(mparts))
    return WritePlanes(
        n_ver, n_keys, first.table_id,
        max(c.safe_ts for c in chunks),
        np.concatenate([c.commit_ts for c in chunks]),
        np.concatenate([c.start_ts for c in chunks]),
        np.concatenate([c.wtype for c in chunks]),
        np.concatenate([c.has_payload for c in chunks]),
        seg_id,
        np.concatenate([c.handles for c in chunks]),
        seg_start, cols, need, tuple(all_ids))


def resolve_host(planes: WritePlanes, read_ts: int) -> np.ndarray:
    """Numpy mirror of the device resolution: ascending version rows of
    the newest committed PUT ≤ read_ts per key (the host-truth side of
    the digest contract; also how the builder learns n before picking
    the padded output shape)."""
    if planes.n_ver == 0:
        return np.empty(0, np.int64)
    elig = (planes.commit_ts <= np.uint64(read_ts)) & \
        (planes.wtype <= WT_DELETE)
    score = np.where(elig, planes.commit_ts, np.uint64(0))
    seg_max = np.maximum.reduceat(score, planes.seg_start[:-1])
    win = elig & (score == seg_max[planes.seg_id]) & (score > 0)
    vis = win & (planes.wtype == WT_PUT)
    return np.nonzero(vis)[0]


def host_mirror(planes: WritePlanes, winners: np.ndarray,
                col_infos: Sequence):
    """Materialize the host-truth columnar arrays for the resolved rows
    (vectorized takes — the cache line, delta patching, gather_rows and
    the scrub digests all read these buffers)."""
    seg = planes.seg_id[winners]
    handles = np.ascontiguousarray(planes.handles[seg])
    columns: dict = {}
    for info in col_infos:
        if info.is_pk_handle:
            continue
        _kind, vals, valid = planes.cols[info.col_id]
        columns[info.col_id] = Column(
            info.field_type.eval_type,
            np.ascontiguousarray(vals[winners]),
            np.ascontiguousarray(valid[winners]))
    return handles, columns


def _bucket(n: int, floor: int = 256) -> int:
    """Geometric capacity bucket (k·2^s, 8 ≤ k ≤ 15 — the _pad_rows
    grid) so version-plane shapes, like feed shapes, mint a bounded
    number of compile classes under growth."""
    n = max(floor, n)
    if n <= 8:
        return 8
    s = max(0, n.bit_length() - 4)
    k = -(-n // (1 << s))
    if k > 15:
        s += 1
        k = -(-n // (1 << s))
    return k << s


class DeviceVersionPlanes:
    """Device-resident, capacity-bucketed version planes for one
    streamed (region, table): chunks append in place via the jitted
    ``dynamic_update_slice`` machinery, so H2D rides the load instead
    of the first query.  Zero-fill is semantically dead: padded rows
    carry commit_ts 0, which the eligibility mask (``score > 0``)
    never selects."""

    __slots__ = ("n_ver", "n_keys", "cap_ver", "cap_keys", "bufs",
                 "nbytes")

    def __init__(self):
        self.n_ver = 0
        self.n_keys = 0
        self.cap_ver = 0
        self.cap_keys = 0
        self.bufs: dict = {}        # name -> device array
        self.nbytes = 0

    def _plane_specs(self, planes: WritePlanes):
        specs = [("commit_ts", planes.commit_ts.view(np.int64), True),
                 ("wtype", planes.wtype, True),
                 ("seg_id", planes.seg_id, True),
                 ("handles", planes.handles, False)]
        for cid in planes.col_ids:
            _k, vals, valid = planes.cols[cid]
            specs.append((f"v{cid}", vals, True))
            specs.append((f"m{cid}", valid, True))
        return specs

    def append(self, resolver: "DeviceMvccResolver",
               planes: WritePlanes, key_base: int) -> None:
        import jax.numpy as jnp
        new_ver = self.n_ver + planes.n_ver
        new_keys = self.n_keys + planes.n_keys
        cap_v = _bucket(new_ver)
        cap_k = _bucket(new_keys)
        specs = self._plane_specs(planes)
        if cap_v > self.cap_ver or cap_k > self.cap_keys:
            # grow: fresh zero buffers at the next bucket, old content
            # copied on device (one dus per plane — no host round
            # trip).  EVERY resident buffer grows, including columns
            # this chunk does not carry (their new tail stays zero =
            # invalid).
            for name, old in list(self.bufs.items()):
                cap = cap_k if name == "handles" else cap_v
                self.bufs[name] = resolver.dus(
                    jnp.zeros(cap, old.dtype), old, 0)
            self.cap_ver, self.cap_keys = cap_v, cap_k
        for name, chunk, per_ver in specs:
            off = self.n_ver if per_ver else self.n_keys
            if name == "seg_id":
                chunk = chunk + np.int32(key_base)
            chunk = np.ascontiguousarray(chunk)
            buf = self.bufs.get(name)
            if buf is None:
                # first content for this plane (first chunk, or a
                # column first seen mid-stream — earlier rows stay zero
                # = invalid, exactly what their payloads said): host-pad
                # + ONE plain H2D copy, no jitted kernel, so a
                # single-chunk stream compiles nothing at all
                cap = self.cap_ver if per_ver else self.cap_keys
                p = np.zeros(cap, chunk.dtype)
                p[off:off + len(chunk)] = chunk
                buf = jnp.asarray(p)
            else:
                buf = resolver.dus(buf, jnp.asarray(chunk), off)
            self.bufs[name] = buf
        self.n_ver, self.n_keys = new_ver, new_keys
        self.nbytes = sum(int(b.nbytes) for b in self.bufs.values())


class ColdFeedBundle:
    """One cold build's device-resolve artifacts, stashed on the new
    cache line's FeedLineage until the runner's first feed miss mints
    the born-resident feed from them (runner._get_feed).

    One-shot and version-0-only: any delta landing first (the line
    moved on) or a mint attempt (success OR failure) drops it — the
    plain host upload path is always a correct fallback.
    """

    __slots__ = ("resolver", "planes", "device", "n", "read_ts",
                 "mirror_handles", "mirror_cols", "has_nulls",
                 "spill_patches", "consumed", "lineage_v")

    def __init__(self, resolver: "DeviceMvccResolver",
                 planes: WritePlanes, device: Optional[DeviceVersionPlanes],
                 n: int, read_ts: int, mirror_handles: np.ndarray,
                 mirror_cols: dict, spill_patches: Optional[dict] = None):
        self.resolver = resolver
        self.planes = planes
        self.device = device            # streamed H2D state, or None
        self.n = n
        self.read_ts = read_ts
        self.mirror_handles = mirror_handles
        self.mirror_cols = mirror_cols  # col_id -> Column (host truth)
        self.has_nulls = {cid: not bool(col.validity.all())
                          for cid, col in mirror_cols.items()}
        # feed-row positions whose PUT payload lives in CF_DEFAULT —
        # patched after the gather from the host-truth mirror (the
        # kernel saw no short value for them)
        self.spill_patches = spill_patches or {}
        self.consumed = False
        self.lineage_v = -1     # stamped by FeedLineage.stash_cold

    def release(self) -> None:
        """Drop every device/host reference (stale bundle teardown)."""
        self.consumed = True
        self.planes = None
        self.device = None
        self.mirror_cols = {}
        self.mirror_handles = None

    # ------------------------------------------------------------ mint

    def mint(self, runner, used_infos: Sequence, dtypes: Sequence,
             n: int, n_pad: int):
        """Build the feed dict (the exact ``_build_flat`` layout) by
        resolving + gathering ON DEVICE.  Returns None when this bundle
        cannot serve the request (shape moved, columns missing) — the
        caller falls through to the host upload path."""
        if self.consumed or self.planes is None or n != self.n or n == 0:
            return None
        for info in used_infos:
            if not info.is_pk_handle and \
                    info.col_id not in self.mirror_cols:
                return None
        try:
            return self.resolver._mint(self, runner, used_infos,
                                       dtypes, n, n_pad)
        finally:
            self.release()


class DeviceMvccResolver:
    """Owns the jitted resolve/gather kernels and the chunked-H2D
    machinery.  Single-device only (the sharded mesh path keeps the
    host upload pipeline — GSPMD re-lays feeds anyway)."""

    def __init__(self, runner):
        self._runner = runner
        self._mu = threading.Lock()
        self._kernels: dict = {}
        self._dus_fn = None
        self.mints = 0
        self.mint_failures = 0

    # -- availability ---------------------------------------------------

    def available(self) -> bool:
        from ..native import mvcc_parse_planes
        r = self._runner
        return mvcc_parse_planes is not None and r is not None and \
            getattr(r, "_single", False)

    def h2d_profitable(self) -> bool:
        """Whether streaming version planes onto the device AHEAD of
        the first query pays: only on a real accelerator.  On the CPU
        backend a device_put is a host-memory alias — there is no
        transfer to overlap, and the chunk-append ``dus`` compiles
        contend (measured: they starve both the loader and the take
        path) for the exact cores the load needs."""
        try:
            import jax
            return jax.devices()[0].platform != "cpu"
        except Exception:   # noqa: BLE001 — no jax, no device leg
            return False

    # -- shared jitted helpers -------------------------------------------

    def dus(self, arr, update, lo: int):
        """Traced-offset slice update (one compile class per
        (buffer shape, update shape, dtype) — chunk appends and buffer
        growth share it)."""
        import jax
        import jax.numpy as jnp
        from jax import lax
        with self._mu:
            fn = self._dus_fn
            if fn is None:
                def _upd(a, u, i):
                    return lax.dynamic_update_slice(a, u, (i,))
                fn = self._dus_fn = jax.jit(_upd)
        return fn(arr, update, jnp.asarray(lo, jnp.int32))

    # -- the resolve + gather kernel --------------------------------------

    def _kernel(self, nver_pad: int, nkeys_pad: int, out_pad: int,
                spec: tuple):
        """spec: per output plane —
        ("h", out_dtype)                      pk-handle column
        ("v", src_slot, out_dtype)            value plane (astype'd)
        ("m", src_slot)                       validity plane (bool)
        src_slot indexes the variadic plane inputs after the fixed
        (commit_ts, wtype, seg_id, handles) quartet."""
        key = (nver_pad, nkeys_pad, out_pad, spec)
        with self._mu:
            fn = self._kernels.get(key)
        if fn is not None:
            return fn

        import jax
        import jax.numpy as jnp

        def resolve(read_ts, n_out, commit_ts, wtype, seg_id, handles,
                    *planes):
            i32 = jnp.int32
            elig = (commit_ts <= read_ts) & (wtype <= WT_DELETE)
            score = jnp.where(elig, commit_ts, jnp.int64(0))
            seg_max = jax.ops.segment_max(score, seg_id,
                                          num_segments=nkeys_pad)
            win = elig & (score == seg_max[seg_id]) & (score > 0)
            vis = win & (wtype == WT_PUT)
            pos = jnp.cumsum(vis.astype(i32)) - 1
            tgt = jnp.where(vis, pos, i32(out_pad))
            idx = jnp.zeros(out_pad, i32).at[tgt].set(
                jnp.arange(nver_pad, dtype=i32), mode="drop")
            live = jnp.arange(out_pad, dtype=i32) < n_out.astype(i32)
            outs = []
            for s in spec:
                if s[0] == "h":
                    v = handles[seg_id[idx]].astype(jnp.dtype(s[1]))
                    outs.append(jnp.where(live, v, 0))
                elif s[0] == "v":
                    v = planes[s[1]][idx].astype(jnp.dtype(s[2]))
                    outs.append(jnp.where(live, v,
                                          jnp.zeros((), v.dtype)))
                else:
                    outs.append(planes[s[1]][idx] & live)
            return tuple(outs)

        fn = jax.jit(resolve)
        with self._mu:
            self._kernels[key] = fn
        return fn

    # -- feed mint ---------------------------------------------------------

    def _mint(self, bundle: ColdFeedBundle, runner, used_infos,
              dtypes, n: int, n_pad: int):
        import jax.numpy as jnp

        from ..utils import tracker
        from ..utils.failpoint import fail_point
        if fail_point("device::mvcc_resolve") is not None:
            self.mint_failures += 1
            return None
        planes = bundle.planes
        dev = bundle.device
        if dev is not None and (dev.n_ver != planes.n_ver or
                                dev.n_keys != planes.n_keys):
            dev = None          # streamed state diverged: re-upload
        # which source planes the kernel needs, in input order
        spec = []
        srcs = []               # (host array, device name)

        def slot(name: str, host_arr) -> int:
            for i, (_a, nm) in enumerate(srcs):
                if nm == name:
                    return i
            srcs.append((host_arr, name))
            return len(srcs) - 1

        null_flags = []
        for info, ds in zip(used_infos, dtypes):
            if info.is_pk_handle:
                spec.append(("h", ds))
                null_flags.append(False)
                continue
            cid = info.col_id
            _k, vals, valid = planes.cols[cid]
            spec.append(("v", slot(f"v{cid}", vals), ds))
            has_nulls = bundle.has_nulls[cid]
            null_flags.append(has_nulls)
            if has_nulls:
                spec.append(("m", slot(f"m{cid}", valid)))

        if dev is not None:
            nver_pad, nkeys_pad = dev.cap_ver, dev.cap_keys
        else:
            nver_pad = _bucket(planes.n_ver)
            nkeys_pad = _bucket(planes.n_keys)

        def pad_put(arr, cap):
            a = np.ascontiguousarray(arr)
            if len(a) != cap:
                p = np.zeros(cap, a.dtype)
                p[:len(a)] = a
                a = p
            return jnp.asarray(a)

        with tracker.phase("h2d_stream"):
            if dev is not None:
                fixed = (dev.bufs["commit_ts"], dev.bufs["wtype"],
                         dev.bufs["seg_id"], dev.bufs["handles"])
                # a column the stream never saw a datum for has no
                # resident plane: all-invalid zeros serve it (the host
                # mirror agrees — it synthesized the same)
                ins = tuple(
                    dev.bufs[nm] if nm in dev.bufs
                    else jnp.zeros(nver_pad, a.dtype)
                    for a, nm in srcs)
            else:
                fixed = (pad_put(planes.commit_ts.view(np.int64),
                                 nver_pad),
                         pad_put(planes.wtype, nver_pad),
                         pad_put(planes.seg_id, nver_pad),
                         pad_put(planes.handles, nkeys_pad))
                ins = tuple(pad_put(a, nver_pad) for a, _nm in srcs)

        with tracker.phase("mvcc_resolve"):
            fn = self._kernel(nver_pad, nkeys_pad, n_pad, tuple(spec))
            read_ts = jnp.asarray(bundle.read_ts, jnp.int64)
            n_out = jnp.asarray(n, jnp.int64)
            flat = list(fn(read_ts, n_out, *fixed, *ins))

            # CF_DEFAULT spills: the kernel gathered zero cells for
            # PUTs whose payload lives in CF_DEFAULT — patch them from
            # the host-truth values fetched at build time
            if bundle.spill_patches:
                plane_of = {}
                fi = 0
                for ci, info in enumerate(used_infos):
                    plane_of[ci] = fi
                    fi += 2 if null_flags[ci] else 1
                for row, payload in bundle.spill_patches.items():
                    for ci, info in enumerate(used_infos):
                        if info.is_pk_handle:
                            continue
                        col = bundle.mirror_cols[info.col_id]
                        fi = plane_of[ci]
                        upd = np.asarray(
                            [col.values[row]]).astype(
                                flat[fi].dtype, copy=False)
                        flat[fi] = runner._dus(flat[fi], jnp.asarray(upd),
                                               row)
                        if null_flags[ci]:
                            m = np.asarray([bool(col.validity[row])])
                            flat[fi + 1] = runner._dus(
                                flat[fi + 1], jnp.asarray(m), row)

        feed = {"flat": tuple(flat), "null_flags": tuple(null_flags),
                "n_pad": n_pad}
        if runner.scrub_digests:
            # digests anchor to HOST truth (the mirror), never to the
            # device planes they audit — a wrong resolve or a corrupt
            # gather diverges at the next scrub instead of laundering
            from .supervisor import host_plane_digest
            digests = []
            for info, ds, nulls in zip(used_infos, dtypes, null_flags):
                if info.is_pk_handle:
                    v = bundle.mirror_handles
                else:
                    v = bundle.mirror_cols[info.col_id].values
                digests.append(host_plane_digest(
                    np.ascontiguousarray(v.astype(np.dtype(ds),
                                                  copy=False)), n))
                if nulls:
                    digests.append(host_plane_digest(
                        np.ascontiguousarray(
                            bundle.mirror_cols[info.col_id].validity), n))
            feed["digests"] = tuple(digests)
            feed["n_live"] = n
            for a in feed["flat"]:
                runner._range_digest_kernel(a.dtype, a.shape[0])
        self.mints += 1
        return feed

    def stats(self) -> dict:
        with self._mu:
            return {"mints": self.mints,
                    "mint_failures": self.mint_failures,
                    "kernels": len(self._kernels)}
