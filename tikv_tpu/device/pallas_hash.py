"""Pallas TPU kernel for the direct-index hash aggregation.

The XLA two-level one-hot kernel (kernels.twolevel_partial) is limited by
two platform costs it cannot remove:

1. XLA materializes ``dot_general`` operands in HBM at fusion
   boundaries, so the generated one-hot planes (~136 B/row) round-trip
   through HBM — measured ~23 us per 2^16-row block, 40+ ms per 100M-row
   request against a ~1.2 ms feed-read roofline.
2. ``lax.scan`` over a large xs feed costs ~31 us per step on this
   runtime, another ~100 ms at 2^15-row chunks.

This kernel fuses one-hot generation, the MXU contraction, and the
accumulator into one ``pallas_call``: planes are generated in VMEM and
consumed immediately (never touching HBM), and the sequential grid
replaces the scan (~17 ms total at 100M rows, vs ~150 ms for the XLA
path).

Layout notes (all empirically forced by Mosaic on v5e):

- Everything is **lane-major**: 1-D row vectors are natively (1, B), so
  the one-hots are built TRANSPOSED — ``A8T (HI, B)``, ``W8T (P8*LO, B)``
  — with major-dim broadcasts (``x[None, :]``; minor-dim ``[:, None]``
  insertion is unsupported for non-32-bit types), and the contraction is
  an NT-form ``dot_general`` over the lane axis.
- Comparisons/selects run in int32 (int8 compares and int8 iota are
  unsupported), with one astype(int8) per operand.
- The accumulator is an int32 pair (alo, ahi): per-block partials are
  exact in int32 (|cell| <= 127*B), and ``x == (x >> 16 << 16) + (x &
  0xFFFF)`` makes the pair reconstruction exact in int64 on the host.
  int64 is unavailable inside Mosaic kernels.
- The kernel call runs under ``jax.enable_x64(False)`` — with x64 on,
  Python ints in index maps trace as i64 and Mosaic rejects the module.

The packed output (2, HI, P8*LO) matches twolevel_partial's layout, so
the host-side unpack (kernels.twolevel_unpack / states_from_matmul) is
shared with the XLA path.

Reference for the role this kernel plays: the fast hash-agg executor
(components/tidb_query_executors/src/fast_hash_aggr.rs) — BASELINE
config 4's hot path.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..expr.eval import eval_rpn

# Rows per grid step.  Swept on v5e at 100M rows: 2^17 beats 2^15 (108ms),
# 2^16 (102ms) and 2^18 (101ms, VMEM pressure) at 87ms end-to-end.
BLOCK = 1 << 17

# HI = slots/LO sublanes in the A operand; cap keeps the (HI, B) one-hot
# intermediates inside VMEM.  Above this the XLA two-level path serves
# (up to its own 2^20 ceiling).
MAX_SLOTS = 1 << 13

_i32 = jnp.int32


def supported(plan, feed, dtypes, pf: int, capacity: int,
              single_device: bool) -> bool:
    """Static gate for the Pallas fast path.

    int32 feed columns only (int64 is unsupported in Mosaic), no NULL
    validity planes (they would need int8 plane inputs), int byte-plane
    aggregates only (pf == 0), and a slot span the (HI, B) one-hot can
    hold in VMEM.
    """
    if not single_device or pf != 0:
        return False
    if capacity + 2 > MAX_SLOTS:
        return False
    if any(feed["null_flags"]):
        return False
    if any(dt != "int32" for dt in dtypes):
        return False
    if feed["n_pad"] % BLOCK != 0:
        return False
    return True


def build(plan, layouts, p8: int, capacity: int, n_pad: int,
          n_cols: int):
    """Build the pallas_call for one (plan, feed-shape) pair.

    Returns ``call(scal_i32[2], *flat) -> (2, HI, p8*LO) int32`` where
    ``scal = [n_rows, key_base]``.
    """
    LO = 32
    slots = capacity + 2
    hi_n = -(-slots // LO)
    HI = ((hi_n + 7) // 8) * 8
    W = p8 * LO
    B = BLOCK
    nblk = n_pad // B
    sel_rpns = plan.sel_rpns
    key_rpn = plan.key_rpn
    agg_rpns = plan.agg_rpns

    def kernel(sref, *refs):
        out_ref = refs[n_cols]
        alo, ahi = refs[n_cols + 1], refs[n_cols + 2]
        i = pl.program_id(0)

        @pl.when(i == 0)
        def _():
            alo[:] = jnp.zeros_like(alo)
            ahi[:] = jnp.zeros_like(ahi)

        n_rows = sref[0]
        base = sref[1]
        row0 = i * _i32(B)
        riota = lax.broadcasted_iota(_i32, (1, B), 1)[0]
        row_mask = (row0 + riota) < n_rows

        # columns are all-valid (gated): validity == row_mask
        pairs = [(refs[c][:], row_mask) for c in range(n_cols)]
        mask = row_mask
        for rpn in sel_rpns:
            v, ok = eval_rpn(rpn, pairs, B, jnp)
            mask = mask & ok & (v != 0)

        kv, km = eval_rpn(key_rpn, pairs, B, jnp)
        kv = jnp.broadcast_to(kv, (B,)).astype(_i32)
        km = jnp.broadcast_to(km, (B,))
        idx = kv - base
        in_range = (idx >= _i32(0)) & (idx < _i32(capacity))
        # slot layout (ops/agg.hash_agg_tile): [0, capacity) groups,
        # capacity = NULL-key slot, capacity+1 = scrap (masked-out rows;
        # also out-of-range keys, which the caller's span precheck rules
        # out)
        idx = jnp.where(mask & km & in_range, idx, _i32(capacity + 1))
        idx = jnp.where(mask & ~km, _i32(capacity), idx)
        hi_ = idx // _i32(LO)
        lo_ = idx - hi_ * _i32(LO)

        hi_iota = lax.broadcasted_iota(_i32, (HI, B), 0)
        lo_iota = lax.broadcasted_iota(_i32, (LO, B), 0)
        A8T = jnp.where(hi_[None, :] == hi_iota, _i32(1),
                        _i32(0)).astype(jnp.int8)
        OLT = lo_[None, :] == lo_iota

        m32 = jnp.where(mask, _i32(1), _i32(0))
        zero = jnp.zeros((LO, B), _i32)
        w_planes = [jnp.where(OLT, m32[None, :], zero)]   # plane 0 = mask
        for lay, rpn in zip(layouts, agg_rpns):
            if lay.kind == "count_star":
                continue
            v, ok = eval_rpn(rpn, pairs, B, jnp)
            v = jnp.broadcast_to(v, (B,)).astype(_i32)
            ok32 = jnp.where(jnp.broadcast_to(ok, (B,)) & mask,
                             _i32(1), _i32(0))
            if lay.ok_plane != 0:
                w_planes.append(jnp.where(OLT, ok32[None, :], zero))
            if lay.byte_planes:
                nb = lay.nb
                biased = v + _i32(1 << (8 * nb - 1))
                for k in range(nb):
                    byte = ((biased >> (8 * k)) & _i32(0xFF)) - _i32(128)
                    byte = byte * ok32
                    w_planes.append(jnp.where(OLT, byte[None, :], zero))
        W8T = jnp.concatenate(w_planes, axis=0).astype(jnp.int8)

        prod = lax.dot_general(A8T, W8T, (((1,), (1,)), ((), ())),
                               preferred_element_type=_i32)
        alo[:] += prod & _i32(0xFFFF)
        ahi[:] += prod >> 16

        @pl.when(i == nblk - 1)
        def _():
            out_ref[0] = alo[:]
            out_ref[1] = ahi[:]

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(nblk,),
        in_specs=[pl.BlockSpec((B,), lambda i, s: (i,))
                  for _ in range(n_cols)],
        out_specs=pl.BlockSpec((2, HI, W), lambda i, s: (0, 0, 0)),
        scratch_shapes=[pltpu.VMEM((HI, W), _i32),
                        pltpu.VMEM((HI, W), _i32)],
    )
    call = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((2, HI, W), _i32),
        grid_spec=grid_spec,
        compiler_params=pltpu.CompilerParams(
            vmem_limit_bytes=100 << 20),
    )

    scal_cache: dict = {}

    def run(n: int, base: int, flat):
        # a fresh scalar H2D on every request adds ~30 ms to the fetch
        # through the tunnel; the (n, base) pair is constant per feed
        scal = scal_cache.get((n, base))
        if scal is None:
            scal = jnp.asarray(np.asarray([n, base], np.int32))
            scal_cache[(n, base)] = scal
        with jax.enable_x64(False):
            return call(scal, *flat)

    return run, LO, HI


def unpack_to_int64(packed: np.ndarray) -> np.ndarray:
    """(2, HI, W) int32 pair -> (HI, W) exact int64 sums."""
    lo = packed[0].astype(np.int64)
    hi = packed[1].astype(np.int64)
    return lo + (hi << 16)
