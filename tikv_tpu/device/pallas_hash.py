"""Pallas TPU kernel for the direct-index hash aggregation.

The XLA two-level one-hot kernel (kernels.twolevel_partial) is limited by
two platform costs it cannot remove:

1. XLA materializes ``dot_general`` operands in HBM at fusion
   boundaries, so the generated one-hot planes (~136 B/row) round-trip
   through HBM — measured ~23 us per 2^16-row block, 40+ ms per 100M-row
   request against the feed-read roofline.
2. ``lax.scan`` over a large xs feed costs ~31 us per step on this
   runtime, another ~100 ms at 2^15-row chunks.

This kernel fuses one-hot generation, the MXU contraction, and the
accumulator into one ``pallas_call``: planes are generated in VMEM and
consumed immediately (never touching HBM), and the sequential grid
replaces the scan.

Three slot-id modes share the kernel body (r6 — the direct-index kernel
is the default body for every aggregation shape that qualifies):

- ``dense``  — GROUP BY over a small contiguous key domain: the key
  expression evaluates in-kernel and ``key - base`` indexes the grid
  directly (BASELINE config 4).
- ``sparse`` — arbitrary int64 key domains: the host dictionary-encodes
  the keys once per snapshot (runner._sparse_slots) and the dense slot
  ids ride as ONE extra int32 input column, so the kernel never touches
  the (Mosaic-unsupported) int64 key values (config 4s).  Columns the
  kernel does not evaluate (the raw key) stay out of its input set, so
  their dtype/NULLability cannot disqualify the plan.
- ``simple`` — no GROUP BY: a single-slot grid (every masked row aims at
  slot 0), which turns SUM/COUNT/AVG over 50M rows into one fused
  HBM pass (config 3).

Design (r5 — all choices measured on v5e at 100M rows):

- **The MXU contraction is the binding constraint, not HBM.**  Pure-dot
  probes (operand generation stripped to ~2 VPU ops/cell) run
  9.4-15 G rows/s depending on output shape; streaming reads alone hit
  ~800 GB/s.  An exact scatter-by-matmul consumes one int8 K-element per
  row, so kernel time ~= rows / dot-rate regardless of byte width.
- **Tight slot grid.**  Rows with no destination (row-mask off,
  predicate false, key out of range) point their one-hot column at a
  sentinel ``hi`` row that does not exist (``idx = HI*LO``): the column
  is all-zero and the row contributes nothing — no scrap slot, and for
  a provably non-NULL key no NULL slot either, so 1024 groups fit
  exactly in HI=32 sublanes (was 40 with scrap+NULL: 20% more one-hot
  generation and dot).
- **Dead grid blocks skip the MXU (r6).**  The feed pads to a bucketed
  shape (runner._pad_rows: the 9/8-geometric grid bounds compile
  classes), but the bucketing must tax only the CACHE KEY, not the
  computed extent: blocks entirely outside [row_lo, row_hi) gate the
  whole one-hot + dot body behind ``pl.when``, so a masked block costs
  its input DMA and the ~10 us grid step — not the contraction that is
  the kernel's binding constraint (up to 12.5% of pass time before).
- **Per-plane dots, no concatenation.**  The weight planes
  (mask / ok / value-byte) each dot against the shared ``A`` one-hot and
  accumulate into their lane slice of the packed output; concatenating
  them first costs a (P*LO, B) VMEM copy per block (~1 ms/100M rows).
- **BLOCK = 2^18.**  Grid-step fixed cost is ~10 us on this runtime;
  halving the step count from 2^17 blocks saves ~4 ms per 100M rows.
  int8 operands with int32 accumulation are exact at any block size
  (products <= 127, per-dot sums <= 127*2^18 << 2^31), unlike bf16/f32
  whose 2^24 mantissa bounds the contraction at 2^17 rows.
- Everything is **lane-major**: 1-D row vectors are natively (1, B), so
  one-hots are built TRANSPOSED — ``A (HI, B)``, planes ``(LO, B)`` —
  with major-dim broadcasts, and the contraction is an NT-form
  ``dot_general`` over the lane axis.  Comparisons/selects run in int32
  (int8 compares and int8 iota are unsupported), one astype(int8) per
  operand.  The kernel call runs under ``jax.enable_x64(False)`` — with
  x64 on, Python ints in index maps trace as i64 and Mosaic rejects the
  module.
- The accumulator is an int32 pair (alo, ahi): per-block partials are
  exact in int32, and ``x == (x >> 16 << 16) + (x & 0xFFFF)`` makes the
  pair reconstruction exact in int64 on the host (int64 is unavailable
  inside Mosaic kernels).

The packed output (2, HI, P8*LO) matches twolevel_partial's layout, so
the host-side unpack (kernels.twolevel_unpack / states_from_matmul) is
shared with the XLA path; when the tight grid has fewer than
``capacity + 2`` slots the caller zero-pads the NULL/scrap rows.

Reference for the role this kernel plays: the fast hash-agg executor
(components/tidb_query_executors/src/fast_hash_aggr.rs) — BASELINE
config 4's hot path.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..expr.eval import eval_rpn
from ..expr.rpn import RpnColumnRef

# Rows per grid step.  Swept on v5e at 100M rows (r5): 2^18 beats 2^17
# by ~3.5 ms/pass (fewer ~10 us grid steps) and 2^19 regresses (VMEM
# pressure breaks double-buffering).
BLOCK = 1 << 18

# Low radix of the slot factorization: slot = hi*LO + lo.  32 balances
# the A one-hot (slots/LO sublane rows, the costlier operand to
# generate) against plane width (measured: LO=16 doubles A-gen cost for
# a ~2x slower kernel; LO=64 pushes multi-plane outputs past one lane
# tile).
LO = 32

# Slot-span cap: A is (slots/LO, BLOCK) int8 in VMEM — 4096 slots is
# a 32 MB A operand at BLOCK=2^18, leaving headroom for the weight
# planes under the ~110 MB VMEM budget.  Above this the XLA two-level
# path serves (up to its own 2^20 ceiling).
MAX_SLOTS = 1 << 12

MODE_DENSE = "dense"
MODE_SPARSE = "sparse"
MODE_SIMPLE = "simple"

_i32 = jnp.int32


def _rpn_cols(rpn) -> set:
    return {n.col_idx for n in rpn.nodes if isinstance(n, RpnColumnRef)}


def kernel_col_ids(plan, mode: str) -> tuple:
    """used_cols positions whose VALUES the kernel evaluates in VMEM.

    Only these columns become kernel inputs (and must therefore be int32
    and non-nullable); a sparse GROUP BY key is consumed as precomputed
    slot ids instead, so its raw (often int64 / nullable) column never
    reaches the kernel.
    """
    ids: set = set()
    for r in plan.sel_rpns:
        ids |= _rpn_cols(r)
    for r in plan.agg_rpns:
        if r is not None:
            ids |= _rpn_cols(r)
    if mode == MODE_DENSE:
        ids |= _rpn_cols(plan.key_rpn)
    return tuple(sorted(ids))


def key_never_null(plan) -> bool:
    """True when the group key provably cannot be NULL: a bare column
    reference over a feed column with no validity plane.  (The
    ``supported`` gate already requires every kernel-input column be
    non-nullable; expression keys keep a NULL slot because a function
    may introduce NULL, e.g. out-of-domain casts.)"""
    nodes = plan.key_rpn.nodes
    return len(nodes) == 1 and isinstance(nodes[0], RpnColumnRef)


def n_slots(plan, capacity: int, mode: str = MODE_DENSE) -> int:
    """Slots the kernel actually materializes (tight grid)."""
    if mode == MODE_SIMPLE:
        return 1
    if mode == MODE_SPARSE:
        # the slot encoding (runner._sparse_slots) reserves slot
        # ``capacity`` for NULL keys; whether a given snapshot has any
        # is data-dependent, so the slot is always materialized
        return capacity + 1
    return capacity + (0 if key_never_null(plan) else 1)


def supported(plan, feed, dtypes, pf: int, capacity: int,
              n_shards: int = 1, mode: str = MODE_DENSE) -> bool:
    """Static gate for the Pallas fast path.

    int32 kernel-input columns only (int64 is unsupported in Mosaic),
    no NULL validity planes on kernel inputs (they would need int8
    plane inputs), int byte-plane aggregates only (pf == 0), and a slot
    span whose one-hot fits VMEM.  Columns outside the kernel's input
    set (e.g. a sparse key consumed as slot ids) are exempt.

    ``n_shards > 1``: the sharded mesh runs this same kernel PER SHARD
    under shard_map — each shard's grid covers its local feed slice,
    so the padded feed must split into whole BLOCKs per shard; the
    per-shard packed partials psum on ICI (runner._try_pallas).
    """
    if pf != 0:
        return False
    if n_slots(plan, capacity, mode) > MAX_SLOTS:
        return False
    if feed["n_pad"] % (max(1, n_shards) * BLOCK) != 0:
        return False
    kcols = kernel_col_ids(plan, mode)
    if not kcols:
        return False        # zero-input pallas_call; XLA serves trivially
    for i in kcols:
        if feed["null_flags"][i] or dtypes[i] != "int32":
            return False
    return True


def build(plan, layouts, p8: int, capacity: int, nblk: int,
          col_map, mode: str = MODE_DENSE):
    """Build the pallas_call for one (plan, grid-span) pair.

    ``nblk`` is the GRID SPAN in blocks, not the whole feed: the
    "region → chip, bucket → tile" mapping (SURVEY §5.7, pd_client
    buckets) dispatches one kernel per covered bucket span — the
    scalar-prefetched block offset shifts the input index map, so a
    request over one bucket of a 100M-row region costs one bucket's
    blocks, and disjoint spans' packed partials merge by addition
    exactly like psum partials.

    ``col_map[i]``: input-ref position of used_cols[i], or -1 when the
    column is not a kernel input (sparse keys, columns only the host
    touches).  In ``sparse`` mode one extra int32 slot-id column rides
    after the mapped columns.

    Returns ``(run, LO, HI)`` with
    ``run(row_lo, row_hi, base, blk0, cols) -> (2, HI, p8*LO) int32``
    packed accumulator pair covering absolute rows
    [row_lo, row_hi) ⊆ [blk0*BLOCK, (blk0+nblk)*BLOCK); ``cols`` is the
    already-selected input tuple (mapped columns, then slot ids when
    sparse).
    """
    slots = n_slots(plan, capacity, mode)
    hi_n = -(-slots // LO)
    HI = ((hi_n + 7) // 8) * 8
    W = p8 * LO
    B = BLOCK
    # the sentinel hi value for rows with no destination slot: outside
    # [0, HI), so the row's one-hot column is all-zero
    SENT = HI * LO
    nullable = mode != MODE_SIMPLE and (
        mode == MODE_SPARSE or not key_never_null(plan))
    sel_rpns = plan.sel_rpns
    key_rpn = plan.key_rpn
    agg_rpns = plan.agg_rpns
    lobits = LO.bit_length() - 1
    n_cols_in = sum(1 for p in col_map if p >= 0)
    sparse = mode == MODE_SPARSE
    n_in = n_cols_in + (1 if sparse else 0)

    def kernel(sref, *refs):
        out_ref = refs[n_in]
        alo, ahi = refs[n_in + 1], refs[n_in + 2]
        i = pl.program_id(0)

        @pl.when(i == 0)
        def _():
            alo[:] = jnp.zeros_like(alo)
            ahi[:] = jnp.zeros_like(ahi)

        row_lo = sref[0]
        row_hi = sref[1]
        base = sref[2]
        blk0 = sref[3]
        row0 = (i + blk0) * _i32(B)

        # dead-block guard: a block entirely outside [row_lo, row_hi)
        # (bucketed feed padding, bucketed tile spans) skips one-hot
        # generation and the dots — the bucketing then costs only this
        # block's DMA + grid step, never MXU time
        @pl.when((row0 < row_hi) & (row0 + _i32(B) > row_lo))
        def _():
            riota = lax.broadcasted_iota(_i32, (1, B), 1)[0]
            rows = row0 + riota
            row_mask = (rows >= row_lo) & (rows < row_hi)

            # kernel-input columns are all-valid (gated): validity ==
            # row_mask; unmapped columns never appear in these rpns
            pairs = [None if p < 0 else (refs[p][:], row_mask)
                     for p in col_map]
            mask = row_mask
            for rpn in sel_rpns:
                v, ok = eval_rpn(rpn, pairs, B, jnp)
                mask = mask & ok & (v != 0)

            if mode == MODE_SIMPLE:
                # single-slot grid: every masked row lands in slot 0
                idx = jnp.where(mask, _i32(0), _i32(SENT))
            elif sparse:
                # precomputed slot ids: [0, capacity) groups, capacity
                # = NULL-key slot, capacity+1 = scrap/padding → SENT
                s = refs[n_cols_in][:].astype(_i32)
                idx = jnp.where(mask & (s < _i32(slots)), s, _i32(SENT))
            else:
                kv, km = eval_rpn(key_rpn, pairs, B, jnp)
                kv = jnp.broadcast_to(kv, (B,)).astype(_i32)
                km = jnp.broadcast_to(km, (B,))
                rel = kv - base
                in_range = (rel >= _i32(0)) & (rel < _i32(capacity))
                # slot layout: [0, capacity) groups, capacity = NULL-key
                # slot (only materialized for expression keys); rows
                # with no slot — masked out, out-of-range, or NULL under
                # a non-null key — aim at SENT: hi = HI, matching no
                # one-hot row, so the whole column is zero and the row
                # vanishes from every plane.
                if nullable:
                    idx = jnp.where(mask & km & in_range, rel, _i32(SENT))
                    idx = jnp.where(mask & ~km, _i32(capacity), idx)
                else:
                    idx = jnp.where(mask & km & in_range, rel, _i32(SENT))
            hi_ = idx >> lobits
            lo_ = idx & _i32(LO - 1)

            hi_iota = lax.broadcasted_iota(_i32, (HI, B), 0)
            lo_iota = lax.broadcasted_iota(_i32, (LO, B), 0)
            A8 = jnp.where(hi_[None, :] == hi_iota, _i32(1),
                           _i32(0)).astype(jnp.int8)
            cmp = lo_[None, :] == lo_iota
            zero = jnp.zeros((LO, B), _i32)
            dn = (((1,), (1,)), ((), ()))

            def accum(p, plane_i32):
                prod = lax.dot_general(A8, plane_i32.astype(jnp.int8), dn,
                                       preferred_element_type=_i32)
                sl = slice(p * LO, (p + 1) * LO)
                alo[:, sl] += prod & _i32(0xFFFF)
                ahi[:, sl] += prod >> 16

            # plane 0 = slot-presence counts; rows without a slot are
            # already dropped by their zero A column, so no mask multiply
            accum(0, jnp.where(cmp, _i32(1), zero))
            p = 1
            for lay, rpn in zip(layouts, agg_rpns):
                if lay.kind == "count_star":
                    continue
                v, ok = eval_rpn(rpn, pairs, B, jnp)
                v = jnp.broadcast_to(v, (B,)).astype(_i32)
                okb = jnp.broadcast_to(ok, (B,))
                aliased = lay.ok_plane == 0
                if not aliased:
                    ok32 = jnp.where(okb, _i32(1), _i32(0))
                    accum(p, jnp.where(cmp, ok32[None, :], zero))
                    p += 1
                if lay.byte_planes:
                    nb = lay.nb
                    biased = v + _i32(1 << (8 * nb - 1))
                    if not aliased:
                        # NULL argument on a live row: bytes must not leak
                        biased = biased * ok32
                    for b in range(nb):
                        byte = ((biased >> (8 * b)) & _i32(0xFF)) - _i32(128)
                        if not aliased:
                            byte = jnp.where(okb, byte, _i32(0))
                        accum(p, jnp.where(cmp, byte[None, :], zero))
                        p += 1

        @pl.when(i == nblk - 1)
        def _():
            out_ref[0] = alo[:]
            out_ref[1] = ahi[:]

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(nblk,),
        in_specs=[pl.BlockSpec((B,), lambda i, s: (i + s[3],))
                  for _ in range(n_in)],
        out_specs=pl.BlockSpec((2, HI, W), lambda i, s: (0, 0, 0)),
        scratch_shapes=[pltpu.VMEM((HI, W), _i32),
                        pltpu.VMEM((HI, W), _i32)],
    )
    call = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((2, HI, W), _i32),
        grid_spec=grid_spec,
        compiler_params=pltpu.CompilerParams(
            vmem_limit_bytes=110 << 20),
    )

    scal_cache: dict = {}

    def run(row_lo, row_hi, base, blk0, cols):
        # a fresh scalar H2D on every request adds ~30 ms to the fetch
        # through the tunnel; the scalar tuple is constant per
        # (feed, tile).  Traced scalars (the sharded per-shard path:
        # row bounds depend on lax.axis_index) stack instead of
        # caching — inside shard_map there is no H2D to save.
        if isinstance(row_lo, (int, np.integer)):
            key = (row_lo, int(row_hi), int(base), int(blk0))
            scal = scal_cache.get(key)
            if scal is None:
                scal = jnp.asarray(np.asarray(key, np.int32))
                scal_cache[key] = scal
        else:
            with jax.enable_x64(False):
                scal = jnp.stack([
                    jnp.asarray(v).astype(jnp.int32)
                    for v in (row_lo, row_hi, base, blk0)])
        with jax.enable_x64(False):
            return call(scal, *cols)

    return run, LO, HI


def unpack_to_int64(packed: np.ndarray) -> np.ndarray:
    """(2, HI, W) int32 pair -> (HI, W) exact int64 sums."""
    lo = packed[0].astype(np.int64)
    hi = packed[1].astype(np.int64)
    return lo + (hi << 16)
