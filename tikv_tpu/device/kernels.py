"""MXU group-by aggregation — one-hot matmul kernels.

XLA lowers ``scatter``-with-duplicate-indices poorly on TPU (measured
~15M rows/s for int64 scatter-add vs >150M rows/s for the MXU path on the
same shapes), so the hash-agg hot path (BASELINE.md config 4) computes
per-group COUNT/SUM via ``dot_general`` against a one-hot slot matrix:

- the group-id per row (slot index: key-base, NULL slot, scrap slot —
  mirror of ops/agg.hash_agg_tile's layout) selects a one-hot column;
- integer values are **byte-split** into int8 planes (biased to [-128,127])
  so the whole aggregation is exact int8×int8→int32 MXU work, widened to
  int64 between blocks: sum(v) = Σ_k 2^(8k)·S_k + count·BIAS_OFFSET;
- real values ride a separate f32 matmul, accumulated in f64 across blocks;
- rows are processed in ``lax.scan`` blocks so the transient one-hot
  (block × slots) stays small and int32 partials cannot overflow
  (block ≤ 2^16 rows × |int8| ≤ 127 < 2^23).

Plane layout: plane 0 is always the row mask (→ present + count_star);
each aggregate appends its own validity plane and value planes.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Sequence

import numpy as np

import jax.numpy as jnp
from jax import lax

try:                                    # varying-manual-axes typing
    _pvary = lax.pvary
except AttributeError:                  # jax 0.4.x: replication implicit
    def _pvary(x, axes):
        return x

BLOCK_ROWS = 1 << 16


def slot_pad(slots: int) -> int:
    """Round the one-hot width up to the MXU lane count."""
    return ((slots + 127) // 128) * 128


def int_planes_needed(vmin: int, vmax: int) -> int:
    """Bytes needed to represent [vmin, vmax] biased to unsigned."""
    for nb in (1, 2, 3, 4, 8):
        lo, hi = -(1 << (8 * nb - 1)), (1 << (8 * nb - 1)) - 1
        if lo <= vmin and vmax <= hi:
            return min(nb, 8)
    return 8


def bias_offset(nb: int) -> int:
    """sum(v) correction: v = Σ(c_k+128)·2^(8k) − 2^(8nb−1)."""
    return 128 * sum(1 << (8 * k) for k in range(nb)) - (1 << (8 * nb - 1))


@dataclass(frozen=True)
class PlaneLayout:
    """Static description of one spec's planes in the stacked matrices.

    ``ok_plane``: index of the validity int8 plane (None → use plane 0).
    ``byte_planes``: int8 plane indices of the value bytes (LSB first).
    ``f32_plane``: index into the f32 matrix for real sums.
    ``nb``: byte count for the int value split.
    """

    kind: str
    ok_plane: Optional[int] = None
    byte_planes: tuple = ()
    f32_plane: Optional[int] = None
    nb: int = 0


def build_layouts(specs, arg_is_real: Sequence[bool],
                  arg_nbytes: Sequence[int],
                  arg_ok_is_mask: Optional[Sequence[bool]] = None):
    """→ (layouts, n_int8_planes, n_f32_planes). Plane 0 = row mask.

    ``arg_ok_is_mask[i]`` — the arg's validity is provably identical to
    the row mask (bare NOT NULL column ref), so its validity plane aliases
    plane 0 instead of shipping a duplicate through the matmul.
    """
    if arg_ok_is_mask is None:
        arg_ok_is_mask = [False] * len(specs)
    layouts = []
    p8 = 1
    pf = 0
    for spec, is_real, nb, ok_is_mask in zip(specs, arg_is_real, arg_nbytes,
                                             arg_ok_is_mask):
        if spec.kind == "count_star":
            layouts.append(PlaneLayout("count_star"))
            continue
        if ok_is_mask:
            okp = 0
        else:
            okp = p8
            p8 += 1
        if spec.kind == "count":
            layouts.append(PlaneLayout("count", ok_plane=okp))
        elif spec.kind in ("sum", "avg"):
            if is_real:
                layouts.append(PlaneLayout(spec.kind, ok_plane=okp,
                                           f32_plane=pf))
                pf += 1
            else:
                bp = tuple(range(p8, p8 + nb))
                layouts.append(PlaneLayout(spec.kind, ok_plane=okp,
                                           byte_planes=bp, nb=nb))
                p8 += nb
        else:
            raise ValueError(f"matmul path cannot handle {spec.kind}")
    return layouts, p8, pf


def matmul_supported(specs) -> bool:
    return all(s.kind in ("count", "count_star", "sum", "avg") for s in specs)


def make_planes(layouts, specs, cols, mask):
    """Build the stacked int8 / f32 plane matrices for one row chunk.

    ``cols[i]``: (values, validity) for spec i (values int or f32).
    Returns (L8: (P8, n) int8, Lf: (Pf, n) f32 | None).
    """
    n = mask.shape[0]
    int8_planes = [mask.astype(jnp.int8)]
    f32_planes = []
    for lay, spec, col in zip(layouts, specs, cols):
        if lay.kind == "count_star":
            continue
        values, validity = col
        if lay.ok_plane == 0:       # validity aliases the row mask
            ok = mask
        else:
            ok = mask & validity
            int8_planes.append(ok.astype(jnp.int8))
        if lay.f32_plane is not None:
            f32_planes.append(
                jnp.where(ok, values, jnp.zeros_like(values))
                .astype(jnp.float32))
        elif lay.byte_planes:
            nb = lay.nb
            v64 = values.astype(jnp.int64) if nb > 4 else \
                values.astype(jnp.int32)
            biased = (v64 + (1 << (8 * nb - 1))).astype(
                jnp.uint64 if nb > 4 else jnp.uint32)
            for k in range(nb):
                byte = ((biased >> (8 * k)) & 0xFF).astype(jnp.int32) - 128
                int8_planes.append(
                    jnp.where(ok, byte, jnp.zeros_like(byte))
                    .astype(jnp.int8))
    L8 = jnp.stack(int8_planes)
    Lf = jnp.stack(f32_planes) if f32_planes else None
    return L8, Lf


def twolevel_lo(p8: int, pf: int) -> Optional[int]:
    """Pick the low-radix width for the factorized group-by, or None.

    The two-level kernel packs every plane's LO lanes side by side into one
    matmul operand, so the widest plane set bounds LO: max(p8, pf)·LO ≤ 128
    keeps each stacked operand inside one MXU lane tile.
    """
    width = max(p8, max(pf, 1))
    lo = 128
    while lo > 4 and width * lo > 128:
        lo //= 2
    return lo if width * lo <= 128 else None


def twolevel_dims(slots: int, p8: int, pf: int) -> tuple:
    """→ (LO, HI) for the factorized kernel (see twolevel_partial)."""
    lo = twolevel_lo(p8, pf)
    assert lo is not None, (p8, pf)
    hi = -(-slots // lo)
    return lo, ((hi + 7) // 8) * 8


def twolevel_partial(idx, L8, Lf, LO: int, HI: int):
    """Factorized one-hot group-by over ONE row block: slot = hi·LO + lo.

    The straight one-hot matmul (matmul_groupby) materializes an
    (block, slots) one-hot operand — both its VPU generation cost and its
    MXU contraction width scale with ``slots`` (≈1152 lanes for 1k
    groups). Factorizing the slot id as hi·LO+lo turns the aggregation
    into

      S2[hi, p·LO+lo] = Σ_rows onehot_hi[row, hi]·(L_p[row]·onehot_lo[row, lo])

    — ONE dot_general with a (block, HI) int8 left operand and a
    (block, P·LO) right operand, so one-hot generation shrinks from
    ``slots`` to ``HI + P·LO`` lanes per row and the MXU width from
    ``slots`` to ≤128. Measured ~8× faster than the straight one-hot on
    v5e for 1k groups (2.2ms vs 19ms per 2^23-row chunk).

    Returns PACKED partials (S2_8 (HI, p8·LO) int32, S2_f (HI, pf·LO)
    float32 | None); accumulate them across blocks in wider dtypes and
    call twolevel_unpack once at the end. int32 packing is exact while the
    per-call block stays ≤ 2^23 rows (|int8| ≤ 127 ⇒ |cell| < 2^30).
    """
    block = idx.shape[0]
    p8 = L8.shape[0]
    hi_iota = lax.broadcasted_iota(jnp.int32, (block, HI), 1)
    lo_iota = lax.broadcasted_iota(jnp.int32, (block, LO), 1)
    i32 = idx.astype(jnp.int32)
    hi = i32 // LO
    lo = i32 - hi * LO
    A8 = (hi[:, None] == hi_iota).astype(jnp.int8)
    onehot_lo = lo[:, None] == lo_iota
    zero8 = jnp.zeros((block, LO), jnp.int8)
    W8 = jnp.concatenate(
        [jnp.where(onehot_lo, L8[p][:, None], zero8) for p in range(p8)],
        axis=1)
    S2_8 = lax.dot_general(A8, W8, (((0,), (0,)), ((), ())),
                           preferred_element_type=jnp.int32)
    S2_f = None
    if Lf is not None:
        pf = Lf.shape[0]
        Af = A8.astype(jnp.float32)
        zerof = jnp.zeros((block, LO), jnp.float32)
        Wf = jnp.concatenate(
            [jnp.where(onehot_lo, Lf[p][:, None], zerof)
             for p in range(pf)], axis=1)
        S2_f = lax.dot_general(Af, Wf, (((0,), (0,)), ((), ())),
                               preferred_element_type=jnp.float32)
    return S2_8, S2_f


def twolevel_unpack(S2, n_planes: int, LO: int, slots: int, xp=jnp):
    """(HI, P·LO) packed partials → (P, slots) plane matrix."""
    HI = S2.shape[0]
    S = xp.transpose(S2.reshape(HI, n_planes, LO), (1, 0, 2)) \
        .reshape(n_planes, HI * LO)
    return S[:, :slots]


def matmul_groupby(idx, L8, Lf, slots: int, block: int = BLOCK_ROWS,
                   vary_axes: tuple = ()):
    """Blocked one-hot matmuls: → (S8: (P8, slots) int64,
    Sf: (Pf, slots) float64 | None).

    ``vary_axes``: when called inside shard_map, the mesh axis names — the
    scan carry must be marked device-varying (lax.pvary) to match the body
    output's varying-manual-axes type."""
    import math
    G = slot_pad(slots)
    n = idx.shape[0]
    # the block length must divide n (lax.scan over equal blocks); chunk
    # sizes are powers of two in practice, so this stays == BLOCK_ROWS
    block = math.gcd(n, min(block, n))
    nblk = n // block
    p8 = L8.shape[0]
    iota = jnp.arange(G, dtype=jnp.int32)

    idx_b = idx.reshape(nblk, block)
    l8_b = L8.reshape(p8, nblk, block).transpose(1, 0, 2)
    if Lf is not None:
        pf = Lf.shape[0]
        lf_b = Lf.reshape(pf, nblk, block).transpose(1, 0, 2)
        xs = (idx_b, l8_b, lf_b)
        carry = (jnp.zeros((p8, G), jnp.int64),
                 jnp.zeros((pf, G), jnp.float64))
    else:
        xs = (idx_b, l8_b)
        carry = (jnp.zeros((p8, G), jnp.int64), None)
    if vary_axes:
        carry = tuple(None if c is None else _pvary(c, vary_axes)
                      for c in carry)

    def body(carry, xs):
        c8, cf = carry
        if Lf is not None:
            i_b, l8, lf = xs
        else:
            i_b, l8 = xs
        onehot8 = (i_b[:, None] == iota[None, :]).astype(jnp.int8)
        prod8 = lax.dot_general(l8, onehot8, (((1,), (0,)), ((), ())),
                                preferred_element_type=jnp.int32)
        c8 = c8 + prod8.astype(jnp.int64)
        if Lf is not None:
            onehotf = (i_b[:, None] == iota[None, :]).astype(jnp.float32)
            prodf = lax.dot_general(lf, onehotf, (((1,), (0,)), ((), ())),
                                    preferred_element_type=jnp.float32)
            cf = cf + prodf.astype(jnp.float64)
        return (c8, cf), None

    (S8, Sf), _ = lax.scan(body, carry, xs)
    return S8[:, :slots], (None if Sf is None else Sf[:, :slots])


def states_from_matmul(layouts, specs, S8, Sf, xp=jnp):
    """Reassemble hash-agg state dicts (ops/agg.py layout) from the matmul
    partials.  Also returns the present mask (mask-plane count > 0).
    ``xp``: jnp in-kernel, or numpy for host-side finalize after a packed
    device→host transfer."""
    mask_count = S8[0]
    present = mask_count > 0
    states = []
    for lay, spec in zip(layouts, specs):
        if lay.kind == "count_star":
            states.append({"count": mask_count})
            continue
        okc = S8[lay.ok_plane]
        if lay.kind == "count":
            states.append({"count": okc})
        elif lay.f32_plane is not None:     # real sum/avg
            s = Sf[lay.f32_plane]
            states.append({"sum": s, "nonnull": okc} if lay.kind == "sum"
                          else {"sum": s, "count": okc})
        else:                               # int sum/avg
            total = xp.zeros_like(okc)
            for k, p in enumerate(lay.byte_planes):
                total = total + (S8[p] << (8 * k))
            total = total + okc * bias_offset(lay.nb)
            states.append({"sum": total, "nonnull": okc}
                          if lay.kind == "sum"
                          else {"sum": total, "count": okc})
    return present, states


def slot_index(key_pair, capacity: int, base, row_mask):
    """Row → slot id (group / NULL / scrap), mirroring
    ops/agg.hash_agg_tile's layout.  Returns (idx int32, overflow bool).

    For int32 keys the shift runs in int32 (int64 is pair-emulated on
    TPU): base is the host-computed key minimum, so every in-range key
    shifts into [0, capacity); a key far enough above base to wrap goes
    negative, fails the range check, and raises ``overflow`` — never a
    silent misclassification.
    """
    kv, km = key_pair
    null_slot = capacity
    scrap = capacity + 1
    if kv.dtype == jnp.int32:
        shifted = kv - base.astype(jnp.int32)
    else:
        shifted = kv.astype(jnp.int64) - base
    in_range = (shifted >= 0) & (shifted < capacity)
    idx = jnp.where(km & in_range, shifted, 0).astype(jnp.int32)
    idx = jnp.where(km, jnp.where(in_range, idx, scrap), null_slot)
    idx = jnp.where(row_mask, idx, scrap)
    overflow = jnp.any(row_mask & km & ~in_range)
    return idx, overflow
