"""Late-materialized device selection & scan kernels (pallas_hash's sibling).

Bench configs 1-2 (table scan, selection) were the last shapes pinned to
the host backend: a selection materializes its FULL output through D2H,
so the old device pass (predicate mask on device, n bool bytes back,
host filter) only added transfer cost on top of the same host gather.
Late materialization (Abadi et al., column-store execution) removes
exactly that cost: evaluate the predicate on device over the resident
HBM feed, move only a COMPACT selection vector, and gather the k
surviving rows host-side from the columnar snapshot that is already
resident — the same sparse-readback discipline an inference stack uses
to avoid shipping dense activations off-chip.

D2H volume per route (n scanned rows, k selected):

  ``mask``     n/8 bytes — packed predicate bitmask (``jnp.packbits``,
               bit order compatible with ``np.unpackbits`` on host).
  ``index``    4·K bytes — on-device compaction of selected row indices
               (``nonzero`` = popcount prefix-sum + scatter under XLA),
               K = pow2 bucket ≥ k so compile classes stay logarithmic.
  ``compact``  K·Σwidth bytes — low-width projected columns gathered ON
               DEVICE at the selected indices, so the host gather is
               skipped entirely (single-device; small k only).
  ``host``     0 — the host pipeline serves; correct at ~99% selectivity
               where every device route's D2H + gather meets or exceeds
               the plain host scan.

The mask and index routes are SHARD-CONCATENABLE and run on sharded
meshes as-is: each shard packs/compacts its local rows in feed order,
the count psums on ICI, and the host sees the same byte layout
concatenated (index entries carry global row offsets via the shard
index).  Only ``compact`` stays single-device — its gathered output is
committed to one chip by construction — and placement-routed requests
(device/placement.py) land on a single-device slice where every route
applies.

Unlike the aggregation kernels there is no Mosaic/Pallas body here by
measurement, not omission: the selection pass is purely elementwise
(predicate eval) plus a segmented popcount/prefix-sum — XLA fuses it
into ONE HBM pass already (no dot_general operand materialization, no
per-step scan cost), so a hand-written kernel has no fusion boundary to
remove.  The routes above attack the actual binding constraint, the
D2H transfer.

Predicate constants are HOISTED into traced scalar parameters
(``split_params``): the kernel cache key (``shape_key``) is const-blind,
so repeated selections at differing thresholds/selectivities share ONE
compile class per (plan shape, feed shape) — the reference's plan-cache
discipline applied to the device JIT cache.  ``split_params`` is also
the hoisting discipline of the device JOIN's fused probe pass
(device/join.py): a join fragment's probe-side selection predicates
evaluate inside the probe dispatch with their constants hoisted the
same way, so rotating thresholds never mint new probe-kernel compile
classes either.
"""

from __future__ import annotations

import math
from typing import Optional

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

try:                                    # jax >= 0.5 top-level alias
    _shard_map = jax.shard_map
except AttributeError:                  # 0.4.x: experimental home
    from jax.experimental.shard_map import shard_map as _shard_map

from ..datatype import device_const_dtype
from ..expr.eval import eval_rpn
from ..expr.rpn import RpnColumnRef, RpnConst, RpnExpression
from ..parallel import ROW_AXES, num_shards

ROUTE_MASK = "mask"
ROUTE_INDEX = "index"
ROUTE_COMPACT = "compact"
ROUTE_HOST = "host"

# Selectivity above which the endpoint router sends selections back to
# the host pipeline: past it the shared cost (materializing ~n output
# rows) dominates both paths, and the device adds its dispatch + D2H
# round trip for no saved work.  Observed-EWMA-gated (runner._sel_stats)
# with periodic re-probes so a workload whose selectivity drifts back
# down is re-discovered.
HOST_SELECTIVITY_CUTOFF = 0.95

# Largest k the compact route will materialize on device (values +
# validity per projected column, K·Σwidth bytes of D2H).  Above it the
# index route's 4·K bytes win and the host gather is cheap anyway.
COMPACT_MAX_ROWS = 1 << 14


def _next_pow2(n: int) -> int:
    return 1 << max(0, (int(n) - 1).bit_length())


def split_params(sel_rpns, n_cols: int):
    """Hoist numeric predicate constants into traced parameters.

    Returns ``(param_rpns, values, dtypes)`` where every int/float
    RpnConst in ``sel_rpns`` is replaced by an RpnColumnRef addressing a
    scalar parameter column at position ``n_cols + i``.  The parameter
    pairs the runner feeds (0-d value array, 0-d True validity) are
    exactly what ``eval._const_pair`` would have produced for the baked
    constant, so traces are value-identical — only the jit cache key
    stops depending on the constant's VALUE.
    """
    vals: list = []
    dts: list = []
    out = []
    for rpn in sel_rpns:
        nodes = []
        for nd in rpn.nodes:
            if isinstance(nd, RpnConst) and nd.value is not None and \
                    isinstance(nd.value, (int, float)):
                dt = device_const_dtype(nd.value)
                nodes.append(RpnColumnRef(n_cols + len(vals), nd.eval_type))
                vals.append(nd.value)
                dts.append(dt)
            else:
                nodes.append(nd)
        out.append(RpnExpression(tuple(nodes)))
    return out, tuple(vals), tuple(dts)


def shape_key(plan) -> tuple:
    """Const-blind identity of a scan_sel plan's predicate structure.

    Two plans differing only in numeric constant VALUES (same device
    dtype) map to the same key and share one compiled kernel; a constant
    crossing the int32/int64 boundary is a genuinely new trace.
    """
    def nk(nd):
        if isinstance(nd, RpnConst):
            if nd.value is None:
                return ("cN", nd.eval_type.value)
            if isinstance(nd.value, (int, float)):
                return ("c", device_const_dtype(nd.value))
            return ("c", repr(nd.value))    # non-numeric: host-only plans
        if isinstance(nd, RpnColumnRef):
            return ("col", nd.col_idx, nd.eval_type.value)
        return ("f", nd.meta.name, nd.n_args, nd.ctx)

    return (type(plan.scan).__name__, bool(getattr(plan.scan, "desc", False)),
            tuple(tuple(nk(nd) for nd in r.nodes) for r in plan.sel_rpns))


def index_bytes(k: float, n_shards: int = 1) -> int:
    """Real D2H bytes of the index route for an expected k: the
    per-shard pow2 capacity bucket (with the runner's 1.5× headroom)
    times the shard count — NOT 4·k.  The pow2 rounding and the
    per-shard replication can inflate the transfer several-fold near
    the crossover, so the router must compare against THIS figure."""
    cap = _next_pow2(max(64, int(math.ceil(k * 1.5)) + 64))
    return 4 * cap * n_shards


def choose_route(n: int, k: float, compact_ok: bool,
                 idx_bytes: Optional[int] = None) -> str:
    """Pick the cheapest device route for ~k selected of n scanned rows.

    Pure D2H-bytes comparison (the shared host gather of k rows cancels
    between mask and index): index wins only when its REAL transfer —
    capacity buckets × shards (``idx_bytes``; the caller passes the
    exact figure, default approximates a single shard) — undercuts the
    n/8-byte mask; compact additionally skips the host gather but
    bounds its on-device materialization at COMPACT_MAX_ROWS.
    """
    if compact_ok and k <= COMPACT_MAX_ROWS:
        return ROUTE_COMPACT
    if idx_bytes is None:
        idx_bytes = index_bytes(k)
    if idx_bytes < n / 8:
        return ROUTE_INDEX
    return ROUTE_MASK


def modeled_d2h_bytes(route: str, n: int, k: int, row_bytes: int = 12,
                      n_shards: int = 1) -> int:
    """Bytes the chosen route moves over D2H (the router's cost model;
    also the bench sweep's reported figure).  ``row_bytes``: per-row
    width of the compact route's projected columns."""
    if route == ROUTE_MASK:
        return -(-n // 8)
    if route == ROUTE_INDEX:
        return index_bytes(k, n_shards)
    if route == ROUTE_COMPACT:
        return row_bytes * _next_pow2(max(64, k))
    return 0


def host_path_bytes(n: int, k: int, pred_bytes: int = 8,
                    row_bytes: int = 24) -> int:
    """Bytes the host pipeline touches for the same request: one pass
    over the predicate columns plus the k-row output gather.  Routes
    whose modeled D2H exceeds this must not be picked (the gather term
    is shared, so comparing totals is conservative for the device)."""
    return n * pred_bytes + k * row_bytes


def _shard_index(mesh):
    tile = mesh.shape[ROW_AXES[1]]
    return (lax.axis_index(ROW_AXES[0]) * tile
            + lax.axis_index(ROW_AXES[1])).astype(jnp.int64)


def _feed_pairs(flat, null_flags, row_mask):
    pairs = []
    fi = 0
    for has_nulls in null_flags:
        v = flat[fi]
        fi += 1
        if has_nulls:
            m = flat[fi]
            fi += 1
        else:
            m = row_mask
        pairs.append((v, m))
    return pairs


def build_mask_kernel(sel_rpns, null_flags, n_pad: int, n_flat: int,
                      n_params: int, mesh=None):
    """Fused predicate-eval pass → ``(count, packed bitmask, bool mask)``.

    One jit dispatch over the whole resident feed: the selection vector
    (bool mask) stays ON DEVICE for a follow-up compaction kernel, the
    packed bitmask (n/8 bytes) is the mask route's D2H payload, and the
    scalar count seeds the router.  ``sel_rpns`` must already be
    parameterized (split_params); the ``n_params`` scalar args follow
    ``n`` and precede the feed columns.  Sharded meshes psum the count
    and emit per-shard mask/packed slices in feed row order.
    """
    S = 1 if mesh is None else num_shards(mesh)
    n_local = n_pad // S
    assert n_local % 8 == 0, n_local
    idt = jnp.int32 if n_pad <= np.iinfo(np.int32).max else jnp.int64

    def local_fn(n_scalar, *args):
        params = args[:n_params]
        flat = args[n_params:]
        base0 = idt(0) if mesh is None else \
            (_shard_index(mesh) * n_local).astype(idt)
        iota = jnp.arange(n_local, dtype=idt)
        row_mask = (base0 + iota) < n_scalar.astype(idt)
        pairs = _feed_pairs(flat, null_flags, row_mask)
        one = jnp.ones((), jnp.bool_)
        for p in params:
            pairs.append((p, one))
        mask = row_mask
        for rpn in sel_rpns:
            v, ok = eval_rpn(rpn, pairs, n_local, jnp)
            mask = mask & ok & (v != 0)
        mask = jnp.broadcast_to(mask, (n_local,))
        count = jnp.sum(mask, dtype=jnp.int64)
        if mesh is not None:
            count = lax.psum(count, ROW_AXES)
        return count, jnp.packbits(mask), mask

    if mesh is None:
        return jax.jit(local_fn)
    return jax.jit(_shard_map(
        local_fn, mesh=mesh,
        in_specs=(P(),) * (1 + n_params) + (P(ROW_AXES),) * n_flat,
        out_specs=(P(), P(ROW_AXES), P(ROW_AXES))))


def build_batched_mask_kernel(sel_rpns, null_flags, n_pad: int,
                              n_flat: int, n_params: int, group: int):
    """Cross-request STACKED predicate pass: ``group`` requests sharing
    one compile class (same ``shape_key``, same feed) evaluate in ONE
    dispatch → ``(counts (G,), packed bitmasks (G, n_pad/8))``.

    The hoisted scalar parameters arrive with a leading group axis —
    shape ``(G,)`` per parameter — and ``jax.vmap`` maps the solo
    kernel's trace over it while the feed columns stay broadcast
    (in_axes=None): the per-request fixed cost (launch + D2H sync) is
    paid once for the whole group, which is the TPU-economics point
    (Jouppi: amortize the launch/transfer overhead across a batch).
    The feed is read once per lane by construction of the elementwise
    pass; XLA keeps the lanes in one fused HBM traversal for the common
    single-predicate shapes.  ``group`` is a pow2 bucket so compile
    classes stay logarithmic in occupancy; dead lanes (group padding)
    repeat a live lane's parameters and their outputs are discarded.

    The stacked kernel itself is single-device, but sharded meshes are
    no longer excluded from coalescing: a placement-routed request
    (device/placement.py) stacks on its anchor's single-device slice.
    Only whole-mesh sharded dispatches — whose per-shard launches GSPMD
    already amortizes — stay solo.
    """
    assert n_params >= 1, "stacked dispatch needs hoisted parameters"
    idt = jnp.int32 if n_pad <= np.iinfo(np.int32).max else jnp.int64

    def local_fn(n_scalar, *args):
        params = args[:n_params]            # each (group,)
        flat = args[n_params:]
        iota = jnp.arange(n_pad, dtype=idt)
        row_mask = iota < n_scalar.astype(idt)

        def one(*ps):
            pairs = _feed_pairs(flat, null_flags, row_mask)
            one_b = jnp.ones((), jnp.bool_)
            for p in ps:
                pairs.append((p, one_b))
            mask = row_mask
            for rpn in sel_rpns:
                v, ok = eval_rpn(rpn, pairs, n_pad, jnp)
                mask = mask & ok & (v != 0)
            mask = jnp.broadcast_to(mask, (n_pad,))
            return jnp.sum(mask, dtype=jnp.int64), jnp.packbits(mask)

        return jax.vmap(one)(*params)

    return jax.jit(local_fn)


def build_index_kernel(n_pad: int, k_cap: int, mesh=None):
    """On-device compaction of selected row indices.

    ``nonzero(size=k_cap)`` lowers to the popcount prefix-sum + scatter
    pattern; indices come back ascending per shard with ``-1`` fill, so
    the host filter ``idx >= 0`` restores the exact scan order.  The
    overflow flag (any shard held more than ``k_cap`` selected rows)
    routes the caller back to the on-device packed mask — never a
    truncated result.  Keyed only on (n_pad, k_cap): every selection
    plan shares these kernels.
    """
    S = 1 if mesh is None else num_shards(mesh)
    n_local = n_pad // S
    idt = jnp.int32 if n_pad <= np.iinfo(np.int32).max else jnp.int64

    def local_fn(mask):
        cnt = jnp.sum(mask, dtype=jnp.int64)
        idx = jnp.nonzero(mask, size=k_cap, fill_value=-1)[0].astype(idt)
        base0 = idt(0) if mesh is None else \
            (_shard_index(mesh) * n_local).astype(idt)
        gidx = jnp.where(idx >= 0, idx + base0, idt(-1))
        ovf = (cnt > k_cap).astype(jnp.int64)
        if mesh is not None:
            ovf = lax.psum(ovf, ROW_AXES)
        return gidx, ovf

    if mesh is None:
        return jax.jit(local_fn)
    return jax.jit(_shard_map(
        local_fn, mesh=mesh,
        in_specs=(P(ROW_AXES),),
        out_specs=(P(ROW_AXES), P())))


def build_compact_kernel(n_pad: int, k_cap: int, null_flags):
    """Single-device column compaction: gather every projected feed
    plane at the selected indices so the host gather is skipped — D2H
    is ``k_cap`` rows of narrow device-dtype columns, nothing else.
    Slots past the true count hold garbage (index 0 gather); the caller
    slices ``[:k]`` with the count that rides along."""
    def fn(mask, *flat):
        idx = jnp.nonzero(mask, size=k_cap, fill_value=-1)[0]
        safe = jnp.where(idx >= 0, idx, 0)
        outs = []
        fi = 0
        for has_nulls in null_flags:
            outs.append(jnp.take(flat[fi], safe))
            fi += 1
            if has_nulls:
                outs.append(jnp.take(flat[fi], safe))
                fi += 1
        ovf = (jnp.sum(mask, dtype=jnp.int64) > k_cap).astype(jnp.int64)
        return tuple(outs), ovf

    return jax.jit(fn)


def index_capacity(k_hint: float, n_local: int) -> int:
    """Pow2 index/compact capacity bucket for an expected k.  Predicted
    hints get ~1.5× headroom (an undersized capacity costs an overflow
    fallback to the mask route, never a wrong answer); capacities are
    clamped to the per-shard row count."""
    need = max(64, int(math.ceil(k_hint)))
    return min(_next_pow2(need), max(64, _next_pow2(n_local)))
