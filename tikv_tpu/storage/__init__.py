"""Storage — the transactional KV facade.

Reference: src/storage/mod.rs:188 ``Storage<E, L, F>``: transactional
reads (get :597 / batch_get :1166 / scan :1360), txn command scheduling
(sched_txn_command :1702), and the raw KV API (:1860-2915).  Reads take
an engine snapshot and resolve Percolator state through MvccReader; writes
go through the latch-serialized TxnScheduler.

API versions (components/api_version/src/lib.rs ApiV1/ApiV1Ttl/ApiV2):
- v1: raw keys are plain (``r`` prefix), last-write-wins, no TTL.
- v2: raw keys are memcomparable-encoded with a causal-ts version suffix
  (same ``append_ts`` layout as txn MVCC keys) so raw writes are
  MVCC-versioned — the property CDC-for-RawKV depends on — and values
  carry a flags byte with optional TTL expiry and tombstones
  (api_version/src/api_v2.rs RawValue encoding).  Write timestamps come
  from a ``causal_ts`` provider (tikv_tpu/causal_ts.py).
"""

from __future__ import annotations

import struct
import time
from typing import Optional, Sequence

from ..kv.engine import Engine, LocalEngine, SnapContext, WriteData
from .mvcc.reader import MvccReader
from .txn.commands import Command
from .txn.scheduler import TxnScheduler
from ..engine.traits import CF_DEFAULT

RAW_PREFIX = b"r"       # raw and txn keyspaces must not overlap (ApiV2
                        # keyspace prefixes, api_version/src/keyspace.rs)

# ApiV2 raw value flags byte
_V2_TOMBSTONE = 0x01
_V2_HAS_TTL = 0x02


class _CounterTs:
    """Process-local fallback causal-ts source (tests / single node).
    Seeded above any ts already persisted in the raw keyspace, so a
    restart over a durable engine cannot hand out timestamps below
    existing versions (which !ts ordering would hide forever)."""

    def __init__(self, start: int = 0):
        self._t = start

    def get_ts(self) -> int:
        self._t += 1
        return self._t

    def flush(self) -> None:
        pass


class Storage:
    def __init__(self, engine: Optional[Engine] = None,
                 lock_manager=None, api_version: int = 1,
                 causal_ts=None):
        from .concurrency_manager import ConcurrencyManager
        import threading
        assert api_version in (1, 2), api_version
        self._engine = engine if engine is not None else LocalEngine()
        self.api_version = api_version
        if causal_ts is not None:
            self.causal_ts = causal_ts
        else:
            seed = self._max_raw_ts() if api_version == 2 else 0
            self.causal_ts = _CounterTs(seed)
        # serializes raw_compare_and_swap (reference runs atomic raw
        # commands through scheduler latches, commands/atomic_store.rs;
        # one mutex is the single-node equivalent)
        self._raw_cas_lock = threading.Lock()
        self.concurrency_manager = ConcurrencyManager()
        self._sched = TxnScheduler(
            self._engine, concurrency_manager=self.concurrency_manager,
            lock_manager=lock_manager)

    def _max_raw_ts(self) -> int:
        """Largest version ts persisted in the raw keyspace (one startup
        scan; 0 when empty)."""
        from .txn_types import split_ts
        snap = self._engine.snapshot(SnapContext())
        it = snap.iterator_cf(CF_DEFAULT, RAW_PREFIX,
                              bytes([RAW_PREFIX[0] + 1]))
        best = 0
        ok = it.seek_to_first()
        while ok:
            key = it.key()
            if len(key) > 8:
                _, ts = split_ts(key)
                best = max(best, ts)
            ok = it.next()
        return best

    @property
    def engine(self) -> Engine:
        return self._engine

    @property
    def lock_manager(self):
        return self._sched.lock_manager

    # -- transactional reads (mod.rs:597,1166,1360) --
    #
    # every read bumps the concurrency manager's max_ts BEFORE checking
    # locks, then checks the in-memory table — the two halves of the
    # async-commit read protocol (mod.rs:626 + concurrency_manager)

    def get(self, key: bytes, read_ts: int,
            bypass_locks=(), replica_read: bool = False,
            stale_read: bool = False) -> Optional[bytes]:
        from .txn_types import encode_key
        cm = self.concurrency_manager
        cm.update_max_ts(read_ts)
        cm.read_key_check(key, read_ts, bypass_locks)
        reader = MvccReader(self._engine.snapshot(
            SnapContext(read_ts=read_ts, key_hint=encode_key(key),
                        replica_read=replica_read,
                        stale_read=stale_read)))
        return reader.get(key, read_ts, bypass_locks)

    def batch_get(self, keys: Sequence[bytes], read_ts: int,
                  bypass_locks=()) -> list:
        from .txn_types import encode_key
        cm = self.concurrency_manager
        cm.update_max_ts(read_ts)
        out = []
        for k in keys:
            cm.read_key_check(k, read_ts, bypass_locks)
            reader = MvccReader(self._engine.snapshot(
                SnapContext(read_ts=read_ts, key_hint=encode_key(k))))
            out.append((k, reader.get(k, read_ts, bypass_locks)))
        return out

    def scan(self, start: Optional[bytes], end: Optional[bytes], limit: int,
             read_ts: int, desc: bool = False, bypass_locks=()) -> list:
        from .txn_types import encode_key
        cm = self.concurrency_manager
        cm.update_max_ts(read_ts)
        cm.read_range_check(start, end, read_ts, bypass_locks)
        hint = encode_key(start) if start else b""
        reader = MvccReader(self._engine.snapshot(
            SnapContext(read_ts=read_ts, key_hint=hint)))
        return reader.scan(start, end, limit, read_ts, desc, bypass_locks)

    # -- txn writes (mod.rs:1702) --

    def sched_txn_command(self, cmd: Command):
        return self._sched.run(cmd)

    # -- raw KV (mod.rs:1860-2915; raw/ module) --

    def _raw_key(self, key: bytes) -> bytes:
        if self.api_version == 2:
            from ..codec.number import encode_bytes_memcomparable
            return RAW_PREFIX + encode_bytes_memcomparable(key)
        return RAW_PREFIX + key

    @staticmethod
    def _v2_value(value: bytes, ttl: Optional[int]) -> bytes:
        if ttl is None:
            return bytes([0]) + value
        expire = int(time.time()) + ttl
        return bytes([_V2_HAS_TTL]) + struct.pack(">Q", expire) + value

    @staticmethod
    def _v2_decode(raw: bytes):
        """→ (value | None, expire_ts | None); None value = dead
        (tombstone or expired)."""
        flags = raw[0]
        if flags & _V2_TOMBSTONE:
            return None, None
        if flags & _V2_HAS_TTL:
            (expire,) = struct.unpack_from(">Q", raw, 1)
            if expire <= int(time.time()):
                return None, expire
            return raw[9:], expire
        return raw[1:], None

    def raw_put(self, key: bytes, value: bytes,
                ttl: Optional[int] = None) -> None:
        self.raw_batch_put([(key, value)], ttl=ttl)

    def raw_batch_put(self, pairs: Sequence[tuple],
                      ttl: Optional[int] = None) -> None:
        if ttl is not None and self.api_version != 2:
            # reference: ApiV1 returns TtlNotEnabled rather than
            # silently storing a key that will never expire
            raise ValueError("TTL requires api_version=2")
        if self.api_version == 2:
            from .txn_types import append_ts
            mods = []
            for k, v in pairs:
                ts = self.causal_ts.get_ts()
                mods.append(("put", CF_DEFAULT,
                             append_ts(self._raw_key(k), ts),
                             self._v2_value(v, ttl)))
        else:
            mods = [("put", CF_DEFAULT, self._raw_key(k), v)
                    for k, v in pairs]
        self._engine.write(SnapContext(), WriteData(mods))

    def _v2_newest(self, snap, enc: bytes):
        """Newest (value, expire) of one ENCODED key, or (None, None);
        smallest ts suffix sorts first — txn_types.append_ts layout."""
        it = snap.iterator_cf(CF_DEFAULT, enc, enc + b"\xff" * 9)
        if not it.seek_to_first():
            return None, None
        return self._v2_decode(it.value())

    def _v2_latest(self, snap, key: bytes):
        return self._v2_newest(snap, self._raw_key(key))[0]

    def raw_get(self, key: bytes) -> Optional[bytes]:
        snap = self._engine.snapshot(SnapContext())
        if self.api_version == 2:
            return self._v2_latest(snap, key)
        return snap.get_value_cf(CF_DEFAULT, self._raw_key(key))

    def raw_get_key_ttl(self, key: bytes) -> Optional[int]:
        """Remaining TTL seconds: None = key absent; 0 = no TTL set
        (raw_get_key_ttl in mod.rs — ApiV1Ttl/ApiV2 only)."""
        if self.api_version != 2:
            raise ValueError("TTL requires api_version=2")
        snap = self._engine.snapshot(SnapContext())
        value, expire = self._v2_newest(snap, self._raw_key(key))
        if value is None:
            return None
        if expire is None:
            return 0
        return max(0, expire - int(time.time()))

    def raw_compare_and_swap(self, key: bytes, previous: Optional[bytes],
                             value: bytes,
                             ttl: Optional[int] = None) -> tuple:
        """→ (succeeded, actual_previous).  Reference:
        RawCompareAndSwap command (storage/txn/commands/atomic_store.rs)
        serialized through scheduler latches; here one mutex serializes
        all CAS ops (single node — contention is per-facade)."""
        with self._raw_cas_lock:
            cur = self.raw_get(key)
            if cur != previous:
                return False, cur
            self.raw_put(key, value, ttl=ttl)
            return True, cur

    def raw_batch_get(self, keys: Sequence[bytes]) -> list:
        snap = self._engine.snapshot(SnapContext())
        if self.api_version == 2:
            return [(k, self._v2_latest(snap, k)) for k in keys]
        return [(k, snap.get_value_cf(CF_DEFAULT, self._raw_key(k)))
                for k in keys]

    def raw_delete(self, key: bytes) -> None:
        if self.api_version == 2:
            # tombstone version — deletes must be MVCC events too (CDC
            # for RawKV observes them like any other write)
            from .txn_types import append_ts
            ts = self.causal_ts.get_ts()
            self._engine.write(SnapContext(), WriteData(
                [("put", CF_DEFAULT, append_ts(self._raw_key(key), ts),
                  bytes([_V2_TOMBSTONE]))]))
            return
        self._engine.write(SnapContext(), WriteData(
            [("del", CF_DEFAULT, self._raw_key(key), None)]))

    def raw_delete_range(self, start: bytes, end: bytes) -> None:
        """Physically removes every version in range (unsafe destroy
        semantics — mod.rs raw_delete_range)."""
        snap = self._engine.snapshot(SnapContext())
        it = snap.iterator_cf(CF_DEFAULT, self._raw_key(start),
                              self._raw_key(end))
        mods = []
        ok = it.seek_to_first()
        while ok:
            mods.append(("del", CF_DEFAULT, it.key(), None))
            ok = it.next()
        if mods:
            self._engine.write(SnapContext(), WriteData(mods))

    def raw_scan(self, start: bytes, end: Optional[bytes], limit: int,
                 desc: bool = False) -> list:
        snap = self._engine.snapshot(SnapContext())
        # end=None → everything in the raw keyspace: bound by the next
        # one-byte prefix (raw keys all start with RAW_PREFIX)
        upper = self._raw_key(end) if end is not None else \
            bytes([RAW_PREFIX[0] + 1])
        it = snap.iterator_cf(CF_DEFAULT, self._raw_key(start), upper)
        if self.api_version != 2:
            out = []
            ok = it.seek_to_last() if desc else it.seek_to_first()
            while ok and len(out) < limit:
                out.append((it.key()[len(RAW_PREFIX):], it.value()))
                ok = it.prev() if desc else it.next()
            return out
        # v2: newest live version per user key.  Ascending, the first
        # version seen for a key is the newest (ts suffix sorts newest
        # first); descending, the LAST version seen is — so collect and
        # resolve per key, bounded by ``limit`` live keys.
        from ..codec.number import decode_bytes_memcomparable
        from .txn_types import split_ts
        out = []
        if not desc:
            # ascending: the FIRST version seen for each key is its
            # newest (ts suffix sorts newest first)
            prev_enc = None
            ok = it.seek_to_first()
            while ok and len(out) < limit:
                enc, _ts = split_ts(it.key())
                if enc != prev_enc:
                    prev_enc = enc
                    value = self._v2_decode(it.value())[0]
                    if value is not None:
                        user, _ = decode_bytes_memcomparable(
                            enc, len(RAW_PREFIX))
                        out.append((user, value))
                ok = it.next()
            return out
        # descending: versions arrive oldest→newest within each key, so
        # the LAST version seen before the key changes is the newest —
        # emit at each key boundary from the one ongoing iterator (no
        # per-key point seeks)
        cur_enc = None
        cur_raw = None

        def emit():
            if cur_enc is None:
                return
            value = self._v2_decode(cur_raw)[0]
            if value is not None:
                user, _ = decode_bytes_memcomparable(cur_enc,
                                                     len(RAW_PREFIX))
                out.append((user, value))

        ok = it.seek_to_last()
        while ok and len(out) < limit:
            enc, _ts = split_ts(it.key())
            if enc != cur_enc:
                emit()
                if len(out) >= limit:
                    break
                cur_enc = enc
            cur_raw = it.value()
            ok = it.prev()
        if len(out) < limit:
            emit()
        return out

