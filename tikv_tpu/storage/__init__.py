"""Storage — the transactional KV facade.

Reference: src/storage/mod.rs:188 ``Storage<E, L, F>``: transactional
reads (get :597 / batch_get :1166 / scan :1360), txn command scheduling
(sched_txn_command :1702), and the raw KV API (:1860-2915).  Reads take
an engine snapshot and resolve Percolator state through MvccReader; writes
go through the latch-serialized TxnScheduler.
"""

from __future__ import annotations

from typing import Optional, Sequence

from ..kv.engine import Engine, LocalEngine, SnapContext, WriteData
from .mvcc.reader import MvccReader
from .txn.commands import Command
from .txn.scheduler import TxnScheduler
from ..engine.traits import CF_DEFAULT

RAW_PREFIX = b"r"       # raw and txn keyspaces must not overlap (ApiV2
                        # keyspace prefixes, api_version/src/keyspace.rs)


class Storage:
    def __init__(self, engine: Optional[Engine] = None,
                 lock_manager=None):
        from .concurrency_manager import ConcurrencyManager
        self._engine = engine if engine is not None else LocalEngine()
        self.concurrency_manager = ConcurrencyManager()
        self._sched = TxnScheduler(
            self._engine, concurrency_manager=self.concurrency_manager,
            lock_manager=lock_manager)

    @property
    def engine(self) -> Engine:
        return self._engine

    @property
    def lock_manager(self):
        return self._sched.lock_manager

    # -- transactional reads (mod.rs:597,1166,1360) --
    #
    # every read bumps the concurrency manager's max_ts BEFORE checking
    # locks, then checks the in-memory table — the two halves of the
    # async-commit read protocol (mod.rs:626 + concurrency_manager)

    def get(self, key: bytes, read_ts: int,
            bypass_locks=(), replica_read: bool = False) -> Optional[bytes]:
        from .txn_types import encode_key
        cm = self.concurrency_manager
        cm.update_max_ts(read_ts)
        cm.read_key_check(key, read_ts, bypass_locks)
        reader = MvccReader(self._engine.snapshot(
            SnapContext(read_ts=read_ts, key_hint=encode_key(key),
                        replica_read=replica_read)))
        return reader.get(key, read_ts, bypass_locks)

    def batch_get(self, keys: Sequence[bytes], read_ts: int,
                  bypass_locks=()) -> list:
        from .txn_types import encode_key
        cm = self.concurrency_manager
        cm.update_max_ts(read_ts)
        out = []
        for k in keys:
            cm.read_key_check(k, read_ts, bypass_locks)
            reader = MvccReader(self._engine.snapshot(
                SnapContext(read_ts=read_ts, key_hint=encode_key(k))))
            out.append((k, reader.get(k, read_ts, bypass_locks)))
        return out

    def scan(self, start: Optional[bytes], end: Optional[bytes], limit: int,
             read_ts: int, desc: bool = False, bypass_locks=()) -> list:
        from .txn_types import encode_key
        cm = self.concurrency_manager
        cm.update_max_ts(read_ts)
        cm.read_range_check(start, end, read_ts, bypass_locks)
        hint = encode_key(start) if start else b""
        reader = MvccReader(self._engine.snapshot(
            SnapContext(read_ts=read_ts, key_hint=hint)))
        return reader.scan(start, end, limit, read_ts, desc, bypass_locks)

    # -- txn writes (mod.rs:1702) --

    def sched_txn_command(self, cmd: Command):
        return self._sched.run(cmd)

    # -- raw KV (mod.rs:1860-2915; ApiV1 semantics, raw/ module) --

    def _raw_key(self, key: bytes) -> bytes:
        return RAW_PREFIX + key

    def raw_put(self, key: bytes, value: bytes) -> None:
        self._engine.write(SnapContext(), WriteData(
            [("put", CF_DEFAULT, self._raw_key(key), value)]))

    def raw_batch_put(self, pairs: Sequence[tuple]) -> None:
        self._engine.write(SnapContext(), WriteData(
            [("put", CF_DEFAULT, self._raw_key(k), v) for k, v in pairs]))

    def raw_get(self, key: bytes) -> Optional[bytes]:
        snap = self._engine.snapshot(SnapContext())
        return snap.get_value_cf(CF_DEFAULT, self._raw_key(key))

    def raw_batch_get(self, keys: Sequence[bytes]) -> list:
        snap = self._engine.snapshot(SnapContext())
        return [(k, snap.get_value_cf(CF_DEFAULT, self._raw_key(k)))
                for k in keys]

    def raw_delete(self, key: bytes) -> None:
        self._engine.write(SnapContext(), WriteData(
            [("del", CF_DEFAULT, self._raw_key(key), None)]))

    def raw_delete_range(self, start: bytes, end: bytes) -> None:
        snap = self._engine.snapshot(SnapContext())
        it = snap.iterator_cf(CF_DEFAULT, self._raw_key(start),
                              self._raw_key(end))
        mods = []
        ok = it.seek_to_first()
        while ok:
            mods.append(("del", CF_DEFAULT, it.key(), None))
            ok = it.next()
        if mods:
            self._engine.write(SnapContext(), WriteData(mods))

    def raw_scan(self, start: bytes, end: Optional[bytes], limit: int,
                 desc: bool = False) -> list:
        snap = self._engine.snapshot(SnapContext())
        # end=None → everything in the raw keyspace: bound by the next
        # one-byte prefix (raw keys all start with RAW_PREFIX)
        upper = self._raw_key(end) if end is not None else \
            bytes([RAW_PREFIX[0] + 1])
        it = snap.iterator_cf(CF_DEFAULT, self._raw_key(start), upper)
        out = []
        ok = it.seek_to_last() if desc else it.seek_to_first()
        while ok and len(out) < limit:
            out.append((it.key()[len(RAW_PREFIX):], it.value()))
            ok = it.prev() if desc else it.next()
        return out
