"""Lock manager — pessimistic-lock waiters + deadlock detection.

Reference: src/server/lock_manager/ — ``WaiterManager`` parks
pessimistic-lock requests that hit a conflicting lock until the holder
releases (or the wait times out), and the ``DeadlockDetector`` keeps a
wait-for graph, reporting a cycle to the waiter that would close it
(deadlock.rs; the reference elects the region-1 leader as the detector
authority — the networked path proxies detect calls the same way).
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Optional


class Deadlock(Exception):
    """The requested wait edge closes a cycle (deadlock.rs)."""

    def __init__(self, waiter_ts: int, holder_ts: int, key: bytes,
                 wait_chain=()):
        super().__init__(
            f"deadlock: txn {waiter_ts} waiting for {holder_ts}")
        self.waiter_ts = waiter_ts
        self.holder_ts = holder_ts
        self.key = key
        self.wait_chain = tuple(wait_chain)


class DeadlockDetector:
    """Wait-for graph with cycle check on edge insertion.

    ``detect(waiter, holder)`` adds waiter→holder and returns the cycle
    path if that edge closes one (the edge is NOT kept in that case —
    the waiter will error out, not wait).
    """

    def __init__(self):
        self._edges: dict[int, set[int]] = {}
        self._mu = threading.Lock()

    def detect(self, waiter_ts: int, holder_ts: int):
        with self._mu:
            # DFS from holder: a path back to waiter means a cycle
            stack = [(holder_ts, (holder_ts,))]
            seen = set()
            while stack:
                cur, path = stack.pop()
                if cur == waiter_ts:
                    return path
                if cur in seen:
                    continue
                seen.add(cur)
                for nxt in self._edges.get(cur, ()):
                    stack.append((nxt, path + (nxt,)))
            self._edges.setdefault(waiter_ts, set()).add(holder_ts)
            return None

    def remove_edge(self, waiter_ts: int, holder_ts: int) -> None:
        with self._mu:
            s = self._edges.get(waiter_ts)
            if s is not None:
                s.discard(holder_ts)
                if not s:
                    del self._edges[waiter_ts]

    def clean_up(self, txn_ts: int) -> None:
        """Txn finished: drop its outgoing edges (incoming edges die
        when their waiters wake and re-detect)."""
        with self._mu:
            self._edges.pop(txn_ts, None)


class WaiterManager:
    """Per-key wait queues (waiter_manager.rs)."""

    def __init__(self):
        self._waiters: dict[bytes, list] = {}
        self._mu = threading.Lock()

    def wait_for(self, key: bytes, timeout_s: float) -> bool:
        """Park until the key's lock is released or timeout.
        Returns True if woken (retry makes sense)."""
        ev = threading.Event()
        with self._mu:
            self._waiters.setdefault(key, []).append(ev)
        woken = ev.wait(timeout_s)
        with self._mu:
            lst = self._waiters.get(key)
            if lst is not None:
                try:
                    lst.remove(ev)
                except ValueError:
                    pass
                if not lst:
                    del self._waiters[key]
        return woken

    def wake_up(self, keys) -> None:
        with self._mu:
            events = []
            for k in keys:
                events.extend(self._waiters.get(k, ()))
        for ev in events:
            ev.set()


class LockManager:
    """Facade the scheduler talks to.

    ``detector``: a DeadlockDetector, or any object with the same
    detect/clean_up surface — the networked node injects a proxy that
    forwards to the cluster's detector leader (lock_manager/client.rs).
    """

    def __init__(self, detector=None):
        self.detector = detector if detector is not None \
            else DeadlockDetector()
        self.waiters = WaiterManager()

    def wait_for(self, waiter_ts: int, key: bytes, holder_ts: int,
                 timeout_s: float) -> bool:
        cycle = self.detector.detect(waiter_ts, holder_ts)
        if cycle:
            raise Deadlock(waiter_ts, holder_ts, key, cycle)
        try:
            return self.waiters.wait_for(key, timeout_s)
        finally:
            self.detector.remove_edge(waiter_ts, holder_ts)

    def on_release(self, txn_ts: int, keys) -> None:
        self.detector.clean_up(txn_ts)
        self.waiters.wake_up(keys)
