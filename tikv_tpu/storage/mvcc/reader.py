"""MVCC readers: point get + range scan resolving lock/write/default CFs.

Reference: src/storage/mvcc/reader/point_getter.rs (PointGetter::get —
CF_LOCK check → CF_WRITE seek(key, read_ts) → CF_DEFAULT fetch),
reader.rs (MvccReader: load_lock, seek_write, get_txn_commit_record) and
reader/scanner/forward.rs / backward.rs (lock-aware version-resolving
range scans).
"""

from __future__ import annotations

from typing import Callable, Optional

from ...engine.traits import CF_DEFAULT, CF_LOCK, CF_WRITE, Snapshot
from ..txn_types import (
    Lock,
    LockType,
    TS_MAX,
    Write,
    WriteType,
    append_ts,
    encode_key,
    split_ts,
)
from .errors import KeyIsLocked

# seeking past every version of an encoded key: versions are key+8 bytes,
# and memcomparable keys are prefix-free, so key+9×0xff sorts after all of
# them and before the next distinct key
_PAST_VERSIONS = b"\xff" * 9


def check_lock_conflict(lock: Lock, key: bytes, read_ts: int,
                        bypass_locks=()) -> None:
    """SI visibility check.  Reference: lock.rs check_ts_conflict —
    LOCK/PESSIMISTIC locks never block reads; a PUT/DELETE lock blocks
    reads at ts >= lock.start_ts (TS_MAX reads block too, unless the
    reader resolves it)."""
    if lock.lock_type in (LockType.LOCK, LockType.PESSIMISTIC):
        return
    if lock.start_ts > read_ts:
        return
    if lock.start_ts in bypass_locks:
        return
    raise KeyIsLocked(key, lock)


class MvccReader:
    """Reads one snapshot's Percolator state."""

    def __init__(self, snapshot: Snapshot):
        self._snap = snapshot

    # -- locks --

    def load_lock(self, key: bytes) -> Optional[Lock]:
        raw = self._snap.get_value_cf(CF_LOCK, encode_key(key))
        return Lock.from_bytes(raw) if raw is not None else None

    def scan_locks(self, start: Optional[bytes], end: Optional[bytes],
                   filter_fn: Optional[Callable[[Lock], bool]] = None,
                   limit: int = 0) -> list[tuple[bytes, Lock]]:
        """Reference: reader.rs scan_locks."""
        lower = encode_key(start) if start else None
        upper = encode_key(end) if end else None
        it = self._snap.iterator_cf(CF_LOCK, lower, upper)
        out: list[tuple[bytes, Lock]] = []
        ok = it.seek_to_first()
        while ok:
            lock = Lock.from_bytes(it.value())
            if filter_fn is None or filter_fn(lock):
                from ..txn_types import decode_key
                out.append((decode_key(it.key()), lock))
                if limit and len(out) >= limit:
                    break
            ok = it.next()
        return out

    # -- write records --

    def seek_write(self, key: bytes, ts: int) -> Optional[tuple[int, Write]]:
        """Newest write with commit_ts <= ts.  Reference: reader.rs
        seek_write."""
        enc = encode_key(key)
        it = self._snap.iterator_cf(CF_WRITE, enc, enc + _PAST_VERSIONS)
        if not it.seek(append_ts(enc, ts)):
            return None
        k, commit_ts = split_ts(it.key())
        if k != enc:
            return None
        return commit_ts, Write.from_bytes(it.value())

    def get_txn_commit_record(self, key: bytes, start_ts: int):
        """Find how txn ``start_ts`` ended on ``key``.

        Reference: reader.rs get_txn_commit_record.  Returns one of
        ("committed", commit_ts, Write) | ("rolled_back", ts, Write) |
        ("none", None, None).  Commit_ts of a write >= its start_ts, so
        only versions with commit_ts >= start_ts need examining.
        """
        enc = encode_key(key)
        it = self._snap.iterator_cf(CF_WRITE, enc, enc + _PAST_VERSIONS)
        ok = it.seek(enc)       # newest first (higher ts sorts first)
        while ok:
            k, commit_ts = split_ts(it.key())
            if k != enc or commit_ts < start_ts:
                break
            w = Write.from_bytes(it.value())
            if w.start_ts == start_ts:
                if w.write_type is WriteType.ROLLBACK:
                    return ("rolled_back", commit_ts, w)
                return ("committed", commit_ts, w)
            if commit_ts == start_ts and w.has_overlapped_rollback:
                return ("rolled_back", commit_ts, w)
            ok = it.next()
        return ("none", None, None)

    # -- values --

    def load_data(self, key: bytes, write: Write) -> Optional[bytes]:
        """Materialize a PUT's value (write.rs: short value else default
        CF at (key, start_ts))."""
        if write.write_type is not WriteType.PUT:
            return None
        if write.short_value is not None:
            return write.short_value
        enc = append_ts(encode_key(key), write.start_ts)
        v = self._snap.get_value_cf(CF_DEFAULT, enc)
        assert v is not None, f"default CF missing for {key!r}@{write.start_ts}"
        return v

    # -- point get (the kv_get path, SURVEY.md §3.3) --

    def get(self, key: bytes, read_ts: int, bypass_locks=()) -> Optional[bytes]:
        lock = self.load_lock(key)
        if lock is not None:
            check_lock_conflict(lock, key, read_ts, bypass_locks)
        ts = read_ts
        while True:
            found = self.seek_write(key, ts)
            if found is None:
                return None
            commit_ts, write = found
            if write.write_type is WriteType.PUT:
                return self.load_data(key, write)
            if write.write_type is WriteType.DELETE:
                return None
            # LOCK / ROLLBACK: look at the next older version
            ts = commit_ts - 1
            if ts < 0:
                return None

    # -- range scan (feeds coprocessor snapshots + Storage::scan) --

    def scan(self, start: Optional[bytes], end: Optional[bytes],
             limit: int, read_ts: int, desc: bool = False,
             bypass_locks=(), ignore_locks: bool = False) -> list[tuple[bytes, bytes]]:
        """Resolve up to ``limit`` visible (user_key, value) pairs.

        Reference: reader/scanner/forward.rs (ForwardKvScanner) and
        backward.rs; SI isolation — a conflicting lock on any key reached
        before the limit is satisfied raises KeyIsLocked (including keys
        with no committed version yet).  ``ignore_locks`` reads only
        committed data, skipping conflict checks entirely — the CDC
        initializer's mode (its resolver tracks the pending locks, so
        resolved-ts stays below them and no downstream finalizes early).
        """
        from ..txn_types import decode_key
        lower = encode_key(start) if start else None
        upper = encode_key(end) if end else None

        # locks are sparse: collect them once, check as keys are passed
        locks: list[tuple[bytes, Lock]] = []
        lit = self._snap.iterator_cf(CF_LOCK, lower, upper)
        ok = lit.seek_to_first()
        while ok:
            locks.append((lit.key(), Lock.from_bytes(lit.value())))
            ok = lit.next()
        if desc:
            locks.reverse()
        lock_i = 0

        def check_locks_through(enc: Optional[bytes]):
            nonlocal lock_i
            if ignore_locks:
                return
            while lock_i < len(locks):
                lk_enc, lock = locks[lock_i]
                if enc is not None:
                    passed = (lk_enc >= enc) if desc else (lk_enc <= enc)
                    if not passed:
                        return
                check_lock_conflict(lock, decode_key(lk_enc), read_ts,
                                    bypass_locks)
                lock_i += 1

        out: list[tuple[bytes, bytes]] = []
        it = self._snap.iterator_cf(CF_WRITE, lower, upper)
        ok = it.seek_to_last() if desc else it.seek_to_first()
        while ok and len(out) < limit:
            enc, _ = split_ts(it.key())
            check_locks_through(enc)
            value = self._resolve(enc, read_ts)
            if value is not None:
                out.append((decode_key(enc), value))
            if desc:
                # versions of enc sort after enc itself; step before them
                ok = it.seek_for_prev(enc)
            else:
                ok = it.seek(enc + _PAST_VERSIONS)
        if len(out) < limit:
            check_locks_through(None)   # locks on keys with no data yet
        return out

    def _resolve(self, enc: bytes, read_ts: int) -> Optional[bytes]:
        """Visible value of one encoded user key at read_ts (no locks)."""
        sub = self._snap.iterator_cf(CF_WRITE, enc, enc + _PAST_VERSIONS)
        ok = sub.seek(append_ts(enc, read_ts))
        while ok:
            k, _commit_ts = split_ts(sub.key())
            if k != enc:
                return None
            w = Write.from_bytes(sub.value())
            if w.write_type is WriteType.PUT:
                from ..txn_types import decode_key
                return self.load_data(decode_key(enc), w)
            if w.write_type is WriteType.DELETE:
                return None
            ok = sub.next()
        return None
