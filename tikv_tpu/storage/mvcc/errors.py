"""MVCC error taxonomy.

Reference: src/storage/mvcc/mod.rs ErrorInner variants (KeyIsLocked,
WriteConflict, TxnLockNotFound, Committed, AlreadyExist,
PessimisticLockRolledBack) — stable error identities the txn scheduler
and clients dispatch on.
"""

from __future__ import annotations


class MvccError(Exception):
    pass


class KeyIsLocked(MvccError):
    def __init__(self, key: bytes, lock):
        super().__init__(f"key {key!r} is locked by txn {lock.start_ts}")
        self.key = key
        self.lock = lock


class WriteConflict(MvccError):
    """reason: "optimistic" | "self_rolled_back" | "pessimistic"
    (reference: mvcc/mod.rs WriteConflictReason)."""

    def __init__(self, key: bytes, start_ts: int, conflict_start_ts: int,
                 conflict_commit_ts: int, reason: str = "optimistic"):
        super().__init__(
            f"write conflict on {key!r}: txn {start_ts} vs committed "
            f"[{conflict_start_ts}, {conflict_commit_ts}] ({reason})")
        self.key = key
        self.start_ts = start_ts
        self.conflict_start_ts = conflict_start_ts
        self.conflict_commit_ts = conflict_commit_ts
        self.reason = reason


class TxnLockNotFound(MvccError):
    def __init__(self, key: bytes, start_ts: int):
        super().__init__(f"lock of txn {start_ts} not found on {key!r}")
        self.key = key
        self.start_ts = start_ts


class Committed(MvccError):
    def __init__(self, key: bytes, start_ts: int, commit_ts: int):
        super().__init__(f"txn {start_ts} already committed @{commit_ts}")
        self.key = key
        self.start_ts = start_ts
        self.commit_ts = commit_ts


class AlreadyExist(MvccError):
    def __init__(self, key: bytes):
        super().__init__(f"key {key!r} already exists")
        self.key = key


class PessimisticLockRolledBack(MvccError):
    def __init__(self, key: bytes, start_ts: int):
        super().__init__(
            f"pessimistic lock of txn {start_ts} on {key!r} rolled back")
        self.key = key
        self.start_ts = start_ts
