"""MVCC cross-CF consistency scan.

Reference: SURVEY.md §5.2 — the reference enforces these invariants with
its scan-based consistency checker (worker/consistency_check.rs Mvcc
observer) and debug-service `bad-regions`/mvcc checks (src/server/debug.rs
MvccChecker): the Percolator record families in CF_LOCK / CF_WRITE /
CF_DEFAULT must cross-reference exactly.

Invariants checked over a key range:
1. every committed PUT without an inline short value has its payload row
   in CF_DEFAULT at (key, start_ts);
2. every CF_DEFAULT row is referenced by a committed write or by the
   key's current lock (no orphan payloads);
3. commit_ts > start_ts for every committed write;
4. a current lock's start_ts is above every committed commit_ts of that
   key (a lock standing below a committed version could never commit
   without violating snapshot isolation);
5. ROLLBACK/LOCK writes carry no payload.
"""

from __future__ import annotations

from typing import Optional

from ...engine.traits import CF_DEFAULT, CF_LOCK, CF_WRITE
from ..txn_types import Lock, Write, WriteType, split_ts


class MvccInconsistency(Exception):
    def __init__(self, problems: list):
        super().__init__(f"{len(problems)} MVCC inconsistencies: "
                         + "; ".join(problems[:5]))
        self.problems = problems


def _range_iter(snap, cf: str, lower: bytes, upper: Optional[bytes]):
    it = snap.iterator_cf(cf, lower, upper)
    ok = it.seek_to_first()
    while ok:
        yield it.key(), it.value()
        ok = it.next()


def check_mvcc_consistency(snap, lower: bytes = b"x",
                           upper: Optional[bytes] = None,
                           raise_on_problem: bool = False) -> list:
    """Scan [lower, upper) of the txn keyspace on an engine snapshot →
    list of problem strings (empty = consistent)."""
    if upper is None:
        upper = bytes([lower[0] + 1])
    problems: list[str] = []

    # CF_DEFAULT payload index: encoded_key -> {start_ts}
    defaults: dict = {}
    for k, _v in _range_iter(snap, CF_DEFAULT, lower, upper):
        if len(k) <= 8:
            problems.append(f"default key too short: {k!r}")
            continue
        enc, ts = split_ts(k)
        defaults.setdefault(enc, set()).add(ts)

    locks: dict = {}
    for k, v in _range_iter(snap, CF_LOCK, lower, upper):
        try:
            locks[k] = Lock.from_bytes(v)
        except Exception as e:   # noqa: BLE001 — corrupt record IS a finding
            problems.append(f"undecodable lock at {k!r}: {e}")

    referenced: dict = {}
    max_commit: dict = {}
    for k, v in _range_iter(snap, CF_WRITE, lower, upper):
        if len(k) <= 8:
            problems.append(f"write key too short: {k!r}")
            continue
        enc, commit_ts = split_ts(k)
        try:
            w = Write.from_bytes(v)
        except Exception as e:   # noqa: BLE001
            problems.append(f"undecodable write at {k!r}: {e}")
            continue
        if w.write_type in (WriteType.PUT, WriteType.DELETE):
            if commit_ts <= w.start_ts:
                problems.append(
                    f"commit_ts {commit_ts} <= start_ts {w.start_ts} "
                    f"on {enc!r}")
            max_commit[enc] = max(max_commit.get(enc, 0), commit_ts)
        if w.write_type is WriteType.PUT:
            if w.short_value is None:
                if w.start_ts not in defaults.get(enc, ()):
                    problems.append(
                        f"PUT {enc!r}@{commit_ts} missing default row "
                        f"at start_ts {w.start_ts}")
                else:
                    referenced.setdefault(enc, set()).add(w.start_ts)
        elif w.write_type in (WriteType.ROLLBACK, WriteType.LOCK):
            if w.short_value:
                problems.append(
                    f"{w.write_type.name} write with payload on {enc!r}")

    for enc, lock in locks.items():
        if lock.start_ts <= max_commit.get(enc, -1):
            problems.append(
                f"lock at start_ts {lock.start_ts} below committed "
                f"version {max_commit[enc]} on {enc!r}")
        if lock.short_value is None:
            # big-value prewrite: payload must already sit in default
            if lock.lock_type.name in ("PUT",) and \
                    lock.start_ts not in defaults.get(enc, ()):
                problems.append(
                    f"PUT lock on {enc!r} missing default row at "
                    f"start_ts {lock.start_ts}")
        referenced.setdefault(enc, set()).add(lock.start_ts)

    for enc, tss in defaults.items():
        orphan = tss - referenced.get(enc, set())
        for ts in sorted(orphan):
            problems.append(f"orphan default row {enc!r}@{ts}")

    if problems and raise_on_problem:
        raise MvccInconsistency(problems)
    return problems
