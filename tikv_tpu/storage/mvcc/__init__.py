"""MVCC layer — Percolator readers + transaction write buffer.

Reference: src/storage/mvcc/ (MvccTxn txn.rs:60, PointGetter
reader/point_getter.rs, MvccReader, forward/backward Scanner
reader/scanner/).
"""

from .errors import (
    AlreadyExist,
    Committed,
    KeyIsLocked,
    MvccError,
    PessimisticLockRolledBack,
    TxnLockNotFound,
    WriteConflict,
)
from .reader import MvccReader
from .txn import MvccTxn

__all__ = [
    "MvccReader", "MvccTxn", "MvccError", "KeyIsLocked", "WriteConflict",
    "TxnLockNotFound", "Committed", "AlreadyExist",
    "PessimisticLockRolledBack",
]
