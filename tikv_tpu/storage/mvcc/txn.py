"""MvccTxn — buffered modifications of one command execution.

Reference: src/storage/mvcc/txn.rs:60 (MvccTxn: modifies vec, lock
put/unlock, put_write/delete_write, put_value/delete_value), flushed into
one engine WriteBatch when the command succeeds (atomicity unit).
"""

from __future__ import annotations

from typing import Optional

from ...engine.traits import CF_DEFAULT, CF_LOCK, CF_WRITE
from ..txn_types import Lock, Write, append_ts, encode_key


class MvccTxn:
    def __init__(self, start_ts: int):
        self.start_ts = start_ts
        self.modifies: list[tuple] = []     # (op, cf, key, value?)
        # user keys whose engine lock this command removes — the waiter
        # manager wakes parked pessimistic lockers on exactly these
        self.released_keys: list[bytes] = []

    # -- locks --

    def put_lock(self, key: bytes, lock: Lock) -> None:
        self.modifies.append(("put", CF_LOCK, encode_key(key),
                              lock.to_bytes()))

    def unlock_key(self, key: bytes) -> None:
        self.modifies.append(("del", CF_LOCK, encode_key(key), None))
        self.released_keys.append(key)

    # -- write records --

    def put_write(self, key: bytes, commit_ts: int, write: Write) -> None:
        self.modifies.append(("put", CF_WRITE,
                              append_ts(encode_key(key), commit_ts),
                              write.to_bytes()))

    def delete_write(self, key: bytes, commit_ts: int) -> None:
        self.modifies.append(("del", CF_WRITE,
                              append_ts(encode_key(key), commit_ts), None))

    # -- values --

    def put_value(self, key: bytes, start_ts: int, value: bytes) -> None:
        self.modifies.append(("put", CF_DEFAULT,
                              append_ts(encode_key(key), start_ts), value))

    def delete_value(self, key: bytes, start_ts: int) -> None:
        self.modifies.append(("del", CF_DEFAULT,
                              append_ts(encode_key(key), start_ts), None))

    # -- flush (the scheduler wraps ``modifies`` into kv.WriteData) --

    def is_empty(self) -> bool:
        return not self.modifies
