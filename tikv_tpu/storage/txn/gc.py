"""MVCC garbage collection.

Reference: src/storage/txn/actions/gc.rs (legacy per-key GC) and
src/server/gc_worker/compaction_filter.rs (the production path folds the
same rule into RocksDB compaction).  Rule per key, given safe_point:
keep every version with commit_ts > safe_point; of the versions with
commit_ts <= safe_point keep only the newest, and only if it is a PUT
(a DELETE at/below the safe point erases the key entirely); ROLLBACK/LOCK
records at/below the safe point always drop.
"""

from __future__ import annotations

from typing import Optional

from ...engine.traits import CF_WRITE
from ..mvcc.reader import MvccReader, _PAST_VERSIONS
from ..mvcc.txn import MvccTxn
from ..txn_types import Write, WriteType, decode_key, encode_key, split_ts


def gc_key(txn: MvccTxn, reader: MvccReader, key: bytes,
           safe_point: int) -> int:
    """GC one key; returns number of versions removed."""
    removed = 0
    found = reader.seek_write(key, safe_point)
    kept_newest = False
    while found is not None:
        commit_ts, write = found
        drop = True
        if not kept_newest:
            if write.write_type is WriteType.PUT:
                drop = False
            # DELETE/LOCK/ROLLBACK as the newest ≤ safe_point: droppable
            # (nothing below is visible anyway)
            if write.write_type in (WriteType.PUT, WriteType.DELETE):
                kept_newest = True
        if drop:
            txn.delete_write(key, commit_ts)
            if write.write_type is WriteType.PUT and \
                    write.short_value is None:
                txn.delete_value(key, write.start_ts)
            removed += 1
        found = reader.seek_write(key, commit_ts - 1) if commit_ts else None
    return removed


def gc_range(txn: MvccTxn, reader: MvccReader, start: Optional[bytes],
             end: Optional[bytes], safe_point: int) -> int:
    """GC every key with versions in [start, end)."""
    lower = encode_key(start) if start else None
    upper = encode_key(end) if end else None
    it = reader._snap.iterator_cf(CF_WRITE, lower, upper)
    removed = 0
    ok = it.seek_to_first()
    while ok:
        enc, _ = split_ts(it.key())
        removed += gc_key(txn, reader, decode_key(enc), safe_point)
        ok = it.seek(enc + _PAST_VERSIONS)
    return removed
