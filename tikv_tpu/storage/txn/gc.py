"""MVCC garbage collection.

Reference: src/storage/txn/actions/gc.rs (legacy per-key GC) and
src/server/gc_worker/compaction_filter.rs (the production path folds the
same rule into RocksDB compaction).  Rule per key, given safe_point:
keep every version with commit_ts > safe_point; of the versions with
commit_ts <= safe_point keep only the newest, and only if it is a PUT
(a DELETE at/below the safe point erases the key entirely); ROLLBACK/LOCK
records at/below the safe point always drop.
"""

from __future__ import annotations

from typing import Optional

from ...engine.traits import CF_WRITE
from ..mvcc.reader import MvccReader, _PAST_VERSIONS
from ..mvcc.txn import MvccTxn
from ..txn_types import (
    Write,
    WriteType,
    append_ts,
    decode_key,
    encode_key,
    split_ts,
)


def gc_key(txn: MvccTxn, reader: MvccReader, key: bytes,
           safe_point: int) -> int:
    """GC one key; returns number of versions removed."""
    removed = 0
    found = reader.seek_write(key, safe_point)
    kept_newest = False
    while found is not None:
        commit_ts, write = found
        drop = True
        if not kept_newest:
            if write.write_type is WriteType.PUT:
                drop = False
            # DELETE/LOCK/ROLLBACK as the newest ≤ safe_point: droppable
            # (nothing below is visible anyway)
            if write.write_type in (WriteType.PUT, WriteType.DELETE):
                kept_newest = True
        if drop:
            txn.delete_write(key, commit_ts)
            if write.write_type is WriteType.PUT and \
                    write.short_value is None:
                txn.delete_value(key, write.start_ts)
            removed += 1
        found = reader.seek_write(key, commit_ts - 1) if commit_ts else None
    return removed


def gc_range(txn: MvccTxn, reader: MvccReader, start: Optional[bytes],
             end: Optional[bytes], safe_point: int) -> int:
    """GC every key with versions in [start, end)."""
    lower = encode_key(start) if start else None
    upper = encode_key(end) if end else None
    it = reader._snap.iterator_cf(CF_WRITE, lower, upper)
    removed = 0
    ok = it.seek_to_first()
    while ok:
        enc, _ = split_ts(it.key())
        removed += gc_key(txn, reader, decode_key(enc), safe_point)
        ok = it.seek(enc + _PAST_VERSIONS)
    return removed


class MvccCompactionFilter:
    """GC folded into engine compaction — the production path
    (src/server/gc_worker/compaction_filter.rs): as the engine rewrites
    its base, write-CF versions at/below the safe point are dropped by
    the same per-key rule as gc_key, and the default-CF payload rows of
    dropped PUTs go with them.  No extra scan, no write amplification.

    Engine contract (DiskEngine ``compaction_filter=``): the engine
    calls ``filter_cf(cf, keys, vals) -> (keys, vals)`` for each CF
    during compaction, offering CF_WRITE before CF_DEFAULT (the write
    pass decides which default rows die).  Keys arrive as ENGINE keys
    (data prefix + encoded user key [+ ts]).
    """

    # process write before default: write decisions drive default drops
    CF_ORDER = ("write", "default")

    def __init__(self, safe_point_provider):
        self._safe_point = safe_point_provider
        self._drop_defaults: set = set()

    def filter_cf(self, cf: str, keys: list, vals: list):
        if cf == CF_WRITE:
            return self._filter_write(keys, vals)
        if cf == "default":
            if not self._drop_defaults:
                return keys, vals
            keep = [i for i, k in enumerate(keys)
                    if k not in self._drop_defaults]
            self._drop_defaults = set()
            return [keys[i] for i in keep], [vals[i] for i in keep]
        return keys, vals

    def _filter_write(self, keys: list, vals: list):
        safe = int(self._safe_point() or 0)
        if not safe:
            return keys, vals
        out_k: list = []
        out_v: list = []
        cur_enc = None
        kept_newest = False
        # engine keys sort newest-version-first within a user key
        for k, v in zip(keys, vals):
            if len(k) <= 9 or not k.startswith(b"z"):
                out_k.append(k)
                out_v.append(v)
                continue
            enc, commit_ts = split_ts(k[1:])
            if enc != cur_enc:
                cur_enc = enc
                kept_newest = False
            if commit_ts > safe:
                out_k.append(k)
                out_v.append(v)
                continue
            w = Write.from_bytes(v)
            drop = True
            if not kept_newest:
                if w.write_type is WriteType.PUT:
                    drop = False
                if w.write_type in (WriteType.PUT, WriteType.DELETE):
                    kept_newest = True
            if drop:
                if w.write_type is WriteType.PUT and \
                        w.short_value is None:
                    self._drop_defaults.add(
                        b"z" + append_ts(enc, w.start_ts))
                continue
            out_k.append(k)
            out_v.append(v)
        return out_k, out_v
