"""Transaction commands — one class per scheduler command.

Reference: src/storage/txn/commands/ (command pattern, one file per
command: prewrite.rs, commit.rs, rollback.rs, cleanup.rs,
check_txn_status.rs, resolve_lock.rs, acquire_pessimistic_lock.rs,
pessimistic_rollback.rs, txn_heart_beat.rs, resolve_lock_lite.rs).
Each command implements ``process_write(txn, reader) -> result`` over the
pure actions (actions.py); the scheduler owns latching + snapshot + flush.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional, Sequence

from ..mvcc.errors import KeyIsLocked
from ..mvcc.reader import MvccReader
from ..mvcc.txn import MvccTxn
from ..txn_types import Lock, LockType
from . import actions
from .actions import Mutation


class Command:
    # subclasses are dataclasses declaring start_ts; no default here (a
    # class-level default would leak into subclass dataclass fields)
    start_ts: int

    def write_keys(self) -> list[bytes]:
        """Keys to latch (latch.rs: commands declare their key set)."""
        raise NotImplementedError

    def process_write(self, txn: MvccTxn, reader: MvccReader):
        raise NotImplementedError


@dataclass
class Prewrite(Command):
    """commands/prewrite.rs (incl. the async-commit and 1PC modes).

    Async commit: min_commit_ts is finalized from the concurrency
    manager's max_ts (the scheduler injects ``_cm`` and publishes the
    memory locks around this command); the primary's lock carries the
    secondary keys.  1PC additionally skips the lock phase, committing
    at that same ts when the whole txn fits one region.
    """

    mutations: Sequence[Mutation]
    primary: bytes
    start_ts: int
    lock_ttl: int = 3000
    txn_size: int = 0
    min_commit_ts: int = 0
    # per-mutation: True if the key holds this txn's pessimistic lock
    is_pessimistic_lock: Sequence[bool] = ()
    use_async_commit: bool = False
    secondaries: Sequence[bytes] = ()
    try_one_pc: bool = False
    _cm: object = field(default=None, repr=False, compare=False)

    def write_keys(self):
        return [m.key for m in self.mutations]

    def process_write(self, txn, reader):
        flags = self.is_pessimistic_lock or [False] * len(self.mutations)
        assert len(flags) == len(self.mutations), \
            "is_pessimistic_lock must match mutations 1:1"
        final_min_commit = self.min_commit_ts
        one_pc_ts = 0
        if self.use_async_commit or self.try_one_pc:
            assert self._cm is not None, \
                "async commit requires the concurrency manager"
            final_min_commit = max(self._cm.max_ts + 1,
                                   self.start_ts + 1,
                                   self.min_commit_ts)
            if self.try_one_pc:
                one_pc_ts = final_min_commit
        for m, pess in zip(self.mutations, flags):
            actions.prewrite(
                txn, reader, m, self.primary, self.lock_ttl,
                self.txn_size, final_min_commit,
                is_pessimistic_lock=pess,
                use_async_commit=self.use_async_commit,
                secondaries=(tuple(self.secondaries)
                             if m.key == self.primary else ()),
                one_pc_commit_ts=one_pc_ts)
        return {"min_commit_ts": final_min_commit
                if (self.use_async_commit or self.try_one_pc)
                else self.min_commit_ts,
                "one_pc_commit_ts": one_pc_ts}


@dataclass
class Commit(Command):
    """commands/commit.rs"""

    keys: Sequence[bytes]
    start_ts: int
    commit_ts: int

    def write_keys(self):
        return list(self.keys)

    def process_write(self, txn, reader):
        for k in self.keys:
            actions.commit(txn, reader, k, self.commit_ts)
        return {"commit_ts": self.commit_ts}


@dataclass
class Rollback(Command):
    """commands/rollback.rs"""

    keys: Sequence[bytes]
    start_ts: int

    def write_keys(self):
        return list(self.keys)

    def process_write(self, txn, reader):
        for k in self.keys:
            actions.rollback(txn, reader, k)
        return {}


@dataclass
class Cleanup(Command):
    """commands/cleanup.rs — rollback a single (expired) lock."""

    key: bytes
    start_ts: int
    current_ts: int

    def write_keys(self):
        return [self.key]

    def process_write(self, txn, reader):
        actions.cleanup(txn, reader, self.key, self.current_ts)
        return {}


@dataclass
class CheckTxnStatus(Command):
    """commands/check_txn_status.rs"""

    primary: bytes
    lock_ts: int
    caller_start_ts: int
    current_ts: int

    @property
    def start_ts(self):
        return self.lock_ts

    def write_keys(self):
        return [self.primary]

    def process_write(self, txn, reader):
        status, ts = actions.check_txn_status(
            txn, reader, self.primary, self.current_ts,
            self.caller_start_ts)
        out = {"status": status, "ts": ts}
        if status == "locked":
            lock = reader.load_lock(self.primary)
            if lock is not None and lock.use_async_commit:
                # the caller resolves via CheckSecondaryLocks
                out["use_async_commit"] = True
                out["secondaries"] = list(lock.secondaries)
                out["min_commit_ts"] = lock.min_commit_ts
        return out


@dataclass
class CheckSecondaryLocks(Command):
    """commands/check_secondary_locks.rs — the async-commit resolution
    probe: for each secondary, report its lock (still pending) or its
    final state; keys with neither get a protective rollback so a late
    prewrite cannot resurrect the txn."""

    keys: Sequence[bytes]
    start_ts: int

    def write_keys(self):
        return list(self.keys)

    def process_write(self, txn, reader):
        min_commit_ts = 0
        for k in self.keys:
            lock = reader.load_lock(k)
            if lock is not None and lock.start_ts == self.start_ts:
                if lock.lock_type is LockType.PESSIMISTIC:
                    # an unprewritten pessimistic lock can't commit:
                    # drop it and mark rolled back (check_secondary_locks.rs)
                    txn.unlock_key(k)
                    actions._put_rollback(txn, reader, k)
                    return {"status": "rolled_back", "commit_ts": 0}
                min_commit_ts = max(min_commit_ts, lock.min_commit_ts)
                continue
            status, ts, _w = reader.get_txn_commit_record(k, self.start_ts)
            if status == "committed":
                return {"status": "committed", "commit_ts": ts}
            if status == "rolled_back":
                return {"status": "rolled_back", "commit_ts": 0}
            # no lock, no record: protective rollback
            actions._put_rollback(txn, reader, k)
            return {"status": "rolled_back", "commit_ts": 0}
        return {"status": "locked", "commit_ts": 0,
                "min_commit_ts": min_commit_ts}


@dataclass
class ResolveLockLite(Command):
    """commands/resolve_lock_lite.rs — commit/rollback a known key set of
    one txn (commit_ts == 0 → rollback)."""

    start_ts: int
    commit_ts: int
    keys: Sequence[bytes] = ()

    def write_keys(self):
        return list(self.keys)

    def process_write(self, txn, reader):
        for k in self.keys:
            if self.commit_ts:
                actions.commit(txn, reader, k, self.commit_ts)
            else:
                actions.rollback(txn, reader, k)
        return {}


@dataclass
class ResolveLock(Command):
    """commands/resolve_lock.rs — scan this txn's locks in range and
    commit/rollback them (the resolver's bulk path)."""

    start_ts: int
    commit_ts: int
    start_key: Optional[bytes] = None
    end_key: Optional[bytes] = None
    scan_limit: int = 256

    _found: list = field(default_factory=list, repr=False)

    def write_keys(self):
        return [k for k, _ in self._found]

    def prepare(self, reader: MvccReader):
        """Scan phase (runs before latching; reference splits the same
        way: read command → write command with the found locks)."""
        self._found = reader.scan_locks(
            self.start_key, self.end_key,
            lambda lock: lock.start_ts == self.start_ts, self.scan_limit)

    def process_write(self, txn, reader):
        for k, _lock in self._found:
            if self.commit_ts:
                actions.commit(txn, reader, k, self.commit_ts)
            else:
                actions.rollback(txn, reader, k)
        return {"resolved": len(self._found),
                "has_more": len(self._found) >= self.scan_limit}


@dataclass
class AcquirePessimisticLock(Command):
    """commands/acquire_pessimistic_lock.rs"""

    keys: Sequence[bytes]
    primary: bytes
    start_ts: int
    for_update_ts: int
    lock_ttl: int = 3000
    return_values: bool = False
    # > 0: on conflict, park in the waiter manager (with deadlock
    # detection) instead of failing — lock_manager/waiter_manager.rs
    wait_timeout_s: float = 0.0

    def write_keys(self):
        return list(self.keys)

    def process_write(self, txn, reader):
        values = []
        for k in self.keys:
            v = actions.acquire_pessimistic_lock(
                txn, reader, k, self.primary, self.for_update_ts,
                self.lock_ttl)
            values.append(v)
        return {"values": values if self.return_values else None}


@dataclass
class PessimisticRollback(Command):
    """commands/pessimistic_rollback.rs — drop our pessimistic locks
    (no rollback record: the txn may still prewrite elsewhere)."""

    keys: Sequence[bytes]
    start_ts: int
    for_update_ts: int

    def write_keys(self):
        return list(self.keys)

    def process_write(self, txn, reader):
        for k in self.keys:
            lock = reader.load_lock(k)
            if lock is not None and lock.start_ts == self.start_ts and \
                    lock.lock_type is LockType.PESSIMISTIC and \
                    lock.for_update_ts <= self.for_update_ts:
                txn.unlock_key(k)
        return {}


@dataclass
class TxnHeartBeat(Command):
    """commands/txn_heart_beat.rs — extend the primary lock's TTL."""

    primary: bytes
    start_ts: int
    advise_ttl: int

    def write_keys(self):
        return [self.primary]

    def process_write(self, txn, reader):
        lock = reader.load_lock(self.primary)
        if lock is None or lock.start_ts != self.start_ts:
            from ..mvcc.errors import TxnLockNotFound
            raise TxnLockNotFound(self.primary, self.start_ts)
        if self.advise_ttl > lock.ttl:
            lock.ttl = self.advise_ttl
            txn.put_lock(self.primary, lock)
        return {"ttl": lock.ttl}
