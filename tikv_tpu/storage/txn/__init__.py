"""Transaction layer: Percolator actions, latches, command scheduler.

Reference: src/storage/txn/ (actions/, commands/, scheduler.rs, latch.rs).
"""

from .actions import (
    Mutation,
    acquire_pessimistic_lock,
    check_txn_status,
    cleanup,
    commit,
    prewrite,
    rollback,
)
from .latch import Latches
from .scheduler import TxnScheduler

__all__ = [
    "Mutation", "prewrite", "commit", "rollback", "cleanup",
    "check_txn_status", "acquire_pessimistic_lock", "Latches",
    "TxnScheduler",
]
