"""Key latches — per-key FIFO serialization of conflicting commands.

Reference: src/storage/txn/latch.rs — keys hash to slots; a command
acquires all its slots or queues behind the current holders; release
wakes the next waiter in FIFO order.  Lock-free in the reference; here a
condition variable guards the slot table (the scheduler pool is small).
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Iterable


class Latches:
    def __init__(self, size: int = 256):
        assert size and (size & (size - 1)) == 0, "size must be a power of 2"
        self._mask = size - 1
        self._slots: list[deque] = [deque() for _ in range(size)]
        self._cv = threading.Condition()
        self._next_cid = 0

    def gen_cid(self) -> int:
        with self._cv:
            self._next_cid += 1
            return self._next_cid

    def _slot_ids(self, keys: Iterable[bytes]) -> list[int]:
        return sorted({hash(k) & self._mask for k in keys})

    def acquire(self, cid: int, keys: Iterable[bytes]) -> list[int]:
        """Block until ``cid`` holds every slot for ``keys`` (FIFO per
        slot).  Returns the slot list for release()."""
        slots = self._slot_ids(keys)
        with self._cv:
            for s in slots:
                self._slots[s].append(cid)
            while not all(self._slots[s][0] == cid for s in slots):
                self._cv.wait()
        return slots

    def release(self, cid: int, slots: list[int]) -> None:
        with self._cv:
            for s in slots:
                assert self._slots[s][0] == cid, "released out of order"
                self._slots[s].popleft()
            self._cv.notify_all()
