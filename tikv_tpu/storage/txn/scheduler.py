"""Txn command scheduler — latch, snapshot, execute, flush.

Reference: src/storage/txn/scheduler.rs — ``TxnScheduler``: every write
command acquires latches on its keys (:396), takes an engine snapshot
(:1174), runs ``process_write`` (:1252) buffering into MvccTxn, flushes
through ``Engine::async_write``, then releases latches (:544) waking
queued commands.  The Python surface is synchronous per command but safe
for concurrent caller threads (the reference runs commands on a worker
pool; conflicting commands serialize on latches either way).
"""

from __future__ import annotations

from typing import Optional

from ...kv.engine import Engine, SnapContext, WriteData
from ..mvcc.reader import MvccReader
from ..mvcc.txn import MvccTxn
from .commands import Command, ResolveLock
from .latch import Latches


class TxnScheduler:
    def __init__(self, engine: Engine, latches: Optional[Latches] = None,
                 concurrency_manager=None, lock_manager=None):
        from ..concurrency_manager import ConcurrencyManager
        from ..lock_manager import LockManager
        self._engine = engine
        self._latches = latches if latches is not None else Latches()
        self.cm = concurrency_manager if concurrency_manager is not None \
            else ConcurrencyManager()
        self.lock_manager = lock_manager if lock_manager is not None \
            else LockManager()

    def run(self, cmd: Command, ctx: Optional[SnapContext] = None):
        import time as _time

        from ..mvcc.errors import KeyIsLocked
        from .commands import AcquirePessimisticLock
        wait_budget = getattr(cmd, "wait_timeout_s", 0.0)
        deadline = _time.monotonic() + wait_budget if wait_budget else None
        while True:
            try:
                return self._run_once(cmd, ctx)
            except KeyIsLocked as e:
                if not isinstance(cmd, AcquirePessimisticLock) or \
                        deadline is None:
                    raise
                # park OUTSIDE the latches (already released): waiting
                # while latched would deadlock against the holder's
                # commit (scheduler.rs hands conflicts to the waiter
                # manager the same way)
                remain = deadline - _time.monotonic()
                if remain <= 0:
                    raise
                woken = self.lock_manager.wait_for(
                    cmd.start_ts, e.key, e.lock.start_ts,
                    min(remain, 1.0))
                if not woken and _time.monotonic() >= deadline:
                    raise

    def _run_once(self, cmd: Command, ctx: Optional[SnapContext]):
        if ctx is None:
            from ..txn_types import encode_key
            keys = cmd.write_keys()
            ctx = SnapContext(key_hint=encode_key(keys[0]) if keys else b"")
        if isinstance(cmd, ResolveLock):
            # read phase before latching (resolve_lock.rs scan → write)
            cmd.prepare(MvccReader(self._engine.snapshot(ctx)))
        from ...utils.failpoint import fail_point
        from ...utils.metrics import SCHED_COMMANDS
        from .commands import Commit, Prewrite
        SCHED_COMMANDS.labels(type(cmd).__name__).inc()
        fail_point("txn::before_latch")
        cid = self._latches.gen_cid()
        slots = self._latches.acquire(cid, cmd.write_keys())
        fail_point("txn::after_latch")
        mem_keys = ()
        released: list = []
        try:
            fail_point("txn::before_process")
            if isinstance(cmd, Commit):
                # the commit boundary: a crash here leaves prewrite
                # locks for the resolver (the 2PC indeterminate window)
                fail_point("txn::before_commit")
            if isinstance(cmd, Prewrite) and \
                    (cmd.use_async_commit or cmd.try_one_pc):
                # async commit step (a): publish memory locks BEFORE
                # reading max_ts so no concurrent read can slip between
                # the min_commit_ts decision and the engine lock
                # (concurrency_manager/src/lib.rs).  The memory lock
                # carries the real TTL so a blocked reader backs off
                # instead of instantly resolving an "expired" lock.
                from ..txn_types import Lock, LockType
                cmd._cm = self.cm
                mem_keys = tuple(m.key for m in cmd.mutations)
                self.cm.lock_keys(
                    mem_keys,
                    [Lock(LockType.PUT, cmd.primary, cmd.start_ts,
                          ttl=cmd.lock_ttl) for _ in mem_keys])
            snapshot = self._engine.snapshot(ctx)
            reader = MvccReader(snapshot)
            txn = MvccTxn(cmd.start_ts)
            result = cmd.process_write(txn, reader)
            fail_point("txn::before_engine_write")
            if not txn.is_empty():
                self._engine.write(ctx, WriteData.from_txn(txn))
            fail_point("txn::after_engine_write")
            released = txn.released_keys
            return result
        finally:
            fail_point("txn::before_release_latch")
            if mem_keys:
                self.cm.unlock_keys(mem_keys)
            self._latches.release(cid, slots)
            if released:
                # AFTER latch release: any command that removed engine
                # locks (commit/rollback/resolve/1PC/ttl-expiry) wakes
                # parked pessimistic waiters; the detector clean_up may
                # be a remote RPC and must never run latched
                self.lock_manager.on_release(cmd.start_ts, released)
