"""Txn command scheduler — latch, snapshot, execute, flush.

Reference: src/storage/txn/scheduler.rs — ``TxnScheduler``: every write
command acquires latches on its keys (:396), takes an engine snapshot
(:1174), runs ``process_write`` (:1252) buffering into MvccTxn, flushes
through ``Engine::async_write``, then releases latches (:544) waking
queued commands.  The Python surface is synchronous per command but safe
for concurrent caller threads (the reference runs commands on a worker
pool; conflicting commands serialize on latches either way).
"""

from __future__ import annotations

from typing import Optional

from ...kv.engine import Engine, SnapContext, WriteData
from ..mvcc.reader import MvccReader
from ..mvcc.txn import MvccTxn
from .commands import Command, ResolveLock
from .latch import Latches


class TxnScheduler:
    def __init__(self, engine: Engine, latches: Optional[Latches] = None):
        self._engine = engine
        self._latches = latches if latches is not None else Latches()

    def run(self, cmd: Command, ctx: Optional[SnapContext] = None):
        if ctx is None:
            from ..txn_types import encode_key
            keys = cmd.write_keys()
            ctx = SnapContext(key_hint=encode_key(keys[0]) if keys else b"")
        if isinstance(cmd, ResolveLock):
            # read phase before latching (resolve_lock.rs scan → write)
            cmd.prepare(MvccReader(self._engine.snapshot(ctx)))
        from ...utils.failpoint import fail_point
        from ...utils.metrics import SCHED_COMMANDS
        SCHED_COMMANDS.labels(type(cmd).__name__).inc()
        cid = self._latches.gen_cid()
        slots = self._latches.acquire(cid, cmd.write_keys())
        try:
            fail_point("txn::before_process")
            snapshot = self._engine.snapshot(ctx)
            reader = MvccReader(snapshot)
            txn = MvccTxn(cmd.start_ts)
            result = cmd.process_write(txn, reader)
            fail_point("txn::before_engine_write")
            if not txn.is_empty():
                self._engine.write(ctx, WriteData.from_txn(txn))
            return result
        finally:
            self._latches.release(cid, slots)
