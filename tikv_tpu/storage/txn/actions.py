"""Pure Percolator actions over (MvccTxn, MvccReader).

Reference: src/storage/txn/actions/ — prewrite.rs:36 (prewrite),
commit.rs (commit), cleanup.rs (rollback path), check_txn_status.rs,
acquire_pessimistic_lock.rs.  Each action reads through MvccReader and
buffers effects in MvccTxn; the scheduler flushes the buffer atomically.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

from ..mvcc.errors import (
    AlreadyExist,
    Committed,
    KeyIsLocked,
    PessimisticLockRolledBack,
    TxnLockNotFound,
    WriteConflict,
)
from ..mvcc.reader import MvccReader
from ..mvcc.txn import MvccTxn
from ..txn_types import (
    Lock,
    LockType,
    SHORT_VALUE_MAX_LEN,
    TS_MAX,
    Write,
    WriteType,
    ts_physical,
)


@dataclass(frozen=True)
class Mutation:
    """One prewrite mutation.  op: put | delete | lock | insert.

    ``insert`` is put + must-not-exist (reference: Mutation::Insert,
    prewrite.rs check_for_newer_version with should_not_exist)."""

    op: str
    key: bytes
    value: Optional[bytes] = None


def _lock_type_of(m: Mutation) -> LockType:
    return {"put": LockType.PUT, "insert": LockType.PUT,
            "delete": LockType.DELETE, "lock": LockType.LOCK}[m.op]


def prewrite(txn: MvccTxn, reader: MvccReader, m: Mutation, primary: bytes,
             lock_ttl: int = 3000, txn_size: int = 0,
             min_commit_ts: int = 0,
             is_pessimistic_lock: bool = False,
             use_async_commit: bool = False,
             secondaries: tuple = (),
             one_pc_commit_ts: int = 0) -> None:
    """Reference: actions/prewrite.rs:36.

    Optimistic: conflict-check against newer committed writes, then lock.
    Pessimistic (``is_pessimistic_lock``): the key must already hold this
    txn's pessimistic lock; convert it in place (no conflict check — it
    happened at acquire time).
    Async commit (``use_async_commit``): the lock carries min_commit_ts
    (computed from the concurrency manager's max_ts by the command) and,
    on the primary, the secondary key list.
    1PC (``one_pc_commit_ts``): skip the lock entirely — write the
    commit record at that ts (prewrite.rs one_pc path).
    """
    start_ts = txn.start_ts
    lock = reader.load_lock(m.key)
    if lock is not None:
        if lock.start_ts != start_ts:
            raise KeyIsLocked(m.key, lock)
        if lock.lock_type is not LockType.PESSIMISTIC:
            return      # duplicate prewrite: idempotent (prewrite.rs)
        # fall through: convert pessimistic lock below
    elif is_pessimistic_lock:
        # lock lost (e.g. rolled back by a resolver): reject
        raise PessimisticLockRolledBack(m.key, start_ts)

    if lock is None:        # optimistic path checks for newer versions
        found = reader.seek_write(m.key, TS_MAX)
        if found is not None:
            commit_ts, write = found
            if commit_ts >= start_ts:
                reason = "self_rolled_back" if (
                    write.start_ts == start_ts and
                    write.write_type is WriteType.ROLLBACK) else "optimistic"
                raise WriteConflict(m.key, start_ts, write.start_ts,
                                    commit_ts, reason)
            if m.op == "insert" and _key_exists(reader, m.key, commit_ts,
                                                write):
                raise AlreadyExist(m.key)
    elif m.op == "insert":
        found = reader.seek_write(m.key, TS_MAX)
        if found is not None and _key_exists(reader, m.key, *found):
            raise AlreadyExist(m.key)

    short_value = None
    if m.value is not None and len(m.value) <= SHORT_VALUE_MAX_LEN:
        short_value = m.value

    if one_pc_commit_ts:
        # 1PC: conflict checks passed; commit directly, no lock phase
        if lock is not None:
            txn.unlock_key(m.key)   # converted pessimistic lock
        wt = {LockType.PUT: WriteType.PUT,
              LockType.DELETE: WriteType.DELETE,
              LockType.LOCK: WriteType.LOCK}[_lock_type_of(m)]
        txn.put_write(m.key, one_pc_commit_ts,
                      Write(wt, start_ts, short_value))
        if m.value is not None and short_value is None:
            txn.put_value(m.key, start_ts, m.value)
        return

    new_lock = Lock(_lock_type_of(m), primary, start_ts, lock_ttl,
                    short_value,
                    for_update_ts=lock.for_update_ts if lock else 0,
                    txn_size=txn_size, min_commit_ts=min_commit_ts,
                    use_async_commit=use_async_commit,
                    secondaries=tuple(secondaries))
    txn.put_lock(m.key, new_lock)
    if m.value is not None and short_value is None:
        txn.put_value(m.key, start_ts, m.value)


def _key_exists(reader: MvccReader, key: bytes, commit_ts: int,
                write: Write) -> bool:
    """Is there a visible value at/under commit_ts? (insert check)"""
    while True:
        if write.write_type is WriteType.PUT:
            return True
        if write.write_type is WriteType.DELETE:
            return False
        found = reader.seek_write(key, commit_ts - 1)
        if found is None:
            return False
        commit_ts, write = found


def commit(txn: MvccTxn, reader: MvccReader, key: bytes,
           commit_ts: int) -> Optional[Lock]:
    """Reference: actions/commit.rs — move lock → write record."""
    start_ts = txn.start_ts
    lock = reader.load_lock(key)
    if lock is None or lock.start_ts != start_ts:
        status, ts, _w = reader.get_txn_commit_record(key, start_ts)
        if status == "committed":
            return None     # idempotent re-commit
        raise TxnLockNotFound(key, start_ts)
    if lock.lock_type is LockType.PESSIMISTIC:
        # committing an un-prewritten pessimistic lock is a protocol error
        raise TxnLockNotFound(key, start_ts)
    assert commit_ts > start_ts, (start_ts, commit_ts)
    wt = {LockType.PUT: WriteType.PUT, LockType.DELETE: WriteType.DELETE,
          LockType.LOCK: WriteType.LOCK}[lock.lock_type]
    txn.put_write(key, commit_ts, Write(wt, start_ts, lock.short_value))
    txn.unlock_key(key)
    return lock


def rollback(txn: MvccTxn, reader: MvccReader, key: bytes,
             protect: bool = True) -> None:
    """Reference: actions/cleanup.rs rollback_lock + check_txn_status
    rollback path.  Writes a ROLLBACK marker at start_ts so a late
    prewrite of the same txn conflicts."""
    start_ts = txn.start_ts
    lock = reader.load_lock(key)
    if lock is not None and lock.start_ts == start_ts:
        if lock.short_value is None and lock.lock_type is LockType.PUT:
            txn.delete_value(key, start_ts)
        txn.unlock_key(key)
        _put_rollback(txn, reader, key)
        return
    status, ts, _w = reader.get_txn_commit_record(key, start_ts)
    if status == "committed":
        raise Committed(key, start_ts, ts)
    if status == "rolled_back":
        return      # idempotent
    _put_rollback(txn, reader, key)     # rollback before prewrite arrives


def _put_rollback(txn: MvccTxn, reader: MvccReader, key: bytes) -> None:
    start_ts = txn.start_ts
    found = reader.seek_write(key, start_ts)
    if found is not None and found[0] == start_ts:
        # a write committed exactly at our start_ts: fold the rollback in
        # (write.rs overlapped rollback)
        commit_ts, w = found
        w.has_overlapped_rollback = True
        txn.put_write(key, commit_ts, w)
        return
    txn.put_write(key, start_ts, Write(WriteType.ROLLBACK, start_ts))


def cleanup(txn: MvccTxn, reader: MvccReader, key: bytes,
            current_ts: int) -> None:
    """Rollback iff the lock is expired (or current_ts == 0 → force).

    Reference: actions/cleanup.rs — used by the resolve path on orphan
    locks."""
    lock = reader.load_lock(key)
    if lock is not None and lock.start_ts == txn.start_ts:
        if current_ts and \
                ts_physical(lock.start_ts) + lock.ttl > ts_physical(current_ts):
            raise KeyIsLocked(key, lock)    # still alive
    rollback(txn, reader, key)


def check_txn_status(txn: MvccTxn, reader: MvccReader, primary: bytes,
                     current_ts: int,
                     caller_start_ts: int = 0) -> tuple[str, int]:
    """Reference: actions/check_txn_status.rs — the resolver's probe on a
    txn's primary key.  Returns (status, ts):
    ("committed", commit_ts) | ("rolled_back", 0) | ("locked", ttl)
    | ("ttl_expired", 0) — ttl_expired also rolls the primary back.
    """
    start_ts = txn.start_ts
    lock = reader.load_lock(primary)
    if lock is not None and lock.start_ts == start_ts:
        if lock.use_async_commit:
            # async-commit fate is decided by the secondaries, never by
            # TTL here (check_txn_status.rs returns the lock info so the
            # caller runs CheckSecondaryLocks)
            return ("locked", lock.ttl)
        if ts_physical(lock.start_ts) + lock.ttl < ts_physical(current_ts):
            rollback(txn, reader, primary)
            return ("ttl_expired", 0)
        if caller_start_ts and lock.min_commit_ts <= caller_start_ts:
            # push min_commit_ts so the reader at caller_start_ts can't be
            # blocked by a later commit (check_txn_status.rs push)
            lock.min_commit_ts = caller_start_ts + 1
            txn.put_lock(primary, lock)
        return ("locked", lock.ttl)
    status, ts, _w = reader.get_txn_commit_record(primary, start_ts)
    if status == "committed":
        return ("committed", ts)
    if status == "rolled_back":
        return ("rolled_back", 0)
    # no lock, no record: roll back so a late prewrite cannot succeed
    _put_rollback(txn, reader, primary)
    return ("rolled_back", 0)


def acquire_pessimistic_lock(txn: MvccTxn, reader: MvccReader, key: bytes,
                             primary: bytes, for_update_ts: int,
                             lock_ttl: int = 3000,
                             should_not_exist: bool = False) -> Optional[bytes]:
    """Reference: actions/acquire_pessimistic_lock.rs.  Returns the
    current value (pessimistic locks read-lock the latest version)."""
    start_ts = txn.start_ts
    lock = reader.load_lock(key)
    if lock is not None:
        if lock.start_ts != start_ts:
            raise KeyIsLocked(key, lock)
        # already ours: refresh for_update_ts if newer
        if for_update_ts > lock.for_update_ts:
            lock.for_update_ts = for_update_ts
            txn.put_lock(key, lock)
        return None
    found = reader.seek_write(key, TS_MAX)
    value = None
    if found is not None:
        commit_ts, write = found
        if commit_ts > for_update_ts:
            raise WriteConflict(key, start_ts, write.start_ts, commit_ts)
        if write.start_ts == start_ts and \
                write.write_type is WriteType.ROLLBACK:
            raise PessimisticLockRolledBack(key, start_ts)
        rec = reader.get_txn_commit_record(key, start_ts)
        if rec[0] == "rolled_back":
            raise PessimisticLockRolledBack(key, start_ts)
        if _key_exists(reader, key, commit_ts, write):
            if should_not_exist:
                raise AlreadyExist(key)
            value = reader.get(key, TS_MAX, bypass_locks=(start_ts,))
    txn.put_lock(key, Lock(LockType.PESSIMISTIC, primary, start_ts,
                           lock_ttl, for_update_ts=for_update_ts))
    return value
