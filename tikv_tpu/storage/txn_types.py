"""MVCC on-disk record formats — the Percolator data model.

Reference: components/txn_types/src/:
- ``TimeStamp`` (timestamp.rs:14): u64, physical<<18 | logical
- ``Key`` (types.rs:49): memcomparable-encoded user key, optionally
  suffixed with 8 bytes of bitwise-NOT commit/start ts so that higher
  timestamps sort FIRST under ascending byte order
- ``Lock`` (lock.rs:75): CF_LOCK value — who holds the key, since when,
  with what intent
- ``Write`` (write.rs:16,70): CF_WRITE value — one committed/rolled-back
  version: (write_type, start_ts, short_value?)

Short values (≤ 255 bytes, write.rs SHORT_VALUE_MAX_LEN) are inlined into
the lock/write record so point reads skip the CF_DEFAULT lookup.
"""

from __future__ import annotations

import struct
from dataclasses import dataclass, field
from enum import Enum
from typing import Optional

from ..codec.number import (
    decode_bytes_memcomparable,
    decode_var_u64,
    encode_bytes_memcomparable,
    encode_var_u64,
)

TS_MAX = (1 << 64) - 1
SHORT_VALUE_MAX_LEN = 255


# ---------------------------------------------------------------- TimeStamp

def compose_ts(physical_ms: int, logical: int) -> int:
    """Reference: timestamp.rs compose — TSO layout."""
    return (physical_ms << 18) | logical


def ts_physical(ts: int) -> int:
    return ts >> 18


# ---------------------------------------------------------------- Key

# Keyspace mode prefixes: txn and raw keys must never collide in the
# engine (reference: api_version/src/keyspace.rs ApiV2 key modes).  The
# raw keyspace uses b"r" (storage/__init__.py); txn keys get b"x".
TXN_PREFIX = b"x"


def encode_key(user_key: bytes) -> bytes:
    """User key → engine key (mode prefix + memcomparable, no ts)."""
    return TXN_PREFIX + encode_bytes_memcomparable(user_key)


def decode_key(encoded: bytes):
    """Engine key (no ts suffix) → user key."""
    assert encoded[:1] == TXN_PREFIX, encoded[:1]
    key, off = decode_bytes_memcomparable(encoded, 1)
    assert off == len(encoded), "trailing bytes after key"
    return key


def append_ts(encoded_key: bytes, ts: int) -> bytes:
    """Append ts so higher ts sorts first (types.rs append_ts: !ts BE)."""
    return encoded_key + struct.pack(">Q", TS_MAX - ts)


def split_ts(key_with_ts: bytes) -> tuple[bytes, int]:
    """→ (encoded key without ts, ts).  Reference: types.rs split_on_ts_for."""
    assert len(key_with_ts) >= 8, key_with_ts
    (inv,) = struct.unpack_from(">Q", key_with_ts, len(key_with_ts) - 8)
    return key_with_ts[:-8], TS_MAX - inv


# ---------------------------------------------------------------- Lock

class LockType(Enum):
    PUT = b"P"
    DELETE = b"D"
    LOCK = b"L"             # prewrite of a LOCK mutation (read lock)
    PESSIMISTIC = b"S"      # acquire_pessimistic_lock placeholder


@dataclass
class Lock:
    """CF_LOCK record.  Reference: lock.rs:75 (Lock struct + to_bytes).

    ``use_async_commit`` + ``secondaries``: the primary lock of an
    async-commit txn carries every secondary key, so any reader can
    resolve the txn's fate from the primary alone (lock.rs async commit
    fields; the resolution protocol is CheckSecondaryLocks)."""

    lock_type: LockType
    primary: bytes
    start_ts: int
    ttl: int = 0
    short_value: Optional[bytes] = None
    for_update_ts: int = 0          # pessimistic txns
    txn_size: int = 0
    min_commit_ts: int = 0
    use_async_commit: bool = False
    secondaries: tuple = ()

    def to_bytes(self) -> bytes:
        out = bytearray()
        out += self.lock_type.value
        out += encode_var_u64(len(self.primary))
        out += self.primary
        out += encode_var_u64(self.start_ts)
        out += encode_var_u64(self.ttl)
        out += encode_var_u64(self.for_update_ts)
        out += encode_var_u64(self.txn_size)
        out += encode_var_u64(self.min_commit_ts)
        if self.short_value is not None:
            out += b"v"
            out += encode_var_u64(len(self.short_value))
            out += self.short_value
        if self.use_async_commit:
            out += b"a"
            out += encode_var_u64(len(self.secondaries))
            for s in self.secondaries:
                out += encode_var_u64(len(s))
                out += s
        return bytes(out)

    @staticmethod
    def from_bytes(b: bytes) -> "Lock":
        lt = LockType(b[0:1])
        off = 1
        n, off = decode_var_u64(b, off)
        primary = b[off:off + n]
        off += n
        start_ts, off = decode_var_u64(b, off)
        ttl, off = decode_var_u64(b, off)
        for_update_ts, off = decode_var_u64(b, off)
        txn_size, off = decode_var_u64(b, off)
        min_commit_ts, off = decode_var_u64(b, off)
        short_value = None
        use_async_commit = False
        secondaries: list = []
        while off < len(b):
            tag = b[off:off + 1]
            off += 1
            if tag == b"v":
                n, off = decode_var_u64(b, off)
                short_value = b[off:off + n]
                off += n
            elif tag == b"a":
                use_async_commit = True
                cnt, off = decode_var_u64(b, off)
                for _ in range(cnt):
                    n, off = decode_var_u64(b, off)
                    secondaries.append(b[off:off + n])
                    off += n
            else:
                raise ValueError(f"bad lock tag {tag!r}")
        return Lock(lt, primary, start_ts, ttl, short_value,
                    for_update_ts, txn_size, min_commit_ts,
                    use_async_commit, tuple(secondaries))


# ---------------------------------------------------------------- Write

class WriteType(Enum):
    PUT = b"P"
    DELETE = b"D"
    LOCK = b"L"
    ROLLBACK = b"R"


@dataclass
class Write:
    """CF_WRITE record.  Reference: write.rs:16 (Write struct).

    ``has_overlapped_rollback``: a Rollback whose ts collided with this
    committed write's commit_ts is folded in (write.rs overlapped rollback).
    """

    write_type: WriteType
    start_ts: int
    short_value: Optional[bytes] = None
    has_overlapped_rollback: bool = False

    def to_bytes(self) -> bytes:
        out = bytearray()
        out += self.write_type.value
        out += encode_var_u64(self.start_ts)
        if self.short_value is not None:
            out += b"v"
            out += encode_var_u64(len(self.short_value))
            out += self.short_value
        if self.has_overlapped_rollback:
            out += b"R"
        return bytes(out)

    @staticmethod
    def from_bytes(b: bytes) -> "Write":
        wt = WriteType(b[0:1])
        off = 1
        start_ts, off = decode_var_u64(b, off)
        short_value = None
        overlapped = False
        while off < len(b):
            tag = b[off:off + 1]
            off += 1
            if tag == b"v":
                n, off = decode_var_u64(b, off)
                short_value = b[off:off + n]
                off += n
            elif tag == b"R":
                overlapped = True
            else:
                raise ValueError(f"bad write tag {tag!r}")
        return Write(wt, start_ts, short_value, overlapped)
