"""Concurrency manager — in-memory lock table + global max_ts.

Reference: components/concurrency_manager/src/lib.rs:1-15 (the async
commit substrate): every read updates the global ``max_ts`` BEFORE
resolving data, and an async-commit prewrite (a) publishes its lock in
the in-memory table first, (b) computes
``min_commit_ts = max(max_ts + 1, start_ts + 1, caller hint)``, then
(c) persists the engine lock.  Any read concurrent with that window
either bumped max_ts first (so min_commit_ts exceeds its read_ts) or
sees the memory lock and blocks — the commit_ts can therefore be
decided at prewrite time with no second PD round-trip.
"""

from __future__ import annotations

import threading
from typing import Callable, Optional

from .mvcc.errors import KeyIsLocked
from .txn_types import Lock


class ConcurrencyManager:
    def __init__(self):
        self._max_ts = 0
        self._mu = threading.Lock()
        self._table: dict[bytes, Lock] = {}     # key -> memory lock

    # -- max_ts (lib.rs update_max_ts / max_ts) --

    def update_max_ts(self, ts: int) -> None:
        with self._mu:
            if ts > self._max_ts:
                self._max_ts = ts

    @property
    def max_ts(self) -> int:
        return self._max_ts

    # -- memory lock table (lock_table.rs) --

    def lock_keys(self, keys, locks) -> None:
        """Publish memory locks (prewrite step a)."""
        with self._mu:
            for k, lk in zip(keys, locks):
                self._table[k] = lk

    def unlock_keys(self, keys) -> None:
        with self._mu:
            for k in keys:
                self._table.pop(k, None)

    def memory_lock_of(self, key: bytes) -> Optional[Lock]:
        return self._table.get(key)

    # -- read-side checks (storage reads + copr snapshots) --

    def read_key_check(self, key: bytes, read_ts: int,
                       bypass_locks=()) -> None:
        lk = self._table.get(key)
        if lk is not None and self._blocks(lk, read_ts, bypass_locks):
            raise KeyIsLocked(key, lk)

    def read_range_check(self, start: Optional[bytes],
                         end: Optional[bytes], read_ts: int,
                         bypass_locks=()) -> None:
        if not self._table:
            return
        with self._mu:
            items = list(self._table.items())
        for k, lk in items:
            if start is not None and k < start:
                continue
            if end is not None and k >= end:
                continue
            if self._blocks(lk, read_ts, bypass_locks):
                raise KeyIsLocked(k, lk)

    def read_ranges_check(self, ranges, read_ts: int,
                          bypass_locks=()) -> None:
        """Range check against coprocessor DAG key ranges — only memory
        locks inside the request's ranges block it, mirroring the
        engine-lock scoping of the row scanner.  Both the lock table
        and DAG ranges are RAW user keys (table record keys), compared
        directly — the same comparison MvccColumnarSnapshot.check_locks
        uses for engine locks."""
        if not self._table:
            return
        with self._mu:
            items = list(self._table.items())
        for k, lk in items:
            if not self._blocks(lk, read_ts, bypass_locks):
                continue
            for r in ranges:
                if r.start <= k < r.end:
                    raise KeyIsLocked(k, lk)

    def read_region_check(self, region, read_ts: int,
                          bypass_locks=()) -> None:
        """Scope the memory-lock check to one REGION (replica-read
        veto): lock keys are raw user keys; region boundaries live in
        the encoded txn keyspace, so each key encodes for the compare."""
        if not self._table:
            return
        from .txn_types import encode_key
        with self._mu:
            items = list(self._table.items())
        for k, lk in items:
            if not self._blocks(lk, read_ts, bypass_locks):
                continue
            if region.contains(encode_key(k)):
                raise KeyIsLocked(k, lk)

    @staticmethod
    def _blocks(lk: Lock, read_ts: int, bypass_locks) -> bool:
        from .txn_types import LockType
        if lk.start_ts in bypass_locks:
            return False
        if lk.lock_type in (LockType.LOCK, LockType.PESSIMISTIC):
            return False
        return lk.start_ts <= read_ts
